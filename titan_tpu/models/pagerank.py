"""PageRank as a DenseProgram.

Parity target: the reference's PageRankVertexProgram OLAP fixture
(reference: titan-test olap/PageRankVertexProgram — damping 0.85, rank
divided over out-edges each superstep, terminate on iteration budget). The
TPU formulation is the classic pull-mode SpMV:

    rank' = (1-α)/n + α · Σ_{(u→v)} rank[u] / outdeg[u]
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from titan_tpu.olap.api import DenseMapReduce, DenseProgram


class PageRank(DenseProgram):
    combine = "sum"

    def __init__(self, alpha: float = 0.85, iterations: int = 20,
                 tol: float = 0.0):
        self.alpha = alpha
        self.max_iterations = iterations
        self.tol = tol

    def init(self, n, params):
        return {
            "rank": jnp.full((n,), 1.0 / n, dtype=jnp.float32),
            "inv_outdeg": params["inv_outdeg"],
        }

    def message(self, src_state, edge_data, params):
        return src_state["rank"] * src_state["inv_outdeg"]

    def apply(self, state, agg, iteration, params):
        n = params["n"]
        new_rank = (1.0 - self.alpha) / n + self.alpha * agg
        return {"rank": new_rank.astype(jnp.float32),
                "inv_outdeg": state["inv_outdeg"]}

    def done(self, state, new_state, agg, iteration, params):
        if self.tol <= 0.0:
            return jnp.array(False)
        return jnp.max(jnp.abs(new_state["rank"] - state["rank"])) < self.tol

    def outputs(self, state, params):
        return {"rank": state["rank"]}


class TopRanksMapReduce(DenseMapReduce):
    """Post-BSP aggregation fixture (reference: titan-test
    olap/PageRankMapReduce companion): top-k (vertex id, rank) pairs,
    computed as one device-side top_k instead of per-vertex map/reduce."""

    memory_key = "pageRank"

    def __init__(self, k: int = 10):
        self.k = k

    def compute(self, state, snapshot, params):
        import jax
        ranks = jnp.asarray(state["rank"])
        k = min(self.k, ranks.shape[0])
        vals, idx = jax.lax.top_k(ranks, k)
        import numpy as np
        idx = np.asarray(idx)
        vals = np.asarray(vals)
        vids = np.asarray(snapshot.vertex_ids)[idx]
        return [(int(v), float(r)) for v, r in zip(vids, vals)]


def _ppr_window_batched():
    """[S, n+1] window sweep: jax.vmap of the EXACT per-row expressions
    of ``frontier._pr_window`` — one shared dstT/colowner gather plan
    serves every source row (the K-way amortization story, applied to
    the recommendation workload)."""
    def build():
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("W",),
                           donate_argnums=(0,))
        def step(acc, contrib, w0, dstT, colowner, W: int):
            def one(acc_r, contrib_r):
                w0c = jnp.minimum(w0, colowner.shape[0] - W)
                owner = jax.lax.dynamic_slice(colowner, (w0c,), (W,))
                nbr = jax.lax.dynamic_slice(dstT, (0, w0c), (8, W))
                fresh = (w0c + jnp.arange(W, dtype=jnp.int32)) >= w0
                c = jnp.where(fresh, contrib_r[owner], 0.0)
                return acc_r.at[nbr].add(
                    jnp.broadcast_to(c[None, :], nbr.shape),
                    mode="drop")
            return jax.vmap(one)(acc, contrib)
        return step
    from titan_tpu.utils.jitcache import jit_once
    return jit_once("ppr_window_batched", build)


def _ppr_finish_batched():
    """[S, n+1] finish: jax.vmap of ``frontier._pr_finish_reset``'s
    per-row expressions (bit-equality per source rides on the two
    staying identical)."""
    def build():
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_",))
        def fin(acc, rank, reset, deg, damping, n_: int):
            def one(acc_r, rank_r, reset_r):
                new_rank = (1.0 - damping) * reset_r[:n_] \
                    + damping * acc_r[:n_]
                new_rank = jnp.concatenate(
                    [new_rank, jnp.zeros((1,), jnp.float32)])
                delta = jnp.abs(new_rank[:n_] - rank_r[:n_]).sum()
                contrib = jnp.where(deg > 0,
                                    new_rank / jnp.maximum(deg, 1), 0.0)
                return new_rank, contrib, delta
            return jax.vmap(one)(acc, rank, reset)
        return fin
    from titan_tpu.utils.jitcache import jit_once
    return jit_once("ppr_finish_batched", build)


def pagerank_personalized_batched(snap_or_graph, sources=None,
                                  iterations: int = 20,
                                  damping: float = 0.85,
                                  reset=None,
                                  return_device: bool = False,
                                  on_round=None, overlay=None):
    """Batched personalized PageRank: one RESET ROW PER USER, vmapped
    over the dense window kernel — S users' recommendation walks run as
    ONE device dispatch sharing every edge gather (the interactive
    lane's flagship workload, olap/serving/interactive).

    ``sources``: dense vertex indices; row s teleports (and starts) at
    the one-hot distribution of ``sources[s]``. ``reset`` ([S, n],
    rows summing to 1) overrides with arbitrary per-user teleport
    distributions. Each row is BIT-EQUAL to a sequential
    ``frontier.pagerank_dense(snap, reset=row)`` run — the oracle the
    property tests pin.

    ``on_round(it)``: per-iteration veto (RoundInterrupted), same
    contract as pagerank_dense. No per-source ``tol`` early exit: the
    shared loop runs the full iteration budget (a per-row tol would
    desynchronize the fused rows). Returns ``(ranks [S, n], iters)``.
    """
    import jax.numpy as jnp

    from titan_tpu.models.bfs_hybrid import build_chunked_csr
    from titan_tpu.models.frontier import (DENSE_WINDOW, RoundInterrupted,
                                           _colowner)
    from titan_tpu.utils.jitcache import dev_scalar

    ov = overlay
    if ov is None and not isinstance(snap_or_graph, dict):
        ov = getattr(snap_or_graph, "_live_overlay", None)
    if ov is not None and not ov.empty:
        # same seam as pagerank_dense: dense window sweeps read
        # contiguous base-CSR columns — compact the overlay first (the
        # interactive lane leases compacted=True for this kind)
        raise RuntimeError(
            "pagerank_personalized_batched on a live overlay: compact "
            "the overlay first (LiveGraphPlane.compact_if_dirty) — "
            "dense window sweeps have no overlay seam")
    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    deg = g["deg"].astype(jnp.float32)
    colowner = _colowner(g)
    total = g["q_total"]
    W = min(DENSE_WINDOW, total)
    if reset is not None:
        r = jnp.asarray(reset, jnp.float32)
        if r.ndim != 2 or r.shape[1] != n:
            raise ValueError(f"reset must be [S, n={n}], got {r.shape}")
        S = r.shape[0]
        reset_dev = jnp.concatenate(
            [r, jnp.zeros((S, 1), jnp.float32)], axis=1)
    else:
        if sources is None or len(sources) == 0:
            raise ValueError("need sources (dense indices) or reset "
                             "rows — one per user")
        src = np.asarray(sources, np.int64)
        if src.min() < 0 or src.max() >= n:
            raise IndexError(f"source out of range [0, {n})")
        S = len(src)
        reset_dev = jnp.zeros((S, n + 1), jnp.float32) \
            .at[jnp.arange(S), jnp.asarray(src.astype(np.int32))] \
            .set(1.0)
    win = _ppr_window_batched()
    fin = _ppr_finish_batched()
    rank = reset_dev
    contrib = jnp.where(deg > 0, rank / jnp.maximum(deg, 1.0), 0.0)
    it = 0
    for it in range(1, iterations + 1):
        if on_round is not None and not on_round(it - 1):
            raise RoundInterrupted(it - 1)
        acc = jnp.zeros((S, n + 1), jnp.float32)
        for w0 in range(0, total, W):
            acc = win(acc, contrib, dev_scalar(w0), g["dstT"],
                      colowner, W=W)
        rank, contrib, _delta = fin(acc, rank, reset_dev, deg,
                                    jnp.float32(damping), n_=n)
    out = rank[:, :n]
    if not return_device:
        from titan_tpu.obs import devprof
        devprof.count_d2h("frontier.result",
                          getattr(out, "nbytes", 0))
        out = np.asarray(out)
    return out, it


def top_k_per_user(ranks, vertex_ids, k: int = 10,
                   exclude=None):
    """Per-user top-k ``(vertex id, rank)`` recommendation rows from a
    batched PPR result ([S, n] host array). ``exclude`` (optional
    [S]-list of dense indices, typically each user's own source) zeroes
    the user's self-rank before ranking — a recommender never
    recommends the user to themselves."""
    ranks = np.asarray(ranks)
    S, n = ranks.shape
    k = min(int(k), n)
    if k <= 0:
        # a non-positive k must answer "no recommendations", never the
        # negative-slice near-whole-graph argpartition surprise
        return [[] for _ in range(S)]
    out = []
    for s in range(S):
        row = ranks[s]
        if exclude is not None and exclude[s] is not None:
            row = row.copy()
            row[exclude[s]] = -1.0
        idx = np.argpartition(-row, k - 1)[:k]
        idx = idx[np.argsort(-row[idx], kind="stable")]
        out.append([(int(vertex_ids[i]), float(ranks[s][i]))
                    for i in idx if row[i] > 0.0])
    return out


def run(computer, alpha: float = 0.85, iterations: int = 20, tol: float = 0.0,
        snapshot=None):
    snap = snapshot or computer.snapshot()
    import numpy as np
    outdeg = np.maximum(snap.out_degree, 1).astype(np.float32)
    inv = np.where(snap.out_degree > 0, 1.0 / outdeg, 0.0).astype(np.float32)
    prog = PageRank(alpha, iterations, tol)
    return computer.run(prog, params={"n": snap.n, "inv_outdeg": inv},
                        snapshot=snap)
