"""Whole-BFS-in-one-dispatch: the direction-optimizing level loop runs
entirely on device, with the alpha/beta mode switch AND the capacity
bucketing done by ``lax.switch``/``lax.cond`` over a ladder of
power-of-two-width branches.

Why: the host-driven hybrid (models/bfs_hybrid.py) sizes every kernel
from per-level stats READBACKS — 4-6 of them per scale-26 BFS. Each
readback costs a tunnel round trip (~0.1s fast day, ~0.9s slow day —
PERF_NOTES.md), so the measured TEPS swings ~30% with tunnel weather
(VERDICT r3 weak #1 asks for >=125M "regardless"). The insight that
makes on-device sizing possible is that a ``lax.cond``/``lax.switch``
branch executes ONLY its taken side on TPU, so a ladder of prebuilt
bucket widths gives the same dead-lane economics as host-sized
dispatch without the readback: each level computes its masses on
device and switches into the matching width.

Structure per level (one ``lax.while_loop`` iteration):

* done      — f_count == 0 or max levels: identity.
* endgame   — remaining unvisited fits (END_C_CAP, END_P_CAP): run the
              trailing levels to completion in an inner while_loop
              (same body as bfs_hybrid._endgame) and mark done.
* td@k      — top-down expansion at (f_cap, p_cap) bucket k; the
              frontier list is rebuilt from ``dist == level`` inside
              the branch (no frontier state carried across levels).
* bu@j      — bottom-up at candidate bucket j: split-lane chunk-0 test
              (lanes 0-3), then an inner cond-ladder refetches lanes
              4-7 for the few misses at a narrower width, then the
              fused chunk rounds + exhaust sweep, again cond-laddered
              by survivor count.

The single dispatch returns (dist, stats); ONE host readback ends the
run. Numerics and level semantics are identical to the host-driven
hybrid — tests/test_frontier_models.py pins bit-equality with plain
BFS over the same graphs (buckets monkeypatched small so every branch
executes on CPU-sized inputs).

Trade-off: the fused program compiles every branch of every ladder
(~10-20 kernel bodies) — a one-time multi-minute compile, amortized by
the persistent XLA compile cache. The host-driven path remains the
default for interactive use; the bench selects the fused path via
``TITAN_TPU_FUSED_BFS=1`` once its numbers win on real hardware.

SYMMETRIC GRAPHS ONLY (same contract as bfs_hybrid).
"""

# graftlint: allow-file[opscan] reason=single-dispatch fused experiment, not a round-loop hot path — its in-branch nonzero compactions are the measured alternative ops.compaction is judged against (exempt since ISSUE r6)

from __future__ import annotations

import functools

import numpy as np

from titan_tpu.models.bfs import INF, _next_pow2
from titan_tpu.models.bfs_hybrid import (_bit_of, _level_stats, _pack_bits,
                                         build_chunked_csr,
                                         enumerate_chunk_pairs)
from titan_tpu.utils.jitcache import jit_once as _get

# stats vector layout
SF, SM8F, SM8U, SNU, SLEVEL, SDONE, SOVERFLOW = range(7)

BU_CHUNK_ROUNDS = 8
END_C_CAP = 1 << 21
END_P_CAP = 1 << 22
# branch-memory diet: the ladders top out here instead of cap_n/cap_q —
# every switch branch's temporaries coexist with the ~9.3GB scale-26
# graph, and the first cut's cap_n-wide branches OOM'd at compile. A bu
# level whose candidate count exceeds the top bucket sets the overflow
# stat instead of truncating, and the driver transparently re-runs via
# the host-driven hybrid (never happens on Graph500-class inputs: the
# heavy level's candidates are ~0.4n < 2^25 at scale 26).
FUSED_BU_MAX = 1 << 25
FUSED_TD_MAX = (1 << 23, 1 << 25)


def _ladders(n: int, total_chunks: int):
    """Bucket ladders sized to the graph (all static at trace time)."""
    cap_n = _next_pow2(max(n, 2))
    cap_q = _next_pow2(max(total_chunks + 1, 2))
    # td (f_cap, p_cap) pairs, ascending; the last p covers any single
    # vertex's mass (max degree < n) and any frontier the alpha test
    # leaves in td mode at bench scales
    # (f, p) pairs tuned to the level shapes a direction-optimized
    # Graph500 run actually visits (head levels; the mid td level whose
    # frontier is ~1/16 of its chunk mass; the pre-switch heavy td).
    # A mismatched pair is pure dead-lane cost — the first fused cut
    # paired (2^18,2^22)->(2^24,2^26) and measured +44% vs the host
    # path at scale 24 because a 1M-vertex/5M-chunk frontier fell into
    # the 2^26-wide kernel. Frontiers past the top pair force bu mode;
    # candidates past FUSED_BU_MAX set the overflow stat (module doc).
    td = []
    for fb, pb in ((1 << 12, 1 << 18), (1 << 20, 1 << 22),
                   FUSED_TD_MAX):
        td.append((min(fb, cap_n), min(pb, cap_q)))
    td = sorted(set(td))
    # bu candidate caps
    bu = sorted({min(1 << 23, cap_n), min(FUSED_BU_MAX, cap_n)})
    return td, bu, cap_n, cap_q


def _bu_level_body(dist, level, dstT, colstart, degc, deg, c_cap: int,
                   n_: int):
    """One full bottom-up level at candidate width ``c_cap`` —
    split-lane opener + laddered survivor rounds + exhaust, all traced
    inline (runs inside a switch branch)."""
    import jax
    import jax.numpy as jnp

    q_pad = dstT.shape[1] - 1
    fbits = _pack_bits(dist, level, n_)
    unvis = (dist[:n_] >= INF) & (degc[:n_] > 0)
    cand = jnp.nonzero(unvis, size=c_cap,
                       fill_value=n_)[0].astype(jnp.int32)
    c_count = unvis.sum().astype(jnp.int32)
    # a candidate set wider than the bucket would be TRUNCATED by the
    # nonzero — flag it so the driver discards and re-runs host-driven
    overflow = (c_count > c_cap).astype(jnp.int32)
    alive = jnp.arange(c_cap) < c_count
    v = jnp.minimum(cand, n_)
    cols = jnp.where(alive, colstart[v], q_pad)
    parents4 = jnp.take(dstT[:4], jnp.clip(cols, 0, q_pad), axis=1)
    found = alive & _bit_of(fbits, parents4).any(axis=0)
    dist = dist.at[jnp.where(found, v, n_ + 1)].set(
        level + 1, mode="drop")
    untested = alive & ~found & (deg[v] > 4)
    nu = untested.sum().astype(jnp.int32)

    def finish47(dist, cand_u, u_cap: int):
        """Lanes 4-7 for the compacted untested list at width u_cap;
        then the chunk rounds + exhaust for full-chunk0 misses."""
        cc = (cand_u < n_).sum().astype(jnp.int32)
        al = jnp.arange(u_cap) < cc
        vv = jnp.minimum(cand_u, n_)
        cl = jnp.where(al, colstart[vv], q_pad)
        p47 = jnp.take(dstT[4:], jnp.clip(cl, 0, q_pad), axis=1)
        fnd = al & _bit_of(fbits, p47).any(axis=0)
        dist = dist.at[jnp.where(fnd, vv, n_ + 1)].set(
            level + 1, mode="drop")
        surv = al & ~fnd & (degc[vv] > 1)
        nc = surv.sum().astype(jnp.int32)
        idx = jnp.nonzero(surv, size=u_cap, fill_value=u_cap - 1)[0]
        keep = jnp.arange(u_cap) < nc
        cand2 = jnp.where(keep, cand_u[idx], n_).astype(jnp.int32)
        off2 = jnp.where(keep, 1, 0).astype(jnp.int32)

        def rounds_and_exhaust(dist, cand_r, off_r, nc_r, w: int):
            def round_(state, _):
                dist, cand, off, ncr = state
                alv = jnp.arange(w) < ncr
                lv = jnp.minimum(cand, n_)
                cls = jnp.where(alv, colstart[lv] + off, q_pad)
                par = jnp.take(dstT, jnp.clip(cls, 0, q_pad), axis=1)
                ft = alv & _bit_of(fbits, par).any(axis=0)
                dist = dist.at[jnp.where(ft, lv, n_ + 1)].set(
                    level + 1, mode="drop")
                sv = alv & ~ft & (off + 1 < degc[lv])
                ix = jnp.nonzero(sv, size=w, fill_value=w - 1)[0]
                nc2 = sv.sum().astype(jnp.int32)
                kp = jnp.arange(w) < nc2
                cand = jnp.where(kp, cand[ix], n_)
                off = jnp.where(kp, off[ix] + 1, 0)
                return (dist, cand, off, nc2), None

            (dist, cand_r, off_r, nc_r), _ = jax.lax.scan(
                round_, (dist, cand_r, off_r, nc_r), None,
                length=BU_CHUNK_ROUNDS - 1)
            # stragglers: K-chunk-stride while_loop — every iteration
            # checks the next K chunks of EVERY survivor, so completion
            # is guaranteed for any degree (a bounded single exhaust
            # sweep would silently drop a hub's chunks past its cap —
            # the enumerate primitive drops out-of-range starts)
            K = max((1 << 16) // max(w, 1), 1)

            def ex_cond(s):
                _, _, _, ncr = s
                return ncr > 0

            def ex_body(s):
                dist, cand, off, ncr = s
                alv = jnp.arange(w) < ncr
                lv = jnp.minimum(cand, n_)
                rem = jnp.where(alv,
                                jnp.maximum(degc[lv] - off, 0), 0)
                j = jnp.arange(K, dtype=jnp.int32)[None, :]
                cls = (colstart[lv] + off)[:, None] + j      # [w, K]
                live = alv[:, None] & (j < rem[:, None])
                cls = jnp.where(live, jnp.clip(cls, 0, q_pad), q_pad)
                par = jnp.take(dstT, cls.reshape(-1), axis=1)
                hit = _bit_of(fbits, par).any(axis=0).reshape(w, K)
                ft = alv & (hit & live).any(axis=1)
                dist = dist.at[jnp.where(ft, lv, n_ + 1)].set(
                    level + 1, mode="drop")
                sv = alv & ~ft & (rem > K)
                ix = jnp.nonzero(sv, size=w, fill_value=w - 1)[0]
                nc2 = sv.sum().astype(jnp.int32)
                kp = jnp.arange(w) < nc2
                cand = jnp.where(kp, cand[ix], n_)
                off = jnp.where(kp, off[ix] + K, 0)
                return (dist, cand, off, nc2)

            dist, _, _, _ = jax.lax.while_loop(
                ex_cond, ex_body, (dist, cand_r, off_r, nc_r))
            return dist

        # survivor-width ladder for the chunk rounds
        wl = sorted({min(1 << 12, u_cap), u_cap})
        if len(wl) == 1:
            return jax.lax.cond(
                nc > 0,
                lambda d: rounds_and_exhaust(d, cand2, off2, nc, u_cap),
                lambda d: d, dist)
        return jax.lax.cond(
            nc == 0, lambda d: d,
            lambda d: jax.lax.cond(
                nc <= wl[0],
                lambda d2: rounds_and_exhaust(
                    d2, cand2[:wl[0]], off2[:wl[0]], nc, wl[0]),
                lambda d2: rounds_and_exhaust(d2, cand2, off2, nc,
                                              u_cap), d), dist)

    # untested-width ladder (measured ~10% of candidates at heavy
    # levels miss lanes 0-3 — the narrow branches are the common case)
    def with_u(u_cap: int):
        def go(dist):
            idx = jnp.nonzero(untested, size=u_cap,
                              fill_value=c_cap - 1)[0]
            keep = jnp.arange(u_cap) < nu
            cand_u = jnp.where(keep, cand[idx], n_).astype(jnp.int32)
            return finish47(dist, cand_u, u_cap)
        return go

    ul = sorted({max(c_cap // 16, 8), max(c_cap // 4, 8), c_cap})

    def pick(dist, ladder):
        # nested cond ladder: smallest fitting width runs
        if len(ladder) == 1:
            return with_u(ladder[0])(dist)
        return jax.lax.cond(nu <= ladder[0], with_u(ladder[0]),
                            lambda d: pick(d, ladder[1:]), dist)

    dist = jax.lax.cond(nu == 0, lambda d: d,
                        lambda d: pick(d, ul), dist)
    return dist, overflow


def _td_level_body(dist, level, dstT, colstart, degc, f_cap: int,
                   p_cap: int, n_: int):
    import jax.numpy as jnp

    q_pad = dstT.shape[1] - 1
    fr_mask = dist[:n_] == level
    frontier = jnp.nonzero(fr_mask, size=f_cap,
                           fill_value=n_)[0].astype(jnp.int32)
    f_count = fr_mask.sum().astype(jnp.int32)
    valid = jnp.arange(f_cap) < f_count
    v = jnp.minimum(frontier, n_)
    cols, _, _ = enumerate_chunk_pairs(
        valid, degc[v], colstart[v], p_cap, q_pad)
    nbr = jnp.take(dstT, cols, axis=1)
    return dist.at[nbr].min(level + 1, mode="drop")


def _endgame_body(dist, level0, max_lv, dstT, colstart, degc,
                  c_cap: int, p_cap: int, n_: int):
    """Inner while_loop finishing every trailing level (same body as
    bfs_hybrid._endgame, traced inline). Returns (dist, final_level)."""
    import jax
    import jax.numpy as jnp

    q_pad = dstT.shape[1] - 1

    def cond(s):
        _, _, _, level, found = s
        return (found > 0) & (level < max_lv)

    def body(s):
        dist, cand, c_count, level, _ = s
        fbits = _pack_bits(dist, level, n_)
        valid = jnp.arange(c_cap) < c_count
        v = jnp.minimum(cand, n_)
        cols, p_total, owner = enumerate_chunk_pairs(
            valid, degc[v], colstart[v], p_cap, q_pad, with_owner=True)
        parents = jnp.take(dstT, cols, axis=1)
        hit = _bit_of(fbits, parents).any(axis=0)
        j = jnp.arange(p_cap, dtype=jnp.int32)
        found_per = jnp.zeros((c_cap,), jnp.int32) \
            .at[jnp.where(j < p_total, owner, c_cap - 1)] \
            .max(hit.astype(jnp.int32), mode="drop")
        found = valid & (found_per > 0)
        dist = dist.at[jnp.where(found, v, n_ + 1)].set(
            level + 1, mode="drop")
        nfound = found.sum().astype(jnp.int32)
        surv = valid & ~found
        idx = jnp.nonzero(surv, size=c_cap, fill_value=c_cap - 1)[0]
        nc = surv.sum().astype(jnp.int32)
        keep = jnp.arange(c_cap) < nc
        cand = jnp.where(keep, v[idx], n_).astype(jnp.int32)
        return (dist, cand, nc, level + 1, nfound)

    unvis = (dist[:n_] >= INF) & (degc[:n_] > 0)
    cand0 = jnp.nonzero(unvis, size=c_cap,
                        fill_value=n_)[0].astype(jnp.int32)
    c0 = unvis.sum().astype(jnp.int32)
    state = (dist, cand0, c0, level0, jnp.int32(1))
    dist, _, _, level, _ = jax.lax.while_loop(cond, body, state)
    return dist, level


def _fused_bfs():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(
            jax.jit,
            static_argnames=("n_", "total_chunks", "end_c", "end_p"),
            donate_argnums=(0,))
        def run(dist, st, max_lv, dstT, colstart, degc, deg, n_: int,
                total_chunks: int, end_c: int, end_p: int):
            td_buckets, bu_buckets, cap_n, cap_q = _ladders(
                n_, total_chunks)

            def level_body(state):
                dist, st = state
                f_count = st[SF]
                m8_f = st[SM8F]
                m8_unvis = st[SM8U]
                n_unvis = st[SNU]
                level = st[SLEVEL]

                endgame_ok = (n_unvis <= end_c) & (m8_unvis <= end_p)
                # a frontier that exceeds the td ladder (by count OR
                # mass) is forced bottom-up — bu is mode-correct for
                # any level. The bu ladder tops out at FUSED_BU_MAX
                # (memory diet), so a wider candidate set WOULD be
                # truncated: _bu_level_body flags SOVERFLOW and the
                # driver re-runs host-driven instead of trusting the
                # result. Do not remove that guard.
                use_bu = ((m8_f > m8_unvis // 8) & (f_count > 1)) \
                    | (m8_f > td_buckets[-1][1]) \
                    | (f_count > td_buckets[-1][0])

                # branch index: 0 = endgame, 1..T = td buckets,
                # T+1..T+B = bu buckets
                T = len(td_buckets)
                tdi = jnp.int32(T - 1)
                for k in range(T - 2, -1, -1):
                    fits = (f_count <= td_buckets[k][0]) \
                        & (m8_f <= td_buckets[k][1])
                    tdi = jnp.where(fits, jnp.int32(k), tdi)
                bui = jnp.int32(len(bu_buckets) - 1)
                for k in range(len(bu_buckets) - 2, -1, -1):
                    bui = jnp.where(n_unvis <= bu_buckets[k],
                                    jnp.int32(k), bui)
                idx = jnp.where(
                    endgame_ok, jnp.int32(0),
                    jnp.where(use_bu, jnp.int32(1 + T) + bui,
                              jnp.int32(1) + tdi))

                def endgame_branch(dist, st):
                    d2, lvl = _endgame_body(
                        dist, st[SLEVEL], max_lv, dstT, colstart, degc,
                        end_c, end_p, n_)
                    # +1 = the empty probe level (host-loop parity)
                    st2 = jnp.stack([
                        jnp.int32(0), jnp.int32(0), jnp.int32(0),
                        jnp.int32(0),
                        jnp.minimum(lvl + 1, max_lv), jnp.int32(1),
                        st[SOVERFLOW]])
                    return d2, st2

                def td_branch(k):
                    def go(dist, st):
                        d2 = _td_level_body(
                            dist, st[SLEVEL], dstT, colstart, degc,
                            td_buckets[k][0], td_buckets[k][1], n_)
                        s4 = _level_stats(d2, degc, st[SLEVEL], n_)
                        st2 = jnp.stack([
                            s4[0], s4[1], s4[2], s4[3],
                            st[SLEVEL] + 1,
                            (s4[0] == 0).astype(jnp.int32),
                            st[SOVERFLOW]])
                        return d2, st2
                    return go

                def bu_branch(k):
                    def go(dist, st):
                        d2, ovf = _bu_level_body(
                            dist, st[SLEVEL], dstT, colstart, degc,
                            deg, bu_buckets[k], n_)
                        s4 = _level_stats(d2, degc, st[SLEVEL], n_)
                        ovf = jnp.maximum(st[SOVERFLOW], ovf)
                        st2 = jnp.stack([
                            s4[0], s4[1], s4[2], s4[3],
                            st[SLEVEL] + 1,
                            # overflow also ends the loop — the result
                            # will be discarded by the driver anyway
                            jnp.maximum((s4[0] == 0).astype(jnp.int32),
                                        ovf),
                            ovf])
                        return d2, st2
                    return go

                branches = [endgame_branch] \
                    + [td_branch(k) for k in range(T)] \
                    + [bu_branch(k) for k in range(len(bu_buckets))]
                dist, st = jax.lax.switch(idx, branches, dist, st)
                return (dist, st)

            def cond(state):
                _, st = state
                return (st[SDONE] == 0) & (st[SLEVEL] < max_lv)

            dist, st = jax.lax.while_loop(cond, level_body, (dist, st))
            return dist, st
        return run
    return _get("hybrid_fused", build)


def frontier_bfs_hybrid_fused(snap, source_dense: int,
                              max_levels: int = 1000,
                              return_device: bool = False):
    """Single-dispatch direction-optimizing BFS (see module doc).
    Returns (dist, levels) like frontier_bfs_hybrid."""
    import jax.numpy as jnp

    from titan_tpu.utils.jitcache import dev_scalar

    g = snap if isinstance(snap, dict) else build_chunked_csr(snap)
    n = g["n"]
    dstT, colstart, degc, deg = (g["dstT"], g["colstart"], g["degc"],
                                 g["deg"])
    total_chunks = int(g["q_total"] - 1)
    run = _fused_bfs()
    end_c = min(END_C_CAP, _next_pow2(max(n, 2)))
    end_p = min(END_P_CAP, _next_pow2(max(total_chunks + 1, 2)))
    dist = jnp.full((n + 1,), INF, jnp.int32).at[source_dense].set(0)
    m8_f0 = degc[source_dense]
    st0 = jnp.stack([
        jnp.int32(1), m8_f0.astype(jnp.int32),
        jnp.where(dist[:n] >= INF, degc[:n], 0).sum(dtype=jnp.int32),
        ((dist[:n] >= INF) & (degc[:n] > 0)).sum().astype(jnp.int32),
        jnp.int32(0), jnp.int32(0), jnp.int32(0)])
    dist, st = run(dist, st0, dev_scalar(max_levels), dstT, colstart,
                   degc, deg, n_=n, total_chunks=total_chunks,
                   end_c=end_c, end_p=end_p)
    st_h = np.asarray(st)
    if int(st_h[SOVERFLOW]):
        # a bu level's candidate set exceeded the trimmed ladder (never
        # on Graph500-class inputs — see FUSED_BU_MAX): the fused result
        # is invalid; re-run through the host-driven hybrid
        from titan_tpu.models.bfs_hybrid import frontier_bfs_hybrid
        return frontier_bfs_hybrid(g, source_dense,
                                   max_levels=max_levels,
                                   return_device=return_device)
    levels = int(st_h[SLEVEL])
    out = dist[:n]
    if not return_device:
        out = np.asarray(out)
    return out, levels
