"""Weakly-connected components via min-label propagation (DenseProgram).

(BASELINE config #5: connected components on the multi-chip sharded CSR.
Pull-mode: label' = min(label, min over in-edges of label[src]); run on a
symmetrized snapshot so components are weak.)"""

from __future__ import annotations

import jax.numpy as jnp

from titan_tpu.olap.api import DenseProgram


class WCC(DenseProgram):
    combine = "min"

    def __init__(self, max_iterations: int = 1000):
        self.max_iterations = max_iterations

    def init(self, n, params):
        return {"label": jnp.arange(n, dtype=jnp.int32)}

    def message(self, src_state, edge_data, params):
        return src_state["label"]

    def apply(self, state, agg, iteration, params):
        return {"label": jnp.minimum(state["label"], agg)}

    def done(self, state, new_state, agg, iteration, params):
        return jnp.all(new_state["label"] == state["label"])

    def outputs(self, state, params):
        return {"label": state["label"]}


def run(computer, snapshot=None, max_iterations: int = 1000):
    snap = snapshot or computer.snapshot(directed=False)
    return computer.run(WCC(max_iterations), params={}, snapshot=snap)
