"""k-core membership as a DenseProgram.

Parity role: part of the OLAP model zoo (the reference ships vertex-program
fixtures — PageRank, ShortestDistance — and any TinkerPop VertexProgram;
k-core is the canonical iterative-peeling program). A vertex stays in the
k-core while it has >= k neighbors that are themselves still in: each
superstep sums alive in-neighbors and peels below-threshold vertices until
a fixed point (runs on the symmetrized snapshot for undirected semantics).
"""

from __future__ import annotations

import jax.numpy as jnp

from titan_tpu.olap.api import DenseProgram


class KCore(DenseProgram):
    combine = "sum"

    def __init__(self, k: int, max_iterations: int = 1000):
        self.k = k
        self.max_iterations = max_iterations

    def init(self, n, params):
        return {"alive": jnp.ones((n,), jnp.float32)}

    def message(self, src_state, edge_data, params):
        return src_state["alive"]

    def apply(self, state, agg, iteration, params):
        # peel: stay alive only with >= k alive neighbors
        return {"alive": (state["alive"] > 0) * (agg >= self.k)
                .astype(jnp.float32)}

    def done(self, state, new_state, agg, iteration, params):
        return jnp.all(new_state["alive"] == state["alive"])

    def outputs(self, state, params):
        return {"in_core": state["alive"] > 0}


def run(computer, k: int, snapshot=None, max_iterations: int = 1000):
    # k-core is an undirected notion: the default snapshot must be the
    # symmetrized graph (same as WCC)
    snap = snapshot or computer.snapshot(directed=False)
    prog = KCore(k, max_iterations)
    return computer.run(prog, snapshot=snap)
