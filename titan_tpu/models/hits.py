"""HITS (hubs & authorities) as a DenseProgram.

Parity role: OLAP model zoo (the reference executes any TinkerPop
VertexProgram; HITS is the classic two-phase eigenvector program). The
engine combines messages per DESTINATION, so the snapshot carries BOTH
edge directions tagged with a per-edge ``fwd`` flag, and the two half-steps
alternate by iteration parity:

  even iteration: authority[v] = Σ hub[u]       over forward edges u→v
  odd  iteration: hub[u]       = Σ authority[v] over backward edges v→u

The phase is carried as a broadcast per-vertex state array so message()
(which only sees per-edge source state) can mask the inactive direction;
L2 normalization follows each half-step.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from titan_tpu.olap.api import DenseProgram


class HITS(DenseProgram):
    combine = "sum"

    def __init__(self, iterations: int = 20):
        # one HITS round = two engine supersteps (authority, then hub)
        self.max_iterations = 2 * iterations

    def edge_keys(self):
        return ("fwd",)

    def init(self, n, params):
        return {"hub": jnp.ones((n,), jnp.float32),
                "auth": jnp.ones((n,), jnp.float32),
                # 1.0 = even phase (authority update); broadcast scalar
                "phase": jnp.ones((n,), jnp.float32)}

    def message(self, src_state, edge_data, params):
        fwd = edge_data["fwd"].astype(jnp.float32)
        p = src_state["phase"]          # all-equal broadcast of the phase
        # even phase: only hub mass over forward edges contributes;
        # odd phase: only authority mass over backward edges
        return p * fwd * src_state["hub"] + \
            (1.0 - p) * (1.0 - fwd) * src_state["auth"]

    def apply(self, state, agg, iteration, params):
        from titan_tpu.parallel.mesh import global_sum
        even = state["phase"][0] > 0.5

        def norm(x):
            # global_sum: the L2 norm must span ALL shards when sharded
            s = jnp.sqrt(global_sum(x * x))
            return jnp.where(s > 0, x / s, x)

        nagg = norm(agg)    # computed once: one psum per superstep
        new_auth = jnp.where(even, nagg, state["auth"])
        new_hub = jnp.where(even, state["hub"], nagg)
        return {"hub": new_hub, "auth": new_auth,
                "phase": 1.0 - state["phase"]}

    def outputs(self, state, params):
        return {"hub": state["hub"], "auth": state["auth"]}


def run(computer, iterations: int = 20, snapshot=None):
    """Run on a bidirectional snapshot (forward + backward edges with the
    ``fwd`` flag). Without an explicit snapshot, the computer's directed
    snapshot is symmetrized here — ``fwd`` is a synthetic flag, never an
    edge property read from the store."""
    if snapshot is None:
        base = computer.snapshot()
        snapshot = bidirectional_snapshot(
            base.n, np.asarray(base.src), np.asarray(base.dst),
            vertex_ids=base.vertex_ids)
    return computer.run(HITS(iterations), params={}, snapshot=snapshot)


def bidirectional_snapshot(n, src, dst, vertex_ids=None):
    """Forward+backward edge list with the ``fwd`` flag HITS needs."""
    from titan_tpu.olap.tpu import snapshot as snap_mod
    src = np.asarray(src, np.int32)
    dst = np.asarray(dst, np.int32)
    s2 = np.concatenate([src, dst])
    d2 = np.concatenate([dst, src])
    fwd = np.concatenate([np.ones(len(src), np.float32),
                          np.zeros(len(dst), np.float32)])
    return snap_mod.from_arrays(n, s2, d2, vertex_ids=vertex_ids,
                                edge_values={"fwd": fwd})
