"""Single-source shortest paths (weighted) as a DenseProgram.

Parity target: the reference's ShortestDistanceVertexProgram OLAP fixture
(reference: titan-test olap/ShortestDistanceVertexProgram — Bellman-Ford
style message-minimum over weighted in-edges until stable)."""

from __future__ import annotations

import jax.numpy as jnp

from titan_tpu.olap.api import DenseMapReduce, DenseProgram

FINF = jnp.float32(3.0e38)


class MaxDistanceMapReduce(DenseMapReduce):
    """(reference: titan-test olap/ShortestDistanceMapReduce companion)
    maximum finite distance reached from the source."""

    memory_key = "shortestDistance.max"

    def compute(self, state, snapshot, params):
        d = jnp.asarray(state["dist"])
        finite = d < FINF
        return float(jnp.where(finite, d, -jnp.inf).max())


class SSSP(DenseProgram):
    combine = "min"

    def __init__(self, weight_key: str = "weight", max_iterations: int = 1000):
        self.weight_key = weight_key
        self.max_iterations = max_iterations

    def edge_keys(self):
        return (self.weight_key,)

    def init(self, n, params):
        import numpy as np
        dist = np.full((n,), float(FINF), dtype=np.float32)
        dist[int(params["source_dense"])] = 0.0
        return {"dist": jnp.asarray(dist)}

    def message(self, src_state, edge_data, params):
        w = edge_data[self.weight_key].astype(jnp.float32)
        d = src_state["dist"]
        return jnp.where(d >= FINF, FINF, d + w)

    def apply(self, state, agg, iteration, params):
        return {"dist": jnp.minimum(state["dist"], agg)}

    def done(self, state, new_state, agg, iteration, params):
        return jnp.all(new_state["dist"] == state["dist"])

    def outputs(self, state, params):
        return {"dist": state["dist"]}


def run(computer, source, weight_key: str = "weight", snapshot=None,
        max_iterations: int = 1000):
    snap = snapshot or computer.snapshot(edge_keys=(weight_key,))
    from titan_tpu.models.bfs import in_snapshot_ids
    dense = snap.dense_of(source) if in_snapshot_ids(snap, source) else int(source)
    prog = SSSP(weight_key, max_iterations)
    return computer.run(prog, params={"source_dense": dense}, snapshot=snap)
