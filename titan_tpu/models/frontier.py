"""Frontier-sparse (active-set) traversal kernels on the chunked CSR.

Generalizes the top-down machinery of ``bfs_hybrid`` to value-carrying
relaxations — the frontier-sparse analogs of the reference's OLAP
fixtures (reference: titan-test olap/ShortestDistanceVertexProgram for
SSSP, min-label propagation for connected components): instead of full
edge sweeps every superstep (O(E x rounds), the FulgoraGraphComputer
model), each round expands ONLY the vertices whose value improved since
their last EXPANSION — ``val_expanded`` records the value each vertex
last pushed, so the frontier needs no per-round state copies and a round
interrupted mid-way (slice-cap overflow) resumes exactly where it left
off.

* ``frontier_sssp`` — DELTA-STEPPING (Meyer & Sanders) over hashed edge
  weights: vertices are expanded in distance buckets of width ``delta``
  (one-sided: every improved vertex below the current bucket top is
  eligible, so stragglers never accumulate), which re-examines each
  vertex's edge list a small constant number of times instead of the
  O(rounds) full re-relaxation a plain Bellman-Ford improvement
  frontier pays on continuous weights. Weights are derived ON DEVICE by
  hashing the edge slot id (uniform in [min_w, min_w+w_range)), so a
  scale-26 run needs no second 9GB weight array; ``slot_weights_np``
  reproduces them on the host for verification.
* ``frontier_wcc`` — hybrid connected components: one
  direction-optimized BFS (models/bfs_hybrid — the most optimized
  kernel in the repo) peels off the seed vertex's ENTIRE component in
  one shot (on power-law graphs that is ~all edge mass), then min-label
  propagation runs only over the leftover components' tiny edge mass.
  A component is a closed set — no edge crosses the peeled boundary —
  so the two phases compose exactly.

All state stays on device with one small plan readback per round
(axon-tunnel D2H is ~0.01 GB/s; see PERF_NOTES.md); the graph dict is
``bfs_hybrid``'s chunked CSR (GraphSnapshot or ``graph500.to_device``
output).
"""

from __future__ import annotations

import functools

import numpy as np

from titan_tpu.models.bfs_hybrid import (build_chunked_csr,
                                         enumerate_chunk_pairs,
                                         frontier_bfs_hybrid)
from titan_tpu.models.bfs import INF, _next_pow2
from titan_tpu.utils.jitcache import dev_scalar, jit_once

FINF = np.float32(3.0e38)
IINF = np.int32(1 << 30)


def _hash_weight_expr(slot, min_w: float, w_range: float):
    """uniform [min_w, min_w + w_range) from an int32 edge slot id
    (murmur-style integer mix, reproduced by slot_weights_np)."""
    import jax.numpy as jnp

    x = slot.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    u = (x & jnp.uint32(0xFFFFFF)).astype(jnp.float32) * (1.0 / (1 << 24))
    return min_w + w_range * u


def slot_weights_np(slots: np.ndarray, min_w: float = 0.0,
                    w_range: float = 1.0) -> np.ndarray:
    x = slots.astype(np.uint32)
    x = (x ^ (x >> np.uint32(16))) * np.uint32(0x7FEB352D)
    x = (x ^ (x >> np.uint32(15))) * np.uint32(0x846CA68B)
    x = x ^ (x >> np.uint32(16))
    u = (x & np.uint32(0xFFFFFF)).astype(np.float32) / np.float32(1 << 24)
    return (min_w + w_range * u).astype(np.float32)


# per-slice chunk budget: caps the [8, p_cap] working blocks (neighbors +
# message + weight-hash temporaries, ~4 of them) at ~1GB — at scale 26
# the graph itself holds 9GB of the 16GB HBM, and unbounded pair caps
# OOMed. Rounds whose frontier mass exceeds the budget are processed as
# multiple slices planned ON DEVICE (one boundary readback per round).
# A round with more mass than SLICE_K_MAX slices simply leaves the
# overflow vertices improved-but-unexpanded; the next plan picks them
# up — the expansion-tracked frontier makes partial rounds sound.
SLICE_BUDGET_CHUNKS = 1 << 23
SLICE_K_MAX = 64
# legacy dense-window machinery (kept for pagerank_dense, where every
# vertex IS active every iteration and slot padding is the only waste)
DENSE_WINDOW = 1 << 22


def _colowner(g):
    """column -> owning vertex map (lazy, cached in the graph dict):
    lets dense sweeps read contiguous column windows with no pair
    enumeration. Pad/sink columns own the sink vertex n."""
    import jax.numpy as jnp

    co = g.get("colowner")
    if co is None:
        n = g["n"]
        q_total = g["q_total"]
        # computed on device (jnp.repeat with a static total length) —
        # reading colstart back to build it on the host would D2H 268MB
        # at scale 26
        degc = g["degc"]
        ids = jnp.arange(n + 1, dtype=jnp.int32)
        owner = jnp.repeat(ids, degc, total_repeat_length=q_total - 1)
        co = jnp.concatenate([owner, jnp.full((1,), n, jnp.int32)])
        g["colowner"] = co
    return co


def _band_plan(kind: str):
    """Round plan for EVERY scheduler mode — ONE dispatch, one
    readback, built on ``ops.compaction.banded_frontier``: membership
    mask -> compacted in-band list + per-member masses (shared-index
    double scatter: NO n-wide nonzero, NO f_cap-wide ``degc[flist]``
    re-gather — the r5 quantile plan paid both, ~1.1s/round at scale
    26) + mass-balanced segment bounds. With ``quantile_mass`` > 0
    (float32 kinds only) the band threshold is computed ON DEVICE by a
    two-level histogram so the band carries ~that much chunk mass;
    otherwise the threshold is the caller's ``bucket_end`` (the
    delta-stepping bucket top, or the +inf sentinel for the plain
    expand-everything frontier). ``f_cap`` is ONE compile bucket per
    scheduler mode (QUANT_LIST_CAP for quantile bands, full w_max for
    plain/delta so a dense round keeps one-round coverage — see
    _frontier_run); an in-band set larger than f_cap is truncated by
    the compaction, which is SOUND: unlisted vertices stay improved
    (val < val_exp) and the next round re-plans them. The
    listed-mass cumsum runs in int64 when x64 is enabled and is
    overflow-flagged otherwise (ADVICE r5 #3): stats[2] nonzero means
    the segment bounds are corrupt and the host must refuse the round.
    The list/bounds/threshold are returned ON DEVICE: push segments
    read them via pooled index scalars, so the host never ships
    per-segment values (each scalar put is a ~0.1-0.9s tunnel round
    trip)."""
    def build():
        import jax
        import jax.numpy as jnp

        from titan_tpu.ops.compaction import banded_frontier

        @functools.partial(jax.jit,
                           static_argnames=("n_", "f_cap", "k_max",
                                            "budget", "quantile_mass",
                                            "bins"))
        def bplan(val, val_exp, degc, bucket_end, n_: int, f_cap: int,
                  k_max: int, budget: int, quantile_mass: int,
                  bins: int = 512):
            hasdeg = degc[:n_] > 0
            changed = (val[:n_] < val_exp[:n_]) & hasdeg
            big_ = jnp.asarray(FINF if val.dtype == jnp.float32
                               else IINF, val.dtype)
            if quantile_mass:
                # two-level histogram threshold (the straddling bin is
                # re-histogrammed = bins^2 resolution — one 512-bin pass
                # over power-law value concentrations overshot the
                # target mass up to 10x, PERF_NOTES r5)
                vals = jnp.where(changed, val[:n_], big_)
                lo = vals.min()
                hi0 = jnp.where(changed, val[:n_], -big_).max()
                span = jnp.maximum(hi0 - lo, 1e-30)
                mass = jnp.where(changed, degc[:n_], 0)
                b = jnp.clip(((val[:n_] - lo) / span
                              * bins).astype(jnp.int32), 0, bins - 1)
                b = jnp.where(changed, b, bins - 1)
                hist = jnp.zeros((bins,), jnp.int32).at[b].add(
                    mass, mode="drop")
                cum = jnp.cumsum(hist)
                pick = jnp.minimum(jnp.searchsorted(
                    cum, jnp.int32(quantile_mass), side="left"),
                    bins - 1)
                lo2 = lo + span * pick.astype(val.dtype) / bins
                span2 = span / bins
                before = jnp.where(pick > 0,
                                   cum[jnp.maximum(pick - 1, 0)], 0)
                in2 = changed & (b == pick)
                b2 = jnp.clip(((val[:n_] - lo2) / span2
                               * bins).astype(jnp.int32), 0, bins - 1)
                hist2 = jnp.zeros((bins,), jnp.int32).at[
                    jnp.where(in2, b2, bins - 1)].add(
                    jnp.where(in2, degc[:n_], 0), mode="drop")
                cum2 = jnp.cumsum(hist2)
                pick2 = jnp.minimum(jnp.searchsorted(
                    cum2, jnp.int32(quantile_mass) - before,
                    side="left"), bins - 1)
                thr = lo2 + span2 * (pick2 + 1).astype(val.dtype) / bins
                thr = jnp.maximum(thr, jnp.nextafter(lo, big_))
            else:
                thr = jnp.asarray(bucket_end, val.dtype)

            inb = changed & (val[:n_] < thr)
            # degc is passed RAW as the mass payload — the compaction
            # only lands masked entries, so no where() pre-mask needed
            nf, m8, overflow, flist, lb = banded_frontier(
                inb, degc[:n_], f_cap, k_max, budget, n_)
            # pending = improved vertices parked above the threshold;
            # their minimum tells the host where the next bucket starts
            pending = changed & ~inb
            pmin = jnp.min(jnp.where(pending, val[:n_], big_))
            stats = jnp.concatenate(
                [jnp.stack([nf, m8, overflow]),
                 jax.lax.bitcast_convert_type(pmin, jnp.int32)[None]
                 if val.dtype == jnp.float32 else pmin[None]])
            return stats, flist, lb, jnp.asarray(thr, val.dtype)
        return bplan
    return jit_once(f"frontier_bandplan_{kind}", build)


# fixed in-band list width for the merged band plan (one compile
# bucket; truncation is sound — see _band_plan)
QUANT_LIST_CAP = 1 << 23


def _push_list(kind: str):
    """Push one mass-balanced SEGMENT of the round's compacted in-band
    list (every mode — quantile band, delta bucket, or the plain
    improved-set frontier; the threshold device scalar encodes the
    difference). Membership is rechecked live (an earlier segment may
    have improved a member further — it pushes its current value); a
    vertex appears in exactly one segment and segment mass is fixed by
    the plan, so p_cap = pow2(segment mass) never defers."""
    def build():
        import jax
        import jax.numpy as jnp

        from titan_tpu.models.bfs_hybrid import _bit_of

        @functools.partial(jax.jit,
                           static_argnames=("f_cap", "p_cap", "n_",
                                            "masked"),
                           donate_argnums=(0, 1))
        def pushl(val, val_exp, flist, lbounds, i, thr, dstT, colstart,
                  degc, wparams, tbits, f_cap: int, p_cap: int,
                  n_: int, masked: bool = False):
            p0 = lbounds[i]
            p1 = lbounds[i + 1]
            L = flist.shape[0]
            s0 = jnp.clip(p0, 0, max(L - f_cap, 0))
            pos = s0 + jnp.arange(f_cap, dtype=jnp.int32)
            seg = jax.lax.dynamic_slice(flist, (s0,), (f_cap,))
            v = jnp.minimum(seg, n_)
            member = (pos >= p0) & (pos < p1) & (seg < n_) \
                & (val[v] < val_exp[v]) & (val[v] < thr)
            valv = val[v]
            counts = jnp.where(member, degc[v], 0).astype(jnp.int32)
            # a segment's true mass can exceed the plan target by one
            # straddling vertex; only members whose WHOLE chunk range
            # fits p_cap are marked expanded — the rest stay improved
            # and the next round re-plans them (same contract as the
            # vertex-range push)
            ends = jnp.cumsum(counts)
            fits = member & (ends <= p_cap)
            val_exp = val_exp.at[jnp.where(fits, v, n_ + 1)].set(
                valv, mode="drop")
            cols, _, owner = enumerate_chunk_pairs(
                fits, counts, colstart[v], p_cap, dstT.shape[1] - 1,
                with_owner=True)
            src_val = valv[owner]
            nbr = jnp.take(dstT, cols, axis=1)
            lane = jnp.arange(8, dtype=jnp.int32)[:, None]
            slot = cols[None, :] * 8 + lane
            if masked:
                # live-overlay tombstones (olap/live): a dead base slot
                # relaxes nothing — its lane scatters to the drop pad
                nbr = jnp.where(_bit_of(tbits, slot), n_ + 1, nbr)
            if kind == "sssp":
                w = _hash_weight_expr(slot, wparams[0], wparams[1])
                msg = src_val[None, :] + w
            else:
                msg = jnp.broadcast_to(src_val[None, :], nbr.shape)
            return val.at[nbr].min(msg, mode="drop"), val_exp
        return pushl
    return jit_once(f"frontier_pushlist_{kind}", build)


def _overlay_relax(kind: str):
    """Relax every LIVE overlay add-edge with the sources' current
    values — the delta-COO push pass of the live plane's expansion seam
    (olap/live). SSSP/WCC are monotone min-fixpoint computations, so
    extra relaxations are always sound; ``_frontier_run`` calls this
    after each round's base pushes (one overlay hop per round) and on
    empty plans, where the returned improvement count decides whether
    overlay-only progress keeps the loop alive. Overlay edges hash
    their weights from slots past the base layout (``slot_base + i``,
    stable under append; a compaction re-slots them with the rebuilt
    CSR — docs/live.md)."""
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit,
                           static_argnames=("cap", "n_"),
                           donate_argnums=(0,))
        def relax(val, ov_src, ov_dst, wparams, slot_base, cap: int,
                  n_: int):
            s = jnp.minimum(ov_src, n_)    # pad (n+1) reads val[n]=inf
            src_val = val[s]
            if kind == "sssp":
                slot = slot_base + jnp.arange(cap, dtype=jnp.int32)
                w = _hash_weight_expr(slot, wparams[0], wparams[1])
                msg = src_val + w
            else:
                msg = src_val
            # improvement detected PRE-scatter (lane-wise msg vs current
            # target value): no read of the donated buffer after the
            # update, and >0 iff the scatter changes anything
            nimp = (msg < val[jnp.minimum(ov_dst, n_)]) \
                .sum(dtype=jnp.int32)
            new = val.at[ov_dst].min(msg, mode="drop")
            return new, nimp
        return relax
    return jit_once(f"frontier_overlay_relax_{kind}", build)


def _quantize_cap(mass: int, p_full: int) -> int:
    """Round a slice's kernel width up to the next power of FOUR
    (capped at p_full). Mass-exact pow2 caps created a distinct compile
    per bucket — and compiles do NOT persist across processes under the
    remote-compile backend (~8-20s each through the tunnel), so a cold
    22-round SSSP paid more compile than compute. Power-of-four rounding
    halves the bucket count for at most 2x dead lanes on the SMALL
    slices (full budget-sized slices hit p_full either way)."""
    c = _next_pow2(max(mass, 2))
    if (c.bit_length() - 1) % 2:
        c <<= 1
    return min(c, p_full)


def _max_degc(g) -> int:
    got = g.get("_max_degc")
    if got is None:
        got = int(np.asarray(g["degc"].max()))
        g["_max_degc"] = got
    return got


# default per-round band mass (chunks) for quantile-batched SSSP — the
# measured r5 winner and the DEFAULT mode: scale-26 warm, same chip-day:
# plain 247s / 1118M chunks vs quantile-2^24 121-130s / 394M chunks
# (after the r5 fixes: two-level threshold so one histogram bin cannot
# swallow 10x the target mass, pow-4 f_cap buckets so band sizes stop
# compiling fresh kernels, and the merged single-dispatch _quant_plan).
# Band-size sweep: 2^23 = 45 rounds (per-round floors dominate), 2^24 =
# 31 rounds/394M, 2^25 = 30/518M, 2^26 = 28/716M — rounds are
# WAVE-limited below 2^24, re-expansion grows above it.
QUANTILE_MASS_DEFAULT = 1 << 24


class RoundInterrupted(Exception):
    """Raised out of ``_frontier_run`` when the caller's ``on_round``
    callback vetoes continuing — the serving layer's cancellation /
    timeout path for single-execution SSSP/WCC jobs (olap/serving
    drops the job at a round boundary instead of abandoning the whole
    process; the device state simply stops being advanced)."""

    def __init__(self, rounds: int):
        super().__init__(f"interrupted after {rounds} rounds")
        self.rounds = rounds


def _frontier_run(snap_or_graph, val, val_exp, kind: str, wparams,
                  max_rounds: int, delta: float | None = None,
                  quantile_mass: int = 0, on_round=None,
                  checkpoint=None, start_rounds: int = 0,
                  bucket_end0: float | None = None, overlay=None):
    """Expansion-tracked round loop: one plan readback per round
    (_band_plan — compacted in-band list + mass-balanced segment
    bounds, no n-wide nonzero), then one _push_list dispatch per
    ~budget chunks of listed mass. With ``delta``, rounds expand only
    the current distance bucket (one-sided) and the bucket advances to
    the minimum pending value when it drains — delta-stepping. With
    ``quantile_mass``, each round's threshold is computed ON DEVICE so
    the expanded band carries ~that much chunk mass — priority-batched
    expansion in near-sorted value order. Without either, every
    improved vertex is in-band every round (threshold = the +inf
    sentinel).

    Checkpoint plane (olap/recovery): ``checkpoint(rounds, state)`` is
    called at every round boundary (after the on_round veto) with the
    COMPLETE loop state — ``{"val", "val_exp", "bucket_end",
    "quantile_mass"}`` — and owns its own cadence; a run restarted
    with that state via ``start_rounds`` / ``bucket_end0`` /
    ``quantile_mass`` continues the exact trajectory (the pushes are
    min-scatters, order-independent and exact, so the final arrays are
    bit-equal to an uninterrupted run even if kernel-width choices
    differ after resume)."""
    import time as _time

    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    dstT, colstart, degc = g["dstT"], g["colstart"], g["degc"]
    plan = _band_plan(kind)
    pushl = _push_list(kind)
    # live-overlay expansion seam (olap/live): tombstoned base slots
    # are masked out of every push; overlay add-edges relax after each
    # round's pushes (and on empty plans, where overlay-only progress
    # keeps the loop alive — see the nf == 0 branch)
    ov = overlay
    if ov is None and not isinstance(snap_or_graph, dict):
        ov = getattr(snap_or_graph, "_live_overlay", None)
    if ov is not None and ov.empty:
        ov = None
    masked = ov is not None and ov.tomb_count > 0
    has_adds = ov is not None and ov.count > 0
    relax = _overlay_relax(kind) if has_adds else None
    max_dc = _max_degc(g)
    is_f32 = val.dtype == jnp.float32
    big = float(FINF) if is_f32 else int(IINF)
    # the in-band list never usefully exceeds the vertex count: cap its
    # width at the largest power of two that fits the state arrays
    w_max = 1 << ((n + 1).bit_length() - 1)
    # a segment carries up to budget + max_dc chunks (one vertex of
    # overshoot), so budget == 2^k would push p_cap to 2^(k+1) and HALF
    # of every big segment's lanes would be padding — shave max_dc off
    # the budget so full segments fit a 2^k kernel exactly (measured
    # 2026-07-31: scale-26 SSSP round cost is dominated by these lanes)
    target = _next_pow2(max(SLICE_BUDGET_CHUNKS, 2))
    if max_dc <= target // 2:
        budget = target - max_dc
        p_full = target
    else:                       # degenerate hub: conservative old scheme
        budget = SLICE_BUDGET_CHUNKS
        p_full = _next_pow2(max(budget + max_dc, 2))

    wp = jnp.asarray(np.asarray(wparams, np.float32))
    tbits = ov.tomb_dev if masked else jnp.zeros((1,), jnp.uint8)

    def _relax(v):
        return relax(v, ov.src_dev, ov.dst_dev, wp,
                     dev_scalar(ov.slot_base), cap=ov.cap, n_=n)

    if has_adds and start_rounds == 0 and bucket_end0 is None:
        # fresh start: seed the overlay's one-hop reach of the initial
        # values (a source with ONLY overlay edges would otherwise
        # terminate on its first empty plan)
        val, _ = _relax(val)
    # the quantile threshold math in _band_plan is float32-only (span
    # floor 1e-30, jnp.nextafter on lo); int-valued kinds (e.g. WCC
    # labels) would trace-error or mis-threshold — fall back to the
    # plain improved-set frontier for them
    if quantile_mass and not is_f32:
        quantile_mass = 0
    bucket_end = big if not delta or delta <= 0 else delta
    if bucket_end0 is not None:         # resume: restored bucket state
        bucket_end = bucket_end0
    trace = g.get("_trace_rounds")      # optional perf instrumentation:
    rounds = int(start_rounds)          # set g["_trace_rounds"] = [] to
    dtname = "float32" if is_f32 else "int32"
    prev_sig = None                     # collect per-round 5-tuples
    # plan-cost isolation drain: opt-in SEPARATELY from the trace — it
    # buys exact per-round plan numbers at one extra host round trip
    # per round (0.1-0.9s each through the tunnel), which the plain
    # mass-accounting trace consumers must not pay
    drain = trace is not None and g.get("_trace_plan_drain")
    while rounds < max_rounds:
        # serving-layer veto (cancellation/timeout) at the round
        # boundary — same per-job early-exit discipline as the batched
        # BFS level mask, for the single-execution kinds
        if on_round is not None and not on_round(rounds):
            raise RoundInterrupted(rounds)
        # checkpoint capture at the same boundary: the callback owns
        # cadence and readback; (val, val_exp) here is a CONSISTENT
        # state — every push of earlier rounds has landed, none of this
        # round's has started
        if checkpoint is not None:
            checkpoint(rounds, {"val": val, "val_exp": val_exp,
                                "bucket_end": bucket_end,
                                "quantile_mass": quantile_mass})
        # list width: quantile mode caps at QUANT_LIST_CAP (the band
        # carries ~quantile_mass chunks, so members are bounded and
        # truncation only defers); plain/delta modes must cover EVERY
        # improved vertex in one round when possible (a dense WCC round
        # lists up to n members — capping it at 2^23 would multiply
        # round count by n/2^23, each paying the plan sync), so they
        # list at full w_max width — per-round coverage is then bounded
        # by nseg exactly like the r5 vertex-range path (64 x budget
        # chunks). Computed per round: a quantile->plain escalation
        # flips it (one extra plan compile, rare fp corner).
        qf_cap = min(QUANT_LIST_CAP, w_max) if quantile_mass else w_max
        if drain:
            # drain the queued pushes first so the plan sync below
            # measures the plan alone, not their completion
            val.block_until_ready()
        t_plan = _time.time()
        be_dev = dev_scalar(bucket_end, dtname)
        stats, flist, lbounds, thr_dev = plan(
            val, val_exp, degc, be_dev, n_=n, f_cap=qf_cap,
            k_max=SLICE_K_MAX, budget=budget,
            quantile_mass=quantile_mass)
        st_h = np.asarray(stats)           # ONE sync per round
        plan_s = _time.time() - t_plan
        nf, m8 = int(st_h[0]), int(st_h[1])
        if int(st_h[2]):
            raise RuntimeError(
                "banded_frontier: listed chunk mass overflowed int32 — "
                "segment bounds are corrupt (enable JAX x64 or shard "
                "the graph below 2^31 chunks)")
        pmin = st_h[3:4].view(np.float32)[0] if is_f32 else st_h[3]
        if trace is not None:
            trace.append((0.0 if quantile_mass else float(bucket_end),
                          nf, m8, _time.time(), plan_s))
        if nf == 0 or m8 == 0:
            if has_adds:
                # the base plan is dry: only overlay edges can make
                # progress (e.g. chains through vertices with no base
                # edges). One relax per round; terminate only when it
                # improves nothing — then base+overlay are at the
                # fixpoint together.
                val, nimp = _relax(val)
                if int(np.asarray(nimp)) > 0:
                    rounds += 1
                    continue
            if float(pmin) >= big * (1 - 1e-6):
                return val[:n], rounds     # no pending work anywhere
            if quantile_mass:
                # the device threshold always includes the minimum
                # value, so an empty round with pending work cannot
                # recur — guard fp corner-cases by escalating to the
                # direct-threshold (expand-everything) mode
                quantile_mass = 0
                continue
            if delta and delta > 0:
                # bucket drained: advance to the minimum pending
                # value's bucket (strictly increases — pmin >= current
                # bucket_end)
                bucket_end = float((np.floor(float(pmin) / delta) + 1)
                                   * delta)
                continue
            # plain mode admits every improved vertex: pending work
            # with an empty band means corrupt state — fail loudly
            # rather than spin
            raise RuntimeError(
                f"frontier_{kind}: empty round with pending work "
                f"(pmin={pmin!r}) in plain mode")
        # a round that changed NOTHING means every listed member was
        # deferred (pathological packing) — escalate to full-size
        # kernels for one round
        sig = (nf, m8, float(pmin), float(bucket_end), quantile_mass)
        escalate = sig == prev_sig
        prev_sig = sig
        nseg = min(-(-m8 // budget), SLICE_K_MAX)
        # f bucket quantized to powers of FOUR: per-nf pow2 buckets
        # compiled a fresh kernel per distinct band size (measured
        # scale 26: seven one-call pushlist compiles at ~17s each
        # through the remote-compile tunnel — more compile than
        # push). A segment holds at most ~budget vertices.
        f_bucket = _quantize_cap(min(nf, budget + max_dc), qf_cap)
        for k in range(nseg):
            # +max_dc headroom: a vertex straddling the mass target
            # lands wholly in one segment (full segments then size
            # to exactly p_full — the budget is pre-shaved by
            # max_dc, see above)
            mass_k = min(budget, m8 - k * budget) + max_dc
            p_cap = p_full if escalate else _quantize_cap(mass_k, p_full)
            fk = min(qf_cap, p_full) if escalate \
                else min(f_bucket, p_cap)
            val, val_exp = pushl(
                val, val_exp, flist, lbounds, dev_scalar(k),
                thr_dev, dstT, colstart, degc, wp, tbits,
                f_cap=fk, p_cap=p_cap, n_=n, masked=masked)
        if has_adds:
            # one overlay hop per round, tracking the base expansion
            val, _ = _relax(val)
        rounds += 1
    return val[:n], rounds


class _CohortMember:
    """Host-side loop state for ONE member of a fused frontier cohort
    (``frontier_sssp_batched`` / ``frontier_wcc_batched``): its own
    device value arrays plus the scheduler-mode knobs the sequential
    ``_frontier_run`` keeps in locals — so every per-round decision the
    cohort driver makes for this member is computed from exactly the
    state the member's solo run would have had."""

    __slots__ = ("k", "val", "val_exp", "bucket_end", "quantile_mass",
                 "prev_sig", "rounds", "out", "stopped")

    def __init__(self, k: int, val, val_exp, bucket_end, quantile_mass):
        self.k = k
        self.val = val
        self.val_exp = val_exp
        self.bucket_end = bucket_end
        self.quantile_mass = int(quantile_mass)
        self.prev_sig = None
        self.rounds = 0
        self.out = None        # device [n] result once terminated
        self.stopped = None    # on_round veto: the vetoed round number


def _frontier_cohort(g, members, kind: str, wparams, max_rounds: int,
                     delta: float = 0.0, on_round=None, checkpoint=None,
                     overlay=None) -> None:
    """Shared round loop over K per-member ``(val, val_exp)`` states —
    the cohort generalization of ``_frontier_run``. Each round
    dispatches every active member's band plan (the member's OWN static
    args, so the SAME jit entries as a solo run) and reads all K stats
    vectors back in ONE stacked host sync — the per-round plan-readback
    floor (PERF_NOTES: 0.1-0.9s D2H through the tunnel) is paid once
    per cohort round instead of once per member.

    Bit-equality contract: every per-member decision — threshold mode,
    segment count, kernel-width buckets, quantile->plain escalation,
    delta bucket advance, repeated-signature escalation, termination —
    is computed from that member's own stats with the sequential code's
    exact expressions, and the pushes are order-independent min-
    scatters, so each member's final arrays AND round count are
    bit-equal to its solo ``_frontier_run``. Mode transitions that
    re-plan without advancing the round (the sequential ``continue``
    branches) are serviced solo for that member — an extra sync on the
    rare transition round, never on the steady state.

    ``on_round(k, rounds)`` / ``checkpoint(k, rounds, state)`` are the
    per-member forms of the sequential hooks (same boundary ordering:
    veto, then checkpoint, then the plan); a vetoed member records
    ``stopped`` and simply leaves the cohort — the analog of
    ``RoundInterrupted`` that cannot abandon its K-1 batchmates.
    Fresh-start cohorts only: resumed jobs run solo through
    ``frontier_sssp``/``frontier_wcc`` (their round counter differs
    from any fresh batchmate — the same split the batched BFS makes)."""
    import jax.numpy as jnp

    n = g["n"]
    dstT, colstart, degc = g["dstT"], g["colstart"], g["degc"]
    plan = _band_plan(kind)
    pushl = _push_list(kind)
    ov = overlay
    if ov is not None and ov.empty:
        ov = None
    masked = ov is not None and ov.tomb_count > 0
    has_adds = ov is not None and ov.count > 0
    relax = _overlay_relax(kind) if has_adds else None
    max_dc = _max_degc(g)
    is_f32 = members[0].val.dtype == jnp.float32
    big = float(FINF) if is_f32 else int(IINF)
    dtname = "float32" if is_f32 else "int32"
    w_max = 1 << ((n + 1).bit_length() - 1)
    target = _next_pow2(max(SLICE_BUDGET_CHUNKS, 2))
    if max_dc <= target // 2:
        budget = target - max_dc
        p_full = target
    else:
        budget = SLICE_BUDGET_CHUNKS
        p_full = _next_pow2(max(budget + max_dc, 2))
    wp = jnp.asarray(np.asarray(wparams, np.float32))
    tbits = ov.tomb_dev if masked else jnp.zeros((1,), jnp.uint8)

    def _relax(v):
        return relax(v, ov.src_dev, ov.dst_dev, wp,
                     dev_scalar(ov.slot_base), cap=ov.cap, n_=n)

    if has_adds:
        # fresh start: seed the overlay's one-hop reach per member
        # (cohorts are fresh-only — see the docstring)
        for m in members:
            m.val, _ = _relax(m.val)

    def _boundary(m) -> bool:
        """Round-boundary hooks in the sequential order (veto first,
        then checkpoint); False = the member was vetoed out."""
        if on_round is not None and not on_round(m.k, m.rounds):
            m.stopped = m.rounds
            return False
        if checkpoint is not None:
            checkpoint(m.k, m.rounds,
                       {"val": m.val, "val_exp": m.val_exp,
                        "bucket_end": m.bucket_end,
                        "quantile_mass": m.quantile_mass})
        return True

    def _dispatch(m):
        qf_cap = min(QUANT_LIST_CAP, w_max) if m.quantile_mass else w_max
        be_dev = dev_scalar(m.bucket_end, dtname)
        stats, flist, lbounds, thr_dev = plan(
            m.val, m.val_exp, degc, be_dev, n_=n, f_cap=qf_cap,
            k_max=SLICE_K_MAX, budget=budget,
            quantile_mass=m.quantile_mass)
        return qf_cap, stats, flist, lbounds, thr_dev

    def _host_step(m, st_h, qf_cap, flist, lbounds, thr_dev) -> str:
        """One member's host-side round logic over its synced stats —
        'done' | 'advanced' | 'replan' (the sequential ``continue``)."""
        nf, m8 = int(st_h[0]), int(st_h[1])
        if int(st_h[2]):
            raise RuntimeError(
                "banded_frontier: listed chunk mass overflowed int32 — "
                "segment bounds are corrupt (enable JAX x64 or shard "
                "the graph below 2^31 chunks)")
        pmin = st_h[3:4].view(np.float32)[0] if is_f32 else st_h[3]
        if nf == 0 or m8 == 0:
            if has_adds:
                m.val, nimp = _relax(m.val)
                if int(np.asarray(nimp)) > 0:
                    m.rounds += 1
                    return "advanced"
            if float(pmin) >= big * (1 - 1e-6):
                m.out = m.val[:n]
                return "done"
            if m.quantile_mass:
                m.quantile_mass = 0
                return "replan"
            if delta and delta > 0:
                m.bucket_end = float(
                    (np.floor(float(pmin) / delta) + 1) * delta)
                return "replan"
            raise RuntimeError(
                f"frontier_{kind}: empty round with pending work "
                f"(pmin={pmin!r}) in plain mode")
        sig = (nf, m8, float(pmin), float(m.bucket_end), m.quantile_mass)
        escalate = sig == m.prev_sig
        m.prev_sig = sig
        nseg = min(-(-m8 // budget), SLICE_K_MAX)
        f_bucket = _quantize_cap(min(nf, budget + max_dc), qf_cap)
        for k in range(nseg):
            mass_k = min(budget, m8 - k * budget) + max_dc
            p_cap = p_full if escalate else _quantize_cap(mass_k, p_full)
            fk = min(qf_cap, p_full) if escalate \
                else min(f_bucket, p_cap)
            m.val, m.val_exp = pushl(
                m.val, m.val_exp, flist, lbounds, dev_scalar(k),
                thr_dev, dstT, colstart, degc, wp, tbits,
                f_cap=fk, p_cap=p_cap, n_=n, masked=masked)
        if has_adds:
            m.val, _ = _relax(m.val)
        m.rounds += 1
        return "advanced"

    def _solo(m) -> None:
        """Drain a member's re-plan rounds alone (its mode knobs just
        changed; the cohort's shared sync has already happened)."""
        while m.out is None and m.stopped is None \
                and m.rounds < max_rounds:
            if not _boundary(m):
                return
            qf_cap, stats, flist, lbounds, thr_dev = _dispatch(m)
            st_h = np.asarray(stats)
            if _host_step(m, st_h, qf_cap, flist, lbounds,
                          thr_dev) != "replan":
                return
        if m.out is None and m.stopped is None:
            m.out = m.val[:n]            # max_rounds exhausted

    active = list(members)
    while True:
        for m in active:
            if m.rounds >= max_rounds and m.out is None \
                    and m.stopped is None:
                m.out = m.val[:n]
        active = [m for m in active
                  if m.out is None and m.stopped is None]
        if not active:
            return
        ready = []
        for m in active:
            if _boundary(m):
                ready.append((m, _dispatch(m)))
        if not ready:
            continue
        # THE amortization: K members' round plans in one stacked sync
        st_all = np.asarray(jnp.stack([d[1] for _m, d in ready]))
        replans = []
        for (m, (qf_cap, _stats, flist, lbounds, thr_dev)), st_h \
                in zip(ready, st_all):
            if _host_step(m, st_h, qf_cap, flist, lbounds,
                          thr_dev) == "replan":
                replans.append(m)
        for m in replans:
            _solo(m)


def frontier_sssp_batched(snap_or_graph, sources, min_w: float = 0.0,
                          w_range: float = 1.0, max_rounds: int = 10_000,
                          delta: float | None = None,
                          quantile_mass: int | None = None,
                          on_round=None, checkpoint=None,
                          return_device: bool = False, overlay=None):
    """K-source SSSP cohort over one shared round loop
    (``_frontier_cohort``): per-member device state, ONE stacked plan
    readback per round. Each member's distances and round count are
    bit-equal to ``frontier_sssp(source=sources[k])`` with the same
    knobs — the mode knobs (``delta``/``quantile_mass``/``max_rounds``)
    are cohort-wide, which is why the serving batch key pins them.

    ``on_round(k, rounds)``: per-member veto — a False drops member
    ``k`` from the cohort (``stopped[k]`` records the round) without
    touching its batchmates. ``checkpoint(k, rounds, state)``: the
    sequential state dict per member. Returns ``(dists, rounds,
    stopped)`` lists of length K; a vetoed member's dist is None."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    if delta is None:
        delta = 0.0
    if quantile_mass is None:
        quantile_mass = 0 if delta and delta > 0 \
            else QUANTILE_MASS_DEFAULT
    if overlay is None and not isinstance(snap_or_graph, dict):
        overlay = getattr(snap_or_graph, "_live_overlay", None)
    bucket0 = float(FINF) if not delta or delta <= 0 else float(delta)
    members = []
    for k, s in enumerate(sources):
        val = jnp.full((n + 1,), FINF, jnp.float32) \
            .at[int(s)].set(0.0)
        val_exp = jnp.full((n + 1,), FINF, jnp.float32)
        members.append(_CohortMember(k, val, val_exp, bucket0,
                                     int(quantile_mass)))
    _frontier_cohort(g, members, "sssp", (min_w, w_range), max_rounds,
                     delta=float(delta), on_round=on_round,
                     checkpoint=checkpoint, overlay=overlay)
    outs = [m.out if return_device or m.out is None
            else np.asarray(m.out) for m in members]
    return outs, [m.rounds for m in members], \
        [m.stopped for m in members]


def frontier_wcc_batched(snap_or_graph, count: int,
                         max_rounds: int = 10_000, on_round=None,
                         checkpoint=None, return_device: bool = False,
                         overlay=None):
    """K-member WCC cohort. WCC has no per-job source, so the BFS peel
    and seed labels are computed ONCE and copied per member; members
    then differ only in their serving-layer hooks (per-job veto,
    checkpoint cadence, fault injection) while sharing the round loop's
    single stacked plan sync. Each member's labels and round count are
    bit-equal to a solo ``frontier_wcc``. ``checkpoint(k, rounds,
    state)`` states carry ``levels`` like the sequential form. Returns
    ``(labels, rounds, stopped)`` with rounds including the shared BFS
    peel's level count."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    if overlay is None and not isinstance(snap_or_graph, dict):
        overlay = getattr(snap_or_graph, "_live_overlay", None)
    if overlay is not None and overlay.empty:
        overlay = None
    n = g["n"]
    if n == 0:
        z = jnp.zeros((0,), jnp.int32)
        out = z if return_device else np.asarray(z)
        return [out] * count, [0] * count, [None] * count
    if overlay is not None:
        # no BFS peel over a live overlay (same fallback as the
        # sequential path): pure min-label propagation from own ids
        ids = jnp.arange(n, dtype=jnp.int32)
        val0 = jnp.concatenate([ids, jnp.full((1,), IINF, jnp.int32)])
        exp0 = jnp.concatenate(
            [ids + 1, jnp.full((1,), IINF, jnp.int32)])
        levels = 0
    else:
        seed_v = int(np.asarray(jnp.argmax(g["deg"][:n])))
        dist, levels = frontier_bfs_hybrid(g, seed_v, max_levels=n,
                                           return_device=True)
        val0, exp0 = _wcc_seed_labels()(dist, n_=n)
    ck = None
    if checkpoint is not None:
        def ck(k, rounds, state, _levels=levels):
            state = dict(state)
            state["levels"] = _levels
            checkpoint(k, rounds, state)
    # per-member COPIES: _push_list donates its value buffers, so two
    # members must never alias one device array
    members = [_CohortMember(k, jnp.array(val0, copy=True),
                             jnp.array(exp0, copy=True),
                             int(IINF), 0)
               for k in range(count)]
    _frontier_cohort(g, members, "wcc", (0.0, 0.0), max_rounds,
                     on_round=on_round, checkpoint=ck, overlay=overlay)
    outs = [m.out if return_device or m.out is None
            else np.asarray(m.out) for m in members]
    return outs, [m.rounds + levels for m in members], \
        [m.stopped for m in members]


def frontier_sssp(snap_or_graph, source_dense: int, min_w: float = 0.0,
                  w_range: float = 1.0, max_rounds: int = 10_000,
                  delta: float | None = None,
                  quantile_mass: int | None = None,
                  return_device: bool = False, on_round=None,
                  checkpoint=None, resume: dict | None = None,
                  overlay=None):
    """SSSP over hashed edge weights with an expansion-tracked frontier;
    ``delta`` > 0 adds delta-stepping buckets. Returns (dist float32 [n]
    with FINF unreachable, rounds).

    ``checkpoint(rounds, state)``: round-boundary state capture (see
    ``_frontier_run``). ``resume``: a dict with ``val``/``val_exp``
    ([n+1] float32), ``rounds``, ``bucket_end`` and ``quantile_mass``
    from a prior checkpoint — the run continues that trajectory and
    its final distances are bit-equal to an uninterrupted run.

    Default is NO buckets: on hub-dominated power-law graphs the
    shortest-path distances concentrate in a band narrower than any
    useful bucket width (measured scale-26 R-MAT: ~all mass lands in
    one bucket at delta=1/4 through 1/32, total relaxation mass floors
    at ~3.2x E/8 regardless), so buckets only add rounds — scale-26 on
    v5e: delta=0 270s/26 rounds vs delta=0.125 300s/64 rounds. On
    graphs with spread distance distributions (road networks, uniform
    meshes) pass delta ~ mean edge weight."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    if delta is None:
        delta = 0.0
    if quantile_mass is None:
        # default: priority-batched expansion at the measured-optimal
        # band mass (see QUANTILE_MASS_DEFAULT — 2x faster than the
        # plain improved-set frontier at scale 26). Pass 0 for the
        # plain expand-everything frontier, or delta>0 for
        # delta-stepping buckets (spread distance distributions).
        quantile_mass = 0 if delta and delta > 0 \
            else QUANTILE_MASS_DEFAULT
    start_rounds, bucket_end0 = 0, None
    if resume is not None:
        # restored checkpoint state overrides the fresh-start init AND
        # the mode knobs that may have mutated mid-run (quantile
        # escalation, delta bucket advance)
        val = jnp.asarray(resume["val"], jnp.float32)
        val_exp = jnp.asarray(resume["val_exp"], jnp.float32)
        start_rounds = int(resume["rounds"])
        bucket_end0 = float(resume["bucket_end"])
        quantile_mass = int(resume["quantile_mass"])
    else:
        val = jnp.full((n + 1,), FINF, jnp.float32) \
            .at[source_dense].set(0.0)
        # nothing has pushed yet: only the source reads as improved
        # (val < val_exp); unreached sit at val == val_exp == FINF
        val_exp = jnp.full((n + 1,), FINF, jnp.float32)
    if overlay is None and not isinstance(snap_or_graph, dict):
        overlay = getattr(snap_or_graph, "_live_overlay", None)
    out, rounds = _frontier_run(g, val, val_exp, "sssp",
                                (min_w, w_range), max_rounds,
                                delta=delta, quantile_mass=quantile_mass,
                                on_round=on_round, checkpoint=checkpoint,
                                start_rounds=start_rounds,
                                bucket_end0=bucket_end0, overlay=overlay)
    if not return_device:
        out = np.asarray(out)
    return out, rounds


def _wcc_seed_labels():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_",))
        def seed(dist, n_: int):
            """Label arrays from a finished BFS: the reached component
            collapses to its minimum vertex id (already expanded — a
            closed component never pushes again); the rest start at
            their own id, improved-state so round 1 expands them."""
            ids = jnp.arange(n_, dtype=jnp.int32)
            reached = dist[:n_] < INF
            rmin = jnp.min(jnp.where(reached, ids, IINF))
            lab = jnp.where(reached, rmin, ids)
            val = jnp.concatenate([lab, jnp.full((1,), IINF, jnp.int32)])
            exp = jnp.concatenate(
                [jnp.where(reached, lab, lab + 1),
                 jnp.full((1,), IINF, jnp.int32)])
            return val, exp
        return seed
    return jit_once("wcc_seed_labels", build)


def pagerank_dense(snap_or_graph, iterations: int = 20,
                   damping: float = 0.85, tol: float | None = None,
                   return_device: bool = False, on_round=None,
                   checkpoint=None, resume: dict | None = None,
                   overlay=None, reset=None):
    """Push-mode PageRank over the chunked CSR via dense window sweeps:
    rank' = (1-d)/n + d * sum over in-edges of rank[src]/outdeg[src]
    (semantics match the pull-mode engine program in models/pagerank.py,
    incl. leaking dangling mass). Returns (rank float32 [n], iterations
    run). ``tol``: early exit when the L1 delta falls below it.
    ``on_round``: per-iteration veto (RoundInterrupted) — the serving
    layer's cancellation/timeout hook, same contract as
    ``_frontier_run``.

    ``checkpoint(it, {"rank": rank})``: called after each completed
    iteration ``it`` (rank [n+1] device). ``resume``: ``{"rank", "it"}``
    — continue from iteration ``it``; ``contrib`` is a pure elementwise
    function of rank (same IEEE expressions as the in-loop recompute),
    so the continuation is bit-equal to an uninterrupted run.

    ``reset`` ([n] float, sums to 1): PERSONALIZED PageRank — the
    teleport distribution becomes ``(1-d) * reset`` (a one-hot row =
    one user's random walk with restart) and the initial rank IS the
    reset vector. ``None`` keeps the uniform formulation above,
    bit-identical to the pre-personalization kernel (it runs the same
    jit cache entries). This is the sequential oracle
    ``models/pagerank.pagerank_personalized_batched`` is pinned
    bit-equal to, per source row."""
    import jax.numpy as jnp

    # an explicitly passed view (the serving lease's, frozen at the
    # job's epoch) overrides the snapshot's latest attached view — the
    # scheduler compacts before leasing for this kind, so its view is
    # empty even when later deltas already re-dirtied the plane
    ov = overlay
    if ov is None and not isinstance(snap_or_graph, dict):
        ov = getattr(snap_or_graph, "_live_overlay", None)
    if ov is not None and not ov.empty:
        # dense sweeps read contiguous base-CSR column windows — there
        # is no per-edge seam to mask tombstones or inject adds. The
        # documented fallback: fold the overlay first (the serving
        # scheduler does this for 'pagerank'/'dense' kinds).
        raise RuntimeError(
            "pagerank_dense on a live overlay: compact the overlay "
            "first (LiveGraphPlane.compact_if_dirty) — dense window "
            "sweeps have no overlay seam")
    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    dstT = g["dstT"]
    deg = g["deg"].astype(jnp.float32)
    colowner = _colowner(g)
    total = g["q_total"]
    W = min(DENSE_WINDOW, total)
    win = _pr_window()
    reset_dev = None
    if reset is not None:
        r = jnp.asarray(reset, jnp.float32)
        if r.shape != (n,):
            raise ValueError(f"reset must be [n={n}], got {r.shape}")
        reset_dev = jnp.concatenate(
            [r, jnp.zeros((1,), jnp.float32)])
    fin = _pr_finish() if reset_dev is None else _pr_finish_reset()
    it0 = 0
    if resume is not None:
        rank = jnp.asarray(resume["rank"], jnp.float32)
        it0 = int(resume["it"])
    elif reset_dev is not None:
        rank = reset_dev
    else:
        rank = jnp.full((n + 1,), 1.0 / n, jnp.float32) \
            .at[n].set(0.0)
    contrib = jnp.where(deg > 0, rank / jnp.maximum(deg, 1.0), 0.0)
    it = it0
    for it in range(it0 + 1, iterations + 1):
        if on_round is not None and not on_round(it - 1):
            raise RoundInterrupted(it - 1)
        acc = jnp.zeros((n + 1,), jnp.float32)
        for w0 in range(0, total, W):
            # pooled window starts: a fresh scalar put per window costs
            # a tunnel round trip (64 windows/iteration at scale 26)
            acc = win(acc, contrib, dev_scalar(w0), dstT, colowner, W=W)
        if reset_dev is None:
            rank, contrib, delta = fin(acc, rank, deg,
                                       jnp.float32(damping), n_=n)
        else:
            rank, contrib, delta = fin(acc, rank, reset_dev, deg,
                                       jnp.float32(damping), n_=n)
        if checkpoint is not None:
            checkpoint(it, {"rank": rank})
        if tol is not None and float(delta) < tol:
            break
    out = rank[:n]
    if not return_device:
        out = np.asarray(out)
    return out, it


def _pr_window():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("W",),
                           donate_argnums=(0,))
        def step(acc, contrib, w0, dstT, colowner, W: int):
            # the final window's slice start gets clamped so it fits, which
            # OVERLAPS the previous window; scatter-ADD is not idempotent,
            # so already-processed columns must contribute exactly 0
            w0c = jnp.minimum(w0, colowner.shape[0] - W)
            owner = jax.lax.dynamic_slice(colowner, (w0c,), (W,))
            nbr = jax.lax.dynamic_slice(dstT, (0, w0c), (8, W))
            fresh = (w0c + jnp.arange(W, dtype=jnp.int32)) >= w0
            c = jnp.where(fresh, contrib[owner], 0.0)
            return acc.at[nbr].add(jnp.broadcast_to(c[None, :], nbr.shape),
                                   mode="drop")
        return step
    return jit_once("pagerank_window", build)


def _pr_finish():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_",))
        def fin(acc, rank, deg, damping, n_: int):
            new_rank = (1.0 - damping) / n_ + damping * acc[:n_]
            new_rank = jnp.concatenate(
                [new_rank, jnp.zeros((1,), jnp.float32)])
            delta = jnp.abs(new_rank[:n_] - rank[:n_]).sum()
            contrib = jnp.where(deg > 0, new_rank / jnp.maximum(deg, 1), 0.0)
            return new_rank, contrib, delta
        return fin
    return jit_once("pagerank_finish", build)


def _pr_finish_reset():
    """Personalized finish: teleport mass lands on the ``reset``
    distribution instead of uniformly — its own jit entry so the
    uniform path keeps its exact pre-personalization cache key and
    HLO. The per-row expressions here must stay IDENTICAL to the
    vmapped batched kernel in models/pagerank.py (bit-equality per
    source is the contract)."""
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_",))
        def fin(acc, rank, reset, deg, damping, n_: int):
            new_rank = (1.0 - damping) * reset[:n_] + damping * acc[:n_]
            new_rank = jnp.concatenate(
                [new_rank, jnp.zeros((1,), jnp.float32)])
            delta = jnp.abs(new_rank[:n_] - rank[:n_]).sum()
            contrib = jnp.where(deg > 0, new_rank / jnp.maximum(deg, 1), 0.0)
            return new_rank, contrib, delta
        return fin
    return jit_once("pagerank_finish_reset", build)


def frontier_wcc(snap_or_graph, max_rounds: int = 10_000,
                 return_device: bool = False, on_round=None,
                 checkpoint=None, resume: dict | None = None,
                 overlay=None):
    """Hybrid connected components (symmetrized graphs): peel the seed
    vertex's whole component with one direction-optimized BFS, then run
    min-label propagation over the remaining components only. Returns
    (label int32 [n] = component minimum vertex id, rounds) where
    rounds counts BFS levels + propagation rounds.

    ``checkpoint(rounds, state)``: propagation-phase round-boundary
    capture (the state dict additionally carries ``levels``, the BFS
    peel's level count, so a resumed run reports the same total).
    ``resume``: ``{"val", "val_exp", "rounds", "levels"}`` — skips the
    BFS peel entirely and continues label propagation; final labels are
    bit-equal to an uninterrupted run."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    if overlay is None and not isinstance(snap_or_graph, dict):
        overlay = getattr(snap_or_graph, "_live_overlay", None)
    if overlay is not None and overlay.empty:
        overlay = None
    n = g["n"]
    if n == 0:
        out = jnp.zeros((0,), jnp.int32)
        return (out if return_device else np.asarray(out)), 0
    start_rounds = 0
    if resume is not None:
        val = jnp.asarray(resume["val"], jnp.int32)
        val_exp = jnp.asarray(resume["val_exp"], jnp.int32)
        start_rounds = int(resume["rounds"])
        levels = int(resume.get("levels", 0))
    elif overlay is not None:
        # live overlay: the BFS peel has no overlay seam, so skip it
        # and run pure min-label propagation — every vertex starts at
        # its own id in improved state. Slower (no giant-component
        # shortcut) but exact: labels converge to the component minimum
        # either way, so the result stays bit-equal to a rebuilt
        # snapshot's frontier_wcc.
        ids = jnp.arange(n, dtype=jnp.int32)
        val = jnp.concatenate([ids, jnp.full((1,), IINF, jnp.int32)])
        val_exp = jnp.concatenate(
            [ids + 1, jnp.full((1,), IINF, jnp.int32)])
        levels = 0
    else:
        # seed at the max-degree vertex — on power-law graphs it anchors
        # the giant component, so the BFS peels ~all edge mass
        seed_v = int(np.asarray(jnp.argmax(g["deg"][:n])))
        # max_levels=n: a truncated BFS would freeze the partially-peeled
        # region as expanded, silently splitting its component's labels
        dist, levels = frontier_bfs_hybrid(g, seed_v, max_levels=n,
                                           return_device=True)
        # frontier_bfs_hybrid returns dist[:n]; the seeding jit
        # re-appends nothing — it only reads [:n_]
        val, val_exp = _wcc_seed_labels()(dist, n_=n)
    if checkpoint is not None:
        _ck = checkpoint

        def checkpoint(rounds, state, _ck=_ck, _levels=levels):
            state = dict(state)
            state["levels"] = _levels
            _ck(rounds, state)
    out, rounds = _frontier_run(g, val, val_exp, "wcc", (0.0, 0.0),
                                max_rounds, on_round=on_round,
                                checkpoint=checkpoint,
                                start_rounds=start_rounds,
                                overlay=overlay)
    if not return_device:
        out = np.asarray(out)
    return out, rounds + levels
