"""Frontier-sparse (active-set) traversal kernels on the chunked CSR.

Generalizes the top-down machinery of ``bfs_hybrid`` to value-carrying
relaxations — the frontier-sparse analogs of the reference's OLAP
fixtures (reference: titan-test olap/ShortestDistanceVertexProgram for
SSSP, min-label propagation for connected components): instead of full
edge sweeps every superstep (O(E x rounds), the FulgoraGraphComputer
model), each round expands ONLY the vertices whose value changed in the
previous round, which bounds total work by the relaxation count.

* ``frontier_sssp`` — Bellman-Ford with an improvement frontier.
  Edge weights are derived ON DEVICE by hashing the edge slot id
  (uniform in [min_w, min_w+w_range)), so a scale-26 run needs no
  second 9GB weight array; ``slot_weights_np`` reproduces them on the
  host for verification.
* ``frontier_wcc`` — min-label propagation with an active set; on the
  symmetrized graph labels converge to per-component minima.

Both keep all state on device with one small stats readback per round
(axon-tunnel D2H is ~0.01 GB/s; see PERF_NOTES.md) and share the
chunked-CSR graph dict of ``bfs_hybrid`` (GraphSnapshot or
``graph500.to_device`` output).
"""

from __future__ import annotations

import functools

import numpy as np

from titan_tpu.models.bfs_hybrid import (build_chunked_csr,
                                         enumerate_chunk_pairs)
from titan_tpu.models.bfs import _next_pow2
from titan_tpu.utils.jitcache import jit_once

FINF = np.float32(3.0e38)
IINF = np.int32(1 << 30)


def _hash_weight_expr(slot, min_w: float, w_range: float):
    """uniform [min_w, min_w + w_range) from an int32 edge slot id
    (murmur-style integer mix, reproduced by slot_weights_np)."""
    import jax.numpy as jnp

    x = slot.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    u = (x & jnp.uint32(0xFFFFFF)).astype(jnp.float32) * (1.0 / (1 << 24))
    return min_w + w_range * u


def slot_weights_np(slots: np.ndarray, min_w: float = 0.0,
                    w_range: float = 1.0) -> np.ndarray:
    x = slots.astype(np.uint32)
    x = (x ^ (x >> np.uint32(16))) * np.uint32(0x7FEB352D)
    x = (x ^ (x >> np.uint32(15))) * np.uint32(0x846CA68B)
    x = x ^ (x >> np.uint32(16))
    u = (x & np.uint32(0xFFFFFF)).astype(np.float32) / np.float32(1 << 24)
    return (min_w + w_range * u).astype(np.float32)


# per-slice chunk budget: caps the [8, p_cap] working blocks (neighbors +
# message + weight-hash temporaries, ~4 of them) at ~1GB — at scale 26
# the graph itself holds 9GB of the 16GB HBM, and unbounded pair caps
# OOMed. Rounds whose frontier mass exceeds the budget are processed as
# multiple slices planned ON DEVICE (one boundary readback per round),
# so total work tracks the ACTUAL relaxation mass — a dense all-slot
# sweep at scale 26 paid 2.15B scatters per round regardless of activity
# and took ~28s/round.
SLICE_BUDGET_CHUNKS = 1 << 23
SLICE_K_MAX = 128
# legacy dense-window machinery (kept for pagerank_dense, where every
# vertex IS active every iteration and slot padding is the only waste)
DENSE_WINDOW = 1 << 22


def _colowner(g):
    """column -> owning vertex map (lazy, cached in the graph dict):
    lets dense sweeps read contiguous column windows with no pair
    enumeration. Pad/sink columns own the sink vertex n."""
    import jax.numpy as jnp

    co = g.get("colowner")
    if co is None:
        n = g["n"]
        q_total = g["q_total"]
        # computed on device (jnp.repeat with a static total length) —
        # reading colstart back to build it on the host would D2H 268MB
        # at scale 26
        degc = g["degc"]
        ids = jnp.arange(n + 1, dtype=jnp.int32)
        owner = jnp.repeat(ids, degc, total_repeat_length=q_total - 1)
        co = jnp.concatenate([owner, jnp.full((1,), n, jnp.int32)])
        g["colowner"] = co
    return co


def _wrap_plan(kind: str):
    """Round end, fused into ONE readback: the new frontier (vertices
    whose value improved vs ``val_old``), the round's stats, and the
    SLICE PLAN for the next round — frontier-index boundaries placed
    every SLICE_BUDGET_CHUNKS of cumulative chunk mass (device
    searchsorted), so the host sizes each slice's kernel without extra
    syncs. A slice may exceed the budget by at most one vertex's chunks
    (p_cap adds max_degc)."""
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit,
                           static_argnames=("n_", "cap", "k_max",
                                            "budget"))
        def wrapplan(val, val_old, degc, fb0, n_: int, cap: int,
                     k_max: int, budget: int):
            changed = val[:n_] < val_old[:n_]
            nf = changed.sum().astype(jnp.int32)
            frontier = jnp.nonzero(
                changed, size=n_, fill_value=n_)[0].astype(jnp.int32)
            if cap > n_:
                frontier = jnp.concatenate(
                    [frontier, jnp.full((cap - n_,), n_, jnp.int32)])
            cdeg = jnp.where(jnp.arange(cap) < nf,
                             degc[jnp.minimum(frontier, n_)], 0)
            cum = jnp.cumsum(cdeg)
            m8 = jnp.where(nf > 0, cum[jnp.maximum(nf - 1, 0)], 0)
            # sequential boundaries with RELATIVE budgets (an absolute
            # target schedule breaks after a forced single-hub slice) and
            # a forced >=1-vertex advance so an over-budget hub cannot
            # stall the plan
            def body(i, bounds):
                b = bounds[i]
                base = jnp.where(b > 0, cum[jnp.maximum(b - 1, 0)], 0)
                nxt = jnp.searchsorted(
                    cum, base + budget, side="right").astype(jnp.int32)
                nxt = jnp.minimum(jnp.maximum(nxt, b + 1), nf)
                return bounds.at[i + 1].set(nxt)

            bounds = jax.lax.fori_loop(
                0, k_max, body,
                jnp.zeros((k_max + 1,), jnp.int32).at[0].set(
                    jnp.minimum(fb0, nf)))
            widths = jnp.diff(bounds)
            plan = jnp.concatenate(
                [jnp.stack([nf, m8, widths.max()]), bounds])
            return frontier, plan
        return wrapplan
    return jit_once(f"frontier_wrapplan_{kind}", build)


def _push_slice(kind: str):
    """One SLICE of a frontier-push round: expand frontier[fb:fb+fcnt]'s
    chunks and relax min(value) into neighbors. The round's changed set
    is derived afterwards by the wrap/plan diff against ``val_old``, so
    slices carry no stats and dispatch back-to-back with no syncs."""
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit,
                           static_argnames=("f_cap", "p_cap", "n_"),
                           donate_argnums=(0,))
        def push(val, frontier, fb, fcnt, dstT, colstart, degc, wparams,
                 f_cap: int, p_cap: int, n_: int):
            # the slice start is clamped so dynamic_slice fits, so the
            # validity window must be expressed in GLOBAL frontier
            # indices — masking arange(f_cap) < fcnt after a clamp would
            # re-process earlier vertices and silently skip the tail
            fbc = jnp.minimum(fb, frontier.shape[0] - f_cap)
            fvert = jax.lax.dynamic_slice(frontier, (fbc,), (f_cap,))
            idx = jnp.arange(f_cap) + fbc
            valid = (idx >= fb) & (idx < fb + fcnt)
            v = jnp.minimum(fvert, n_)
            cols, _, owner = enumerate_chunk_pairs(
                valid, degc[v], colstart[v], p_cap, dstT.shape[1] - 1,
                with_owner=True)
            src_val = val[v][owner]                   # [p_cap]
            nbr = jnp.take(dstT, cols, axis=1)        # [8, p_cap], pad n+1
            if kind == "sssp":
                lane = jnp.arange(8, dtype=jnp.int32)[:, None]
                slot = cols[None, :] * 8 + lane
                w = _hash_weight_expr(slot, wparams[0], wparams[1])
                msg = src_val[None, :] + w
            else:
                msg = jnp.broadcast_to(src_val[None, :], nbr.shape)
            return val.at[nbr].min(msg, mode="drop")
        return push
    return jit_once(f"frontier_push_{kind}", build)


def _max_degc(g) -> int:
    got = g.get("_max_degc")
    if got is None:
        got = int(np.asarray(g["degc"].max()))
        g["_max_degc"] = got
    return got


def _frontier_run(snap_or_graph, val, val_old, kind: str, wparams,
                  max_rounds: int):
    """Round loop: one wrap/plan readback per round, then budget-sliced
    push dispatches (work tracks the actual relaxation mass). Relaxations
    from earlier slices are visible to later ones in the same round —
    min-relax only converges faster for it."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    dstT, colstart, degc = g["dstT"], g["colstart"], g["degc"]
    cap_n = _next_pow2(max(n, 2))
    push = _push_slice(kind)
    wrapplan = _wrap_plan(kind)
    max_dc = _max_degc(g)
    # a slice carries up to budget + max_dc chunks (one vertex of
    # overshoot), so budget == 2^k would push p_cap to 2^(k+1) and HALF
    # of every big slice's lanes would be padding — shave max_dc off the
    # budget instead so full slices fit a 2^k kernel exactly (measured
    # 2026-07-31: scale-26 SSSP round cost is dominated by these lanes)
    target = _next_pow2(max(SLICE_BUDGET_CHUNKS, 2))
    if max_dc <= target // 2:
        budget = target - max_dc
        p_full = target
    else:                       # degenerate hub: conservative old scheme
        budget = SLICE_BUDGET_CHUNKS
        p_full = _next_pow2(max(budget + max_dc, 2))

    wp = jnp.asarray(np.asarray(wparams, np.float32))
    rounds = 0
    while rounds < max_rounds:
        fb0 = 0
        done_round = False
        round_start = None
        while not done_round:
            # continuations (fb0 > 0, rare: only when a round needs more
            # than SLICE_K_MAX slices) re-plan from the FROZEN round-start
            # diff so the frontier indices don't shift mid-round
            frontier, plan = wrapplan(
                round_start if round_start is not None else val,
                val_old, degc, jnp.int32(fb0), n_=n, cap=cap_n,
                k_max=SLICE_K_MAX, budget=budget)
            plan_h = np.asarray(plan)          # ONE sync per plan
            nf, m8, wmax = (int(x) for x in plan_h[:3])
            bounds = plan_h[3:]
            if nf == 0 or m8 == 0:
                return val[:n], rounds
            if round_start is None:
                # a REAL copy: the first push donates val's buffer
                round_start = jnp.copy(val)
            f_cap = min(_next_pow2(max(wmax, 2)), cap_n)
            p_cap = min(_next_pow2(max(m8 + max_dc, 2)), p_full)
            for i in range(SLICE_K_MAX):
                fb, fe = int(bounds[i]), int(bounds[i + 1])
                if fe <= fb:
                    break
                val = push(val, frontier, jnp.int32(fb),
                           jnp.int32(fe - fb), dstT, colstart, degc, wp,
                           f_cap=f_cap, p_cap=p_cap, n_=n)
            if int(bounds[-1]) >= nf:
                done_round = True
            else:
                fb0 = int(bounds[-1])
        val_old = round_start
        rounds += 1
    return val[:n], rounds


def frontier_sssp(snap_or_graph, source_dense: int, min_w: float = 0.0,
                  w_range: float = 1.0, max_rounds: int = 10_000,
                  return_device: bool = False):
    """Bellman-Ford SSSP with an improvement frontier over hashed edge
    weights. Returns (dist float32 [n] with FINF unreachable, rounds)."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    val = jnp.full((n + 1,), FINF, jnp.float32).at[source_dense].set(0.0)
    # synthetic previous state: only the source reads as "improved"
    val_old = jnp.full((n + 1,), FINF, jnp.float32)
    out, rounds = _frontier_run(g, val, val_old, "sssp",
                                (min_w, w_range), max_rounds)
    if not return_device:
        out = np.asarray(out)
    return out, rounds


def _pr_window():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("W",),
                           donate_argnums=(0,))
        def step(acc, contrib, w0, dstT, colowner, W: int):
            # the final window's slice start gets clamped so it fits, which
            # OVERLAPS the previous window; scatter-ADD is not idempotent,
            # so already-processed columns must contribute exactly 0
            w0c = jnp.minimum(w0, colowner.shape[0] - W)
            owner = jax.lax.dynamic_slice(colowner, (w0c,), (W,))
            nbr = jax.lax.dynamic_slice(dstT, (0, w0c), (8, W))
            fresh = (w0c + jnp.arange(W, dtype=jnp.int32)) >= w0
            c = jnp.where(fresh, contrib[owner], 0.0)
            return acc.at[nbr].add(jnp.broadcast_to(c[None, :], nbr.shape),
                                   mode="drop")
        return step
    return jit_once("pagerank_window", build)


def _pr_finish():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_",))
        def fin(acc, rank, deg, damping, n_: int):
            new_rank = (1.0 - damping) / n_ + damping * acc[:n_]
            new_rank = jnp.concatenate(
                [new_rank, jnp.zeros((1,), jnp.float32)])
            delta = jnp.abs(new_rank[:n_] - rank[:n_]).sum()
            contrib = jnp.where(deg > 0, new_rank / jnp.maximum(deg, 1), 0.0)
            return new_rank, contrib, delta
        return fin
    return jit_once("pagerank_finish", build)


def pagerank_dense(snap_or_graph, iterations: int = 20,
                   damping: float = 0.85, tol: float | None = None,
                   return_device: bool = False):
    """Push-mode PageRank over the chunked CSR via dense window sweeps:
    rank' = (1-d)/n + d * sum over in-edges of rank[src]/outdeg[src]
    (semantics match the pull-mode engine program in models/pagerank.py,
    incl. leaking dangling mass). Returns (rank float32 [n], iterations
    run). ``tol``: early exit when the L1 delta falls below it."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    dstT = g["dstT"]
    deg = g["deg"].astype(jnp.float32)
    colowner = _colowner(g)
    total = g["q_total"]
    W = min(DENSE_WINDOW, total)
    win = _pr_window()
    fin = _pr_finish()
    rank = jnp.full((n + 1,), 1.0 / n, jnp.float32) \
        .at[n].set(0.0)
    contrib = jnp.where(deg > 0, rank / jnp.maximum(deg, 1.0), 0.0)
    it = 0
    for it in range(1, iterations + 1):
        acc = jnp.zeros((n + 1,), jnp.float32)
        for w0 in range(0, total, W):
            acc = win(acc, contrib, jnp.int32(w0), dstT, colowner, W=W)
        rank, contrib, delta = fin(acc, rank, deg,
                                   jnp.float32(damping), n_=n)
        if tol is not None and float(delta) < tol:
            break
    out = rank[:n]
    if not return_device:
        out = np.asarray(out)
    return out, it


def frontier_wcc(snap_or_graph, max_rounds: int = 10_000,
                 return_device: bool = False):
    """Min-label propagation with an active set (symmetrized graphs).
    Returns (label int32 [n] = component minimum vertex id, rounds)."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    # labels live in [0, n); the sink slot n stays at IINF. The synthetic
    # previous state reads every vertex as "improved" (round 1 = all)
    val = jnp.concatenate([jnp.arange(n, dtype=jnp.int32),
                           jnp.full((1,), IINF, jnp.int32)])
    val_old = val + 1
    out, rounds = _frontier_run(g, val, val_old, "wcc", (0.0, 0.0),
                                max_rounds)
    if not return_device:
        out = np.asarray(out)
    return out, rounds
