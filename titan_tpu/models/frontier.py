"""Frontier-sparse (active-set) traversal kernels on the chunked CSR.

Generalizes the top-down machinery of ``bfs_hybrid`` to value-carrying
relaxations — the frontier-sparse analogs of the reference's OLAP
fixtures (reference: titan-test olap/ShortestDistanceVertexProgram for
SSSP, min-label propagation for connected components): instead of full
edge sweeps every superstep (O(E x rounds), the FulgoraGraphComputer
model), each round expands ONLY the vertices whose value improved since
their last EXPANSION — ``val_expanded`` records the value each vertex
last pushed, so the frontier needs no per-round state copies and a round
interrupted mid-way (slice-cap overflow) resumes exactly where it left
off.

* ``frontier_sssp`` — DELTA-STEPPING (Meyer & Sanders) over hashed edge
  weights: vertices are expanded in distance buckets of width ``delta``
  (one-sided: every improved vertex below the current bucket top is
  eligible, so stragglers never accumulate), which re-examines each
  vertex's edge list a small constant number of times instead of the
  O(rounds) full re-relaxation a plain Bellman-Ford improvement
  frontier pays on continuous weights. Weights are derived ON DEVICE by
  hashing the edge slot id (uniform in [min_w, min_w+w_range)), so a
  scale-26 run needs no second 9GB weight array; ``slot_weights_np``
  reproduces them on the host for verification.
* ``frontier_wcc`` — hybrid connected components: one
  direction-optimized BFS (models/bfs_hybrid — the most optimized
  kernel in the repo) peels off the seed vertex's ENTIRE component in
  one shot (on power-law graphs that is ~all edge mass), then min-label
  propagation runs only over the leftover components' tiny edge mass.
  A component is a closed set — no edge crosses the peeled boundary —
  so the two phases compose exactly.

All state stays on device with one small plan readback per round
(axon-tunnel D2H is ~0.01 GB/s; see PERF_NOTES.md); the graph dict is
``bfs_hybrid``'s chunked CSR (GraphSnapshot or ``graph500.to_device``
output).
"""

from __future__ import annotations

import functools

import numpy as np

from titan_tpu.models.bfs_hybrid import (build_chunked_csr,
                                         enumerate_chunk_pairs,
                                         frontier_bfs_hybrid)
from titan_tpu.models.bfs import INF, _next_pow2
from titan_tpu.utils.jitcache import dev_scalar, jit_once

FINF = np.float32(3.0e38)
IINF = np.int32(1 << 30)


def _hash_weight_expr(slot, min_w: float, w_range: float):
    """uniform [min_w, min_w + w_range) from an int32 edge slot id
    (murmur-style integer mix, reproduced by slot_weights_np)."""
    import jax.numpy as jnp

    x = slot.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    u = (x & jnp.uint32(0xFFFFFF)).astype(jnp.float32) * (1.0 / (1 << 24))
    return min_w + w_range * u


def slot_weights_np(slots: np.ndarray, min_w: float = 0.0,
                    w_range: float = 1.0) -> np.ndarray:
    x = slots.astype(np.uint32)
    x = (x ^ (x >> np.uint32(16))) * np.uint32(0x7FEB352D)
    x = (x ^ (x >> np.uint32(15))) * np.uint32(0x846CA68B)
    x = x ^ (x >> np.uint32(16))
    u = (x & np.uint32(0xFFFFFF)).astype(np.float32) / np.float32(1 << 24)
    return (min_w + w_range * u).astype(np.float32)


# per-slice chunk budget: caps the [8, p_cap] working blocks (neighbors +
# message + weight-hash temporaries, ~4 of them) at ~1GB — at scale 26
# the graph itself holds 9GB of the 16GB HBM, and unbounded pair caps
# OOMed. Rounds whose frontier mass exceeds the budget are processed as
# multiple slices planned ON DEVICE (one boundary readback per round).
# A round with more mass than SLICE_K_MAX slices simply leaves the
# overflow vertices improved-but-unexpanded; the next plan picks them
# up — the expansion-tracked frontier makes partial rounds sound.
SLICE_BUDGET_CHUNKS = 1 << 23
SLICE_K_MAX = 64
# legacy dense-window machinery (kept for pagerank_dense, where every
# vertex IS active every iteration and slot padding is the only waste)
DENSE_WINDOW = 1 << 22


def _colowner(g):
    """column -> owning vertex map (lazy, cached in the graph dict):
    lets dense sweeps read contiguous column windows with no pair
    enumeration. Pad/sink columns own the sink vertex n."""
    import jax.numpy as jnp

    co = g.get("colowner")
    if co is None:
        n = g["n"]
        q_total = g["q_total"]
        # computed on device (jnp.repeat with a static total length) —
        # reading colstart back to build it on the host would D2H 268MB
        # at scale 26
        degc = g["degc"]
        ids = jnp.arange(n + 1, dtype=jnp.int32)
        owner = jnp.repeat(ids, degc, total_repeat_length=q_total - 1)
        co = jnp.concatenate([owner, jnp.full((1,), n, jnp.int32)])
        g["colowner"] = co
    return co


def _wrap_plan(kind: str):
    """Build the round plan in ONE readback — pure elementwise + scan
    work (NO n-scale nonzero, NO random gathers: the round-1 design
    gathered ``degc[frontier]`` at cap scale, ~1s/round at scale 26
    against the 67M elem/s big-table regime, which dominated fine-delta
    runs). The frontier is never materialized as a list: slices are
    VERTEX RANGES whose in-bucket chunk mass is ~SLICE_BUDGET_CHUNKS
    (one masked cumsum + k_max searchsorteds), and each push slice
    recomputes the membership mask for its contiguous range."""
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit,
                           static_argnames=("n_", "k_max", "budget"))
        def wrapplan(val, val_exp, degc, bucket_end, n_: int, k_max: int,
                     budget: int):
            # plain / delta-stepping plan; the priority-batched
            # (quantile) mode has its own merged single-dispatch plan,
            # _quant_plan
            hasdeg = degc[:n_] > 0
            changed = (val[:n_] < val_exp[:n_]) & hasdeg
            inb = changed & (val[:n_] < bucket_end)
            nf = inb.sum().astype(jnp.int32)
            cummass = jnp.cumsum(
                jnp.where(inb, degc[:n_], 0), dtype=jnp.int32)
            m8 = cummass[-1]
            # vertex-space boundaries on an ABSOLUTE mass schedule —
            # one BATCHED searchsorted (a sequential fori of dependent
            # searchsorteds measured ~0.8s/plan at scale 26; this is the
            # empty-round floor). A >budget hub makes consecutive bounds
            # equal (slice still <= budget + max_degc); the host skips
            # zero-width slices and splits over-wide ones.
            targets = jnp.arange(1, k_max + 1, dtype=jnp.int32) * budget
            bounds = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32),
                 jnp.searchsorted(cummass, targets,
                                  side="right").astype(jnp.int32)])
            bounds = jnp.minimum(bounds, jnp.int32(n_))
            bmass = jnp.where(bounds > 0,
                              cummass[jnp.maximum(bounds - 1, 0)], 0)
            # pending = improved vertices parked above the bucket; their
            # minimum value tells the host where the next bucket starts
            pending = changed & ~inb
            big = jnp.asarray(FINF if val.dtype == jnp.float32 else IINF,
                              val.dtype)
            pmin = jnp.min(jnp.where(pending, val[:n_], big))
            plan = jnp.concatenate(
                [jnp.stack([nf, m8]), bounds, bmass,
                 jax.lax.bitcast_convert_type(pmin, jnp.int32)[None]
                 if val.dtype == jnp.float32 else pmin[None]])
            # bounds (and the effective bucket threshold — quantile mode
            # computes it on device) returned separately ON DEVICE: push
            # slices read their vertex range / threshold from them via
            # pooled index scalars, so the host never ships per-slice
            # values (each scalar put is a ~0.1-0.9s tunnel round trip)
            return plan, bounds, jnp.asarray(bucket_end, val.dtype)
        return wrapplan
    return jit_once(f"frontier_wrapplan_{kind}", build)


def _push_slice(kind: str):
    """One vertex-range SLICE of a frontier-push round: recompute the
    in-bucket membership mask over [vlo, vhi) from live state (all
    contiguous dynamic_slice reads — no random gathers outside the
    essential neighbor fetch/relax), expand the members' chunks, relax
    min(value) into neighbors, and record the pushed values in
    ``val_exp``. A member whose chunk range does not fit p_cap (possible
    when an earlier slice of the same round improved a vertex INTO the
    bucket after planning) is left unexpanded — still improved, so the
    next plan picks it up; partial pushes can never mark a vertex
    expanded."""
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit,
                           static_argnames=("f_cap", "p_cap", "n_"),
                           donate_argnums=(0, 1))
        def push(val, val_exp, bounds, idx, sub, bucket_end, dstT,
                 colstart, degc, wparams, f_cap: int, p_cap: int,
                 n_: int):
            # the slice's vertex range comes from the DEVICE bounds
            # array (idx/sub are pooled scalars — no per-call host
            # transfers): range = width-window `sub` of plan slice `idx`
            vlo = bounds[idx] + sub * f_cap
            vhi = jnp.minimum(bounds[idx + 1], vlo + f_cap)
            # clamp so the dynamic_slice fits; validity is expressed in
            # GLOBAL vertex indices so the clamp shift cannot re-process
            # earlier vertices or skip the tail
            v0 = jnp.minimum(vlo, jnp.int32(n_ + 1 - f_cap))
            v0 = jnp.maximum(v0, 0)
            idx = v0 + jnp.arange(f_cap, dtype=jnp.int32)
            valv = jax.lax.dynamic_slice(val, (v0,), (f_cap,))
            vexp = jax.lax.dynamic_slice(val_exp, (v0,), (f_cap,))
            degr = jax.lax.dynamic_slice(degc, (v0,), (f_cap,))
            colr = jax.lax.dynamic_slice(colstart, (v0,), (f_cap,))
            member = (idx >= vlo) & (idx < vhi) & (idx < n_) \
                & (valv < vexp) & (valv < bucket_end) & (degr > 0)
            counts = jnp.where(member, degr, 0).astype(jnp.int32)
            # only members whose WHOLE chunk range fits p_cap may be
            # marked expanded (see docstring)
            ends = jnp.cumsum(counts)
            fits = member & (ends <= p_cap)
            vexp2 = jnp.where(fits, valv, vexp)
            val_exp = jax.lax.dynamic_update_slice(val_exp, vexp2, (v0,))
            cols, _, owner = enumerate_chunk_pairs(
                fits, counts, colr, p_cap, dstT.shape[1] - 1,
                with_owner=True)
            src_val = valv[owner]                     # [p_cap], 32MB table
            nbr = jnp.take(dstT, cols, axis=1)        # [8, p_cap], pad n+1
            if kind == "sssp":
                lane = jnp.arange(8, dtype=jnp.int32)[:, None]
                slot = cols[None, :] * 8 + lane
                w = _hash_weight_expr(slot, wparams[0], wparams[1])
                msg = src_val[None, :] + w
            else:
                msg = jnp.broadcast_to(src_val[None, :], nbr.shape)
            return val.at[nbr].min(msg, mode="drop"), val_exp
        return push
    return jit_once(f"frontier_push_{kind}", build)


def _quant_plan(kind: str):
    """Quantile-mode round plan in ONE dispatch: 2-level histogram
    threshold + in-band list compaction + mass-balanced segment bounds
    (r4 split this across two kernels — threshold in the wrap plan,
    list build in a second dispatch — paying an extra n-scale pass and
    a dispatch/sync per round, ~0.4s of the measured ~2s/round overhead
    at scale 26). ``f_cap`` is a FIXED
    module-level width (one compile bucket); an in-band set larger than
    f_cap is truncated by the nonzero, which is SOUND: unlisted vertices
    stay improved (val < val_exp) and the next round re-plans them."""
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit,
                           static_argnames=("n_", "f_cap", "k_max",
                                            "budget", "quantile_mass",
                                            "bins"))
        def qplan(val, val_exp, degc, n_: int, f_cap: int, k_max: int,
                  budget: int, quantile_mass: int, bins: int = 512):
            hasdeg = degc[:n_] > 0
            changed = (val[:n_] < val_exp[:n_]) & hasdeg
            big_ = jnp.asarray(FINF, val.dtype)
            vals = jnp.where(changed, val[:n_], big_)
            lo = vals.min()
            hi0 = jnp.where(changed, val[:n_], -big_).max()
            span = jnp.maximum(hi0 - lo, 1e-30)
            mass = jnp.where(changed, degc[:n_], 0)
            b = jnp.clip(((val[:n_] - lo) / span
                          * bins).astype(jnp.int32), 0, bins - 1)
            b = jnp.where(changed, b, bins - 1)
            hist = jnp.zeros((bins,), jnp.int32).at[b].add(mass,
                                                          mode="drop")
            cum = jnp.cumsum(hist)
            pick = jnp.minimum(jnp.searchsorted(
                cum, jnp.int32(quantile_mass), side="left"), bins - 1)
            lo2 = lo + span * pick.astype(val.dtype) / bins
            span2 = span / bins
            before = jnp.where(pick > 0, cum[jnp.maximum(pick - 1, 0)], 0)
            in2 = changed & (b == pick)
            b2 = jnp.clip(((val[:n_] - lo2) / span2
                           * bins).astype(jnp.int32), 0, bins - 1)
            hist2 = jnp.zeros((bins,), jnp.int32).at[
                jnp.where(in2, b2, bins - 1)].add(
                jnp.where(in2, degc[:n_], 0), mode="drop")
            cum2 = jnp.cumsum(hist2)
            pick2 = jnp.minimum(jnp.searchsorted(
                cum2, jnp.int32(quantile_mass) - before, side="left"),
                bins - 1)
            thr = lo2 + span2 * (pick2 + 1).astype(val.dtype) / bins
            thr = jnp.maximum(thr, jnp.nextafter(lo, big_))

            inb = changed & (val[:n_] < thr)
            flist = jnp.nonzero(inb, size=f_cap,
                                fill_value=n_)[0].astype(jnp.int32)
            valid = flist < n_
            nf = valid.sum().astype(jnp.int32)
            degl = jnp.where(valid, degc[jnp.minimum(flist, n_)], 0)
            cmass = jnp.cumsum(degl.astype(jnp.int32))
            m8 = cmass[-1]                       # LISTED mass
            targets = jnp.arange(1, k_max + 1, dtype=jnp.int32) * budget
            lb = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32),
                 jnp.minimum(jnp.searchsorted(cmass, targets,
                                              side="right"),
                             f_cap).astype(jnp.int32)])
            pending = changed & ~inb
            pmin = jnp.min(jnp.where(pending, val[:n_], big_))
            stats = jnp.concatenate(
                [jnp.stack([nf, m8]),
                 jax.lax.bitcast_convert_type(pmin, jnp.int32)[None]])
            return stats, flist, lb, jnp.asarray(thr, val.dtype)
        return qplan
    return jit_once(f"frontier_quantplan_{kind}", build)


# fixed in-band list width for the merged quantile plan (one compile
# bucket; truncation is sound — see _quant_plan)
QUANT_LIST_CAP = 1 << 23


def _push_list(kind: str):
    """Push one mass-balanced SEGMENT of the round's compacted in-band
    list (quantile mode). Membership is rechecked live (an earlier
    segment may have improved a member further — it pushes its current
    value); a vertex appears in exactly one segment and segment mass is
    fixed by the plan, so p_cap = pow2(segment mass) never defers."""
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit,
                           static_argnames=("f_cap", "p_cap", "n_"),
                           donate_argnums=(0, 1))
        def pushl(val, val_exp, flist, lbounds, i, thr, dstT, colstart,
                  degc, wparams, f_cap: int, p_cap: int, n_: int):
            p0 = lbounds[i]
            p1 = lbounds[i + 1]
            L = flist.shape[0]
            s0 = jnp.clip(p0, 0, max(L - f_cap, 0))
            pos = s0 + jnp.arange(f_cap, dtype=jnp.int32)
            seg = jax.lax.dynamic_slice(flist, (s0,), (f_cap,))
            v = jnp.minimum(seg, n_)
            member = (pos >= p0) & (pos < p1) & (seg < n_) \
                & (val[v] < val_exp[v]) & (val[v] < thr)
            valv = val[v]
            counts = jnp.where(member, degc[v], 0).astype(jnp.int32)
            # a segment's true mass can exceed the plan target by one
            # straddling vertex; only members whose WHOLE chunk range
            # fits p_cap are marked expanded — the rest stay improved
            # and the next round re-plans them (same contract as the
            # vertex-range push)
            ends = jnp.cumsum(counts)
            fits = member & (ends <= p_cap)
            val_exp = val_exp.at[jnp.where(fits, v, n_ + 1)].set(
                valv, mode="drop")
            cols, _, owner = enumerate_chunk_pairs(
                fits, counts, colstart[v], p_cap, dstT.shape[1] - 1,
                with_owner=True)
            src_val = valv[owner]
            nbr = jnp.take(dstT, cols, axis=1)
            if kind == "sssp":
                lane = jnp.arange(8, dtype=jnp.int32)[:, None]
                slot = cols[None, :] * 8 + lane
                w = _hash_weight_expr(slot, wparams[0], wparams[1])
                msg = src_val[None, :] + w
            else:
                msg = jnp.broadcast_to(src_val[None, :], nbr.shape)
            return val.at[nbr].min(msg, mode="drop"), val_exp
        return pushl
    return jit_once(f"frontier_pushlist_{kind}", build)


def _quantize_cap(mass: int, p_full: int) -> int:
    """Round a slice's kernel width up to the next power of FOUR
    (capped at p_full). Mass-exact pow2 caps created a distinct compile
    per bucket — and compiles do NOT persist across processes under the
    remote-compile backend (~8-20s each through the tunnel), so a cold
    22-round SSSP paid more compile than compute. Power-of-four rounding
    halves the bucket count for at most 2x dead lanes on the SMALL
    slices (full budget-sized slices hit p_full either way)."""
    c = _next_pow2(max(mass, 2))
    if (c.bit_length() - 1) % 2:
        c <<= 1
    return min(c, p_full)


def _max_degc(g) -> int:
    got = g.get("_max_degc")
    if got is None:
        got = int(np.asarray(g["degc"].max()))
        g["_max_degc"] = got
    return got


# vertex-range slice width: sparse rounds dispatch >= n/width slices, so
# width trades dispatch count against the src_val gather table size
# (2^23 int32 = 32MB, the last fast-gather size — see PERF_NOTES.md)
SLICE_WIDTH = 1 << 23
# default per-round band mass (chunks) for quantile-batched SSSP — the
# measured r5 winner and the DEFAULT mode: scale-26 warm, same chip-day:
# plain 247s / 1118M chunks vs quantile-2^24 121-130s / 394M chunks
# (after the r5 fixes: two-level threshold so one histogram bin cannot
# swallow 10x the target mass, pow-4 f_cap buckets so band sizes stop
# compiling fresh kernels, and the merged single-dispatch _quant_plan).
# Band-size sweep: 2^23 = 45 rounds (per-round floors dominate), 2^24 =
# 31 rounds/394M, 2^25 = 30/518M, 2^26 = 28/716M — rounds are
# WAVE-limited below 2^24, re-expansion grows above it.
QUANTILE_MASS_DEFAULT = 1 << 24


def _frontier_run(snap_or_graph, val, val_exp, kind: str, wparams,
                  max_rounds: int, delta: float | None = None,
                  quantile_mass: int = 0):
    """Expansion-tracked round loop: one plan readback per round, then
    budget-bounded vertex-range push dispatches. With ``delta``, rounds
    expand only the current distance bucket (one-sided) and the bucket
    advances to the minimum pending value when it drains —
    delta-stepping. With ``quantile_mass``, each round's threshold is
    computed ON DEVICE so the expanded band carries ~that much chunk
    mass — priority-batched expansion in near-sorted value order (see
    _wrap_plan). Without either, every improved vertex is eligible
    every round."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    dstT, colstart, degc = g["dstT"], g["colstart"], g["degc"]
    push = _push_slice(kind)
    wrapplan = _wrap_plan(kind)
    max_dc = _max_degc(g)
    is_f32 = val.dtype == jnp.float32
    big = float(FINF) if is_f32 else int(IINF)
    # dynamic_slice needs f_cap <= n+1: cap the range width at the
    # largest power of two that fits the state arrays
    w_max = 1 << ((n + 1).bit_length() - 1)
    width = min(SLICE_WIDTH, w_max)
    # a slice carries up to budget + max_dc chunks (one vertex of
    # overshoot), so budget == 2^k would push p_cap to 2^(k+1) and HALF
    # of every big slice's lanes would be padding — shave max_dc off the
    # budget so full slices fit a 2^k kernel exactly (measured
    # 2026-07-31: scale-26 SSSP round cost is dominated by these lanes)
    target = _next_pow2(max(SLICE_BUDGET_CHUNKS, 2))
    if max_dc <= target // 2:
        budget = target - max_dc
        p_full = target
    else:                       # degenerate hub: conservative old scheme
        budget = SLICE_BUDGET_CHUNKS
        p_full = _next_pow2(max(budget + max_dc, 2))

    wp = jnp.asarray(np.asarray(wparams, np.float32))
    # the quantile threshold math in _wrap_plan is float32-only (span
    # floor 1e-30, jnp.nextafter on lo); int-valued kinds (e.g. WCC
    # labels) would trace-error or mis-threshold — fall back to the
    # plain improved-set frontier for them
    if quantile_mass and not is_f32:
        quantile_mass = 0
    bucket_end = big if not delta or delta <= 0 else delta
    trace = g.get("_trace_rounds")      # optional perf instrumentation:
    rounds = 0                          # set g["_trace_rounds"] = [] to
    dtname = "float32" if is_f32 else "int32"
    prev_sig = None
    escalate = False
    qf_cap = min(QUANT_LIST_CAP, w_max)
    while rounds < max_rounds:          # collect (bucket_end, nf, m8)
        if quantile_mass:
            # priority-batched mode: ONE merged plan dispatch
            # (threshold + in-band list + segment bounds, _quant_plan)
            # then a pushl per ~budget chunks of listed mass. Expansion
            # happens in near-sorted value order — the Dijkstra
            # no-re-expansion property, batched; exactness is
            # val_exp-tracked and does not depend on the threshold.
            qplan = _quant_plan(kind)
            pushl = _push_list(kind)
            stats, flist, lbounds, thr_dev = qplan(
                val, val_exp, degc, n_=n, f_cap=qf_cap,
                k_max=SLICE_K_MAX, budget=budget,
                quantile_mass=quantile_mass)
            st_h = np.asarray(stats)       # ONE sync per round
            nf, m8 = int(st_h[0]), int(st_h[1])
            pmin = st_h[2:3].view(np.float32)[0]
            if trace is not None:
                import time as _t
                trace.append((0.0, nf, m8, _t.time()))
            if nf == 0 or m8 == 0:
                if float(pmin) >= big * (1 - 1e-6):
                    return val[:n], rounds   # no pending work anywhere
                # the device threshold always includes the minimum
                # value, so an empty round with pending work cannot
                # recur — guard fp corner-cases by escalating to plain
                quantile_mass = 0
                continue
            sig_q = (nf, m8, float(pmin))
            if sig_q == prev_sig:
                # two identical rounds = every member was fits-deferred
                # (pathological segment packing) — permanently fall
                # back to the vertex-range path, whose escalate
                # handling is proven
                quantile_mass = 0
                continue
            prev_sig = sig_q
            nseg = min(-(-m8 // budget), SLICE_K_MAX)
            # f bucket quantized to powers of FOUR: per-nf pow2 buckets
            # compiled a fresh kernel per distinct band size (measured
            # scale 26: seven one-call pushlist compiles at ~17s each
            # through the remote-compile tunnel — more compile than
            # push). A segment holds at most ~budget vertices.
            f_bucket = _quantize_cap(min(nf, budget + max_dc), qf_cap)
            for k in range(nseg):
                # +max_dc headroom: a vertex straddling the mass target
                # lands wholly in one segment (full segments then size
                # to exactly p_full — the budget is pre-shaved by
                # max_dc, see above)
                mass_k = min(budget, m8 - k * budget) + max_dc
                p_cap = _quantize_cap(mass_k, p_full)
                fk = min(f_bucket, p_cap)
                val, val_exp = pushl(
                    val, val_exp, flist, lbounds, dev_scalar(k),
                    thr_dev, dstT, colstart, degc, wp,
                    f_cap=fk, p_cap=p_cap, n_=n)
            rounds += 1
            continue
        be_dev = dev_scalar(bucket_end, dtname)
        plan, bounds_dev, thr_dev = wrapplan(
            val, val_exp, degc, be_dev, n_=n, k_max=SLICE_K_MAX,
            budget=budget)
        plan_h = np.asarray(plan)          # ONE sync per round
        nf, m8 = (int(x) for x in plan_h[:2])
        bounds = plan_h[2:2 + SLICE_K_MAX + 1]
        bmass = plan_h[3 + SLICE_K_MAX:3 + 2 * SLICE_K_MAX + 1]
        pmin = plan_h[-1].view(np.float32) if is_f32 else plan_h[-1]
        if trace is not None:
            import time as _t
            trace.append((float(bucket_end), nf, m8, _t.time()))
        if nf == 0 or m8 == 0:
            if float(pmin) >= big * (1 - 1e-6):
                return val[:n], rounds     # no pending work anywhere
            # bucket drained: advance to the minimum pending value's
            # bucket (strictly increases — pmin >= current bucket_end)
            bucket_end = float((np.floor(float(pmin) / delta) + 1)
                               * delta)
            continue
        # a round that changed NOTHING means every remaining member was
        # fits-deferred (its chunk range exceeded the tight p_cap) —
        # escalate to full-size kernels for one round
        sig = (nf, m8, float(pmin), float(bucket_end))
        escalate = sig == prev_sig
        prev_sig = sig
        for i in range(SLICE_K_MAX):
            vlo, vhi = int(bounds[i]), int(bounds[i + 1])
            # equal bounds = a >budget hub straddling the target (or
            # coverage exhausted); zero-mass slices carry no members
            if vhi <= vlo or int(bmass[i + 1]) == int(bmass[i]):
                continue
            # per-slice p_cap from the plan's mass column: a kernel
            # pays its FULL p_cap whether or not lanes are live
            # (measured 1.15s for a ZERO-mass 2^23 dispatch, 0.2s at
            # 2^18), so sparse slices get kernels sized to their mass.
            # No max_dc pad: a member whose chunks exceed p_cap is
            # fits-deferred, and the stall signature above escalates.
            mass_i = int(bmass[i + 1]) - int(bmass[i])
            p_cap = p_full if escalate \
                else _quantize_cap(mass_i, p_full)
            # device-side width split: sub index selects a width-window
            # of slice i, both from the scalar pool — no host puts
            for j in range((vhi - vlo + width - 1) // width):
                # quantile rounds never reach here (their branch ends
                # in `continue`; the stall fallback zeroes the mode)
                val, val_exp = push(
                    val, val_exp, bounds_dev, dev_scalar(i),
                    dev_scalar(j), be_dev, dstT, colstart, degc, wp,
                    f_cap=width, p_cap=p_cap, n_=n)
        rounds += 1
    return val[:n], rounds


def frontier_sssp(snap_or_graph, source_dense: int, min_w: float = 0.0,
                  w_range: float = 1.0, max_rounds: int = 10_000,
                  delta: float | None = None,
                  quantile_mass: int | None = None,
                  return_device: bool = False):
    """SSSP over hashed edge weights with an expansion-tracked frontier;
    ``delta`` > 0 adds delta-stepping buckets. Returns (dist float32 [n]
    with FINF unreachable, rounds).

    Default is NO buckets: on hub-dominated power-law graphs the
    shortest-path distances concentrate in a band narrower than any
    useful bucket width (measured scale-26 R-MAT: ~all mass lands in
    one bucket at delta=1/4 through 1/32, total relaxation mass floors
    at ~3.2x E/8 regardless), so buckets only add rounds — scale-26 on
    v5e: delta=0 270s/26 rounds vs delta=0.125 300s/64 rounds. On
    graphs with spread distance distributions (road networks, uniform
    meshes) pass delta ~ mean edge weight."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    if delta is None:
        delta = 0.0
    if quantile_mass is None:
        # default: priority-batched expansion at the measured-optimal
        # band mass (see QUANTILE_MASS_DEFAULT — 2x faster than the
        # plain improved-set frontier at scale 26). Pass 0 for the
        # plain expand-everything frontier, or delta>0 for
        # delta-stepping buckets (spread distance distributions).
        quantile_mass = 0 if delta and delta > 0 \
            else QUANTILE_MASS_DEFAULT
    val = jnp.full((n + 1,), FINF, jnp.float32).at[source_dense].set(0.0)
    # nothing has pushed yet: only the source reads as improved
    # (val < val_exp); unreached vertices sit at val == val_exp == FINF
    val_exp = jnp.full((n + 1,), FINF, jnp.float32)
    out, rounds = _frontier_run(g, val, val_exp, "sssp",
                                (min_w, w_range), max_rounds,
                                delta=delta, quantile_mass=quantile_mass)
    if not return_device:
        out = np.asarray(out)
    return out, rounds


def _wcc_seed_labels():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_",))
        def seed(dist, n_: int):
            """Label arrays from a finished BFS: the reached component
            collapses to its minimum vertex id (already expanded — a
            closed component never pushes again); the rest start at
            their own id, improved-state so round 1 expands them."""
            ids = jnp.arange(n_, dtype=jnp.int32)
            reached = dist[:n_] < INF
            rmin = jnp.min(jnp.where(reached, ids, IINF))
            lab = jnp.where(reached, rmin, ids)
            val = jnp.concatenate([lab, jnp.full((1,), IINF, jnp.int32)])
            exp = jnp.concatenate(
                [jnp.where(reached, lab, lab + 1),
                 jnp.full((1,), IINF, jnp.int32)])
            return val, exp
        return seed
    return jit_once("wcc_seed_labels", build)


def pagerank_dense(snap_or_graph, iterations: int = 20,
                   damping: float = 0.85, tol: float | None = None,
                   return_device: bool = False):
    """Push-mode PageRank over the chunked CSR via dense window sweeps:
    rank' = (1-d)/n + d * sum over in-edges of rank[src]/outdeg[src]
    (semantics match the pull-mode engine program in models/pagerank.py,
    incl. leaking dangling mass). Returns (rank float32 [n], iterations
    run). ``tol``: early exit when the L1 delta falls below it."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    dstT = g["dstT"]
    deg = g["deg"].astype(jnp.float32)
    colowner = _colowner(g)
    total = g["q_total"]
    W = min(DENSE_WINDOW, total)
    win = _pr_window()
    fin = _pr_finish()
    rank = jnp.full((n + 1,), 1.0 / n, jnp.float32) \
        .at[n].set(0.0)
    contrib = jnp.where(deg > 0, rank / jnp.maximum(deg, 1.0), 0.0)
    it = 0
    for it in range(1, iterations + 1):
        acc = jnp.zeros((n + 1,), jnp.float32)
        for w0 in range(0, total, W):
            # pooled window starts: a fresh scalar put per window costs
            # a tunnel round trip (64 windows/iteration at scale 26)
            acc = win(acc, contrib, dev_scalar(w0), dstT, colowner, W=W)
        rank, contrib, delta = fin(acc, rank, deg,
                                   jnp.float32(damping), n_=n)
        if tol is not None and float(delta) < tol:
            break
    out = rank[:n]
    if not return_device:
        out = np.asarray(out)
    return out, it


def _pr_window():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("W",),
                           donate_argnums=(0,))
        def step(acc, contrib, w0, dstT, colowner, W: int):
            # the final window's slice start gets clamped so it fits, which
            # OVERLAPS the previous window; scatter-ADD is not idempotent,
            # so already-processed columns must contribute exactly 0
            w0c = jnp.minimum(w0, colowner.shape[0] - W)
            owner = jax.lax.dynamic_slice(colowner, (w0c,), (W,))
            nbr = jax.lax.dynamic_slice(dstT, (0, w0c), (8, W))
            fresh = (w0c + jnp.arange(W, dtype=jnp.int32)) >= w0
            c = jnp.where(fresh, contrib[owner], 0.0)
            return acc.at[nbr].add(jnp.broadcast_to(c[None, :], nbr.shape),
                                   mode="drop")
        return step
    return jit_once("pagerank_window", build)


def _pr_finish():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_",))
        def fin(acc, rank, deg, damping, n_: int):
            new_rank = (1.0 - damping) / n_ + damping * acc[:n_]
            new_rank = jnp.concatenate(
                [new_rank, jnp.zeros((1,), jnp.float32)])
            delta = jnp.abs(new_rank[:n_] - rank[:n_]).sum()
            contrib = jnp.where(deg > 0, new_rank / jnp.maximum(deg, 1), 0.0)
            return new_rank, contrib, delta
        return fin
    return jit_once("pagerank_finish", build)


def frontier_wcc(snap_or_graph, max_rounds: int = 10_000,
                 return_device: bool = False):
    """Hybrid connected components (symmetrized graphs): peel the seed
    vertex's whole component with one direction-optimized BFS, then run
    min-label propagation over the remaining components only. Returns
    (label int32 [n] = component minimum vertex id, rounds) where
    rounds counts BFS levels + propagation rounds."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    if n == 0:
        out = jnp.zeros((0,), jnp.int32)
        return (out if return_device else np.asarray(out)), 0
    # seed at the max-degree vertex — on power-law graphs it anchors the
    # giant component, so the BFS peels ~all edge mass
    seed_v = int(np.asarray(jnp.argmax(g["deg"][:n])))
    # max_levels=n: a truncated BFS would freeze the partially-peeled
    # region as expanded, silently splitting its component's labels
    dist, levels = frontier_bfs_hybrid(g, seed_v, max_levels=n,
                                       return_device=True)
    # frontier_bfs_hybrid returns dist[:n]; the seeding jit re-appends
    # nothing — it only reads [:n_]
    val, val_exp = _wcc_seed_labels()(dist, n_=n)
    out, rounds = _frontier_run(g, val, val_exp, "wcc", (0.0, 0.0),
                                max_rounds)
    if not return_device:
        out = np.asarray(out)
    return out, rounds + levels
