"""Frontier-sparse (active-set) traversal kernels on the chunked CSR.

Generalizes the top-down machinery of ``bfs_hybrid`` to value-carrying
relaxations — the frontier-sparse analogs of the reference's OLAP
fixtures (reference: titan-test olap/ShortestDistanceVertexProgram for
SSSP, min-label propagation for connected components): instead of full
edge sweeps every superstep (O(E x rounds), the FulgoraGraphComputer
model), each round expands ONLY the vertices whose value changed in the
previous round, which bounds total work by the relaxation count.

* ``frontier_sssp`` — Bellman-Ford with an improvement frontier.
  Edge weights are derived ON DEVICE by hashing the edge slot id
  (uniform in [min_w, min_w+w_range)), so a scale-26 run needs no
  second 9GB weight array; ``slot_weights_np`` reproduces them on the
  host for verification.
* ``frontier_wcc`` — min-label propagation with an active set; on the
  symmetrized graph labels converge to per-component minima.

Both keep all state on device with one small stats readback per round
(axon-tunnel D2H is ~0.01 GB/s; see PERF_NOTES.md) and share the
chunked-CSR graph dict of ``bfs_hybrid`` (GraphSnapshot or
``graph500.to_device`` output).
"""

from __future__ import annotations

import functools

import numpy as np

from titan_tpu.models.bfs_hybrid import (build_chunked_csr,
                                         enumerate_chunk_pairs)
from titan_tpu.models.bfs import _next_pow2
from titan_tpu.utils.jitcache import jit_once

FINF = np.float32(3.0e38)
IINF = np.int32(1 << 30)


def _hash_weight_expr(slot, min_w: float, w_range: float):
    """uniform [min_w, min_w + w_range) from an int32 edge slot id
    (murmur-style integer mix, reproduced by slot_weights_np)."""
    import jax.numpy as jnp

    x = slot.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    u = (x & jnp.uint32(0xFFFFFF)).astype(jnp.float32) * (1.0 / (1 << 24))
    return min_w + w_range * u


def slot_weights_np(slots: np.ndarray, min_w: float = 0.0,
                    w_range: float = 1.0) -> np.ndarray:
    x = slots.astype(np.uint32)
    x = (x ^ (x >> np.uint32(16))) * np.uint32(0x7FEB352D)
    x = (x ^ (x >> np.uint32(15))) * np.uint32(0x846CA68B)
    x = x ^ (x >> np.uint32(16))
    u = (x & np.uint32(0xFFFFFF)).astype(np.float32) / np.float32(1 << 24)
    return (min_w + w_range * u).astype(np.float32)


# above this frontier chunk mass, rounds run as dense window sweeps.
# Both caps are sized so the [8, cap] working blocks (neighbors + message
# + weight-hash temporaries, ~4 of them) stay ~1GB: at scale 26 the graph
# itself holds 9GB of the 16GB HBM and the enumeration path OOMed with
# 2^25 pair caps.
DENSE_THRESHOLD_CHUNKS = 1 << 23
DENSE_WINDOW = 1 << 22


def _colowner(g):
    """column -> owning vertex map (lazy, cached in the graph dict):
    lets dense sweeps read contiguous column windows with no pair
    enumeration. Pad/sink columns own the sink vertex n."""
    import jax.numpy as jnp

    co = g.get("colowner")
    if co is None:
        n = g["n"]
        q_total = g["q_total"]
        # computed on device (jnp.repeat with a static total length) —
        # reading colstart back to build it on the host would D2H 268MB
        # at scale 26
        degc = g["degc"]
        ids = jnp.arange(n + 1, dtype=jnp.int32)
        owner = jnp.repeat(ids, degc, total_repeat_length=q_total - 1)
        co = jnp.concatenate([owner, jnp.full((1,), n, jnp.int32)])
        g["colowner"] = co
    return co


def _dense_step(kind: str):
    """One WINDOW of a dense sweep: relax every column in
    [w0, w0+W) whose owner improved last round. No readback."""
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("W", "n_"),
                           donate_argnums=(0,))
        def step(val, changed, w0, dstT, colowner, wparams, W: int,
                 n_: int):
            w0 = jnp.minimum(w0, colowner.shape[0] - W)
            owner = jax.lax.dynamic_slice(colowner, (w0,), (W,))
            nbr = jax.lax.dynamic_slice(dstT, (0, w0), (8, W))
            active = changed[owner]
            src_val = val[owner]
            if kind == "sssp":
                lane = jnp.arange(8, dtype=jnp.int32)[:, None]
                slot = (jnp.arange(W, dtype=jnp.int32) + w0)[None, :] * 8 \
                    + lane
                w = _hash_weight_expr(slot, wparams[0], wparams[1])
                msg = src_val[None, :] + w
            else:
                msg = jnp.broadcast_to(src_val[None, :], nbr.shape)
            big = jnp.asarray(FINF, val.dtype) if kind == "sssp" \
                else jnp.asarray(IINF, val.dtype)
            msg = jnp.where(active[None, :], msg, big)
            return val.at[nbr].min(msg, mode="drop")
        return step
    return jit_once(f"frontier_dense_{kind}", build)


def _dense_wrap(kind: str):
    """After a dense round's windows: the new changed mask + stats
    (frontier lists are built lazily when dropping back to the
    enumeration path)."""
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_",))
        def wrap(val, val_old, degc, n_: int):
            changed = val[:n_] < val_old[:n_]
            nf = changed.sum().astype(jnp.int32)
            m8 = jnp.where(changed, degc[:n_], 0).sum(dtype=jnp.int32)
            cmask = jnp.concatenate(
                [changed, jnp.zeros((1,), bool)])
            return cmask, jnp.stack([nf, m8])
        return wrap
    return jit_once(f"frontier_dense_wrap_{kind}", build)


def _frontier_list():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_", "cap"))
        def fl(cmask, n_: int, cap: int):
            ids = jnp.nonzero(cmask[:n_], size=n_, fill_value=n_)[0] \
                .astype(jnp.int32)
            if cap > n_:
                ids = jnp.concatenate(
                    [ids, jnp.full((cap - n_,), n_, jnp.int32)])
            return ids
        return fl
    return jit_once("frontier_list", build)


def _mask_from_list():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_",))
        def mk(frontier, f_count, n_: int):
            valid = jnp.arange(frontier.shape[0]) < f_count
            tgt = jnp.where(valid, jnp.minimum(frontier, n_), n_ + 1)
            return jnp.zeros((n_ + 1,), bool).at[tgt].set(
                True, mode="drop")
        return mk
    return jit_once("frontier_mask_from_list", build)


def _push_step(kind: str):
    """One frontier-push round: expand the frontier's chunks, relax
    min(value) into neighbors, return the new frontier (= improved
    vertices) + stats. kind: 'sssp' (float dist + hashed weights) or
    'wcc' (int label copy)."""
    return jit_once(f"frontier_push_{kind}", lambda: _build_push(kind))


def _build_push(kind: str):
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit,
                       static_argnames=("f_cap", "p_cap", "n_"),
                       donate_argnums=(0,))
    def push(val, frontier, f_count, dstT, colstart, degc, wparams,
             f_cap: int, p_cap: int, n_: int):
        valid = jnp.arange(f_cap) < f_count
        v = jnp.minimum(frontier, n_)
        cols, _, owner = enumerate_chunk_pairs(
            valid, degc[v], colstart[v], p_cap, dstT.shape[1] - 1,
            with_owner=True)
        src_val = val[v][owner]                       # [p_cap]
        nbr = jnp.take(dstT, cols, axis=1)            # [8, p_cap], pad n+1
        old = val
        if kind == "sssp":
            lane = jnp.arange(8, dtype=jnp.int32)[:, None]
            slot = cols[None, :] * 8 + lane
            w = _hash_weight_expr(slot, wparams[0], wparams[1])
            msg = src_val[None, :] + w
        else:
            msg = jnp.broadcast_to(src_val[None, :], nbr.shape)
        val = old.at[nbr].min(msg, mode="drop")
        changed = val[:n_] < old[:n_]
        nf = changed.sum().astype(jnp.int32)
        cap = _next_pow2(max(n_, 2))
        next_frontier = jnp.nonzero(
            changed, size=n_, fill_value=n_)[0].astype(jnp.int32)
        if cap > n_:
            next_frontier = jnp.concatenate(
                [next_frontier,
                 jnp.full((cap - n_,), n_, jnp.int32)])
        m8_next = jnp.where(changed, degc[:n_], 0).sum(dtype=jnp.int32)
        return val, next_frontier, jnp.stack([nf, m8_next])

    return push


def _frontier_run(snap_or_graph, val0, kind: str, wparams,
                  max_rounds: int):
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    dstT, colstart, degc = g["dstT"], g["colstart"], g["degc"]
    total_chunks = g["q_total"] - 1
    cap_n = _next_pow2(max(n, 2))
    push = _push_step(kind)
    dense = _dense_step(kind)
    dwrap = _dense_wrap(kind)
    flist = _frontier_list()
    val, frontier, f_count, m8_f, cmask = val0

    wp = jnp.asarray(np.asarray(wparams, np.float32))
    W = min(DENSE_WINDOW, _next_pow2(max(total_chunks, 2)))
    rounds = 0
    while f_count > 0 and m8_f > 0 and rounds < max_rounds:
        if m8_f > DENSE_THRESHOLD_CHUNKS and total_chunks + 1 >= W:
            # dense window sweep: contiguous column slices, activity
            # masked by last round's changed set, no pair enumeration
            colowner = _colowner(g)
            if cmask is None:    # entering dense mode from a list round
                cmask = _mask_from_list()(frontier, jnp.int32(f_count),
                                          n_=n)
            val_old = val + 0 if kind == "wcc" else val + 0.0
            for w0 in range(0, total_chunks + 1, W):
                val = dense(val, cmask, jnp.int32(w0), dstT, colowner,
                            wp, W=W, n_=n)
            cmask, st = dwrap(val, val_old, degc, n_=n)
            f_count, m8_f = (int(x) for x in np.asarray(st))
            frontier = None
        else:
            if frontier is None:     # dropping out of dense mode
                frontier = flist(cmask, n_=n, cap=cap_n)
            f_cap = min(_next_pow2(max(f_count, 2)), cap_n)
            p_cap = min(_next_pow2(max(m8_f, 2)),
                        _next_pow2(max(total_chunks + n, 2)))
            val, frontier, st = push(val, frontier[:f_cap],
                                     jnp.int32(f_count), dstT, colstart,
                                     degc, wp, f_cap=f_cap, p_cap=p_cap,
                                     n_=n)
            f_count, m8_f = (int(x) for x in np.asarray(st))
            cmask = None
        rounds += 1
    return val[:n], rounds


def frontier_sssp(snap_or_graph, source_dense: int, min_w: float = 0.0,
                  w_range: float = 1.0, max_rounds: int = 10_000,
                  return_device: bool = False):
    """Bellman-Ford SSSP with an improvement frontier over hashed edge
    weights. Returns (dist float32 [n] with FINF unreachable, rounds)."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    cap_n = _next_pow2(max(n, 2))
    val = jnp.full((n + 1,), FINF, jnp.float32).at[source_dense].set(0.0)
    frontier = jnp.full((cap_n,), n, jnp.int32).at[0].set(source_dense)
    m8 = int(np.asarray(g["degc"][source_dense]))
    out, rounds = _frontier_run(g, (val, frontier, 1, m8, None), "sssp",
                                (min_w, w_range), max_rounds)
    if not return_device:
        out = np.asarray(out)
    return out, rounds


def _pr_window():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("W",),
                           donate_argnums=(0,))
        def step(acc, contrib, w0, dstT, colowner, W: int):
            # the final window's slice start gets clamped so it fits, which
            # OVERLAPS the previous window; scatter-ADD is not idempotent,
            # so already-processed columns must contribute exactly 0
            w0c = jnp.minimum(w0, colowner.shape[0] - W)
            owner = jax.lax.dynamic_slice(colowner, (w0c,), (W,))
            nbr = jax.lax.dynamic_slice(dstT, (0, w0c), (8, W))
            fresh = (w0c + jnp.arange(W, dtype=jnp.int32)) >= w0
            c = jnp.where(fresh, contrib[owner], 0.0)
            return acc.at[nbr].add(jnp.broadcast_to(c[None, :], nbr.shape),
                                   mode="drop")
        return step
    return jit_once("pagerank_window", build)


def _pr_finish():
    def build():
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("n_",))
        def fin(acc, rank, deg, damping, n_: int):
            new_rank = (1.0 - damping) / n_ + damping * acc[:n_]
            new_rank = jnp.concatenate(
                [new_rank, jnp.zeros((1,), jnp.float32)])
            delta = jnp.abs(new_rank[:n_] - rank[:n_]).sum()
            contrib = jnp.where(deg > 0, new_rank / jnp.maximum(deg, 1), 0.0)
            return new_rank, contrib, delta
        return fin
    return jit_once("pagerank_finish", build)


def pagerank_dense(snap_or_graph, iterations: int = 20,
                   damping: float = 0.85, tol: float | None = None,
                   return_device: bool = False):
    """Push-mode PageRank over the chunked CSR via dense window sweeps:
    rank' = (1-d)/n + d * sum over in-edges of rank[src]/outdeg[src]
    (semantics match the pull-mode engine program in models/pagerank.py,
    incl. leaking dangling mass). Returns (rank float32 [n], iterations
    run). ``tol``: early exit when the L1 delta falls below it."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    dstT = g["dstT"]
    deg = g["deg"].astype(jnp.float32)
    colowner = _colowner(g)
    total = g["q_total"]
    W = min(DENSE_WINDOW, total)
    win = _pr_window()
    fin = _pr_finish()
    rank = jnp.full((n + 1,), 1.0 / n, jnp.float32) \
        .at[n].set(0.0)
    contrib = jnp.where(deg > 0, rank / jnp.maximum(deg, 1.0), 0.0)
    it = 0
    for it in range(1, iterations + 1):
        acc = jnp.zeros((n + 1,), jnp.float32)
        for w0 in range(0, total, W):
            acc = win(acc, contrib, jnp.int32(w0), dstT, colowner, W=W)
        rank, contrib, delta = fin(acc, rank, deg,
                                   jnp.float32(damping), n_=n)
        if tol is not None and float(delta) < tol:
            break
    out = rank[:n]
    if not return_device:
        out = np.asarray(out)
    return out, it


def frontier_wcc(snap_or_graph, max_rounds: int = 10_000,
                 return_device: bool = False):
    """Min-label propagation with an active set (symmetrized graphs).
    Returns (label int32 [n] = component minimum vertex id, rounds)."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    cap_n = _next_pow2(max(n, 2))
    # labels live in [0, n); the sink slot n stays at IINF
    val = jnp.concatenate([jnp.arange(n, dtype=jnp.int32),
                           jnp.full((1,), IINF, jnp.int32)])
    frontier = jnp.concatenate(
        [jnp.arange(n, dtype=jnp.int32),
         jnp.full((cap_n - n,), n, jnp.int32)]) if cap_n > n \
        else jnp.arange(cap_n, dtype=jnp.int32)
    cmask = jnp.concatenate([jnp.ones((n,), bool),
                             jnp.zeros((1,), bool)])
    total_chunks = int(g["q_total"]) - 1
    out, rounds = _frontier_run(g, (val, frontier, n, total_chunks, cmask),
                                "wcc", (0.0, 0.0), max_rounds)
    if not return_device:
        out = np.asarray(out)
    return out, rounds
