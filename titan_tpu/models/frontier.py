"""Frontier-sparse (active-set) traversal kernels on the chunked CSR.

Generalizes the top-down machinery of ``bfs_hybrid`` to value-carrying
relaxations — the frontier-sparse analogs of the reference's OLAP
fixtures (reference: titan-test olap/ShortestDistanceVertexProgram for
SSSP, min-label propagation for connected components): instead of full
edge sweeps every superstep (O(E x rounds), the FulgoraGraphComputer
model), each round expands ONLY the vertices whose value changed in the
previous round, which bounds total work by the relaxation count.

* ``frontier_sssp`` — Bellman-Ford with an improvement frontier.
  Edge weights are derived ON DEVICE by hashing the edge slot id
  (uniform in [min_w, min_w+w_range)), so a scale-26 run needs no
  second 9GB weight array; ``slot_weights_np`` reproduces them on the
  host for verification.
* ``frontier_wcc`` — min-label propagation with an active set; on the
  symmetrized graph labels converge to per-component minima.

Both keep all state on device with one small stats readback per round
(axon-tunnel D2H is ~0.01 GB/s; see PERF_NOTES.md) and share the
chunked-CSR graph dict of ``bfs_hybrid`` (GraphSnapshot or
``graph500.to_device`` output).
"""

from __future__ import annotations

import functools

import numpy as np

from titan_tpu.models.bfs_hybrid import (build_chunked_csr,
                                         enumerate_chunk_pairs)
from titan_tpu.models.bfs import _next_pow2
from titan_tpu.utils.jitcache import jit_once

FINF = np.float32(3.0e38)
IINF = np.int32(1 << 30)


def _hash_weight_expr(slot, min_w: float, w_range: float):
    """uniform [min_w, min_w + w_range) from an int32 edge slot id
    (murmur-style integer mix, reproduced by slot_weights_np)."""
    import jax.numpy as jnp

    x = slot.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    u = (x & jnp.uint32(0xFFFFFF)).astype(jnp.float32) * (1.0 / (1 << 24))
    return min_w + w_range * u


def slot_weights_np(slots: np.ndarray, min_w: float = 0.0,
                    w_range: float = 1.0) -> np.ndarray:
    x = slots.astype(np.uint32)
    x = (x ^ (x >> np.uint32(16))) * np.uint32(0x7FEB352D)
    x = (x ^ (x >> np.uint32(15))) * np.uint32(0x846CA68B)
    x = x ^ (x >> np.uint32(16))
    u = (x & np.uint32(0xFFFFFF)).astype(np.float32) / np.float32(1 << 24)
    return (min_w + w_range * u).astype(np.float32)


def _push_step(kind: str):
    """One frontier-push round: expand the frontier's chunks, relax
    min(value) into neighbors, return the new frontier (= improved
    vertices) + stats. kind: 'sssp' (float dist + hashed weights) or
    'wcc' (int label copy)."""
    return jit_once(f"frontier_push_{kind}", lambda: _build_push(kind))


def _build_push(kind: str):
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit,
                       static_argnames=("f_cap", "p_cap", "n_"),
                       donate_argnums=(0,))
    def push(val, frontier, f_count, dstT, colstart, degc, wparams,
             f_cap: int, p_cap: int, n_: int):
        valid = jnp.arange(f_cap) < f_count
        v = jnp.minimum(frontier, n_)
        cols, _, owner = enumerate_chunk_pairs(
            valid, degc[v], colstart[v], p_cap, dstT.shape[1] - 1,
            with_owner=True)
        src_val = val[v][owner]                       # [p_cap]
        nbr = jnp.take(dstT, cols, axis=1)            # [8, p_cap], pad n+1
        old = val
        if kind == "sssp":
            lane = jnp.arange(8, dtype=jnp.int32)[:, None]
            slot = cols[None, :] * 8 + lane
            w = _hash_weight_expr(slot, wparams[0], wparams[1])
            msg = src_val[None, :] + w
        else:
            msg = jnp.broadcast_to(src_val[None, :], nbr.shape)
        val = old.at[nbr].min(msg, mode="drop")
        changed = val[:n_] < old[:n_]
        nf = changed.sum().astype(jnp.int32)
        cap = _next_pow2(max(n_, 2))
        next_frontier = jnp.nonzero(
            changed, size=n_, fill_value=n_)[0].astype(jnp.int32)
        if cap > n_:
            next_frontier = jnp.concatenate(
                [next_frontier,
                 jnp.full((cap - n_,), n_, jnp.int32)])
        m8_next = jnp.where(changed, degc[:n_], 0).sum(dtype=jnp.int32)
        return val, next_frontier, jnp.stack([nf, m8_next])

    return push


def _frontier_run(snap_or_graph, val0, kind: str, wparams,
                  max_rounds: int):
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    dstT, colstart, degc = g["dstT"], g["colstart"], g["degc"]
    total_chunks = g["q_total"] - 1
    cap_n = _next_pow2(max(n, 2))
    push = _push_step(kind)
    val, frontier, f_count, m8_f = val0

    wp = jnp.asarray(np.asarray(wparams, np.float32))
    rounds = 0
    while f_count > 0 and m8_f > 0 and rounds < max_rounds:
        f_cap = min(_next_pow2(max(f_count, 2)), cap_n)
        p_cap = min(_next_pow2(max(m8_f, 2)),
                    _next_pow2(max(total_chunks + n, 2)))
        val, frontier, st = push(val, frontier[:f_cap],
                                 jnp.int32(f_count), dstT, colstart, degc,
                                 wp, f_cap=f_cap, p_cap=p_cap, n_=n)
        f_count, m8_f = (int(x) for x in np.asarray(st))
        rounds += 1
    return val[:n], rounds


def frontier_sssp(snap_or_graph, source_dense: int, min_w: float = 0.0,
                  w_range: float = 1.0, max_rounds: int = 10_000,
                  return_device: bool = False):
    """Bellman-Ford SSSP with an improvement frontier over hashed edge
    weights. Returns (dist float32 [n] with FINF unreachable, rounds)."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    cap_n = _next_pow2(max(n, 2))
    val = jnp.full((n + 1,), FINF, jnp.float32).at[source_dense].set(0.0)
    frontier = jnp.full((cap_n,), n, jnp.int32).at[0].set(source_dense)
    m8 = int(np.asarray(g["degc"][source_dense]))
    out, rounds = _frontier_run(g, (val, frontier, 1, m8), "sssp",
                                (min_w, w_range), max_rounds)
    if not return_device:
        out = np.asarray(out)
    return out, rounds


def frontier_wcc(snap_or_graph, max_rounds: int = 10_000,
                 return_device: bool = False):
    """Min-label propagation with an active set (symmetrized graphs).
    Returns (label int32 [n] = component minimum vertex id, rounds)."""
    import jax.numpy as jnp

    g = snap_or_graph if isinstance(snap_or_graph, dict) \
        else build_chunked_csr(snap_or_graph)
    n = g["n"]
    cap_n = _next_pow2(max(n, 2))
    # labels live in [0, n); the sink slot n stays at IINF
    val = jnp.concatenate([jnp.arange(n, dtype=jnp.int32),
                           jnp.full((1,), IINF, jnp.int32)])
    frontier = jnp.concatenate(
        [jnp.arange(n, dtype=jnp.int32),
         jnp.full((cap_n - n,), n, jnp.int32)]) if cap_n > n \
        else jnp.arange(cap_n, dtype=jnp.int32)
    total_chunks = int(g["q_total"]) - 1
    out, rounds = _frontier_run(g, (val, frontier, n, total_chunks), "wcc",
                                (0.0, 0.0), max_rounds)
    if not return_device:
        out = np.asarray(out)
    return out, rounds
