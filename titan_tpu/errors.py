"""Exception taxonomy for titan_tpu.

Mirrors the capability of the reference's two-tier backend exception model
(reference: titan-core diskstorage/TemporaryBackendException.java,
PermanentBackendException.java) plus graph-level errors: temporary errors are
retried with backoff by the backend-operation executor
(storage/tx.py:backend_op); permanent errors escalate immediately.
"""

from __future__ import annotations


class TitanError(Exception):
    """Root of all titan_tpu errors."""


class ConfigurationError(TitanError):
    """Invalid or unsupported configuration (bad backend name, option
    value out of range, mutually exclusive settings)."""


# ---------------------------------------------------------------------------
# storage plane
# ---------------------------------------------------------------------------

class BackendError(TitanError):
    """Any error raised by the storage/index plane."""


class TemporaryBackendError(BackendError):
    """Transient failure (timeouts, contention); safe to retry with backoff."""


class PermanentBackendError(BackendError):
    """Non-retriable failure (corruption, misconfiguration, unsupported op)."""


class TemporaryLockingError(TemporaryBackendError):
    """Lock could not be acquired right now (held by someone else)."""


class PermanentLockingError(PermanentBackendError):
    """Lock protocol failed irrecoverably (e.g. expected-value mismatch)."""


class IDPoolExhaustedError(TemporaryBackendError):
    """An id partition/namespace ran out of allocatable blocks."""


# ---------------------------------------------------------------------------
# graph plane
# ---------------------------------------------------------------------------

class InvalidIDError(TitanError):
    """Element id does not satisfy the bit-layout contract (ids/idmanager.py)."""


class InvalidElementError(TitanError):
    """Operation on a removed or foreign element."""

    def __init__(self, msg: str, element=None):
        super().__init__(msg)
        self.element = element


class SchemaViolationError(TitanError):
    """Operation violates a schema constraint (cardinality, multiplicity, ...)."""


class SchemaNameExistsError(SchemaViolationError):
    """A schema element with this name already exists (possibly created by
    a racing transaction or peer instance)."""


class QueryError(TitanError):
    """Malformed or unsupported query."""


class TransactionClosedError(TitanError):
    """Operation on a committed/rolled-back transaction."""
