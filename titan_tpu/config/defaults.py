"""The root option tree.

Counterpart of the reference's GraphDatabaseConfiguration option declarations
(reference: titan-core graphdb/configuration/GraphDatabaseConfiguration.java:85-1275,
~200 options across the root/storage/cache/ids/index/cluster/log/metrics
namespaces). Backend-specific options are declared here too for the built-in
backends; external adapter modules may attach their own namespaces at import
time (the tree is a live registry, like the reference's
ReflectiveConfigOptionLoader auto-discovery).
"""

from __future__ import annotations

from titan_tpu.config.options import (ConfigNamespace, ConfigOption, Mutability,
                                      non_negative, one_of, positive)

ROOT = ConfigNamespace(None, "root", "titan_tpu root namespace")

# --- graph ------------------------------------------------------------------
GRAPH_NS = ConfigNamespace(ROOT, "graph", "general graph options")
UNIQUE_INSTANCE_ID = ConfigOption(
    GRAPH_NS, "unique-instance-id",
    "unique id of this graph instance within the cluster; auto-generated when unset",
    str, None, Mutability.LOCAL)
ALLOW_SETTING_VERTEX_ID = ConfigOption(
    GRAPH_NS, "set-vertex-id", "allow users to supply vertex ids",
    bool, False, Mutability.FIXED)
TIMESTAMP_PROVIDER = ConfigOption(
    GRAPH_NS, "timestamps", "clock resolution for backend timestamps",
    str, "micro", Mutability.FIXED, one_of("nano", "micro", "milli"))

# --- cluster ----------------------------------------------------------------
CLUSTER_NS = ConfigNamespace(ROOT, "cluster", "cluster-wide data layout")
MAX_PARTITIONS = ConfigOption(
    CLUSTER_NS, "max-partitions",
    "number of virtual partitions vertex ids are spread over; must be a power "
    "of 2; equals the maximum useful TPU shard count for the OLAP engine",
    int, 32, Mutability.FIXED, lambda v: v > 0 and (v & (v - 1)) == 0)
PARTITIONED_VERTICES = ConfigOption(
    CLUSTER_NS, "partition", "enable partitioned (vertex-cut) vertex labels",
    bool, False, Mutability.FIXED)

# --- storage ----------------------------------------------------------------
STORAGE_NS = ConfigNamespace(ROOT, "storage", "storage backend")
STORAGE_BACKEND = ConfigOption(
    STORAGE_NS, "backend",
    "storage backend shorthand or import path (shorthands: inmemory, sqlite)",
    str, None, Mutability.LOCAL)
STORAGE_DIRECTORY = ConfigOption(
    STORAGE_NS, "directory", "data directory for local backends",
    str, None, Mutability.LOCAL)
STORAGE_HOSTNAME = ConfigOption(
    STORAGE_NS, "hostname", "comma-separated backend hosts",
    list, [], Mutability.LOCAL)
STORAGE_PORT = ConfigOption(STORAGE_NS, "port", "backend port", int, None, Mutability.LOCAL)
STORAGE_READONLY = ConfigOption(STORAGE_NS, "read-only", "open read-only",
                                bool, False, Mutability.LOCAL)
STORAGE_BATCH = ConfigOption(
    STORAGE_NS, "batch-loading", "bulk-load mode: disables locking and "
    "consistency checks for ingest", bool, False, Mutability.LOCAL)
STORAGE_TRANSACTIONAL = ConfigOption(
    STORAGE_NS, "transactions", "use backend transactions when available",
    bool, True, Mutability.MASKABLE)
BUFFER_SIZE = ConfigOption(
    STORAGE_NS, "buffer-size", "mutations buffered per backend flush",
    int, 1024, Mutability.MASKABLE, positive)
WRITE_ATTEMPTS = ConfigOption(
    STORAGE_NS, "write-attempts", "max retries for backend writes",
    int, 5, Mutability.MASKABLE, positive)
READ_ATTEMPTS = ConfigOption(
    STORAGE_NS, "read-attempts", "max retries for backend reads",
    int, 3, Mutability.MASKABLE, positive)
STORAGE_ATTEMPT_WAIT_MS = ConfigOption(
    STORAGE_NS, "attempt-wait", "initial backoff between retries (ms)",
    int, 250, Mutability.MASKABLE, non_negative)
PARALLEL_BACKEND_OPS = ConfigOption(
    STORAGE_NS, "parallel-backend-ops", "execute multi-key slices on a host pool",
    bool, True, Mutability.MASKABLE)

CLUSTER_NS = ConfigNamespace(
    STORAGE_NS, "cluster", "remote-cluster backend (sharded + replicated "
    "storage nodes; reference role: the Cassandra/HBase cluster itself)")
CLUSTER_REPLICATION = ConfigOption(
    CLUSTER_NS, "replication-factor",
    "copies of each key across storage nodes", int, 1,
    Mutability.GLOBAL_OFFLINE, positive)
CLUSTER_WRITE_CONSISTENCY = ConfigOption(
    CLUSTER_NS, "write-consistency",
    "acks required per write: all | quorum | one", str, "all",
    Mutability.MASKABLE,
    lambda v: v in ("all", "quorum", "one"))
CLUSTER_VNODES = ConfigOption(
    CLUSTER_NS, "virtual-nodes", "hash-ring virtual nodes per storage node",
    int, 64, Mutability.GLOBAL_OFFLINE, positive)
CLUSTER_READ_REPAIR = ConfigOption(
    CLUSTER_NS, "read-repair",
    "chance per read of a full-replica merge + write-back of stale cells "
    "under write-consistency=all (quorum/one always merge-read)",
    float, 0.1, Mutability.MASKABLE, lambda v: 0.0 <= v <= 1.0)
CLUSTER_MAX_HINTS = ConfigOption(
    CLUSTER_NS, "max-hints-per-peer",
    "hinted-handoff queue cap per down peer; overflow converges via "
    "merged reads + the next anti-entropy pass", int, 50_000,
    Mutability.MASKABLE, positive)
CLUSTER_TIMEOUT = ConfigOption(
    CLUSTER_NS, "request-timeout-s",
    "socket timeout applied to EVERY storage-node RPC (reads, "
    "mutations, probes) on remote and remote-cluster backends", float,
    30.0, Mutability.MASKABLE, positive)
CLUSTER_COMPACTION_INTERVAL = ConfigOption(
    CLUSTER_NS, "compaction-interval-s",
    "period of the background anti-entropy + tombstone-GC daemon "
    "(0 disables; cycles are skipped while a replica is down or hints "
    "are undelivered — the Cassandra scheduled repair/compaction role)",
    float, 0.0, Mutability.MASKABLE, lambda v: v >= 0.0)
CLUSTER_GC_GRACE = ConfigOption(
    CLUSTER_NS, "gc-grace-seconds",
    "minimum tombstone age before the compaction daemon may purge it "
    "(Cassandra gc_grace_seconds role)", float, 86400.0,
    Mutability.MASKABLE, lambda v: v >= 0.0)

SCAN_NS = ConfigNamespace(STORAGE_NS, "scan", "backend scan framework")
SCAN_THREADS = ConfigOption(
    SCAN_NS, "threads", "processor threads per scan job", int, 4,
    Mutability.MASKABLE, positive)
SCAN_QUEUE_SIZE = ConfigOption(
    SCAN_NS, "queue-size", "bounded row-queue capacity between the data "
    "puller and the processors", int, 1024, Mutability.MASKABLE, positive)
SCAN_BLOCK_SIZE = ConfigOption(
    SCAN_NS, "block-size", "rows per processor progress block", int, 1000,
    Mutability.MASKABLE, positive)

LOCK_NS = ConfigNamespace(STORAGE_NS, "lock", "distributed locking")
LOCK_RETRIES = ConfigOption(LOCK_NS, "retries", "lock-claim write retries",
                            int, 3, Mutability.MASKABLE, positive)
LOCK_WAIT_MS = ConfigOption(
    LOCK_NS, "wait-time", "ms to wait for a lock claim to become visible; must "
    "exceed worst-case clock skew + write latency", int, 100,
    Mutability.GLOBAL_OFFLINE, positive)
LOCK_EXPIRY_MS = ConfigOption(
    LOCK_NS, "expiry-time", "ms after which an unreleased lock claim is stale",
    int, 300_000, Mutability.GLOBAL_OFFLINE, positive)
LOCK_CLEAN_EXPIRED = ConfigOption(
    LOCK_NS, "clean-expired", "background-delete expired lock claims",
    bool, False, Mutability.MASKABLE)
LOCK_LOCAL_MEDIATOR_GROUP = ConfigOption(
    LOCK_NS, "local-mediator-group",
    "processes sharing a mediator group arbitrate locks in-process first",
    str, None, Mutability.LOCAL)

# --- ids --------------------------------------------------------------------
IDS_NS = ConfigNamespace(ROOT, "ids", "id allocation")
IDS_BLOCK_SIZE = ConfigOption(
    IDS_NS, "block-size", "ids claimed per allocation block; raise for ingest",
    int, 10_000, Mutability.GLOBAL_OFFLINE, positive)
IDS_RENEW_TIMEOUT_MS = ConfigOption(
    IDS_NS, "renew-timeout", "ms to keep trying to claim an id block",
    int, 120_000, Mutability.MASKABLE, positive)
IDS_RENEW_PERCENTAGE = ConfigOption(
    IDS_NS, "renew-percentage", "fraction of the current block left when "
    "background renewal starts", float, 0.3, Mutability.MASKABLE,
    lambda v: 0.01 <= v <= 1.0)
IDS_PLACEMENT = ConfigOption(
    IDS_NS, "placement", "partition placement strategy (simple|property)",
    str, "simple", Mutability.MASKABLE)
IDS_FLUSH = ConfigOption(
    IDS_NS, "flush", "assign ids immediately on element creation instead of "
    "at commit", bool, True, Mutability.MASKABLE)
IDS_AUTHORITY_NS = ConfigNamespace(IDS_NS, "authority", "id authority protocol")
IDAUTH_WAIT_MS = ConfigOption(
    IDS_AUTHORITY_NS, "wait-time",
    "ms a claim must remain uncontested before an id block is owned",
    int, 300, Mutability.GLOBAL_OFFLINE, positive)
IDAUTH_CONFLICT_AVOIDANCE = ConfigOption(
    IDS_AUTHORITY_NS, "conflict-avoidance-mode",
    "NONE | GLOBAL_AUTO (randomized uniqueid per claim attempt)",
    str, "NONE", Mutability.GLOBAL_OFFLINE, one_of("NONE", "GLOBAL_AUTO"))

# --- cache ------------------------------------------------------------------
CACHE_NS = ConfigNamespace(ROOT, "cache", "database-level store cache")
DB_CACHE = ConfigOption(CACHE_NS, "db-cache", "enable the backend read cache",
                        bool, False, Mutability.MASKABLE)
DB_CACHE_SIZE = ConfigOption(
    CACHE_NS, "db-cache-size", "cache size: entries (>1) ",
    int, 200_000, Mutability.MASKABLE, positive)
DB_CACHE_TIME_MS = ConfigOption(
    CACHE_NS, "db-cache-time", "expiration ms for cached slices (0=never)",
    int, 10_000, Mutability.GLOBAL_OFFLINE, non_negative)
DB_CACHE_CLEAN_WAIT_MS = ConfigOption(
    CACHE_NS, "db-cache-clean-wait",
    "ms a dirty key stays blacklisted after invalidation",
    int, 50, Mutability.GLOBAL_OFFLINE, non_negative)
TX_CACHE_SIZE = ConfigOption(
    CACHE_NS, "tx-cache-size", "per-transaction vertex cache size",
    int, 20_000, Mutability.MASKABLE, positive)
TX_DIRTY_SIZE = ConfigOption(
    CACHE_NS, "tx-dirty-size", "initial sizing for per-tx dirty sets",
    int, 32, Mutability.MASKABLE, positive)

# --- index (umbrella: index.<name>.*) ---------------------------------------
INDEX_NS = ConfigNamespace(ROOT, "index", "mixed index providers", umbrella=True)
INDEX_BACKEND = ConfigOption(
    INDEX_NS, "backend", "index backend shorthand or import path "
    "(shorthands: memindex)", str, "memindex", Mutability.GLOBAL_OFFLINE)
INDEX_DIRECTORY = ConfigOption(INDEX_NS, "directory", "index data directory",
                               str, None, Mutability.MASKABLE)
INDEX_HOSTNAME = ConfigOption(INDEX_NS, "hostname", "index hosts", list, [],
                              Mutability.MASKABLE)
INDEX_PORT = ConfigOption(INDEX_NS, "port", "index node port", int, None,
                          Mutability.MASKABLE)
INDEX_MAX_RESULT_SET = ConfigOption(
    INDEX_NS, "max-result-set-size", "cap on index result sets", int, 100_000,
    Mutability.MASKABLE, positive)

# --- log (umbrella: log.<name>.*) -------------------------------------------
LOG_NS = ConfigNamespace(ROOT, "log", "KCVS log bus (TitanBus analog)", umbrella=True)
LOG_BACKEND = ConfigOption(LOG_NS, "backend", "log implementation", str,
                           "default", Mutability.GLOBAL_OFFLINE)
LOG_NUM_BUCKETS = ConfigOption(
    LOG_NS, "num-buckets", "write parallelism buckets per partition", int, 1,
    Mutability.GLOBAL_OFFLINE, positive)
LOG_SEND_DELAY_MS = ConfigOption(
    LOG_NS, "send-delay", "ms messages may linger in the outgoing buffer",
    int, 1000, Mutability.MASKABLE, non_negative)
LOG_SEND_BATCH = ConfigOption(
    LOG_NS, "send-batch-size", "max messages per outgoing batch", int, 256,
    Mutability.MASKABLE, positive)
LOG_READ_INTERVAL_MS = ConfigOption(
    LOG_NS, "read-interval", "poll interval for log readers (ms)", int, 500,
    Mutability.MASKABLE, positive)
LOG_READ_BATCH = ConfigOption(
    LOG_NS, "read-batch-size", "max messages per read poll", int, 1024,
    Mutability.MASKABLE, positive)
LOG_TTL_S = ConfigOption(
    LOG_NS, "ttl", "seconds log entries are retained (0 = forever)", int, 0,
    Mutability.GLOBAL, non_negative)

# --- tx ---------------------------------------------------------------------
TX_NS = ConfigNamespace(ROOT, "tx", "transaction handling")
LOG_TX = ConfigOption(
    TX_NS, "log-tx", "write a WAL record for every transaction into the "
    "tx log for recovery", bool, False, Mutability.GLOBAL)
TX_LOG_NAME = ConfigOption(TX_NS, "log-name", "name of the WAL log", str,
                           "txlog", Mutability.GLOBAL_OFFLINE)
TX_RECOVERY_INTERVAL_MS = ConfigOption(
    TX_NS, "recovery-interval", "how far behind the recovery reader starts",
    int, 10_000, Mutability.MASKABLE, positive)

# --- query ------------------------------------------------------------------
QUERY_NS = ConfigNamespace(ROOT, "query", "query execution")
FORCE_INDEX = ConfigOption(
    QUERY_NS, "force-index", "refuse graph queries that would full-scan",
    bool, False, Mutability.MASKABLE)
QUERY_BATCH = ConfigOption(
    QUERY_NS, "batch", "batch multi-vertex backend retrievals", bool, True,
    Mutability.MASKABLE)
SMART_LIMIT = ConfigOption(
    QUERY_NS, "smart-limit", "guess small limits for interactive queries",
    bool, True, Mutability.MASKABLE)
FAST_PROPERTY = ConfigOption(
    QUERY_NS, "fast-property",
    "prefetch all properties on first single-property access",
    bool, True, Mutability.MASKABLE)
TRAVERSAL_BATCH = ConfigOption(
    QUERY_NS, "traversal-batch",
    "vertices per batched multi-vertex adjacency fetch in the traversal "
    "engine (the multiQuery batch width)", int, 512,
    Mutability.MASKABLE, positive)
BARRIER_SIZE = ConfigOption(
    QUERY_NS, "barrier-size",
    "bulking-barrier chunk — TP3 LazyBarrierStrategy's max barrier size "
    "(bounds how much laziness a barrier may consume)", int, 2500,
    Mutability.MASKABLE, positive)

# --- metrics ----------------------------------------------------------------
METRICS_NS = ConfigNamespace(ROOT, "metrics", "metrics collection")
BASIC_METRICS = ConfigOption(METRICS_NS, "enabled", "collect per-op metrics",
                             bool, False, Mutability.MASKABLE)
METRICS_PREFIX = ConfigOption(METRICS_NS, "prefix", "metric name prefix", str,
                              "titan_tpu", Mutability.MASKABLE)
# periodic background reporters (reference: per-reporter config
# namespaces metrics.console/csv/ganglia/graphite with intervals,
# GraphDatabaseConfiguration.java:1010-1226); interval 0 = reporter off
METRICS_CONSOLE_NS = ConfigNamespace(METRICS_NS, "console",
                                     "console metrics reporter")
METRICS_CONSOLE_INTERVAL = ConfigOption(
    METRICS_CONSOLE_NS, "interval-s",
    "seconds between console metric reports (0 = off)", float, 0.0,
    Mutability.MASKABLE, non_negative)
METRICS_CSV_NS = ConfigNamespace(METRICS_NS, "csv",
                                 "CSV metrics reporter")
METRICS_CSV_INTERVAL = ConfigOption(
    METRICS_CSV_NS, "interval-s",
    "seconds between CSV metric snapshots (0 = off)", float, 0.0,
    Mutability.MASKABLE, non_negative)
METRICS_CSV_DIR = ConfigOption(
    METRICS_CSV_NS, "directory",
    "directory for timestamped CSV metric snapshots", str, "metrics-csv",
    Mutability.MASKABLE)
METRICS_GRAPHITE_NS = ConfigNamespace(METRICS_NS, "graphite",
                                      "Graphite/Carbon metrics reporter")
METRICS_GRAPHITE_INTERVAL = ConfigOption(
    METRICS_GRAPHITE_NS, "interval-s",
    "seconds between Graphite pushes (0 = off)", float, 0.0,
    Mutability.MASKABLE, non_negative)
METRICS_GRAPHITE_HOST = ConfigOption(
    METRICS_GRAPHITE_NS, "host", "Graphite/Carbon plaintext host", str,
    "localhost", Mutability.MASKABLE)
METRICS_GRAPHITE_PORT = ConfigOption(
    METRICS_GRAPHITE_NS, "port", "Graphite/Carbon plaintext port", int,
    2003, Mutability.MASKABLE, positive)

# --- computer / TPU OLAP -----------------------------------------------------
COMPUTER_NS = ConfigNamespace(ROOT, "computer", "OLAP graph computer")
COMPUTER_BACKEND = ConfigOption(
    COMPUTER_NS, "backend", "graph computer: host (thread-pool scan executor) "
    "or tpu (sharded-CSR superstep engine)", str, "tpu", Mutability.MASKABLE,
    one_of("host", "tpu"))
COMPUTER_THREADS = ConfigOption(
    COMPUTER_NS, "threads", "host computer worker threads (0 = n_cpus)", int,
    0, Mutability.MASKABLE, non_negative)
TPU_NS = ConfigNamespace(COMPUTER_NS, "tpu", "TPU engine tuning")
TPU_MESH_SHAPE = ConfigOption(
    TPU_NS, "mesh", "device mesh size over the vertex axis (0 = all devices)",
    int, 0, Mutability.MASKABLE, non_negative)
TPU_EDGE_BLOCK = ConfigOption(
    TPU_NS, "edge-block-size", "edges per scan block when building snapshots",
    int, 1 << 20, Mutability.MASKABLE, positive)
TPU_DTYPE = ConfigOption(
    TPU_NS, "value-dtype", "dtype for dense vertex state (bfloat16|float32)",
    str, "float32", Mutability.MASKABLE, one_of("bfloat16", "float32"))
TPU_CHANGE_BACKLOG = ConfigOption(
    TPU_NS, "change-backlog",
    "commits a snapshot's delta listener may buffer before declaring "
    "overflow (a rebuild is then required instead of refresh())", int,
    10_000, Mutability.MASKABLE, positive)
# keep config a LEAF module: core.changes keeps its own copy of this
# default; the pairing is pinned by
# tests/test_config.py::test_change_backlog_default_single_source
