"""Configuration views over raw key→value data.

Re-creation of the reference's BasicConfiguration / ModifiableConfiguration /
MergedConfiguration stack (reference: titan-core
diskstorage/configuration/BasicConfiguration.java,
ModifiableConfiguration.java, MergedConfiguration.java): a read view binds a
raw dotted-path→value mapping to the typed option tree and enforces
restrictions (a GLOBAL-restricted view refuses LOCAL options and vice versa);
a modifiable view additionally enforces mutability on ``set``.

The cluster-global configuration that the reference stores *inside* the
storage backend (KCVSConfiguration over the ``system_properties`` store,
Backend.java:273-298) is provided by storage/config_store.py using the same
ReadConfiguration/WriteConfiguration contracts defined here.
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Iterable, Iterator, Optional

from titan_tpu.config.options import (ConfigNamespace, ConfigOption, Mutability, SEPARATOR)


class ReadConfiguration:
    """Raw read view: dotted path → value (strings allowed, coerced later)."""

    def get(self, key: str) -> Any:
        raise NotImplementedError

    def keys(self, prefix: str = "") -> Iterable[str]:
        raise NotImplementedError


class WriteConfiguration(ReadConfiguration):
    def set(self, key: str, value: Any) -> None:
        raise NotImplementedError

    def remove(self, key: str) -> None:
        raise NotImplementedError


class MapConfiguration(WriteConfiguration):
    """Dict-backed raw configuration (thread-safe)."""

    def __init__(self, data: Optional[dict] = None):
        self._data = dict(data or {})
        self._lock = threading.RLock()

    def get(self, key: str) -> Any:
        with self._lock:
            return self._data.get(key)

    def keys(self, prefix: str = "") -> list[str]:
        with self._lock:
            return [k for k in self._data if k.startswith(prefix)]

    def set(self, key: str, value: Any) -> None:
        with self._lock:
            self._data[key] = value

    def remove(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)

    def as_dict(self) -> dict:
        with self._lock:
            return dict(self._data)


class Restriction(enum.Enum):
    NONE = "NONE"      # accept any option
    LOCAL = "LOCAL"    # only LOCAL/MASKABLE options visible
    GLOBAL = "GLOBAL"  # only GLOBAL* / FIXED options visible


class Configuration:
    """Typed read view over a ReadConfiguration bound to an option tree root."""

    def __init__(self, root: ConfigNamespace, raw: ReadConfiguration,
                 restriction: Restriction = Restriction.NONE):
        if not root.is_root():
            raise ValueError("configuration must be bound to the tree root")
        self.root = root
        self.raw = raw
        self.restriction = restriction

    # -- option resolution --------------------------------------------------

    def _check_restriction(self, opt: ConfigOption):
        if self.restriction is Restriction.LOCAL and not opt.mutability.is_local:
            raise ValueError(f"option {opt.name!r} is not local-mutable")
        if self.restriction is Restriction.GLOBAL and not opt.mutability.is_global:
            raise ValueError(f"option {opt.name!r} is not global")

    def has(self, opt: ConfigOption, *umbrella: str) -> bool:
        return self.raw.get(opt.path(*umbrella)) is not None

    def get(self, opt: ConfigOption, *umbrella: str) -> Any:
        self._check_restriction(opt)
        value = self.raw.get(opt.path(*umbrella))
        if value is None:
            return opt.default
        return opt.validate(value)

    def get_subset(self, namespace: ConfigNamespace, *umbrella: str) -> dict:
        """All raw entries under a namespace path, keys relative to it."""
        prefix = namespace._build_path(list(umbrella)) + SEPARATOR
        out = {}
        for key in self.raw.keys(prefix):
            out[key[len(prefix):]] = self.raw.get(key)
        return out

    def container_names(self, umbrella_ns: ConfigNamespace, *umbrella: str) -> list[str]:
        """User-chosen middle elements configured under an umbrella namespace
        (e.g. the index names under ``index.<name>``)."""
        if not umbrella_ns.is_umbrella:
            raise ValueError(f"{umbrella_ns.name!r} is not an umbrella namespace")
        parent = umbrella_ns.parent
        if parent is None or parent.is_root():
            base = umbrella_ns.name
        else:
            base = parent._build_path(list(umbrella)) + SEPARATOR + umbrella_ns.name
        prefix = base + SEPARATOR
        names = set()
        for key in self.raw.keys(prefix):
            rest = key[len(prefix):]
            if SEPARATOR in rest:
                names.add(rest.split(SEPARATOR, 1)[0])
        return sorted(names)

    def resolve_option(self, path: str) -> tuple[ConfigOption, list[str]]:
        """Map a dotted path back to (option, umbrella elements). Raises
        KeyError for unknown paths (reference: ConfigElement.parse)."""
        parts = path.split(SEPARATOR)
        node: ConfigNamespace = self.root
        umbrella: list[str] = []
        i = 0
        while i < len(parts):
            child = node.child(parts[i])
            if child is None:
                raise KeyError(f"unknown config path: {path!r} (at {parts[i]!r})")
            if isinstance(child, ConfigOption):
                if i != len(parts) - 1:
                    raise KeyError(f"config path continues past option: {path!r}")
                return child, umbrella
            assert isinstance(child, ConfigNamespace)
            node = child
            i += 1
            if node.is_umbrella:
                if i >= len(parts):
                    raise KeyError(f"umbrella namespace path truncated: {path!r}")
                umbrella.append(parts[i])
                i += 1
        raise KeyError(f"config path names a namespace, not an option: {path!r}")


class ModifiableConfiguration(Configuration):
    """Typed write view; enforces mutability levels on set()."""

    def __init__(self, root: ConfigNamespace, raw: WriteConfiguration,
                 restriction: Restriction = Restriction.NONE):
        super().__init__(root, raw, restriction)
        self.raw: WriteConfiguration = raw

    def set(self, opt: ConfigOption, value: Any, *umbrella: str,
            force: bool = False) -> None:
        self._check_restriction(opt)
        if not force:
            if opt.mutability is Mutability.FIXED:
                raise ValueError(f"option {opt.name!r} is FIXED and cannot be changed")
            if opt.mutability is Mutability.GLOBAL_OFFLINE:
                raise ValueError(
                    f"option {opt.name!r} is GLOBAL_OFFLINE; use the management "
                    f"system with all instances closed")
        value = opt.validate(value)
        self.raw.set(opt.path(*umbrella), value)

    def remove(self, opt: ConfigOption, *umbrella: str) -> None:
        self._check_restriction(opt)
        self.raw.remove(opt.path(*umbrella))


class MergedConfiguration(Configuration):
    """first (typically local) masks second (typically global), respecting
    mutability: for GLOBAL* options the *second* (global) wins unless the
    option is MASKABLE (reference: MergedConfiguration + the merge logic in
    GraphDatabaseConfiguration's constructor)."""

    def __init__(self, first: Configuration, second: Configuration):
        if first.root is not second.root:
            raise ValueError("merged configurations must share an option tree")
        super().__init__(first.root, first.raw, Restriction.NONE)
        self.first = first
        self.second = second

    def has(self, opt: ConfigOption, *umbrella: str) -> bool:
        return self.first.has(opt, *umbrella) or self.second.has(opt, *umbrella)

    def get(self, opt: ConfigOption, *umbrella: str) -> Any:
        first_has = self.first.has(opt, *umbrella)
        second_has = self.second.has(opt, *umbrella)
        if opt.mutability.is_global and not (opt.mutability is Mutability.MASKABLE):
            # global value authoritative when present
            if second_has:
                return self.second.get(opt, *umbrella)
            if first_has:
                return self.first.get(opt, *umbrella)
        else:
            if first_has:
                return self.first.get(opt, *umbrella)
            if second_has:
                return self.second.get(opt, *umbrella)
        return opt.default

    def get_subset(self, namespace: ConfigNamespace, *umbrella: str) -> dict:
        out = self.second.get_subset(namespace, *umbrella)
        out.update(self.first.get_subset(namespace, *umbrella))
        return out

    def container_names(self, umbrella_ns: ConfigNamespace, *umbrella: str) -> list[str]:
        names = set(self.first.container_names(umbrella_ns, *umbrella))
        names.update(self.second.container_names(umbrella_ns, *umbrella))
        return sorted(names)
