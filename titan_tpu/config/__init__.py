from titan_tpu.config.options import (ConfigElement, ConfigNamespace, ConfigOption,
                                      Mutability, SEPARATOR)
from titan_tpu.config.configuration import (Configuration, MapConfiguration,
                                            MergedConfiguration,
                                            ModifiableConfiguration,
                                            ReadConfiguration, Restriction,
                                            WriteConfiguration)
from titan_tpu.config import defaults

__all__ = [
    "ConfigElement", "ConfigNamespace", "ConfigOption", "Mutability", "SEPARATOR",
    "Configuration", "MapConfiguration", "MergedConfiguration",
    "ModifiableConfiguration", "ReadConfiguration", "Restriction",
    "WriteConfiguration", "defaults",
]
