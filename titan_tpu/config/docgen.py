"""Config reference generator: the typed option tree -> markdown.

The reference ships a static config surface with its distribution
(titan-dist/src/assembly/static/conf/ + the ~200 options declared in
GraphDatabaseConfiguration.java); here the single source of truth is the
option tree itself (config/defaults.py), and the docs page is GENERATED
from it so it can never drift — tests/test_config_docs.py regenerates and
compares.

Usage: ``python -m titan_tpu.config.docgen > docs/config-reference.md``
(or call :func:`render`).
"""

from __future__ import annotations

from titan_tpu.config.options import ConfigNamespace, ConfigOption


def _walk(ns: ConfigNamespace, path: str = ""):
    opts, subs = [], []
    for child in sorted(ns.children(), key=lambda c: c.name):
        if isinstance(child, ConfigNamespace):
            subs.append(child)
        else:
            opts.append(child)
    yield path, ns, opts
    for sub in subs:
        sub_path = f"{path}.{sub.name}" if path else sub.name
        yield from _walk(sub, sub_path)


def _cell(text: str) -> str:
    """Escape table-breaking characters in a markdown cell."""
    return str(text).replace("|", "\\|")


def _fmt_default(opt: ConfigOption) -> str:
    d = opt.default
    if d is None:
        return "(none)"
    if isinstance(d, str):
        return _cell(f"`{d!r}`")
    return _cell(f"`{d}`")


def render() -> str:
    from titan_tpu.config import defaults as d

    lines = [
        "# Configuration reference",
        "",
        "GENERATED from the typed option tree (`titan_tpu/config/"
        "defaults.py`) by `python -m titan_tpu.config.docgen` — do not "
        "edit by hand; `tests/test_config_docs.py` enforces sync.",
        "",
        "Options are set via `titan_tpu.open({...})` dicts, properties "
        "files, or the management system (GLOBAL options live in the "
        "storage backend itself and merge at open — reference: "
        "KCVSConfiguration over the system_properties store, "
        "Backend.java:273-298).",
        "",
        "Mutability levels (reference: ConfigOption.java): **LOCAL** = "
        "per-instance, from local config only; **MASKABLE** = local "
        "value overrides the global one; **GLOBAL** = cluster-wide, "
        "changed online via the management system; **GLOBAL_OFFLINE** = "
        "cluster-wide, all instances must be down to change; **FIXED** = "
        "set at cluster creation, immutable.",
        "",
    ]
    for path, ns, opts in _walk(d.ROOT):
        if not opts:
            continue
        title = path or "(root)"
        lines.append(f"## `{title}` — {ns.description}")
        lines.append("")
        lines.append("| option | type | default | mutability | "
                     "description |")
        lines.append("|---|---|---|---|---|")
        for opt in opts:
            full = f"{path}.{opt.name}" if path else opt.name
            lines.append(
                f"| `{full}` | {opt.datatype.__name__} | "
                f"{_fmt_default(opt)} | {opt.mutability.name} | "
                f"{_cell(opt.description)} |")
        lines.append("")
    return "\n".join(lines) + "\n"


def main() -> None:
    print(render(), end="")


if __name__ == "__main__":
    main()
