"""Typed hierarchical configuration tree.

Re-creation of the reference's distinctive config kernel
(reference: titan-core diskstorage/configuration/ConfigOption.java,
ConfigNamespace.java, ConfigElement.java): a tree of namespaces holding typed
options, each with a datatype, default, verification function and a
*mutability level* that governs where the value may be changed:

    LOCAL          — only via local config at open time
    MASKABLE       — local config may override the cluster-global value
    GLOBAL         — cluster-wide, changed online through management
    GLOBAL_OFFLINE — cluster-wide, all instances must be down to change
    FIXED          — set once at cluster initialization, immutable after

Umbrella namespaces (``index.<name>.backend``) carry a user-chosen middle
path element, exactly like the reference's ``ConfigNamespace(isUmbrella)``.
"""

from __future__ import annotations

import enum
import re
from typing import Any, Callable, Optional, Sequence


class Mutability(enum.Enum):
    LOCAL = "LOCAL"
    MASKABLE = "MASKABLE"
    GLOBAL = "GLOBAL"
    GLOBAL_OFFLINE = "GLOBAL_OFFLINE"
    FIXED = "FIXED"

    @property
    def is_global(self) -> bool:
        return self in (Mutability.GLOBAL, Mutability.GLOBAL_OFFLINE, Mutability.FIXED)

    @property
    def is_local(self) -> bool:
        return self in (Mutability.LOCAL, Mutability.MASKABLE)

    def is_stricter_or_equal(self, other: "Mutability") -> bool:
        order = [Mutability.LOCAL, Mutability.MASKABLE, Mutability.GLOBAL,
                 Mutability.GLOBAL_OFFLINE, Mutability.FIXED]
        return order.index(self) >= order.index(other)


_NAME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9_-]*$")
SEPARATOR = "."


class ConfigElement:
    """A node in the config tree; path = dotted names from the root."""

    def __init__(self, parent: Optional["ConfigNamespace"], name: str, description: str = ""):
        if parent is not None and not _NAME_RE.match(name):
            raise ValueError(f"invalid config element name: {name!r}")
        self.parent = parent
        self.name = name
        self.description = description
        if parent is not None:
            parent._register(self)

    def is_root(self) -> bool:
        return self.parent is None

    @property
    def root(self) -> "ConfigNamespace":
        el = self
        while el.parent is not None:
            el = el.parent
        assert isinstance(el, ConfigNamespace)
        return el

    def path(self, *umbrella_elements: str) -> str:
        """Full dotted path; umbrella elements fill umbrella namespaces
        top-down (same contract as the reference's ConfigElement.getPath)."""
        return self._build_path(list(umbrella_elements))

    def _build_path(self, fills: list[str]) -> str:
        chain: list[ConfigElement] = []
        el: Optional[ConfigElement] = self
        while el is not None and not el.is_root():
            chain.append(el)
            el = el.parent
        chain.reverse()
        parts: list[str] = []
        fi = 0
        for node in chain:
            parts.append(node.name)
            if isinstance(node, ConfigNamespace) and node.is_umbrella:
                if fi >= len(fills):
                    raise ValueError(
                        f"missing umbrella element under namespace {node.name!r} "
                        f"for {self.name!r}")
                parts.append(fills[fi])
                fi += 1
        if fi != len(fills):
            raise ValueError(f"too many umbrella elements for {self.name!r}")
        return SEPARATOR.join(parts)

    def __repr__(self):
        try:
            return f"<{type(self).__name__} {self._build_path(['*'] * self._umbrella_depth())}>"
        except ValueError:
            return f"<{type(self).__name__} {self.name}>"

    def _umbrella_depth(self) -> int:
        """Number of umbrella fills needed to path to this element (counting
        the element itself if it is an umbrella namespace)."""
        n = 0
        el: Optional[ConfigElement] = self
        while el is not None and not el.is_root():
            if isinstance(el, ConfigNamespace) and el.is_umbrella:
                n += 1
            el = el.parent
        return n


class ConfigNamespace(ConfigElement):
    def __init__(self, parent: Optional["ConfigNamespace"], name: str,
                 description: str = "", umbrella: bool = False):
        self.is_umbrella = umbrella
        self._children: dict[str, ConfigElement] = {}
        super().__init__(parent, name, description)

    def _register(self, child: ConfigElement):
        if child.name in self._children:
            raise ValueError(f"duplicate config element {child.name!r} in {self.name!r}")
        self._children[child.name] = child

    def child(self, name: str) -> Optional[ConfigElement]:
        return self._children.get(name)

    def children(self) -> Sequence[ConfigElement]:
        return list(self._children.values())


class ConfigOption(ConfigElement):
    def __init__(self, parent: ConfigNamespace, name: str, description: str,
                 datatype: type, default: Any = None,
                 mutability: Mutability = Mutability.LOCAL,
                 verify: Optional[Callable[[Any], bool]] = None):
        super().__init__(parent, name, description)
        self.datatype = datatype
        self.default = default
        self.mutability = mutability
        self._verify = verify
        if default is not None:
            self.validate(default)

    def coerce(self, value: Any) -> Any:
        """Coerce a raw (possibly string) value to the option's datatype."""
        if isinstance(value, self.datatype):
            return value
        if self.datatype is bool:
            if isinstance(value, str):
                low = value.strip().lower()
                if low in ("true", "1", "yes", "on"):
                    return True
                if low in ("false", "0", "no", "off"):
                    return False
            if isinstance(value, int):
                return bool(value)
            raise ValueError(f"cannot coerce {value!r} to bool for option {self.name}")
        if self.datatype in (int, float, str):
            try:
                return self.datatype(value)
            except (TypeError, ValueError) as e:
                raise ValueError(f"cannot coerce {value!r} for option {self.name}: {e}")
        if self.datatype is list and isinstance(value, str):
            return [v.strip() for v in value.split(",") if v.strip()]
        if self.datatype is list and isinstance(value, (tuple, list)):
            return list(value)
        raise ValueError(f"cannot coerce {value!r} ({type(value).__name__}) "
                         f"to {self.datatype.__name__} for option {self.name}")

    def validate(self, value: Any) -> Any:
        value = self.coerce(value)
        if self._verify is not None and not self._verify(value):
            raise ValueError(f"value {value!r} failed verification for option {self.name}")
        return value


# common verifiers (reference: ConfigOption.positiveInt() etc.)
def positive(v) -> bool:
    return v > 0

def non_negative(v) -> bool:
    return v >= 0

def one_of(*allowed):
    def check(v):
        return v in allowed
    return check
