"""Deployment assembly: start/stop/status for a whole node set.

(reference: titan-dist/src/assembly/static — ``titan.sh`` boots the
storage backend, the index backend, and Gremlin Server as one unit with
pidfiles; here ``python -m titan_tpu.deploy <cmd> <deployment.yaml>``
does the same for this framework's services.)

Deployment file shape (docs/config-reference.md documents graph options)::

    pid-dir: /var/run/titan-tpu        # default: <yaml-dir>/.pids
    services:
      - kind: storage-node             # python -m titan_tpu.storage.remote
        data-dir: /data/store-a
        port: 8283
      - kind: index-node               # python -m titan_tpu.indexing.remote
        data-dir: /data/index-a
        port: 8304
      - kind: scan-worker              # python -m titan_tpu.olap.scan_worker
        port: 8391
      - kind: graph-server             # python -m titan_tpu.server
        conf: server.yaml              # gremlin-server.yaml analog

Commands: ``start`` (spawns anything not already running), ``stop``
(SIGTERM by pidfile), ``status``.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Optional

_KINDS = {
    "storage-node": lambda s: [sys.executable, "-m",
                               "titan_tpu.storage.remote",
                               s.get("data-dir", "."),
                               str(s.get("port", 8283)),
                               s.get("host", "0.0.0.0")],
    "index-node": lambda s: [sys.executable, "-m",
                             "titan_tpu.indexing.remote",
                             s.get("data-dir", "."),
                             str(s.get("port", 8304)),
                             s.get("host", "0.0.0.0")],
    # scan workers execute shipped job factories — localhost unless the
    # deployment explicitly opts into a wider bind (pair with
    # TITAN_TPU_NODE_TOKEN in the service env)
    "scan-worker": lambda s: [sys.executable, "-m",
                              "titan_tpu.olap.scan_worker",
                              str(s.get("port", 8391)),
                              s.get("host", "127.0.0.1")],
    "graph-server": lambda s: [sys.executable, "-m", "titan_tpu.server",
                               s["conf"]],
}


def _load(path: str) -> tuple[dict, str]:
    import yaml
    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    pid_dir = cfg.get("pid-dir") or os.path.join(
        os.path.dirname(os.path.abspath(path)), ".pids")
    return cfg, pid_dir


def _name(i: int, svc: dict) -> str:
    return svc.get("name") or f"{svc['kind']}-{i}"


def _pidfile(pid_dir: str, name: str) -> str:
    return os.path.join(pid_dir, name + ".pid")


def _running(pidfile: str) -> Optional[int]:
    try:
        with open(pidfile) as f:
            pid = int(f.read().strip())
    except (OSError, ValueError):
        return None
    try:
        os.kill(pid, 0)
    except PermissionError:
        return pid   # exists, owned by another user (e.g. root-started)
    except OSError:
        return None
    return pid


def start(path: str) -> int:
    cfg, pid_dir = _load(path)
    os.makedirs(pid_dir, exist_ok=True)
    started = 0
    for i, svc in enumerate(cfg.get("services", ())):
        name = _name(i, svc)
        pf = _pidfile(pid_dir, name)
        if _running(pf):
            print(f"{name}: already running")
            continue
        kind = svc.get("kind")
        if kind not in _KINDS:
            raise SystemExit(f"unknown service kind {kind!r} ({name})")
        logf = open(os.path.join(pid_dir, name + ".log"), "ab")
        proc = subprocess.Popen(
            _KINDS[kind](svc), stdout=logf, stderr=subprocess.STDOUT,
            cwd=os.path.dirname(os.path.abspath(path)) or ".",
            start_new_session=True)
        with open(pf, "w") as f:
            f.write(str(proc.pid))
        print(f"{name}: started (pid {proc.pid})")
        started += 1
    return started


def stop(path: str) -> int:
    cfg, pid_dir = _load(path)
    stopped = 0
    for i, svc in enumerate(cfg.get("services", ())):
        name = _name(i, svc)
        pf = _pidfile(pid_dir, name)
        pid = _running(pf)
        if pid is None:
            print(f"{name}: not running")
            continue
        os.kill(pid, signal.SIGTERM)
        for _ in range(50):
            if _running(pf) is None:
                break
            time.sleep(0.1)
        else:
            os.kill(pid, signal.SIGKILL)
        try:
            os.remove(pf)
        except OSError:
            pass
        print(f"{name}: stopped")
        stopped += 1
    return stopped


def status(path: str) -> dict:
    cfg, pid_dir = _load(path)
    out = {}
    for i, svc in enumerate(cfg.get("services", ())):
        name = _name(i, svc)
        pid = _running(_pidfile(pid_dir, name))
        out[name] = pid
        print(f"{name}: {'running (pid %d)' % pid if pid else 'stopped'}")
    return out


def main(argv: Optional[list] = None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    if len(args) != 2 or args[0] not in ("start", "stop", "status"):
        print("usage: python -m titan_tpu.deploy start|stop|status "
              "<deployment.yaml>", file=sys.stderr)
        raise SystemExit(2)
    {"start": start, "stop": stop, "status": status}[args[0]](args[1])


if __name__ == "__main__":
    main()
