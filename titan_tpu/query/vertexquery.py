"""Vertex-centric query builder.

(reference: titan-core graphdb/query/vertex/BasicVertexCentricQueryBuilder.java:719
— builds sliced adjacency queries: relation type + direction + sort-key
interval become column ranges (via EdgeSerializer.getQuery), everything else
becomes an in-memory filter; merges stored results with the transaction's
in-memory delta. ``interval()`` on the label's FIRST sort key narrows the
slice server-side — the vertex-centric-index fast path.)
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

from titan_tpu.core.defs import Direction, RelationCategory
from titan_tpu.core.elements import Edge, VertexProperty
from titan_tpu.query.predicates import P
from titan_tpu.storage.api import KeySliceQuery


class VertexCentricQueryBuilder:
    def __init__(self, tx, vertex_id: int):
        self._tx = tx
        self._vid = vertex_id
        self._labels: Optional[list[str]] = None
        self._direction = Direction.BOTH
        self._limit: Optional[int] = None
        self._interval: Optional[tuple] = None   # (key_name, lo, hi)
        self._filters: list[tuple] = []          # (key_name, P)

    def labels(self, *names: str) -> "VertexCentricQueryBuilder":
        self._labels = list(names)
        return self

    def direction(self, d: Direction) -> "VertexCentricQueryBuilder":
        self._direction = d
        return self

    def interval(self, key: str, lo: Any, hi: Any) -> "VertexCentricQueryBuilder":
        """[lo, hi) on a sort-key property → server-side column range."""
        self._interval = (key, lo, hi)
        return self

    def has(self, key: str, value: Any) -> "VertexCentricQueryBuilder":
        pred = value if isinstance(value, P) else P.eq(value)
        self._filters.append((key, pred))
        return self

    def limit(self, n: int) -> "VertexCentricQueryBuilder":
        self._limit = n
        return self

    # -- execution -----------------------------------------------------------

    def _sort_key_bounds(self, label_id: int):
        """If the interval targets the label's first sort key, return
        (sort_start, sort_end) lists for the codec slice."""
        if self._interval is None:
            return None, None
        key_name, lo, hi = self._interval
        st = self._tx.schema.get_by_name(key_name)
        sort = self._tx.schema.sort_key(label_id)
        if st is not None and sort and sort[0] == st.id:
            return [lo], [hi]
        return None, None

    def edges(self) -> Iterator[Edge]:
        tx = self._tx
        label_ids = None
        if self._labels is not None:
            label_ids = [st.id for n in self._labels
                         if (st := tx.schema.get_by_name(n)) is not None]
            if not label_ids:
                return
        count = 0
        emitted = set()
        if self._vid not in tx._new_vertices and label_ids is not None:
            for lid in label_ids:
                sort_start, sort_end = self._sort_key_bounds(lid)
                # the interval is server-side iff it was folded into the slice
                interval_pushed = sort_start is not None or self._interval is None
                for q in tx.codec.query_type(lid, self._direction, tx.schema,
                                             sort_start=sort_start,
                                             sort_end=sort_end):
                    # only push the limit down when no client-side check can
                    # reject rows (filters, unpushed intervals, OR tx-deleted
                    # relations — all would make the slice under-return)
                    if self._limit is not None and not self._filters and \
                            interval_pushed and not tx._deleted:
                        q = q.with_limit(self._limit)
                    for entry in tx.backend_tx.edge_store_query(
                            KeySliceQuery(tx.idm.key_bytes(self._vid), q)):
                        rc = tx.codec.parse(entry, tx.schema)
                        rel = tx._relation_from_cache(self._vid, rc)
                        if rel.relation_id in tx._deleted:
                            continue
                        e = Edge(tx, rel)
                        if self._accept(e):
                            k = (rel.relation_id, rc.direction)
                            if k in emitted:
                                continue
                            emitted.add(k)
                            yield e
                            count += 1
                            if self._limit is not None and count >= self._limit:
                                return
        else:
            for e in tx.vertex_edges(self._vid, self._direction, self._labels):
                if self._accept(e):
                    yield e
                    count += 1
                    if self._limit is not None and count >= self._limit:
                        return
            return
        # in-tx additions
        for rel in tx._added_by_vertex.get(self._vid, ()):
            if not rel.is_edge or (label_ids and rel.type_id not in label_ids):
                continue
            if self._direction is not Direction.BOTH and \
                    rel.direction_of(self._vid) is not self._direction:
                continue
            e = Edge(tx, rel)
            if self._accept(e):
                yield e
                count += 1
                if self._limit is not None and count >= self._limit:
                    return

    def _accept(self, e: Edge) -> bool:
        if self._interval is not None:
            key, lo, hi = self._interval
            v = e.value(key)
            if v is None or not (lo <= v < hi):
                return False
        for key, pred in self._filters:
            v = e.value(key)
            if v is None or not pred(v):
                return False
        return True

    def vertices(self):
        me = self._tx.vertex_handle(self._vid)
        for e in self.edges():
            yield e.other(me)

    def properties(self) -> Iterator[VertexProperty]:
        it = self._tx.vertex_properties(self._vid, self._labels)
        count = 0
        for p in it:
            ok = True
            for key, pred in self._filters:
                if p.key() != key or not pred(p.value):
                    ok = False
                    break
            if ok:
                yield p
                count += 1
                if self._limit is not None and count >= self._limit:
                    return

    def count(self) -> int:
        return sum(1 for _ in self.edges())
