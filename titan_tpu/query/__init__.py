from titan_tpu.query.predicates import P

__all__ = ["P"]
