"""Graph-centric queries: ``g.query().has(...)`` with index selection.

(reference: titan-core graphdb/query/graph/GraphCentricQueryBuilder.java:426
— pick the best composite index (all keys matched by equality), fall back to
mixed indexes whose provider supports the predicates, intersect multiple
retrievals (QueryUtil.processIntersectingRetrievals), and finally full-scan
with a warning (StandardTitanTx.java:1260-1282). Results always re-filter
against the full condition set and merge the transaction's own deltas.)
"""

from __future__ import annotations

import logging
from typing import Any, Iterator, Optional

from titan_tpu.core.defs import Direction, RelationCategory
from titan_tpu.core.schema import IndexDefinition, PropertyKey
from titan_tpu.errors import TitanError
from titan_tpu.query.predicates import P

log = logging.getLogger(__name__)

_EXISTS = object()


class GraphQuery:
    """Builder for graph-centric element retrieval."""

    def __init__(self, tx):
        self.tx = tx
        self.schema = tx.schema
        self._conditions: list[tuple[str, P]] = []
        self._label: Optional[str] = None
        self._orders: list[tuple[str, str]] = []
        self._limit: Optional[int] = None
        from titan_tpu.query.profile import NO_OP
        self._profiler = NO_OP

    # -- builder -------------------------------------------------------------

    def has(self, key: str, value: Any = _EXISTS) -> "GraphQuery":
        if value is _EXISTS:
            self._conditions.append((key, P("exists", None,
                                            lambda c: c is not None)))
        elif isinstance(value, P):
            self._conditions.append((key, value))
        else:
            self._conditions.append((key, P.eq(value)))
        return self

    def has_not(self, key: str) -> "GraphQuery":
        self._conditions.append((key, P("absent", None, lambda c: c is None)))
        return self

    def has_label(self, label: str) -> "GraphQuery":
        self._label = label
        return self

    def interval(self, key: str, lo, hi) -> "GraphQuery":
        return self.has(key, P.between(lo, hi))

    def order_by(self, key: str, order: str = "asc") -> "GraphQuery":
        self._orders.append((key, order))
        return self

    def limit(self, n: int) -> "GraphQuery":
        self._limit = n
        return self

    def with_profiler(self, profiler) -> "GraphQuery":
        """Thread a QueryProfiler through execution (reference: profiler
        threading at StandardTitanTx.java:1030,1116,1247)."""
        self._profiler = profiler
        return self

    # -- execution -----------------------------------------------------------

    def vertices(self) -> list:
        return self._execute("vertex")

    def edges(self) -> list:
        return self._execute("edge")

    def count(self) -> int:
        return len(self.vertices())

    def _execute(self, element: str) -> list:
        from titan_tpu.query import profile as prof
        tx = self.tx
        with self._profiler.group(prof.OPTIMIZATION) as p:
            p.annotate("conditions", len(self._conditions))
            ids = self._index_retrieval(element)
            p.annotate("indexed", ids is not None)
        if ids is None:
            with self._profiler.group(prof.FULL_SCAN) as p:
                out = list(self._full_scan(element))
                p.annotate("results", len(out))
        else:
            with self._profiler.group(prof.BACKEND_QUERY) as p:
                p.annotate("hits", len(ids))
                out = []
                seen = set()
                # mixed-edge hits carry only a relation id; resolve them all
                # in ONE edge-store pass instead of one scan per hit
                rel_ids = {h[1] for h in ids
                           if isinstance(h, tuple) and len(h) == 2
                           and h[0] == "rel"}
                rel_map = self._edges_by_rel_ids(rel_ids) if rel_ids else {}
                for eid in ids:
                    if element == "vertex":
                        el = tx.vertex(eid)
                    elif isinstance(eid, tuple) and len(eid) == 2 \
                            and eid[0] == "rel":
                        el = rel_map.get(eid[1])
                    else:
                        el = self._edge_from_hit(eid)
                    if el is None or el.id in seen:
                        continue
                    seen.add(el.id)
                    if self._matches(el):
                        out.append(el)
                # the index can't see this tx's uncommitted elements — merge
                # the tx delta the way edgeProcessor merges adjacency deltas
                out.extend(el for el in self._tx_delta(element)
                           if el.id not in seen and self._matches(el))
                p.annotate("results", len(out))
        for key, direction in reversed(self._orders):
            out.sort(key=lambda el: ((v := el.value(key)) is None, v),
                     reverse=(direction == "desc"))
        if self._limit is not None:
            out = out[:self._limit]
        return out

    # -- matching ------------------------------------------------------------

    def _matches(self, el) -> bool:
        if self._label is not None and el.label() != self._label:
            return False
        for key, pred in self._conditions:
            values = el.values(key) if hasattr(el, "values") else []
            # Edge.values yields None placeholders for absent keys (Vertex
            # yields nothing) — absent is absent for predicate purposes
            values = [v for v in values if v is not None]
            if pred.op == "absent":
                if values:
                    return False
                continue
            if not values:
                return False
            if not any(pred(v) for v in values):
                return False
        return True

    # -- index selection (the GraphCentricQueryBuilder core) -----------------

    def _index_retrieval(self, element: str) -> Optional[list]:
        """Element-id stream from the best index cover, or None when no
        index applies (→ full scan)."""
        eq_keys = {}
        for key, pred in self._conditions:
            if pred.op == "eq":
                eq_keys.setdefault(key, pred.value)
        label_id = 0
        if self._label is not None:
            st = self.schema.get_by_name(self._label)
            if st is not None:
                label_id = st.id

        candidates = [ix for ix in self.schema.indexes(element)
                      if ix.queryable and
                      (not ix.index_only or ix.index_only == label_id)]

        # composite cover: every index key has an equality condition;
        # greedy largest-first, intersecting multiple retrievals
        retrievals = []
        covered: set[str] = set()
        composites = sorted(
            (ix for ix in candidates if ix.composite),
            key=lambda ix: -len(ix.key_ids))
        for ix in composites:
            names = [self.schema.get_type(k).name for k in ix.key_ids]
            if not all(n in eq_keys for n in names):
                continue
            if set(names) <= covered:
                continue
            retrievals.append(("composite", ix,
                               tuple(eq_keys[n] for n in names)))
            covered |= set(names)

        # mixed cover for the remaining conditions
        remaining = [(k, p) for k, p in self._conditions
                     if k not in covered and p.op not in ("exists", "absent")]
        if remaining:
            graph = self.tx.graph
            for ix in candidates:
                if ix.composite:
                    continue
                provider = graph.index_provider(ix.backing)
                if provider is None:
                    continue
                names = {self.schema.get_type(k).name: (k, param)
                         for k, param in zip(ix.key_ids, ix.key_params)}
                cover = [(k, p) for k, p in remaining
                         if k in names and provider.supports(
                             self._keyinfo(*names[k]), p)]
                if cover:
                    retrievals.append(("mixed", ix, tuple(cover)))
                    covered |= {k for k, _ in cover}
                    remaining = [(k, p) for k, p in remaining
                                 if k not in covered]
                    if not remaining:
                        break

        if not retrievals:
            return None

        # execute + intersect (reference: processIntersectingRetrievals);
        # hits are normalized to {element id: payload} so composite-edge
        # (4-tuple) and mixed-edge retrievals intersect correctly
        result: Optional[dict] = None
        for kind, ix, payload in retrievals:
            hits = self._run_retrieval(kind, ix, payload, element)
            if result is None:
                result = hits
            else:
                result = {k: self._prefer(result[k], hits[k])
                          for k in result.keys() & hits.keys()}
            if not result:
                break
        return [result[k] for k in sorted(result or ())]

    @staticmethod
    def _prefer(a, b):
        """Keep the richer payload: a composite-edge 4-tuple reconstructs the
        edge directly, a mixed ("rel", id) hit needs a scan."""
        if isinstance(a, tuple) and len(a) == 4:
            return a
        return b if isinstance(b, tuple) and len(b) == 4 else a

    def _keyinfo(self, key_id: int, param: str = "DEFAULT"):
        from titan_tpu.indexing.provider import KeyInformation
        st = self.schema.get_type(key_id)
        return KeyInformation(st.dtype, st.cardinality,
                              (param,) if param != "DEFAULT" else ())

    def _run_retrieval(self, kind: str, ix: IndexDefinition, payload,
                       element: str) -> dict:
        """→ {element id: payload} (vertex id, or relation id → edge hit)."""
        graph = self.tx.graph
        if kind == "composite":
            hits = graph.index_serializer.query_composite(
                self.tx.backend_tx, ix, payload)
            if element == "vertex":
                return {h: h for h in hits}
            return {h[0]: h for h in hits}
        from titan_tpu.indexing.provider import And, FieldCondition, IndexQuery
        cond = And(tuple(FieldCondition(k, p) for k, p in payload))
        itx = self.tx.backend_tx.index_txs.get(ix.backing)
        provider = graph.index_provider(ix.backing)
        docids = (itx or provider).query(ix.name, IndexQuery(cond))
        ser = graph.index_serializer
        if element == "vertex":
            return {(eid := ser.element_id_of(d)): eid for d in docids}
        return {(rid := ser.element_id_of(d)): ("rel", rid) for d in docids}

    # -- fallbacks / reconstruction ------------------------------------------

    def _full_scan(self, element: str) -> Iterator:
        log.warning("Query requires iterating over all %ss [%s] — consider "
                    "adding an index", element,
                    [k for k, _ in self._conditions])
        if element == "vertex":
            for v in self.tx.vertices():
                if self._matches(v):
                    yield v
            return
        seen = set()
        for v in self.tx.vertices():
            for e in v.edges(Direction.OUT):
                if e.id not in seen:
                    seen.add(e.id)
                    if self._matches(e):
                        yield e

    def _edge_from_hit(self, hit):
        """Rebuild an Edge from an index hit: (rel_id, out, in, type) from a
        composite index, or ("rel", rel_id) from a mixed one."""
        tx = self.tx
        if isinstance(hit, tuple) and hit and hit[0] == "rel":
            return self._edges_by_rel_ids({hit[1]}).get(hit[1])
        rel_id, out_vid, in_vid, type_id = hit
        if rel_id in tx._deleted:
            return None
        st = self.schema.get_type(type_id)
        if st is None:
            return None
        for e in tx.vertex_edges(out_vid, Direction.OUT, [st.name]):
            if e.id == rel_id:
                return e
        return None

    def _edges_by_rel_ids(self, rel_ids: set) -> dict:
        """Resolve relation ids to Edges with one pass over the edge store
        (mixed edge indexes key documents by relation id only)."""
        tx = self.tx
        wanted = {r for r in rel_ids if r not in tx._deleted}
        found: dict = {}
        if not wanted:
            return found
        for v in tx.vertices():
            for e in v.edges(Direction.OUT):
                if e.id in wanted:
                    found[e.id] = e
                    if len(found) == len(wanted):
                        return found
        return found

    def _tx_delta(self, element: str) -> Iterator:
        """Elements the committed indexes can't see: created in this tx OR
        with property changes in this tx (their index entries are stale)."""
        tx = self.tx
        if element == "vertex":
            seen = set()
            for vid in tx._new_vertices:
                if vid not in tx._removed_vertices:
                    seen.add(vid)
                    yield tx.vertex_handle(vid)
            for rel in list(tx._added.values()) + list(tx._deleted.values()):
                if not rel.is_property or \
                        self.schema.system.is_system(rel.type_id):
                    continue
                vid = rel.out_vertex_id
                if vid in seen or vid in tx._removed_vertices or \
                        not tx.idm.is_user_vertex_id(vid):
                    continue
                seen.add(vid)
                yield tx.vertex_handle(vid)
            return
        from titan_tpu.core.elements import Edge
        for rel in tx._added.values():
            if rel.is_edge and not self.schema.system.is_system(rel.type_id):
                yield Edge(tx, rel)
