"""Query predicates.

(reference: titan-core core/attribute/Cmp.java, Text.java, Contain.java —
comparison, text-search and containment predicates usable in ``has()``
conditions and index queries.)
"""

from __future__ import annotations

import re
from typing import Any, Callable, Iterable


class P:
    """A typed predicate: ``P.eq(5)``, ``P.gt(3)``, ``P.text_contains("x")``."""

    def __init__(self, op: str, value: Any, test: Callable[[Any], bool]):
        self.op = op
        self.value = value
        self._test = test

    def __call__(self, candidate: Any) -> bool:
        try:
            return self._test(candidate)
        except TypeError:
            return False

    def __repr__(self):
        return f"P.{self.op}({self.value!r})"

    # -- comparison (Cmp) ---------------------------------------------------

    @staticmethod
    def eq(v):
        return P("eq", v, lambda c: c == v)

    @staticmethod
    def neq(v):
        return P("neq", v, lambda c: c != v)

    @staticmethod
    def lt(v):
        return P("lt", v, lambda c: c < v)

    @staticmethod
    def lte(v):
        return P("lte", v, lambda c: c <= v)

    @staticmethod
    def gt(v):
        return P("gt", v, lambda c: c > v)

    @staticmethod
    def gte(v):
        return P("gte", v, lambda c: c >= v)

    @staticmethod
    def between(lo, hi):
        """[lo, hi) interval (reference: Cmp interval semantics)."""
        return P("between", (lo, hi), lambda c: lo <= c < hi)

    @staticmethod
    def inside(lo, hi):
        return P("inside", (lo, hi), lambda c: lo < c < hi)

    # -- containment (Contain) ----------------------------------------------

    @staticmethod
    def within(*values):
        vs = set(values[0]) if len(values) == 1 and \
            isinstance(values[0], (list, set, tuple)) else set(values)
        return P("within", vs, lambda c: c in vs)

    @staticmethod
    def without(*values):
        vs = set(values[0]) if len(values) == 1 and \
            isinstance(values[0], (list, set, tuple)) else set(values)
        return P("without", vs, lambda c: c not in vs)

    # -- text (Text) ---------------------------------------------------------

    @staticmethod
    def text_contains(query: str):
        # reference Text.CONTAINS: the value must contain ALL terms of the
        # (tokenized) query; a token-less query matches nothing
        toks = [t for t in re.split(r"\W+", query.lower()) if t]

        def _test(c, _toks=toks):
            if not _toks:
                return False
            words = set(re.split(r"\W+", str(c).lower()))
            return all(t in words for t in _toks)

        return P("textContains", query, _test)

    @staticmethod
    def text_prefix(prefix: str):
        return P("textPrefix", prefix,
                 lambda c: any(w.startswith(prefix.lower())
                               for w in re.split(r"\W+", str(c).lower())))

    @staticmethod
    def text_regex(pattern: str):
        rx = re.compile(pattern)
        return P("textRegex", pattern,
                 lambda c: any(rx.fullmatch(w)
                               for w in re.split(r"\W+", str(c))))

    @staticmethod
    def string_prefix(prefix: str):
        return P("stringPrefix", prefix, lambda c: str(c).startswith(prefix))

    @staticmethod
    def string_regex(pattern: str):
        rx = re.compile(pattern)
        return P("stringRegex", pattern,
                 lambda c: rx.fullmatch(str(c)) is not None)

    # -- geo (reference: core/attribute/Geo.java) ----------------------------

    @staticmethod
    def geo_within(shape):
        return P("geoWithin", shape, lambda c: c.within(shape))

    @staticmethod
    def geo_intersect(shape):
        return P("geoIntersect", shape, lambda c: c.intersect(shape))

    @staticmethod
    def geo_disjoint(shape):
        return P("geoDisjoint", shape, lambda c: c.disjoint(shape))

    @staticmethod
    def geo_contains(shape):
        return P("geoContains", shape, lambda c: shape.within(c))
