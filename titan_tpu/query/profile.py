"""Query profiler: tree-structured timers + annotations.

(reference: titan-core graphdb/query/profile/QueryProfiler.java — a tree of
timed groups with key/value annotations threaded through every query
(StandardTitanTx.java:1030,1116,1247); surfaced in Gremlin ``.profile()``
via graphdb/tinkerpop/profile/TP3ProfileWrapper.java. The rebuild keeps the
same shape: ``QueryProfiler`` nodes nest via ``group()``, annotate with
``annotate()``, and render as an indented tree; traversal ``.profile()``
returns per-step ``TraversalMetrics``.)
"""

from __future__ import annotations

import time
from typing import Any, Optional

AND_QUERY = "AND-query"
OR_QUERY = "OR-query"
OPTIMIZATION = "optimization"
BACKEND_QUERY = "backend-query"
FULL_SCAN = "full-scan"


class QueryProfiler:
    """One profiled group. Use as a context manager to time it:

        with profiler.group("backend-query") as p:
            p.annotate("query", q)
            ...
    """

    def __init__(self, name: str = "root"):
        self.name = name
        self.annotations: dict[str, Any] = {}
        self.children: list[QueryProfiler] = []
        self.time_ns = 0
        self._t0: Optional[int] = None

    # -- structure -----------------------------------------------------------

    def group(self, name: str) -> "QueryProfiler":
        child = QueryProfiler(name)
        self.children.append(child)
        return child

    def annotate(self, key: str, value: Any) -> "QueryProfiler":
        self.annotations[key] = value
        return self

    # -- timing --------------------------------------------------------------

    def __enter__(self) -> "QueryProfiler":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc) -> bool:
        if self._t0 is not None:
            self.time_ns += time.perf_counter_ns() - self._t0
            self._t0 = None
        return False

    @property
    def time_ms(self) -> float:
        return self.time_ns / 1e6

    # -- reporting -----------------------------------------------------------

    def to_dict(self) -> dict:
        return {"name": self.name, "time_ms": self.time_ms,
                "annotations": dict(self.annotations),
                "children": [c.to_dict() for c in self.children]}

    def render(self, indent: int = 0) -> str:
        pad = "  " * indent
        ann = " ".join(f"{k}={v}" for k, v in self.annotations.items())
        lines = [f"{pad}{self.name} [{self.time_ms:.3f}ms]"
                 + (f" {ann}" if ann else "")]
        for c in self.children:
            lines.append(c.render(indent + 1))
        return "\n".join(lines)

    def __repr__(self):
        return f"QueryProfiler({self.name}, {self.time_ms:.3f}ms, " \
               f"{len(self.children)} children)"


class _NoOpProfiler(QueryProfiler):
    """Shared do-nothing profiler; all paths thread it by default so
    profiling costs nothing when off (reference: QueryProfiler.NO_OP)."""

    def __init__(self):
        super().__init__("no-op")

    def group(self, name: str) -> "QueryProfiler":
        return self

    def annotate(self, key: str, value: Any) -> "QueryProfiler":
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NO_OP = _NoOpProfiler()


class StepMetrics:
    __slots__ = ("name", "count", "time_ns", "own_ns")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.time_ns = 0
        self.own_ns = 0


class TraversalMetrics:
    """Per-step timing/count table returned by ``traversal.profile()``
    (reference: TP3ProfileWrapper → TinkerPop TraversalMetrics)."""

    def __init__(self, steps: list[StepMetrics], total_ns: int,
                 compiled: bool = False):
        self.steps = steps
        self.total_ns = total_ns
        self.compiled = compiled

    @property
    def total_ms(self) -> float:
        return self.total_ns / 1e6

    def render(self) -> str:
        header = f"{'step':<32}{'traversers':>12}{'time(ms)':>12}{'%':>8}"
        lines = [header, "-" * len(header)]
        for s in self.steps:
            pct = 100.0 * s.own_ns / self.total_ns if self.total_ns else 0.0
            lines.append(f"{s.name:<32}{s.count:>12}"
                         f"{s.own_ns / 1e6:>12.3f}{pct:>8.2f}")
        lines.append("-" * len(header))
        lines.append(f"{'TOTAL':<32}{'':>12}{self.total_ns / 1e6:>12.3f}"
                     f"{100.0 if self.total_ns else 0.0:>8.2f}")
        if self.compiled:
            lines.append("(executed as a compiled OLAP superstep program)")
        return "\n".join(lines)

    def __repr__(self):
        return f"TraversalMetrics({len(self.steps)} steps, " \
               f"{self.total_ms:.3f}ms)"


class TimedStage:
    """Iterator wrapper accumulating pull time + traverser count for one
    step of the interpreter pipeline. Own time = this stage's pull time
    minus the upstream stage's (they nest, since pulling here drives the
    whole upstream chain)."""

    def __init__(self, inner, metrics: StepMetrics,
                 upstream: Optional["TimedStage"]):
        self._inner = iter(inner)
        self.metrics = metrics
        self._upstream = upstream

    def __iter__(self):
        return self

    def __next__(self):
        t0 = time.perf_counter_ns()
        try:
            item = next(self._inner)
        finally:
            self.metrics.time_ns += time.perf_counter_ns() - t0
        self.metrics.count += 1
        return item

    def finalize(self) -> None:
        up = self._upstream.metrics.time_ns if self._upstream else 0
        self.metrics.own_ns = max(0, self.metrics.time_ns - up)
