"""titan_tpu — a TPU-native distributed transactional property-graph framework.

Capability surface modeled on the reference graph database surveyed in
SURVEY.md (Titan 1.0): OLTP property graph with schema + composite/mixed
indexes over a pluggable BigTable-style storage SPI, Gremlin-style traversal,
and an OLAP vertex-program engine that executes frontier supersteps as batched
JAX gather/segment-reduce kernels over a chip-sharded CSR snapshot of the edge
store (``titan_tpu.olap.tpu``).

Entry point parity with the reference's ``TitanFactory.open``
(reference: titan-core core/TitanFactory.java:42):

    import titan_tpu
    g = titan_tpu.open("inmemory")              # shorthand
    g = titan_tpu.open({"storage.backend": "inmemory"})
"""

__version__ = "0.1.0"

from titan_tpu import errors


def open(config):  # noqa: A001  (deliberate builtin shadow, package-level)
    """Open a graph (lazy import keeps the core importable without JAX)."""
    from titan_tpu.factory import open_graph
    return open_graph(config)


def open_log_processors(graph):
    """Change-stream framework over the graph's user trigger logs
    (reference: TitanFactory.openTransactionLog → LogProcessorFramework)."""
    from titan_tpu.core.changes import LogProcessorFramework
    return LogProcessorFramework(graph)


__all__ = ["open", "open_log_processors", "errors", "__version__"]
