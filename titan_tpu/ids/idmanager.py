"""Element-id bit layout and key mapping.

TPU-first redesign of the reference's id scheme (reference: titan-core
graphdb/idmanagement/IDManager.java:428-555). The reference packs ids as
``[count | partition | variable-length type suffix]``; we keep the same field
ORDER (count in the MSBs, partition in the middle, type in the LSBs) but make
the type field a FIXED 4-bit code. Rationale: fixed-width fields decode with
one mask/shift, which vectorizes over numpy/jnp arrays — the OLAP snapshot
builder and the TPU kernels strip type/partition bits on-device; a
variable-length suffix would force host-side scalar loops.

Layout of a 63-bit element id (bit 63 kept zero — ids are non-negative):

    [ count : 59-P bits | partition : P bits | type : 4 bits ]

P = log2(cluster.max-partitions), fixed at cluster creation.

Key mapping for key-ordered stores moves the partition field to the MSBs so a
partition occupies one contiguous key range (reference: IDManager.getKey
IDManager.java:467-493):

    key = [ partition : P bits | count : 59-P bits | type : 4 bits ]

Partitioned ("vertex-cut") vertices spread one logical vertex over ALL
partitions; each copy's id substitutes a different partition value and the
canonical representative lives at partition ``hash(count) % num_partitions``
(reference: IDManager.getPartitionedVertexRepresentatives IDManager.java:547-555).

Relation (edge/property) ids live in their own unpartitioned counter space.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np

from titan_tpu.errors import InvalidIDError

TYPE_BITS = 4
TYPE_MASK = (1 << TYPE_BITS) - 1
TOTAL_BITS = 63  # keep sign bit clear


class IDType(enum.IntEnum):
    """4-bit element type code (LSBs of every element id)."""
    NORMAL_VERTEX = 0
    PARTITIONED_VERTEX = 1
    UNMODIFIABLE_VERTEX = 2
    INVISIBLE_VERTEX = 3
    USER_PROPERTY_KEY = 4
    SYSTEM_PROPERTY_KEY = 5
    USER_EDGE_LABEL = 6
    SYSTEM_EDGE_LABEL = 7
    VERTEX_LABEL = 8
    GENERIC_SCHEMA = 9

    @property
    def is_user_vertex(self) -> bool:
        return self in (IDType.NORMAL_VERTEX, IDType.PARTITIONED_VERTEX,
                        IDType.UNMODIFIABLE_VERTEX)

    @property
    def is_schema(self) -> bool:
        return self >= IDType.USER_PROPERTY_KEY

    @property
    def is_relation_type(self) -> bool:
        return self in (IDType.USER_PROPERTY_KEY, IDType.SYSTEM_PROPERTY_KEY,
                        IDType.USER_EDGE_LABEL, IDType.SYSTEM_EDGE_LABEL)

    @property
    def is_property_key(self) -> bool:
        return self in (IDType.USER_PROPERTY_KEY, IDType.SYSTEM_PROPERTY_KEY)

    @property
    def is_edge_label(self) -> bool:
        return self in (IDType.USER_EDGE_LABEL, IDType.SYSTEM_EDGE_LABEL)

    @property
    def is_system(self) -> bool:
        return self in (IDType.SYSTEM_PROPERTY_KEY, IDType.SYSTEM_EDGE_LABEL,
                        IDType.INVISIBLE_VERTEX)


SCHEMA_PARTITION = 0


@dataclass(frozen=True)
class IDManager:
    """Stateless id packing/unpacking for a fixed partition-bit width."""

    partition_bits: int

    def __post_init__(self):
        if not (0 <= self.partition_bits <= 16):
            raise InvalidIDError(f"partition_bits out of range: {self.partition_bits}")

    # -- derived constants --------------------------------------------------

    @property
    def num_partitions(self) -> int:
        return 1 << self.partition_bits

    @property
    def partition_mask(self) -> int:
        return (1 << self.partition_bits) - 1

    @property
    def count_bits(self) -> int:
        return TOTAL_BITS - TYPE_BITS - self.partition_bits

    @property
    def max_count(self) -> int:
        return (1 << self.count_bits) - 1

    @property
    def max_relation_count(self) -> int:
        return (1 << TOTAL_BITS) - 1

    # -- packing ------------------------------------------------------------

    def make_id(self, idtype: IDType, count: int, partition: int = 0) -> int:
        if not (0 < count <= self.max_count):
            raise InvalidIDError(f"count out of range: {count}")
        if not (0 <= partition < self.num_partitions):
            raise InvalidIDError(f"partition out of range: {partition}")
        if idtype.is_schema and partition != SCHEMA_PARTITION:
            raise InvalidIDError("schema ids live in partition 0")
        return (count << (TYPE_BITS + self.partition_bits)) | \
               (partition << TYPE_BITS) | int(idtype)

    def vertex_id(self, count: int, partition: int,
                  idtype: IDType = IDType.NORMAL_VERTEX) -> int:
        if not idtype.is_user_vertex:
            raise InvalidIDError(f"not a user vertex type: {idtype}")
        return self.make_id(idtype, count, partition)

    def schema_id(self, idtype: IDType, count: int) -> int:
        if not idtype.is_schema:
            raise InvalidIDError(f"not a schema type: {idtype}")
        return self.make_id(idtype, count, SCHEMA_PARTITION)

    def relation_id(self, count: int) -> int:
        """Relation ids are a bare counter (no partition/type fields); they
        never appear as row keys."""
        if not (0 < count <= self.max_relation_count):
            raise InvalidIDError(f"relation count out of range: {count}")
        return count

    # -- unpacking ----------------------------------------------------------

    def id_type(self, eid: int) -> IDType:
        try:
            return IDType(eid & TYPE_MASK)
        except ValueError:
            raise InvalidIDError(f"unknown type code in id {eid}")

    def partition(self, eid: int) -> int:
        return (eid >> TYPE_BITS) & self.partition_mask

    def count(self, eid: int) -> int:
        return eid >> (TYPE_BITS + self.partition_bits)

    def is_user_vertex_id(self, eid: int) -> bool:
        return eid > 0 and (eid & TYPE_MASK) <= int(IDType.UNMODIFIABLE_VERTEX)

    def is_schema_id(self, eid: int) -> bool:
        return eid > 0 and (eid & TYPE_MASK) >= int(IDType.USER_PROPERTY_KEY)

    def is_partitioned_vertex(self, eid: int) -> bool:
        return (eid & TYPE_MASK) == int(IDType.PARTITIONED_VERTEX)

    # -- key mapping (partition bits → MSBs) --------------------------------

    def key_of(self, eid: int) -> int:
        """Element id → 63-bit key integer with partition in the MSBs, so each
        partition is one contiguous key range in a key-ordered store."""
        t = eid & TYPE_MASK
        p = self.partition(eid)
        c = self.count(eid)
        return (p << (TOTAL_BITS - self.partition_bits)) | (c << TYPE_BITS) | t

    def id_of_key(self, key: int) -> int:
        p = key >> (TOTAL_BITS - self.partition_bits)
        c = (key >> TYPE_BITS) & ((1 << self.count_bits) - 1)
        t = key & TYPE_MASK
        return (c << (TYPE_BITS + self.partition_bits)) | (p << TYPE_BITS) | t

    def key_bytes(self, eid: int) -> bytes:
        return self.key_of(eid).to_bytes(8, "big")

    def id_of_key_bytes(self, key: bytes) -> int:
        return self.id_of_key(int.from_bytes(key, "big"))

    def partition_key_range(self, partition: int) -> tuple[bytes, bytes]:
        """[start, end) key range holding every element of a partition."""
        shift = TOTAL_BITS - self.partition_bits
        start = partition << shift
        end = (partition + 1) << shift
        return start.to_bytes(8, "big"), end.to_bytes(8, "big")

    # -- partitioned (vertex-cut) vertices ----------------------------------

    def canonical_partition(self, count: int) -> int:
        # cheap splittable hash so canonical copies spread over partitions
        h = (count * 0x9E3779B97F4A7C15) & ((1 << 64) - 1)
        return (h >> 40) & self.partition_mask

    def partitioned_vertex_id(self, count: int, partition: int) -> int:
        return self.make_id(IDType.PARTITIONED_VERTEX, count, partition)

    def canonical_vertex_id(self, eid: int) -> int:
        """Canonical representative of a partitioned vertex (identity for
        ordinary vertices)."""
        if not self.is_partitioned_vertex(eid):
            return eid
        c = self.count(eid)
        return self.partitioned_vertex_id(c, self.canonical_partition(c))

    def partitioned_vertex_representatives(self, eid: int) -> list[int]:
        if not self.is_partitioned_vertex(eid):
            raise InvalidIDError(f"not a partitioned vertex: {eid}")
        c = self.count(eid)
        return [self.partitioned_vertex_id(c, p) for p in range(self.num_partitions)]

    def canonicalize_np(self, ids: np.ndarray) -> np.ndarray:
        """Vectorized canonical_vertex_id: partitioned-vertex ids are mapped
        to their canonical representative, everything else passes through
        (the OLAP snapshot builder merges vertex-cut rows with this)."""
        ids = np.asarray(ids, dtype=np.int64)
        is_part = (ids & TYPE_MASK) == int(IDType.PARTITIONED_VERTEX)
        if not is_part.any():
            return ids
        counts = ids >> (TYPE_BITS + self.partition_bits)
        h = counts.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
        canon_p = ((h >> np.uint64(40)).astype(np.int64)) & self.partition_mask
        canon = ((counts << (TYPE_BITS + self.partition_bits))
                 | (canon_p << TYPE_BITS) | int(IDType.PARTITIONED_VERTEX))
        return np.where(is_part, canon, ids)

    # -- vectorized unpacking (device/bulk paths) ---------------------------

    def partitions_np(self, ids: np.ndarray) -> np.ndarray:
        return (ids >> TYPE_BITS) & self.partition_mask

    def counts_np(self, ids: np.ndarray) -> np.ndarray:
        return ids >> (TYPE_BITS + self.partition_bits)

    def types_np(self, ids: np.ndarray) -> np.ndarray:
        return ids & TYPE_MASK

    def keys_np(self, ids: np.ndarray) -> np.ndarray:
        t = ids & TYPE_MASK
        p = (ids >> TYPE_BITS) & self.partition_mask
        c = ids >> (TYPE_BITS + self.partition_bits)
        return (p << (TOTAL_BITS - self.partition_bits)) | (c << TYPE_BITS) | t
