"""Partition placement strategies.

(reference: titan-core graphdb/database/idassigner/placement/
SimpleBulkPlacementStrategy.java — picks a random partition and reuses it for
a batch of vertices so co-created vertices co-locate; PropertyPlacementStrategy
hashes a designated property so equal values co-locate.)
"""

from __future__ import annotations

import random
import threading
from typing import Optional


class IDPlacementStrategy:
    def partition_for(self, vertex) -> int:
        raise NotImplementedError

    def exhausted(self, partition: int) -> None:
        """Called when a partition's id space ran out; avoid it from now on."""


class SimpleBulkPlacement(IDPlacementStrategy):
    def __init__(self, num_partitions: int, batch_size: int = 10_000,
                 seed: Optional[int] = None):
        self._n = num_partitions
        self._batch = batch_size
        self._rng = random.Random(seed)
        self._exhausted: set[int] = set()
        self._lock = threading.Lock()
        self._current = self._pick()
        self._used = 0

    def _pick(self) -> int:
        live = [p for p in range(self._n) if p not in self._exhausted]
        if not live:
            raise RuntimeError("all partitions exhausted")
        return self._rng.choice(live)

    def partition_for(self, vertex) -> int:
        with self._lock:
            self._used += 1
            if self._used >= self._batch or self._current in self._exhausted:
                self._current = self._pick()
                self._used = 0
            return self._current

    def exhausted(self, partition: int) -> None:
        with self._lock:
            self._exhausted.add(partition)
            if self._current == partition:
                self._current = self._pick()
                self._used = 0


class PropertyPlacement(IDPlacementStrategy):
    """Co-locate vertices by the hash of a property value
    (reference: placement/PropertyPlacementStrategy.java)."""

    def __init__(self, num_partitions: int, key_name: str,
                 fallback: Optional[IDPlacementStrategy] = None):
        self._n = num_partitions
        self._key = key_name
        self._fallback = fallback or SimpleBulkPlacement(num_partitions)

    def partition_for(self, vertex) -> int:
        value = None
        getter = getattr(vertex, "pending_property", None)
        if getter is not None:
            value = getter(self._key)
        if value is None:
            return self._fallback.partition_for(vertex)
        h = hash((self._key, value)) & 0x7FFFFFFF
        return h % self._n

    def exhausted(self, partition: int) -> None:
        self._fallback.exhausted(partition)
