"""Vertex/relation/schema id assignment.

(reference: titan-core graphdb/database/idassigner/VertexIDAssigner.java:486 —
routes each new element to an id pool: vertices to a per-partition pool chosen
by the placement strategy (retrying exhausted partitions, :44
MAX_PARTITION_RENEW_ATTEMPTS), relations to a flat pool, schema elements to
the partition-0 schema namespace.)
"""

from __future__ import annotations

import threading

from titan_tpu.errors import IDPoolExhaustedError
from titan_tpu.ids.authority import IDAuthority
from titan_tpu.ids.idmanager import IDManager, IDType
from titan_tpu.ids.placement import IDPlacementStrategy, SimpleBulkPlacement
from titan_tpu.ids.pool import StandardIDPool

MAX_PARTITION_ATTEMPTS = 10


class IDAssigner:
    def __init__(self, idm: IDManager, authority: IDAuthority,
                 block_size: int = 10_000, renew_percentage: float = 0.3,
                 placement: IDPlacementStrategy | None = None):
        self._idm = idm
        self._authority = authority
        self._block_size = block_size
        self._renew = renew_percentage
        self.placement = placement or SimpleBulkPlacement(idm.num_partitions)
        self._vertex_pools: dict[int, StandardIDPool] = {}
        self._relation_pool = StandardIDPool(
            authority, b"relation", block_size * 4, idm.max_relation_count,
            renew_percentage)
        self._schema_pool = StandardIDPool(
            authority, b"schema", 64, idm.max_count, renew_percentage)
        self._lock = threading.Lock()

    def _vertex_pool(self, partition: int) -> StandardIDPool:
        pool = self._vertex_pools.get(partition)
        if pool is None:
            with self._lock:
                pool = self._vertex_pools.get(partition)
                if pool is None:
                    pool = StandardIDPool(
                        self._authority, b"partition%d" % partition,
                        self._block_size, self._idm.max_count, self._renew)
                    self._vertex_pools[partition] = pool
        return pool

    def next_vertex_id(self, vertex=None,
                       idtype: IDType = IDType.NORMAL_VERTEX) -> int:
        for _ in range(MAX_PARTITION_ATTEMPTS):
            partition = self.placement.partition_for(vertex)
            try:
                count = self._vertex_pool(partition).next_id()
            except IDPoolExhaustedError:
                self.placement.exhausted(partition)
                continue
            return self._idm.vertex_id(count, partition, idtype)
        raise IDPoolExhaustedError("no partition with available ids")

    def next_relation_id(self) -> int:
        return self._idm.relation_id(self._relation_pool.next_id())

    def next_schema_id(self, idtype: IDType) -> int:
        return self._idm.schema_id(idtype, self._schema_pool.next_id())

    def close(self):
        for p in self._vertex_pools.values():
            p.close()
        self._relation_pool.close()
        self._schema_pool.close()
