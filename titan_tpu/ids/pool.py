"""Per-namespace id pools with background block renewal.

(reference: titan-core graphdb/database/idassigner/StandardIDPool.java:291 —
claims contiguous blocks from the IDAuthority and hands out ids one at a
time; when the current block is ``renew_percentage`` from exhaustion a
background fetch starts so callers rarely block on the authority.)
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional

from titan_tpu.errors import IDPoolExhaustedError
from titan_tpu.ids.authority import IDAuthority, IDBlock

_renew_pool = ThreadPoolExecutor(max_workers=2, thread_name_prefix="idpool-renew")


class StandardIDPool:
    def __init__(self, authority: IDAuthority, namespace: bytes,
                 block_size: int, max_id: int, renew_percentage: float = 0.3,
                 renew_timeout_s: float = 120.0):
        self._authority = authority
        self._namespace = namespace
        self._block_size = block_size
        self._max_id = max_id
        self._renew_at = max(1, int(block_size * renew_percentage))
        self._timeout = renew_timeout_s
        self._lock = threading.Lock()
        self._block: Optional[IDBlock] = None
        self._next = 0
        self._pending: Optional[Future] = None
        self._closed = False

    def _fetch(self) -> IDBlock:
        block = self._authority.get_id_block(self._namespace, self._block_size,
                                             self._timeout)
        if block.start >= self._max_id:
            raise IDPoolExhaustedError(
                f"id namespace {self._namespace!r} exhausted (max {self._max_id})")
        return block

    def next_id(self) -> int:
        with self._lock:
            if self._closed:
                raise IDPoolExhaustedError("pool closed")
            while self._block is None or self._next >= self._block.end:
                if self._pending is not None:
                    fut, self._pending = self._pending, None
                    self._block = fut.result()
                else:
                    self._block = self._fetch()
                self._next = self._block.start
            nid = self._next
            self._next += 1
            if (self._block.end - self._next) == self._renew_at and \
                    self._pending is None:
                self._pending = _renew_pool.submit(self._fetch)
            if nid >= self._max_id:
                raise IDPoolExhaustedError(
                    f"id namespace {self._namespace!r} exhausted")
            return nid

    def close(self):
        with self._lock:
            self._closed = True
