"""Cluster-unique id-block allocation over the storage backend.

Re-creation of the reference's lock-free timestamped-claim protocol
(reference: titan-core diskstorage/idmanagement/ConsistentKeyIDAuthority.java:200+,
AbstractIDAuthority.java, IDBlock): allocation never uses locks — an instance
proposes a claim column for the next block, waits out the uncertainty window,
re-reads, and owns the block iff its claim sorts first (earliest timestamp,
uid tiebreak). Losers delete their claim and retry. All coordination happens
through the shared ``system_ids`` store, so any key-consistent backend works.

Claim column layout (byte-ordered so one slice read finds the newest block):

    [ 2^63 - block_end : u64 big-endian ][ timestamp : u64 ][ uid bytes ]

The complement puts the HIGHEST block first; within one block_end, claims
sort by (timestamp, uid) — the total order that picks the winner.
"""

from __future__ import annotations

import abc
import logging
import time as _time
from dataclasses import dataclass

from titan_tpu.errors import IDPoolExhaustedError, TemporaryBackendError
from titan_tpu.storage.api import Entry, KeySliceQuery, SliceQuery
from titan_tpu.storage.tx import backend_op
from titan_tpu.utils.times import TimestampProvider

log = logging.getLogger(__name__)

_COMPL = 1 << 63


@dataclass(frozen=True)
class IDBlock:
    start: int  # inclusive
    end: int    # exclusive

    def __len__(self):
        return self.end - self.start


class IDAuthority(abc.ABC):
    @abc.abstractmethod
    def get_id_block(self, namespace: bytes, block_size: int,
                     timeout_s: float) -> IDBlock: ...

    def close(self) -> None:
        pass


class LocalIDAuthority(IDAuthority):
    """In-process allocator for tests/single-process graphs."""

    def __init__(self):
        import threading
        self._next: dict[bytes, int] = {}
        self._lock = threading.Lock()

    def get_id_block(self, namespace: bytes, block_size: int,
                     timeout_s: float = 0) -> IDBlock:
        with self._lock:
            start = self._next.get(namespace, 1)
            self._next[namespace] = start + block_size
            return IDBlock(start, start + block_size)


def _claim_column(block_end: int, timestamp: int, uid: bytes) -> bytes:
    return ((_COMPL - block_end).to_bytes(8, "big") +
            timestamp.to_bytes(8, "big") + uid)


def _parse_claim(column: bytes) -> tuple[int, int, bytes]:
    block_end = _COMPL - int.from_bytes(column[:8], "big")
    ts = int.from_bytes(column[8:16], "big")
    return block_end, ts, column[16:]


class ConsistentKeyIDAuthority(IDAuthority):
    def __init__(self, store, manager, uid: bytes, times: TimestampProvider,
                 wait_ms: int = 300, base: int = 1):
        self._store = store
        self._manager = manager
        self._uid = uid
        self._times = times
        self._wait = wait_ms / 1000.0
        self._base = base  # first allocatable id (0 is reserved)

    def _tx(self):
        return self._manager.begin_transaction()

    def _read_newest_end(self, namespace: bytes) -> int:
        txh = self._tx()
        try:
            entries = backend_op(
                lambda: self._store.get_slice(
                    KeySliceQuery(namespace, SliceQuery(limit=1)), txh),
                what="idauthority read")
            if not entries:
                return self._base
            block_end, _, _ = _parse_claim(entries[0].column)
            return block_end
        finally:
            txh.commit()

    def get_id_block(self, namespace: bytes, block_size: int,
                     timeout_s: float = 120.0) -> IDBlock:
        deadline = _time.monotonic() + timeout_s
        backoff = 0.01
        while _time.monotonic() < deadline:
            next_start = self._read_newest_end(namespace)
            target_end = next_start + block_size
            ts = self._times.time()
            mine = _claim_column(target_end, ts, self._uid)

            txh = self._tx()
            try:
                self._store.mutate(namespace, [Entry(mine, b"\x01")], [], txh)
                txh.commit()
            except TemporaryBackendError:
                _time.sleep(backoff)
                backoff = min(backoff * 2, 1.0)
                continue

            # uncertainty window: let racing claims become visible
            self._times.sleep_past(ts + int(self._wait * self._times.unit_per_second))

            # re-read ALL claims for this block_end; first sorted wins
            prefix = (_COMPL - target_end).to_bytes(8, "big")
            txh = self._tx()
            try:
                claims = backend_op(
                    lambda: self._store.get_slice(
                        KeySliceQuery(namespace,
                                      SliceQuery(prefix, prefix + b"\xff" * 17)),
                        txh),
                    what="idauthority verify")
            finally:
                txh.commit()
            same_block = [e.column for e in claims
                          if e.column.startswith(prefix)]
            if same_block and same_block[0] == mine:
                return IDBlock(next_start, target_end)

            # lost the race: withdraw our claim and retry
            txh = self._tx()
            try:
                self._store.mutate(namespace, [], [mine], txh)
                txh.commit()
            except TemporaryBackendError:
                pass  # stale claim is harmless: it names an already-won block
            _time.sleep(backoff)
            backoff = min(backoff * 2, 1.0)
        raise IDPoolExhaustedError(
            f"could not claim an id block in {timeout_s}s for {namespace!r}")
