from titan_tpu.ids.idmanager import IDManager, IDType, TYPE_BITS, TYPE_MASK

__all__ = ["IDManager", "IDType", "TYPE_BITS", "TYPE_MASK"]
