"""Index maintenance scan jobs: REINDEX backfill + index data removal.

(reference: titan-core graphdb/olap/job/IndexRepairJob.java — rebuilds a
single index by scanning every element and re-emitting its index entries;
IndexRemoveJob.java — deletes an index's rows from the graphindex store (or
its documents from the mixed provider); both run under SchemaAction via
ManagementSystem.updateIndex and report progress through ScanMetrics.)
"""

from __future__ import annotations

from titan_tpu.core.defs import Direction, RelationCategory
from titan_tpu.olap.api import ScanJob, ScanMetrics
from titan_tpu.storage.api import Entry, SliceQuery
from titan_tpu.storage.scan import StandardScanner

ADDED = "index-entries-added"
REMOVED = "index-rows-removed"
_FLUSH = 1000


class IndexRepairJob(ScanJob):
    """Scan the edgestore and (re)write every entry of ONE index."""

    def __init__(self, graph, index):
        self.graph = graph
        self.index = index
        self.ser = graph.index_serializer
        self.schema = graph.schema
        self._all = SliceQuery()
        self._pending_rows: list = []      # composite: (row_key, Entry)
        self._pending_docs: dict = {}      # mixed: docid -> {field: value}

    def get_queries(self):
        return [self._all]

    def process(self, key: bytes, entries_by_query: dict,
                metrics: ScanMetrics) -> None:
        entries = entries_by_query[self._all]
        if not entries:
            return
        eid = self.graph.idm.id_of_key_bytes(key)
        if self.index.element == "vertex":
            self._process_vertex(eid, entries, metrics)
        else:
            self._process_edges(eid, entries, metrics)

    def _process_vertex(self, vid: int, entries, metrics) -> None:
        if not self.graph.idm.is_user_vertex_id(vid):
            return
        values: dict[int, list] = {}
        alive = False
        for e in entries:
            rc = self.graph.codec.parse(e, self.schema)
            if rc.type_id == self.schema.system.vertex_exists:
                alive = True
            if rc.category is RelationCategory.PROPERTY and \
                    rc.type_id in self.index.key_ids:
                values.setdefault(rc.type_id, []).append(rc.value)
        if not alive:
            return
        if any(k not in values for k in self.index.key_ids):
            return   # all-keys-present rule
        if self.index.composite:
            from itertools import product
            if len(self.index.key_ids) > 1 and \
                    any(len(v) > 1 for v in values.values()):
                # the live write path rejects multi-valued keys on multi-key
                # composite indexes — don't backfill rows it can't maintain
                metrics.increment(ScanMetrics.FAILURE)
                return
            col = self.ser.vertex_column(vid)
            for vals in product(*(values[k] for k in self.index.key_ids)):
                row = self.ser.composite_row_key(self.index, vals)
                self._pending_rows.append((row, Entry(col, b"")))
                metrics.increment(ADDED)
        else:
            doc = {}
            for kid in self.index.key_ids:
                name = self.schema.get_type(kid).name
                vals = values[kid]
                doc[name] = vals[0] if len(vals) == 1 else list(vals)
            self._pending_docs[self.ser.docid_for(vid)] = doc
            metrics.increment(ADDED)

    def _process_edges(self, vid: int, entries, metrics) -> None:
        for e in entries:
            rc = self.graph.codec.parse(e, self.schema)
            if rc.category is not RelationCategory.EDGE or \
                    rc.direction is not Direction.OUT:
                continue   # each edge indexes once, from its OUT row
            if self.schema.system.is_system(rc.type_id):
                continue
            if self.index.index_only and rc.type_id != self.index.index_only:
                continue
            vals = []
            for kid in self.index.key_ids:
                if kid not in rc.properties:
                    break
                vals.append(rc.properties[kid])
            else:
                if self.index.composite:
                    row = self.ser.composite_row_key(self.index, vals)

                    class _R:   # edge_column needs the relation view
                        relation_id = rc.relation_id
                        out_vertex_id = vid
                        in_vertex_id = rc.other_vertex_id
                        type_id = rc.type_id
                    self._pending_rows.append(
                        (row, Entry(self.ser.edge_column(_R), b"")))
                else:
                    doc = {self.schema.get_type(k).name: v
                           for k, v in zip(self.index.key_ids, vals)}
                    self._pending_docs[self.ser.docid_for(rc.relation_id)] = doc
                metrics.increment(ADDED)

    def worker_iteration_end(self, metrics: ScanMetrics) -> None:
        if self._pending_rows:
            batch, self._pending_rows = self._pending_rows, []
            backend = self.graph.backend
            txh = backend.manager.begin_transaction()
            try:
                for row, entry in batch:
                    backend.index_store.store.mutate(row, [entry], [], txh)
                    backend.index_store.invalidate(row)
                txh.commit()
            except BaseException:
                txh.rollback()
                raise
        if self._pending_docs:
            docs, self._pending_docs = self._pending_docs, {}
            provider = self.graph.index_provider(self.index.backing)
            from titan_tpu.indexing.provider import IndexMutation
            provider.mutate({self.index.name: {
                docid: IndexMutation(additions=doc)
                for docid, doc in docs.items()}})


class IndexRemoveJob(ScanJob):
    """Delete every row of ONE composite index from the graphindex store
    (scans the graphindex store itself, keyed by the index-id prefix)."""

    def __init__(self, graph, index):
        self.graph = graph
        self.index = index
        from titan_tpu.codec.dataio import DataOutput
        out = DataOutput()
        out.put_uvar(index.id)
        self._prefix = out.getvalue()
        self._all = SliceQuery()
        self._pending: list = []

    def get_queries(self):
        return [self._all]

    def process(self, key: bytes, entries_by_query: dict,
                metrics: ScanMetrics) -> None:
        if not key.startswith(self._prefix):
            return
        cols = [e.column for e in entries_by_query[self._all]]
        if cols:
            self._pending.append((key, cols))
            metrics.increment(REMOVED)

    def worker_iteration_end(self, metrics: ScanMetrics) -> None:
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        backend = self.graph.backend
        txh = backend.manager.begin_transaction()
        try:
            for key, cols in batch:
                backend.index_store.store.mutate(key, [], cols, txh)
                backend.index_store.invalidate(key)
            txh.commit()
        except BaseException:
            txh.rollback()
            raise


def reindex(graph, index, num_threads: int = 2) -> ScanMetrics:
    """Backfill an index from existing data (SchemaAction.REINDEX)."""
    if not index.composite:
        provider = graph.index_provider(index.backing)
        if provider is not None:   # replay field registrations
            graph.index_serializer.register_keys(provider, index)
    scanner = StandardScanner(graph.backend.edge_store.store,
                              graph.backend.manager)
    return scanner.execute(IndexRepairJob(graph, index), graph,
                           num_threads=num_threads)


def remove_index_data(graph, index, num_threads: int = 2) -> ScanMetrics:
    """Drop an index's stored data (SchemaAction.REMOVE_INDEX)."""
    if index.composite:
        scanner = StandardScanner(graph.backend.index_store.store,
                                  graph.backend.manager)
        return scanner.execute(IndexRemoveJob(graph, index), graph,
                               num_threads=num_threads)
    provider = graph.index_provider(index.backing)
    if provider is not None:
        provider.drop_store(index.name)
    return ScanMetrics()
