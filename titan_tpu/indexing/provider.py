"""The mixed-index provider SPI.

(reference: titan-core diskstorage/indexing/IndexProvider.java:18-105 —
typed key registration, batched document mutations, condition-tree queries,
native raw queries, feature flags; IndexTransaction.java buffers mutations
per (store, docid) and flushes on commit.)
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

from titan_tpu.core.defs import Cardinality
from titan_tpu.query.predicates import P


# -- condition tree ----------------------------------------------------------

@dataclass(frozen=True)
class FieldCondition:
    field: str
    predicate: P

    def evaluate(self, doc: dict) -> bool:
        value = doc.get(self.field)
        if value is None:
            return False          # missing field never matches a predicate
        if isinstance(value, list):
            return any(self.predicate(v) for v in value)
        return self.predicate(value)


@dataclass(frozen=True)
class And:
    children: tuple

    def evaluate(self, doc: dict) -> bool:
        return all(c.evaluate(doc) for c in self.children)


@dataclass(frozen=True)
class Or:
    children: tuple

    def evaluate(self, doc: dict) -> bool:
        return any(c.evaluate(doc) for c in self.children)


@dataclass(frozen=True)
class Not:
    child: Any

    def evaluate(self, doc: dict) -> bool:
        return not self.child.evaluate(doc)


@dataclass(frozen=True)
class IndexQuery:
    """Condition tree + optional order/limit.
    (reference: diskstorage/indexing/IndexQuery.java)"""
    condition: Any
    orders: tuple = ()          # ((field, "asc"|"desc"), ...)
    limit: Optional[int] = None


@dataclass(frozen=True)
class RawQuery:
    """Provider-native query string (reference: indexing/RawQuery.java)."""
    query: str
    limit: Optional[int] = None
    offset: int = 0


@dataclass(frozen=True)
class KeyInformation:
    """What the provider needs to know about an indexed field.
    (reference: diskstorage/indexing/KeyInformation.java)"""
    dtype: type
    cardinality: Cardinality = Cardinality.SINGLE
    parameters: tuple = ()      # mapping hints, e.g. ("TEXT",) / ("STRING",)


@dataclass(frozen=True)
class IndexFeatures:
    """Capability flags the query planner branches on.
    (reference: diskstorage/indexing/IndexFeatures.java)"""
    supports_text: bool = True
    supports_geo: bool = True
    supports_numeric_range: bool = True
    supports_order: bool = True
    supports_raw_query: bool = False


# -- mutations ---------------------------------------------------------------

@dataclass
class IndexMutation:
    """Field changes for one document. ``deleted`` drops the whole doc.
    (reference: diskstorage/indexing/IndexMutation.java)"""
    additions: dict = field(default_factory=dict)   # field -> value
    deletions: set = field(default_factory=set)     # field names
    deleted: bool = False

    @property
    def empty(self) -> bool:
        return not self.additions and not self.deletions and not self.deleted


# -- SPI ---------------------------------------------------------------------

class IndexProvider(abc.ABC):
    name: str = "index"

    @property
    @abc.abstractmethod
    def features(self) -> IndexFeatures: ...

    @abc.abstractmethod
    def register(self, store: str, key: str, info: KeyInformation) -> None:
        """Declare a field before first use (type + mapping hints)."""

    @abc.abstractmethod
    def mutate(self, mutations: dict[str, dict[str, IndexMutation]]) -> None:
        """Apply {store -> {docid -> IndexMutation}} atomically-ish."""

    @abc.abstractmethod
    def query(self, store: str, query: IndexQuery) -> list[str]:
        """Doc ids matching a condition tree, ordered per query.orders."""

    def raw_query(self, store: str, query: RawQuery) -> list[tuple[str, float]]:
        """(docid, score) for a native query string."""
        raise NotImplementedError(f"{self.name} has no raw-query support")

    @abc.abstractmethod
    def close(self) -> None: ...

    def clear_storage(self) -> None:
        """Drop all documents (test helper)."""

    def drop_store(self, store: str) -> None:
        """Drop one index's documents (REMOVE_INDEX lifecycle)."""

    def begin_transaction(self) -> "IndexTransaction":
        return IndexTransaction(self)

    def supports(self, info: KeyInformation, predicate: P) -> bool:
        """Can this provider answer ``predicate`` on a field of this type +
        mapping? (reference: IndexProvider.supports — string fields follow
        their mapping: TEXT (default) answers tokenized text predicates,
        STRING answers exact/prefix/regex-on-whole-value predicates.)"""
        op = predicate.op
        f = self.features
        if info.dtype is str:
            string_mapped = "STRING" in info.parameters
            if op in ("textContains", "textPrefix", "textRegex"):
                return f.supports_text and not string_mapped
            if op in ("stringPrefix", "stringRegex"):
                return f.supports_text and string_mapped
            if op in ("eq", "neq", "within", "without"):
                return string_mapped
            return False
        try:
            from titan_tpu.core.attribute import Geoshape
            if info.dtype is Geoshape:
                return f.supports_geo and op in (
                    "geoWithin", "geoIntersect", "geoDisjoint", "geoContains")
        except ImportError:
            pass
        if op in ("lt", "lte", "gt", "gte", "between", "inside"):
            return f.supports_numeric_range
        return op in ("eq", "neq", "within", "without")


class IndexTransaction:
    """Buffers document mutations; flushed on commit as ONE provider call.
    (reference: diskstorage/indexing/IndexTransaction.java)"""

    def __init__(self, provider: IndexProvider):
        self.provider = provider
        self._mutations: dict[str, dict[str, IndexMutation]] = {}

    def _m(self, store: str, docid: str) -> IndexMutation:
        return self._mutations.setdefault(store, {}).setdefault(
            docid, IndexMutation())

    def add(self, store: str, docid: str, field_name: str, value) -> None:
        m = self._m(store, docid)
        m.additions[field_name] = value
        m.deletions.discard(field_name)

    def delete(self, store: str, docid: str, field_name: str) -> None:
        m = self._m(store, docid)
        m.additions.pop(field_name, None)
        m.deletions.add(field_name)

    def delete_document(self, store: str, docid: str) -> None:
        m = self._m(store, docid)
        m.additions.clear()
        m.deletions.clear()
        m.deleted = True

    def register(self, store: str, key: str, info: KeyInformation) -> None:
        self.provider.register(store, key, info)

    def query(self, store: str, query: IndexQuery) -> list[str]:
        return self.provider.query(store, query)

    def raw_query(self, store: str, query: RawQuery):
        return self.provider.raw_query(store, query)

    def commit(self) -> None:
        if self._mutations:
            self.provider.mutate(self._mutations)
            self._mutations = {}

    def rollback(self) -> None:
        self._mutations = {}
