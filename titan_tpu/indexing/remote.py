"""Remote index provider: an index node over HTTP + the client adapter.

The networked index tier (reference: titan-es ElasticSearchIndex.java — an
external index SERVICE reached over the network implementing the
IndexProvider SPI; titan-solr plays the same role). An ``IndexServer``
hosts any local provider (the FTS5 engine for persistence, the in-memory
one for tests); ``RemoteIndexProvider`` — configured as
``index.<name>.backend=remote-index`` with hostname/port — forwards the
SPI over JSON. Values ride the framework's self-describing attribute
serializer (base64) so Geoshape/datetime/etc. round-trip; predicate trees
are reconstructed server-side from (op, value) pairs.
"""

from __future__ import annotations

import base64
from typing import Optional

from titan_tpu.codec.attributes import Serializer
from titan_tpu.errors import PermanentBackendError
from titan_tpu.utils.httpnode import JsonNode, json_call, run_node_cli
from titan_tpu.indexing.provider import (And, FieldCondition, IndexFeatures,
                                         IndexMutation, IndexProvider,
                                         IndexQuery, KeyInformation, Not, Or,
                                         RawQuery)
from titan_tpu.query.predicates import P

_SER = Serializer()


def _v(x) -> str:
    return base64.b64encode(_SER.value_bytes(x)).decode()


def _uv(s: str):
    return _SER.value_from_bytes(base64.b64decode(s))


_MULTI_OPS = {"between", "inside", "within", "without"}


def _p_to_wire(p: P) -> dict:
    # multi-valued predicates carry tuples/sets, which the attribute
    # serializer doesn't encode — ship their elements individually
    if p.op in _MULTI_OPS:
        return {"op": p.op, "vs": [_v(x) for x in p.value]}
    return {"op": p.op, "value": _v(p.value)}


_P_FACTORIES = {
    "eq": P.eq, "neq": P.neq, "lt": P.lt, "lte": P.lte, "gt": P.gt,
    "gte": P.gte,
    "between": lambda v: P.between(*v), "inside": lambda v: P.inside(*v),
    "within": lambda v: P.within(*v), "without": lambda v: P.without(*v),
    "textContains": P.text_contains, "textPrefix": P.text_prefix,
    "textRegex": P.text_regex, "stringPrefix": P.string_prefix,
    "stringRegex": P.string_regex, "geoWithin": P.geo_within,
    "geoIntersect": P.geo_intersect, "geoDisjoint": P.geo_disjoint,
    "geoContains": P.geo_contains,
}


def _p_from_wire(d: dict) -> P:
    try:
        factory = _P_FACTORIES[d["op"]]
    except KeyError:
        raise PermanentBackendError(f"unknown predicate op {d['op']!r}")
    if "vs" in d:
        # every multi-op factory takes the value sequence as ONE argument
        # (the lambdas in _P_FACTORIES unpack as needed)
        return factory([_uv(x) for x in d["vs"]])
    return factory(_uv(d["value"]))


def _cond_to_wire(c) -> dict:
    if isinstance(c, FieldCondition):
        return {"t": "f", "field": c.field, "p": _p_to_wire(c.predicate)}
    if isinstance(c, And):
        return {"t": "and", "c": [_cond_to_wire(x) for x in c.children]}
    if isinstance(c, Or):
        return {"t": "or", "c": [_cond_to_wire(x) for x in c.children]}
    if isinstance(c, Not):
        return {"t": "not", "c": _cond_to_wire(c.child)}
    raise PermanentBackendError(f"unserializable condition {type(c).__name__}")


def _cond_from_wire(d: dict):
    t = d["t"]
    if t == "f":
        return FieldCondition(d["field"], _p_from_wire(d["p"]))
    if t == "and":
        return And(tuple(_cond_from_wire(x) for x in d["c"]))
    if t == "or":
        return Or(tuple(_cond_from_wire(x) for x in d["c"]))
    if t == "not":
        return Not(_cond_from_wire(d["c"]))
    raise PermanentBackendError(f"unknown condition tag {t!r}")


class IndexServer(JsonNode):
    """Hosts a local IndexProvider as an index node (the dtype in
    register() travels by NAME through the schema dtype registry, so
    Geoshape/datetime keys keep their real type server-side)."""

    def __init__(self, provider: IndexProvider, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__(self._dispatch, host, port, name="index-node")
        self.provider = provider

    def _dispatch(self, path: str, req: dict):
        from titan_tpu.core.schema import _DTYPES
        p = self.provider
        if path == "/register":
            try:
                dtype = _DTYPES[req["dtype"]]
            except KeyError:
                raise PermanentBackendError(
                    f"unknown dtype name {req['dtype']!r}")
            from titan_tpu.core.defs import Cardinality
            info = KeyInformation(
                dtype, Cardinality(req.get("cardinality", "single")),
                parameters=tuple(req["parameters"]))
            p.register(req["store"], req["key"], info)
            return {"ok": True}
        if path == "/mutate":
            muts = {}
            for store, per_doc in req["mutations"].items():
                m = muts.setdefault(store, {})
                for docid, d in per_doc.items():
                    m[docid] = IndexMutation(
                        {k: _uv(v) for k, v in d["add"].items()},
                        set(d["del"]), d["deleted"])
            p.mutate(muts)
            return {"ok": True}
        if path == "/query":
            q = IndexQuery(
                _cond_from_wire(req["condition"]),
                orders=tuple((f, o) for f, o in req["orders"]),
                limit=req.get("limit"))
            return {"ids": p.query(req["store"], q)}
        if path == "/raw":
            hits = p.raw_query(req["store"],
                               RawQuery(req["query"],
                                        limit=req.get("limit"),
                                        offset=req.get("offset", 0)))
            return {"hits": [[d, s] for d, s in hits]}
        if path == "/admin":
            op = req["op"]
            if op == "features":
                f = p.features
                return {"supports_text": f.supports_text,
                        "supports_geo": f.supports_geo,
                        "supports_numeric_range": f.supports_numeric_range,
                        "supports_order": f.supports_order,
                        "supports_raw_query": f.supports_raw_query}
            if op == "drop_store":
                p.drop_store(req["store"])
            elif op == "clear":
                p.clear_storage()
            elif op == "flush":
                flush = getattr(p, "flush", None)
                if flush:
                    flush()
            else:
                raise PermanentBackendError(f"unknown admin op {op!r}")
            return {"ok": True}
        raise PermanentBackendError(f"unknown endpoint {path!r}")


class RemoteIndexProvider(IndexProvider):
    """Client side of the index node (titan-es role)."""

    def __init__(self, name: str = "search", directory=None,
                 hostname: str = "127.0.0.1", port: int = 8284,
                 timeout: float = 30.0):
        self.name = name
        self._url = f"http://{hostname}:{port}"
        self._timeout = timeout
        # mirror the NODE's capabilities (it may host any provider)
        f = self._call("/admin", {"op": "features"})
        self._features = IndexFeatures(
            supports_text=f["supports_text"],
            supports_geo=f["supports_geo"],
            supports_numeric_range=f["supports_numeric_range"],
            supports_order=f["supports_order"],
            supports_raw_query=f["supports_raw_query"])

    def _call(self, path: str, payload: dict) -> dict:
        return json_call(self._url, path, payload, timeout=self._timeout)

    @property
    def features(self) -> IndexFeatures:
        return self._features

    def register(self, store: str, key: str, info: KeyInformation) -> None:
        from titan_tpu.core.schema import _DTYPE_NAMES
        self._call("/register", {
            "store": store, "key": key,
            # by NAME via the dtype registry — a "sample value" degrades
            # Geoshape/datetime keys to str on the node
            "dtype": _DTYPE_NAMES.get(info.dtype, "str"),
            "cardinality": info.cardinality.value,
            "parameters": list(info.parameters)})

    def mutate(self, mutations) -> None:
        wire = {}
        for store, per_doc in mutations.items():
            m = wire.setdefault(store, {})
            for docid, mut in per_doc.items():
                m[docid] = {"add": {k: _v(v)
                                    for k, v in mut.additions.items()},
                            "del": sorted(mut.deletions),
                            "deleted": mut.deleted}
        self._call("/mutate", {"mutations": wire})

    def query(self, store: str, query: IndexQuery) -> list:
        res = self._call("/query", {
            "store": store, "condition": _cond_to_wire(query.condition),
            "orders": [list(o) for o in query.orders],
            "limit": query.limit})
        return res["ids"]

    def raw_query(self, store: str, query: RawQuery) -> list:
        res = self._call("/raw", {"store": store, "query": query.query,
                                  "limit": query.limit,
                                  "offset": query.offset})
        return [(d, float(s)) for d, s in res["hits"]]

    def drop_store(self, store: str) -> None:
        self._call("/admin", {"op": "drop_store", "store": store})

    def clear_storage(self) -> None:
        self._call("/admin", {"op": "clear"})

    def flush(self) -> None:
        self._call("/admin", {"op": "flush"})

    def close(self) -> None:
        pass


def main(argv: Optional[list] = None) -> None:
    """``python -m titan_tpu.indexing.remote <data-dir> [port] [host]`` —
    run an index node (FTS5-backed, binds 0.0.0.0 by default) mounted with
    ``index.<name>.backend=remote-index``."""
    def make(directory, host, port):
        from titan_tpu.indexing.ftsindex import FTSIndex
        return IndexServer(FTSIndex("node", directory), host=host,
                           port=port or 8284)
    run_node_cli(argv, "usage: python -m titan_tpu.indexing.remote "
                       "<data-dir> [port] [host]", make)


if __name__ == "__main__":
    main()
