"""MemoryIndex: the in-process mixed-index provider (the Lucene analog).

(reference: titan-lucene LuceneIndex.java — an embedded, single-machine
full-text/numeric/geo index; here: inverted token maps + per-field doc maps
with an optional directory snapshot for durability. Like the reference's
Lucene adapter it is the default local provider the test suites run against;
distributed providers plug in through the same IndexProvider SPI.)
"""

from __future__ import annotations

import os
import pickle
import re
import threading
import tempfile
from typing import Optional

from titan_tpu.core.attribute import Geoshape
from titan_tpu.indexing.provider import (IndexFeatures, IndexMutation,
                                         IndexProvider, IndexQuery,
                                         KeyInformation, RawQuery)

_TOKEN = re.compile(r"\w+")


def _tokens(text: str) -> list[str]:
    return _TOKEN.findall(str(text).lower())


class _Store:
    __slots__ = ("docs", "keyinfo", "tokens")

    def __init__(self):
        self.docs: dict[str, dict] = {}          # docid -> {field: value}
        self.keyinfo: dict[str, KeyInformation] = {}
        # field -> token -> set(docid), maintained for TEXT-mapped strings
        self.tokens: dict[str, dict[str, set]] = {}


class MemoryIndex(IndexProvider):
    def __init__(self, name: str = "search", directory: Optional[str] = None):
        self.name = name
        self.directory = directory
        self._stores: dict[str, _Store] = {}
        self._lock = threading.RLock()
        self._dirty = False
        if directory:
            os.makedirs(directory, exist_ok=True)
            self._load()

    @property
    def features(self) -> IndexFeatures:
        return IndexFeatures(supports_text=True, supports_geo=True,
                             supports_numeric_range=True, supports_order=True,
                             supports_raw_query=True)

    # -- registration / mutation ---------------------------------------------

    def register(self, store: str, key: str, info: KeyInformation) -> None:
        with self._lock:
            self._stores.setdefault(store, _Store()).keyinfo[key] = info
            self._dirty = True

    def _text_mapped(self, st: _Store, field: str, value) -> bool:
        if not isinstance(value, str):
            return False
        info = st.keyinfo.get(field)
        if info is None:
            return True                       # strings default to TEXT
        return "STRING" not in info.parameters

    def _untoken(self, st: _Store, docid: str, field: str) -> None:
        old = st.docs.get(docid, {}).get(field)
        if old is None:
            return
        for v in old if isinstance(old, list) else [old]:
            if self._text_mapped(st, field, v):
                for t in _tokens(v):
                    st.tokens.get(field, {}).get(t, set()).discard(docid)

    def _token(self, st: _Store, docid: str, field: str, value) -> None:
        for v in value if isinstance(value, list) else [value]:
            if self._text_mapped(st, field, v):
                for t in _tokens(v):
                    st.tokens.setdefault(field, {}).setdefault(
                        t, set()).add(docid)

    def mutate(self, mutations: dict[str, dict[str, IndexMutation]]) -> None:
        with self._lock:
            for store, per_doc in mutations.items():
                st = self._stores.setdefault(store, _Store())
                for docid, m in per_doc.items():
                    if m.deleted:
                        for field in list(st.docs.get(docid, {})):
                            self._untoken(st, docid, field)
                        st.docs.pop(docid, None)
                        continue
                    doc = st.docs.setdefault(docid, {})
                    for field in m.deletions:
                        self._untoken(st, docid, field)
                        doc.pop(field, None)
                    for field, value in m.additions.items():
                        self._untoken(st, docid, field)
                        doc[field] = value
                        self._token(st, docid, field, value)
                    if not doc:
                        st.docs.pop(docid, None)
            # durability is deferred to flush()/close() — snapshotting the
            # whole index per mutation would make commit cost O(index size)
            self._dirty = True

    # -- queries -------------------------------------------------------------

    def query(self, store: str, query: IndexQuery) -> list[str]:
        with self._lock:
            st = self._stores.get(store)
            if st is None:
                return []
            candidates = self._candidates(st, query.condition)
            if candidates is None:
                candidates = list(st.docs)
            hits = [d for d in candidates
                    if d in st.docs and query.condition.evaluate(st.docs[d])]
            for field, direction in reversed(query.orders):
                hits.sort(key=lambda d: (st.docs[d].get(field) is None,
                                         st.docs[d].get(field)),
                          reverse=(direction == "desc"))
            if not query.orders:
                hits.sort()
            if query.limit is not None:
                hits = hits[:query.limit]
            return hits

    def _candidates(self, st: _Store, cond) -> Optional[list]:
        """Token-accelerated candidate narrowing for textContains conjuncts;
        None = no narrowing possible (scan all docs)."""
        from titan_tpu.indexing.provider import And, FieldCondition
        conjuncts = cond.children if isinstance(cond, And) else (cond,)
        best: Optional[set] = None
        for c in conjuncts:
            if isinstance(c, FieldCondition) and c.predicate.op == "textContains":
                toks = _tokens(c.predicate.value)
                for t in toks:
                    s = st.tokens.get(c.field, {}).get(t, set())
                    best = set(s) if best is None else best & s
        return None if best is None else sorted(best)

    def raw_query(self, store: str, query: RawQuery) -> list:
        """Native syntax: ``field:token`` terms, whitespace = AND.
        (reference: LuceneIndex raw query parsing)"""
        with self._lock:
            st = self._stores.get(store)
            if st is None:
                return []
            result: Optional[set] = None
            for term in query.query.split():
                if ":" in term:
                    field, tok = term.split(":", 1)
                else:
                    field, tok = None, term
                tok = tok.lower()
                matches = set()
                if field is not None:
                    matches = st.tokens.get(field, {}).get(tok, set())
                else:
                    for fmap in st.tokens.values():
                        matches |= fmap.get(tok, set())
                result = matches if result is None else result & matches
            hits = sorted(result or ())
            if query.offset:
                hits = hits[query.offset:]
            if query.limit is not None:
                hits = hits[:query.limit]
            return [(d, 1.0) for d in hits]

    def count(self, store: str) -> int:
        with self._lock:
            st = self._stores.get(store)
            return len(st.docs) if st else 0

    # -- durability ----------------------------------------------------------

    def _path(self) -> str:
        return os.path.join(self.directory, f"{self.name}.idx")

    def _snapshot(self) -> None:
        data = {s: (st.docs, st.tokens, st.keyinfo)
                for s, st in self._stores.items()}
        fd, tmp = tempfile.mkstemp(dir=self.directory)
        with os.fdopen(fd, "wb") as f:
            pickle.dump(data, f)
        os.replace(tmp, self._path())

    def _load(self) -> None:
        try:
            with open(self._path(), "rb") as f:
                data = pickle.load(f)
        except FileNotFoundError:
            return
        for s, (docs, tokens, keyinfo) in data.items():
            st = _Store()
            st.docs, st.tokens, st.keyinfo = docs, tokens, keyinfo
            self._stores[s] = st

    def close(self) -> None:
        self.flush()

    def flush(self) -> None:
        with self._lock:
            if self.directory and self._dirty:
                self._snapshot()
                self._dirty = False

    def drop_store(self, store: str) -> None:
        with self._lock:
            self._stores.pop(store, None)
            if self.directory:
                self._snapshot()
                self._dirty = False

    def clear_storage(self) -> None:
        with self._lock:
            self._stores.clear()
            if self.directory:
                try:
                    os.remove(self._path())
                except FileNotFoundError:
                    pass
