"""Persistent full-text mixed-index provider on sqlite FTS5 — the Lucene
analog.

(reference: titan-lucene LuceneIndex.java — an embedded, single-machine
full-text index implementing the IndexProvider SPI; this provider plays the
same role with sqlite FTS5 as the inverted-index engine. Documents also live
as pickled field dicts so the full predicate set — numeric ranges, geo,
STRING-mapped exacts — evaluates exactly like the in-memory provider; FTS
only narrows textContains candidates and powers raw queries with bm25
scoring.)

Layout per index store (two tables, created on first use):
  ``d_<store>``  (docid TEXT PRIMARY KEY, doc BLOB)        — source of truth
  ``f_<store>``  FTS5(docid UNINDEXED, field, txt)         — one row per
                 TEXT-mapped string field value of a doc

Field names are matched as FTS tokens, so exotic names that tokenize into
multiple terms fall back to un-narrowed evaluation (correct, just slower).
"""

from __future__ import annotations

import os
import pickle
import re
import sqlite3
import threading
from typing import Optional

from titan_tpu.indexing.provider import (And, FieldCondition, IndexFeatures,
                                         IndexMutation, IndexProvider,
                                         IndexQuery, KeyInformation, RawQuery)

_NAME = re.compile(r"[^A-Za-z0-9_]")
# unicode tokens, matching the predicate layer's \W+ split — FTS5's
# unicode61 tokenizer normalizes both sides, so 'café' queries hit 'café'
# documents
_TOKEN = re.compile(r"\w+", re.UNICODE)


def _t(store: str, prefix: str) -> str:
    return f"{prefix}_{_NAME.sub('_', store)}"


def _fts_escape(token: str) -> str:
    return '"' + token.replace('"', '""') + '"'


class FTSIndex(IndexProvider):
    def __init__(self, name: str = "search", directory: Optional[str] = None):
        self.name = name
        self.directory = directory
        if directory:
            os.makedirs(directory, exist_ok=True)
            path = os.path.join(directory, f"{name}.ftsdb")
        else:
            path = ":memory:"
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._lock = threading.RLock()
        self._tables: set[str] = set()
        self._keyinfo: dict[tuple, KeyInformation] = {}
        self._load_keyinfo()

    @property
    def features(self) -> IndexFeatures:
        return IndexFeatures(supports_text=True, supports_geo=True,
                             supports_numeric_range=True, supports_order=True,
                             supports_raw_query=True)

    # -- setup ---------------------------------------------------------------

    def _ensure(self, store: str) -> None:
        d, f = _t(store, "d"), _t(store, "f")
        if d in self._tables:
            return
        self._conn.execute(
            f"CREATE TABLE IF NOT EXISTS {d} "
            f"(docid TEXT PRIMARY KEY, doc BLOB NOT NULL)")
        self._conn.execute(
            f"CREATE VIRTUAL TABLE IF NOT EXISTS {f} "
            f"USING fts5(docid UNINDEXED, field, txt)")
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS keyinfo "
            "(store TEXT, key TEXT, info BLOB, PRIMARY KEY (store, key))")
        self._tables.add(d)

    def _load_keyinfo(self) -> None:
        try:
            rows = self._conn.execute(
                "SELECT store, key, info FROM keyinfo").fetchall()
        except sqlite3.OperationalError:
            return
        for store, key, blob in rows:
            self._keyinfo[(store, key)] = pickle.loads(blob)

    def register(self, store: str, key: str, info: KeyInformation) -> None:
        with self._lock:
            self._ensure(store)
            self._keyinfo[(store, key)] = info
            self._conn.execute(
                "INSERT OR REPLACE INTO keyinfo(store, key, info) "
                "VALUES (?, ?, ?)", (store, key, pickle.dumps(info)))
            self._conn.commit()

    def _text_mapped(self, store: str, field: str, value) -> bool:
        if not isinstance(value, str):
            return False
        info = self._keyinfo.get((store, field))
        if info is None:
            return True                      # strings default to TEXT
        return "STRING" not in info.parameters

    # -- mutation ------------------------------------------------------------

    def mutate(self, mutations: dict[str, dict[str, IndexMutation]]) -> None:
        with self._lock:
            for store, per_doc in mutations.items():
                self._ensure(store)
                d, f = _t(store, "d"), _t(store, "f")
                for docid, m in per_doc.items():
                    row = self._conn.execute(
                        f"SELECT doc FROM {d} WHERE docid = ?",
                        (docid,)).fetchone()
                    doc = pickle.loads(row[0]) if row else {}
                    if m.deleted:
                        doc = {}
                    else:
                        for field in m.deletions:
                            doc.pop(field, None)
                        doc.update(m.additions)
                    self._conn.execute(
                        f"DELETE FROM {f} WHERE docid = ?", (docid,))
                    if not doc:
                        self._conn.execute(
                            f"DELETE FROM {d} WHERE docid = ?", (docid,))
                        continue
                    self._conn.execute(
                        f"INSERT OR REPLACE INTO {d}(docid, doc) "
                        f"VALUES (?, ?)", (docid, pickle.dumps(doc)))
                    rows = []
                    for field, value in doc.items():
                        for v in value if isinstance(value, list) else [value]:
                            if self._text_mapped(store, field, v):
                                rows.append((docid, field, v))
                    if rows:
                        self._conn.executemany(
                            f"INSERT INTO {f}(docid, field, txt) "
                            f"VALUES (?, ?, ?)", rows)
            self._conn.commit()

    # -- queries -------------------------------------------------------------

    def _fts_docids(self, store: str, field: str, text: str) -> set:
        """Doc ids with ALL tokens of ``text`` in ``field`` (one FTS query)."""
        toks = _TOKEN.findall(text.lower())
        if not toks:
            return set()
        f = _t(store, "f")
        match = "field : " + _fts_escape(field) + " AND txt : (" + \
            " AND ".join(_fts_escape(t) for t in toks) + ")"
        try:
            rows = self._conn.execute(
                f"SELECT docid FROM {f} WHERE {f} MATCH ?", (match,)).fetchall()
        except sqlite3.OperationalError:
            return set()
        return {r[0] for r in rows}

    def _candidates(self, store: str, cond) -> Optional[list]:
        """FTS-accelerated narrowing for textContains conjuncts; None = scan."""
        conjuncts = cond.children if isinstance(cond, And) else (cond,)
        best: Optional[set] = None
        for c in conjuncts:
            if isinstance(c, FieldCondition) and \
                    c.predicate.op == "textContains":
                s = self._fts_docids(store, c.field, str(c.predicate.value))
                best = s if best is None else best & s
        return None if best is None else sorted(best)

    def _doc(self, store: str, docid: str) -> Optional[dict]:
        row = self._conn.execute(
            f"SELECT doc FROM {_t(store, 'd')} WHERE docid = ?",
            (docid,)).fetchone()
        return pickle.loads(row[0]) if row else None

    def query(self, store: str, query: IndexQuery) -> list[str]:
        with self._lock:
            self._ensure(store)
            d = _t(store, "d")
            candidates = self._candidates(store, query.condition)
            hits = []
            docs: dict[str, dict] = {}
            if candidates is None:
                rows = self._conn.execute(
                    f"SELECT docid, doc FROM {d}").fetchall()
                pairs = [(docid, pickle.loads(blob)) for docid, blob in rows]
            else:
                pairs = [(docid, doc) for docid in candidates
                         if (doc := self._doc(store, docid)) is not None]
            for docid, doc in pairs:
                if query.condition.evaluate(doc):
                    hits.append(docid)
                    docs[docid] = doc
            for field, direction in reversed(query.orders):
                hits.sort(key=lambda i: (docs[i].get(field) is None,
                                         docs[i].get(field)),
                          reverse=(direction == "desc"))
            if not query.orders:
                hits.sort()
            if query.limit is not None:
                hits = hits[:query.limit]
            return hits

    def raw_query(self, store: str, query: RawQuery) -> list:
        """``field:token`` terms, whitespace = AND (same native syntax as the
        in-memory provider / reference LuceneIndex); bm25-summed scores."""
        with self._lock:
            self._ensure(store)
            f = _t(store, "f")
            result: Optional[dict[str, float]] = None
            for term in query.query.split():
                if ":" in term:
                    field, tok = term.split(":", 1)
                else:
                    field, tok = None, term
                toks = _TOKEN.findall(tok.lower())
                if not toks:
                    continue
                match = "txt : (" + " AND ".join(
                    _fts_escape(t) for t in toks) + ")"
                if field is not None:
                    match = "field : " + _fts_escape(field) + " AND " + match
                try:
                    rows = self._conn.execute(
                        f"SELECT docid, bm25({f}) FROM {f} WHERE {f} MATCH ?",
                        (match,)).fetchall()
                except sqlite3.OperationalError:
                    rows = []
                scores: dict[str, float] = {}
                for docid, s in rows:
                    # bm25() returns negative-better; flip to positive-better
                    scores[docid] = scores.get(docid, 0.0) + (-float(s))
                result = scores if result is None else \
                    {d_: result[d_] + s for d_, s in scores.items()
                     if d_ in result}
            if not result:
                return []
            hits = sorted(result.items(), key=lambda kv: (-kv[1], kv[0]))
            if query.offset:
                hits = hits[query.offset:]
            if query.limit is not None:
                hits = hits[:query.limit]
            return hits

    def count(self, store: str) -> int:
        with self._lock:
            self._ensure(store)
            return self._conn.execute(
                f"SELECT COUNT(*) FROM {_t(store, 'd')}").fetchone()[0]

    # -- lifecycle -----------------------------------------------------------

    def drop_store(self, store: str) -> None:
        with self._lock:
            self._ensure(store)
            self._conn.execute(f"DELETE FROM {_t(store, 'd')}")
            self._conn.execute(f"DELETE FROM {_t(store, 'f')}")
            self._conn.commit()

    def clear_storage(self) -> None:
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS keyinfo "
                "(store TEXT, key TEXT, info BLOB, PRIMARY KEY (store, key))")
            tables = [r[0] for r in self._conn.execute(
                "SELECT name FROM sqlite_master WHERE type='table' AND "
                "(name LIKE 'd\\_%' ESCAPE '\\')").fetchall()]
            for d in tables:
                self._conn.execute(f"DROP TABLE IF EXISTS {d}")
                self._conn.execute(f"DROP TABLE IF EXISTS f{d[1:]}")
            self._conn.execute("DELETE FROM keyinfo")
            self._conn.commit()
            self._tables.clear()
            self._keyinfo.clear()

    def flush(self) -> None:
        with self._lock:
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            try:
                self._conn.commit()
                self._conn.close()
            except sqlite3.Error:
                pass
