"""IndexSerializer: composite-index row codec + mixed-index document mapping
+ per-transaction index-update collection.

(reference: titan-core graphdb/database/IndexSerializer.java:784 —
``getIndexUpdates`` collects IndexUpdate records from a transaction's
added/deleted relations; composite row key = [index id][byte-ordered key
values]; row columns = one per matching element; mixed indexes map elements
to documents keyed by element id.)

Composite semantics mirrored from the reference:
* an element is recorded under an index only when it has a value for EVERY
  indexed key (all-keys-present rule);
* a multi-key composite index requires SINGLE cardinality on all keys; a
  single-key index on a SET/LIST key yields one entry per value;
* writes go to indexes whose status is REGISTERED or ENABLED, queries only
  use ENABLED indexes.
"""

from __future__ import annotations

from dataclasses import dataclass
from itertools import product
from typing import Iterable, Optional

from titan_tpu.codec.dataio import DataOutput, ReadBuffer
from titan_tpu.core.defs import Cardinality
from titan_tpu.core.schema import IndexDefinition
from titan_tpu.errors import SchemaViolationError
from titan_tpu.storage.api import Entry, KeySliceQuery, SliceQuery


@dataclass(frozen=True)
class IndexUpdate:
    """One pending index mutation.

    ``composite``: store row mutation for the graphindex store —
    ``key``/``entry`` set, deletion when ``addition`` is False.
    ``mixed``: document field change routed to an IndexTransaction —
    ``index_name``/``docid``/``field``/``value`` set (value None = delete
    field).
    """
    index: IndexDefinition
    addition: bool
    # composite:
    key: Optional[bytes] = None
    entry: Optional[Entry] = None
    # mixed:
    docid: Optional[str] = None
    field: Optional[str] = None
    value: object = None


class IndexSerializer:
    def __init__(self, serializer, idm, schema):
        self.serializer = serializer
        self.idm = idm
        self.schema = schema

    # -- composite row codec -------------------------------------------------

    def composite_row_key(self, index: IndexDefinition,
                          values: Iterable) -> bytes:
        out = DataOutput()
        out.put_uvar(index.id)
        for kid, value in zip(index.key_ids, values):
            self.serializer.write_ordered(out, value,
                                          self.schema.data_type(kid))
        return out.getvalue()

    def vertex_column(self, vid: int) -> bytes:
        return vid.to_bytes(8, "big")

    def edge_column(self, rel) -> bytes:
        out = DataOutput()
        out.put_uvar(rel.relation_id)
        out.put_u64(rel.out_vertex_id)
        out.put_u64(rel.in_vertex_id)
        out.put_uvar(rel.type_id)
        return out.getvalue()

    @staticmethod
    def parse_vertex_column(column: bytes) -> int:
        return int.from_bytes(column, "big")

    @staticmethod
    def parse_edge_column(column: bytes) -> tuple:
        """→ (relation_id, out_vid, in_vid, type_id)"""
        buf = ReadBuffer(column)
        rid = buf.get_uvar()
        out_vid = buf.get_u64()
        in_vid = buf.get_u64()
        tid = buf.get_uvar()
        return rid, out_vid, in_vid, tid

    # -- document mapping (mixed) -------------------------------------------

    @staticmethod
    def docid_for(element_id: int) -> str:
        return format(element_id, "x")

    @staticmethod
    def element_id_of(docid: str) -> int:
        return int(docid, 16)

    # -- update collection (the getIndexUpdates equivalent) ------------------

    def _label_ttl(self, tx, vid: int) -> float:
        lid = tx._vertex_labels.get(vid) or 0
        if not lid:
            return 0.0
        st = self.schema.get_type(lid)
        return getattr(st, "ttl", 0.0) if st else 0.0

    def _composite_entry(self, tx, column: bytes, ix, vid=None, rel=None):
        """Composite index entry, TTL'd to match its element so expired
        elements don't leave permanent ghost rows (reference: prepareCommit
        attaches the element TTL to index-store entries too)."""
        ttls = [self.schema.ttl_of(kid) for kid in ix.key_ids]
        if vid is not None:
            ttls.append(self._label_ttl(tx, vid))
        if rel is not None:
            ttls.append(self.schema.ttl_of(rel.type_id))
            ttls.append(self._label_ttl(tx, rel.out_vertex_id))
            ttls.append(self._label_ttl(tx, rel.in_vertex_id))
        live = [t for t in ttls if t > 0]
        if not live:
            return Entry(column, b"")
        from titan_tpu.storage.api import TTLEntry
        return TTLEntry(column, b"", min(live))

    def collect_updates(self, tx) -> list[IndexUpdate]:
        """Index updates implied by a transaction's added/deleted relations."""
        updates: list[IndexUpdate] = []
        self._vertex_updates(tx, updates)
        self._edge_updates(tx, updates)
        return updates

    # vertices: find (vid, key) pairs whose property set changed, then for
    # every writable index containing an affected key emit delete(pre-tuple)
    # + add(post-tuple) when the all-keys-present rule holds on that side.
    def _vertex_updates(self, tx, updates: list[IndexUpdate]) -> None:
        affected: dict[int, set] = {}   # vid -> {key id}
        for rel in list(tx._added.values()) + list(tx._deleted.values()):
            if not rel.is_property:
                continue
            if self.schema.system.is_system(rel.type_id):
                continue
            affected.setdefault(rel.out_vertex_id, set()).add(rel.type_id)
        if not affected:
            return

        vertex_indexes = [ix for ix in self.schema.indexes("vertex")
                          if ix.writable]
        for vid, keys in affected.items():
            if not self.idm.is_user_vertex_id(vid):
                continue
            removed = vid in tx._removed_vertices
            new = vid in tx._new_vertices
            label_id = None   # resolved lazily for index_only checks
            for ix in vertex_indexes:
                if not keys & set(ix.key_ids):
                    continue
                if ix.index_only:
                    if label_id is None:
                        label_id = self._label_id(tx, vid)
                    if label_id != ix.index_only:
                        continue
                pre = None if new else \
                    self._value_tuples(tx, vid, ix, "pre")
                post = None if removed else \
                    self._value_tuples(tx, vid, ix, "post")
                if ix.composite:
                    col = self.vertex_column(vid)
                    for vals in (pre or ()):
                        if post and vals in post:
                            continue   # unchanged tuple: no churn
                        updates.append(IndexUpdate(
                            ix, False,
                            key=self.composite_row_key(ix, vals),
                            entry=Entry(col, b"")))
                    for vals in (post or ()):
                        if pre and vals in pre:
                            continue
                        updates.append(IndexUpdate(
                            ix, True,
                            key=self.composite_row_key(ix, vals),
                            entry=self._composite_entry(tx, col, ix,
                                                        vid=vid)))
                else:
                    docid = self.docid_for(vid)
                    for kid in keys & set(ix.key_ids):
                        key_name = self.schema.get_type(kid).name
                        post_vals = None if removed else \
                            self._key_values(tx, vid, kid, "post")
                        value = post_vals[0] if post_vals else None
                        card = self.schema.cardinality(kid)
                        if card is not Cardinality.SINGLE and post_vals:
                            value = list(post_vals)
                        updates.append(IndexUpdate(
                            ix, value is not None, docid=docid,
                            field=key_name, value=value))

    def _label_id(self, tx, vid: int) -> int:
        from titan_tpu.core.defs import Direction, RelationCategory
        for rel in tx._iter_relations(vid, Direction.OUT, None,
                                      RelationCategory.EDGE,
                                      include_system=True):
            if rel.type_id == self.schema.system.vertex_label_edge:
                return rel.in_vertex_id
        return 0

    # edges: added/deleted edge relations carry their properties inline
    def _edge_updates(self, tx, updates: list[IndexUpdate]) -> None:
        edge_indexes = [ix for ix in self.schema.indexes("edge")
                        if ix.writable]
        if not edge_indexes:
            return
        for rel, addition in ([(r, True) for r in tx._added.values()] +
                              [(r, False) for r in tx._deleted.values()]):
            if not rel.is_edge or self.schema.system.is_system(rel.type_id):
                continue
            for ix in edge_indexes:
                if ix.index_only and rel.type_id != ix.index_only:
                    continue
                vals = []
                for kid in ix.key_ids:
                    if kid not in rel.properties:
                        break
                    vals.append(rel.properties[kid])
                else:
                    if ix.composite:
                        col_e = self.edge_column(rel)
                        entry = self._composite_entry(tx, col_e, ix,
                                                      rel=rel) \
                            if addition else Entry(col_e, b"")
                        updates.append(IndexUpdate(
                            ix, addition,
                            key=self.composite_row_key(ix, vals),
                            entry=entry))
                    else:
                        docid = self.docid_for(rel.relation_id)
                        for kid, value in zip(ix.key_ids, vals):
                            updates.append(IndexUpdate(
                                ix, addition, docid=docid,
                                field=self.schema.get_type(kid).name,
                                value=value if addition else None))

    # -- pre/post value reconstruction --------------------------------------

    def _key_values(self, tx, vid: int, key_id: int, when: str) -> list:
        """Values of ``key_id`` on ``vid`` before ("pre") or after ("post")
        the transaction. Post is the tx-visible view; pre is post with the
        tx's additions removed and deletions restored."""
        from titan_tpu.core.defs import Direction, RelationCategory
        post = [rel.value
                for rel in tx._iter_relations(vid, Direction.OUT, [key_id],
                                              RelationCategory.PROPERTY)]
        if when == "post":
            return post
        pre = list(post)
        for rel in tx._added.values():
            if rel.is_property and rel.type_id == key_id and \
                    rel.out_vertex_id == vid and rel.value in pre:
                pre.remove(rel.value)
        for rel in tx._deleted.values():
            if rel.is_property and rel.type_id == key_id and \
                    rel.out_vertex_id == vid:
                pre.append(rel.value)
        return pre

    def _value_tuples(self, tx, vid: int, ix: IndexDefinition,
                      when: str) -> list[tuple]:
        """All indexed value tuples for a vertex (cartesian product over
        multi-valued keys; empty list when any key is absent)."""
        per_key = []
        for kid in ix.key_ids:
            vals = self._key_values(tx, vid, kid, when)
            if not vals:
                return []
            if len(vals) > 1 and len(ix.key_ids) > 1:
                raise SchemaViolationError(
                    f"multi-key composite index {ix.name!r} requires SINGLE "
                    f"cardinality keys")
            per_key.append(vals)
        return [tuple(p) for p in product(*per_key)]

    # -- provider field registration ------------------------------------------

    def register_keys(self, provider, index: IndexDefinition) -> None:
        """Replay a mixed index's field registrations onto its provider
        (used at build time and when reindexing on a fresh provider)."""
        from titan_tpu.indexing.provider import KeyInformation
        for kid, param in zip(index.key_ids, index.key_params):
            pk = self.schema.get_type(kid)
            provider.register(index.name, pk.name, KeyInformation(
                pk.dtype, pk.cardinality,
                (param,) if param != "DEFAULT" else ()))

    # -- composite query ------------------------------------------------------

    def query_composite(self, backend_tx, ix: IndexDefinition,
                        values: Iterable, limit: Optional[int] = None) -> list:
        """Element ids (vertex ids, or edge column tuples) matching an
        equality tuple on a composite index."""
        row = self.composite_row_key(ix, values)
        entries = backend_tx.index_query(
            KeySliceQuery(row, SliceQuery(limit=limit)))
        if ix.element == "vertex":
            return [self.parse_vertex_column(e.column) for e in entries]
        return [self.parse_edge_column(e.column) for e in entries]
