"""Index subsystem: composite indexes in the graphindex store + the mixed
(external provider) index SPI.

(reference: titan-core graphdb/database/IndexSerializer.java — composite key
codec + mixed document mapping; diskstorage/indexing/ — IndexProvider SPI.)
"""

from titan_tpu.indexing.serializer import IndexSerializer, IndexUpdate
from titan_tpu.indexing.provider import (IndexProvider, IndexTransaction,
                                         KeyInformation, IndexQuery,
                                         FieldCondition, And, Or, Not)
from titan_tpu.indexing.memindex import MemoryIndex

__all__ = ["IndexSerializer", "IndexUpdate", "IndexProvider",
           "IndexTransaction", "KeyInformation", "IndexQuery",
           "FieldCondition", "And", "Or", "Not", "MemoryIndex"]
