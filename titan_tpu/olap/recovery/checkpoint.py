"""Per-job binding of the checkpoint plane: cadence, faults, metrics.

The scheduler attaches a ``JobRecovery`` to a job at submit time when
checkpointing is enabled (``JobScheduler(checkpoint_dir=...)`` +
``JobSpec.checkpoint_every > 0``) or a fault plan is injected; the
batcher then drives it from the round-boundary hooks:

* ``due(round)`` — is a checkpoint owed at this round (cadence)?
* ``save(round, arrays, ...)`` — write one checkpoint for this job's
  current attempt through the store (applying the slow-write /
  corrupt-after-commit faults, which must wrap the REAL write path);
* ``latest(kind=, epoch=)`` — newest valid checkpoint that is safe to
  resume from: kind must match, and when the snapshot carries an epoch
  the checkpoint must have been captured at the SAME epoch — a
  refreshed snapshot means the graph changed under the job, so a
  deterministic resume is unsound and the job restarts clean instead
  (never a wrong answer);
* ``resumed(round)`` / ``restarted()`` — metrics bookkeeping at the
  start of a retry attempt: ``serving.recovery.resumes`` and
  ``serving.recovery.rounds_replayed`` (rounds the previous attempt
  had executed past the adopted checkpoint — the work the crash cost).
"""

from __future__ import annotations

import time
from typing import Optional

from titan_tpu.olap.recovery.store import Checkpoint, CheckpointStore


class JobRecovery:
    """One job's handle on the checkpoint & fault plane. ``store`` may
    be None (fault injection without checkpointing: retries restart
    clean)."""

    def __init__(self, store: Optional[CheckpointStore], job,
                 every: int = 0, faults=None, metrics=None,
                 key: Optional[str] = None):
        self.store = store
        self.job = job
        self.every = int(every or 0)
        self.faults = faults
        self._metrics = metrics
        # store key: job ids restart at job-1 per PROCESS while the
        # store persists on disk, so the scheduler namespaces the key
        # with a per-instance nonce — a restarted server must never
        # adopt a previous process's checkpoint for an unrelated job
        self.key = key if key is not None else job.id

    # -- write side ----------------------------------------------------------

    def due(self, round_: int) -> bool:
        return (self.store is not None and self.every > 0
                and round_ > 0 and round_ % self.every == 0)

    def save(self, round_: int, arrays: dict, *, kind: str,
             meta: Optional[dict] = None,
             objects: Optional[dict] = None) -> str:
        t0 = time.time()
        if self.faults is not None and self.faults.slow_write_s > 0:
            time.sleep(self.faults.slow_write_s)
        path = self.store.save(self.key, attempt=self.job.attempt,
                               round_=round_, kind=kind, arrays=arrays,
                               meta=meta, objects=objects)
        self.job.checkpoint_round = round_
        h = getattr(self.job, "trace", None)
        if h is not None:    # obs: commit latency in the job's timeline
            h.event("checkpoint", t0=t0, round=round_)
        if self.faults is not None \
                and self.faults.should_corrupt(round_, self.job.attempt):
            self.faults.corrupt(path)
        return path

    # -- resume side ---------------------------------------------------------

    def latest(self, *, kind: str, epoch=None) -> Optional[Checkpoint]:
        if self.store is None:
            return None
        ck = self.store.latest(self.key)
        if ck is None or ck.kind != kind:
            return None
        if epoch is not None and ck.meta.get("epoch") != epoch:
            return None     # snapshot changed under the job: clean restart
        return ck

    def resumed(self, round_: int) -> None:
        """An execution attempt is starting FROM a checkpoint at
        ``round_``."""
        replayed = max(0, int(self.job.last_round) - int(round_))
        self.job.rounds_replayed += replayed
        h = getattr(self.job, "trace", None)
        if h is not None:
            h.event("resume", from_round=int(round_),
                    rounds_replayed=replayed)
        if self._metrics is not None:
            self._metrics.counter("serving.recovery.resumes").inc()
            if replayed:
                self._metrics.counter(
                    "serving.recovery.rounds_replayed").inc(replayed)

    def restarted(self) -> None:
        """A retry attempt is starting CLEAN (no usable checkpoint):
        every round the failed attempt ran is replayed."""
        replayed = max(0, int(self.job.last_round))
        self.job.rounds_replayed += replayed
        h = getattr(self.job, "trace", None)
        if h is not None:
            h.event("restart_clean", rounds_replayed=replayed)
        if self._metrics is not None and replayed:
            self._metrics.counter(
                "serving.recovery.rounds_replayed").inc(replayed)
