"""Deterministic fault injection for the recovery test matrix.

Every recovery path (crash → RETRYING → resume, corrupt checkpoint →
fallback, snapshot eviction → rebuild) must be drivable WITHOUT
flakiness, so the injector is a declarative plan of exact round
indices, not a random killer: the round-boundary hooks in the batcher
call ``FaultPlan.check(round, attempt, snapshot)`` and the plan raises
on the configured round — only while ``attempt <= fail_attempts``, so
a retried attempt runs clean and the test observes recovery, not an
infinite crash loop.

Fault matrix (docs/recovery.md):

  crash_at_round    raise InjectedFault at round k (worker death /
                    host preemption analog — the whole batch dies)
  evict_at_round    drop the snapshot's device-resident caches, then
                    raise SnapshotEvicted (HBM eviction race analog;
                    the retry re-uploads from host arrays)
  corrupt_at_round  after the checkpoint written at round k commits,
                    flip bytes inside one array payload on disk (torn
                    storage analog; the NEXT resume must reject it by
                    digest and fall back)
  slow_write_s      sleep before every checkpoint write (slow-disk
                    analog; exercises checkpoint-vs-cancel timing)

``FaultPlan.seeded(seed, max_round)`` derives the crash round from a
seeded RNG — deterministic per seed, for property tests that sweep
crash positions.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional

import numpy as np


class InjectedFault(RuntimeError):
    """Deterministic injected worker fault (test harness only)."""


class SnapshotEvicted(InjectedFault):
    """Injected mid-job loss of the snapshot's device residency."""


#: snapshot attributes holding device-resident state; the evict fault
#: drops them all, forcing the retried attempt to re-upload
_DEVICE_CACHE_ATTRS = ("_hybrid_csr", "_dev_single", "_dev_sharded",
                       "_out_csr")


@dataclass
class FaultPlan:
    """Declarative, deterministic fault schedule for ONE job."""

    crash_at_round: Optional[int] = None
    evict_at_round: Optional[int] = None
    corrupt_at_round: Optional[int] = None
    slow_write_s: float = 0.0
    #: inject only while attempt <= this (default: first attempt only)
    fail_attempts: int = 1

    def check(self, round_: int, attempt: int, snapshot=None) -> None:
        """Round-boundary hook: raise the configured fault, if due."""
        if attempt > self.fail_attempts:
            return
        if self.evict_at_round is not None and round_ == self.evict_at_round:
            if snapshot is not None:
                for attr in _DEVICE_CACHE_ATTRS:
                    if hasattr(snapshot, attr):
                        delattr(snapshot, attr)
            raise SnapshotEvicted(
                f"injected: snapshot evicted at round {round_} "
                f"(attempt {attempt})")
        if self.crash_at_round is not None and round_ == self.crash_at_round:
            raise InjectedFault(
                f"injected: crash at round {round_} (attempt {attempt})")

    def should_corrupt(self, round_: int, attempt: int) -> bool:
        return (self.corrupt_at_round is not None
                and attempt <= self.fail_attempts
                and round_ == self.corrupt_at_round)

    @staticmethod
    def corrupt(path: str) -> None:
        """Flip bytes inside the LARGEST array payload of a COMMITTED
        checkpoint directory — the manifest stays intact, so only the
        digest check can catch it (the scenario under test). Raises
        rather than silently not corrupting (a no-op here would make a
        fallback test pass without exercising the rejection path)."""
        cands = [(os.path.getsize(os.path.join(path, f)), f)
                 for f in os.listdir(path) if f.endswith(".npy")]
        if not cands:
            raise FileNotFoundError(f"no array payload to corrupt in {path}")
        size, name = max(cands)
        fp = os.path.join(path, name)
        with open(fp, "r+b") as f:
            # stay clear of the .npy header (~128B): damage data
            off = max(128, size - 16)
            f.seek(off)
            chunk = f.read(4)
            if not chunk:
                raise ValueError(
                    f"{name} too small to corrupt past its header "
                    f"({size} bytes)")
            f.seek(off)
            f.write(bytes(b ^ 0xFF for b in chunk))

    @classmethod
    def seeded(cls, seed: int, max_round: int, **kwargs) -> "FaultPlan":
        """Crash round drawn deterministically from ``seed`` in
        [1, max_round) — same seed, same plan, every run."""
        rng = np.random.default_rng(seed)
        k = int(rng.integers(1, max(2, int(max_round))))
        return cls(crash_at_round=k, **kwargs)
