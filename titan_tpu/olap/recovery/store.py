"""Versioned on-disk checkpoint store: manifest + digests + atomic commit.

The durability half of the superstep checkpoint plane (Pregel's
superstep-boundary checkpointing, Malewicz et al. SIGMOD 2010 §4.2 —
the canonical BSP fault-tolerance design the reference's Fulgora
executor never rebuilt). One checkpoint is one DIRECTORY::

    <root>/<job_id>/ckpt-a0001-r00000012/
        manifest.json          # written LAST, fsynced
        <name>.npy             # one file per state array
        objects.pkl            # optional host-object payload

committed by writing everything into a ``.tmp-*`` sibling and
``os.replace``-ing it into place — a crash mid-write leaves only a tmp
directory the reader never looks at, so a torn checkpoint is detected
(missing/garbled manifest), never adopted.

The manifest records the job id, attempt, round, kind and a sha256
digest + dtype/shape per array; ``load`` re-hashes every payload and
raises ``CheckpointInvalid`` on any mismatch. ``latest`` walks the
job's checkpoints newest-attempt-first / highest-round-first and
returns the first one that VALIDATES — a corrupted newest checkpoint
falls back to the previous valid one (or None → clean restart), never
to a wrong answer.

``objects.pkl`` exists for the host BSP computer (olap/computer.py),
whose superstep state is Python dicts; it is digest-checked like the
arrays but deserialized with pickle — checkpoint directories are
trusted local state, not a wire format.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import shutil
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

MANIFEST = "manifest.json"
FORMAT_VERSION = 1

#: ckpt-a<attempt>-r<round> — zero-padded so lexicographic order is
#: (attempt, round) order, but the reader parses, never trusts sorting
_CKPT_RE = re.compile(r"^ckpt-a(\d+)-r(\d+)$")


class CheckpointInvalid(RuntimeError):
    """Checkpoint failed validation (torn write, digest mismatch,
    shape/dtype drift, unreadable payload). Never resumed from."""


def _digest(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


@dataclass
class Checkpoint:
    """One loaded-and-verified checkpoint."""

    path: str
    job_id: str
    attempt: int
    round: int
    kind: str
    meta: dict = field(default_factory=dict)
    arrays: dict = field(default_factory=dict)    # name -> np.ndarray
    objects: dict = field(default_factory=dict)   # host-object payload


class CheckpointStore:
    """See module doc. ``metrics``: optional utils/metrics.MetricManager;
    when set, every committed checkpoint records
    ``serving.recovery.checkpoints`` / ``.checkpoint_bytes`` counters and
    a ``serving.recovery.checkpoint_ms`` histogram sample, and every
    checkpoint rejected during ``latest()`` bumps
    ``serving.recovery.invalid_checkpoints``."""

    def __init__(self, root: str, metrics=None,
                 prefix: str = "serving.recovery"):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)
        self._metrics = metrics
        self._prefix = prefix

    # -- paths ---------------------------------------------------------------

    def job_dir(self, job_id: str) -> str:
        return os.path.join(self.root, str(job_id))

    def checkpoints(self, job_id: str) -> list[str]:
        """Committed checkpoint paths, (attempt, round) ascending.
        Tmp leftovers and foreign entries are ignored."""
        jd = self.job_dir(job_id)
        if not os.path.isdir(jd):
            return []
        found = []
        for name in os.listdir(jd):
            m = _CKPT_RE.match(name)
            if m is not None:
                found.append((int(m.group(1)), int(m.group(2)),
                              os.path.join(jd, name)))
        found.sort()
        return [p for _a, _r, p in found]

    # -- write ---------------------------------------------------------------

    def save(self, job_id: str, *, attempt: int, round_: int, kind: str,
             arrays: Optional[dict] = None, meta: Optional[dict] = None,
             objects: Optional[dict] = None) -> str:
        """Commit one checkpoint atomically; returns its final path.
        Re-saving the same (attempt, round) replaces the old directory
        (same rename-commit, so the swap is still atomic)."""
        t0 = time.time()
        name = f"ckpt-a{attempt:04d}-r{round_:08d}"
        jd = self.job_dir(job_id)
        os.makedirs(jd, exist_ok=True)
        tmp = os.path.join(jd, f".tmp-{name}-{os.getpid()}")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        entries: dict = {}
        nbytes = 0
        for nm, arr in (arrays or {}).items():
            a = np.ascontiguousarray(np.asarray(arr))
            np.save(os.path.join(tmp, nm + ".npy"), a)
            entries[nm] = {"kind": "array", "digest": _digest(a.tobytes()),
                           "dtype": str(a.dtype), "shape": list(a.shape)}
            nbytes += a.nbytes
        if objects:
            blob = pickle.dumps(objects, protocol=pickle.HIGHEST_PROTOCOL)
            with open(os.path.join(tmp, "objects.pkl"), "wb") as f:
                f.write(blob)
            entries["objects"] = {"kind": "pickle",
                                  "digest": _digest(blob),
                                  "bytes": len(blob)}
            nbytes += len(blob)
        manifest = {"version": FORMAT_VERSION, "job": str(job_id),
                    "attempt": int(attempt), "round": int(round_),
                    "kind": str(kind), "meta": meta or {},
                    "entries": entries}
        mpath = os.path.join(tmp, MANIFEST)
        with open(mpath, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        final = os.path.join(jd, name)
        shutil.rmtree(final, ignore_errors=True)
        os.replace(tmp, final)
        if self._metrics is not None:
            self._metrics.counter(f"{self._prefix}.checkpoints").inc()
            self._metrics.counter(
                f"{self._prefix}.checkpoint_bytes").inc(nbytes)
            self._metrics.histogram(
                f"{self._prefix}.checkpoint_ms").update(
                (time.time() - t0) * 1e3)
        return final

    # -- read ----------------------------------------------------------------

    def load(self, path: str) -> Checkpoint:
        """Read + VERIFY one checkpoint; raises ``CheckpointInvalid`` on
        any torn/corrupt/mismatched payload."""
        mpath = os.path.join(path, MANIFEST)
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            raise CheckpointInvalid(
                f"unreadable manifest at {path}: {e}") from e
        if manifest.get("version") != FORMAT_VERSION:
            raise CheckpointInvalid(
                f"unknown checkpoint format version "
                f"{manifest.get('version')!r} at {path}")
        arrays: dict = {}
        objects: dict = {}
        for nm, ent in manifest.get("entries", {}).items():
            if ent.get("kind") == "pickle":
                try:
                    with open(os.path.join(path, "objects.pkl"), "rb") as f:
                        blob = f.read()
                except OSError as e:
                    raise CheckpointInvalid(
                        f"missing objects payload at {path}: {e}") from e
                if _digest(blob) != ent["digest"]:
                    raise CheckpointInvalid(
                        f"objects digest mismatch at {path}")
                objects = pickle.loads(blob)
                continue
            try:
                a = np.load(os.path.join(path, nm + ".npy"),
                            allow_pickle=False)
            except (OSError, ValueError) as e:
                raise CheckpointInvalid(
                    f"unreadable array {nm!r} at {path}: {e}") from e
            if str(a.dtype) != ent["dtype"] \
                    or list(a.shape) != list(ent["shape"]):
                raise CheckpointInvalid(
                    f"array {nm!r} shape/dtype drift at {path}")
            if _digest(np.ascontiguousarray(a).tobytes()) != ent["digest"]:
                raise CheckpointInvalid(
                    f"array {nm!r} digest mismatch at {path}")
            arrays[nm] = a
        return Checkpoint(path=path, job_id=manifest["job"],
                          attempt=int(manifest["attempt"]),
                          round=int(manifest["round"]),
                          kind=manifest["kind"],
                          meta=manifest.get("meta", {}),
                          arrays=arrays, objects=objects)

    def validate(self, path: str) -> bool:
        try:
            self.load(path)
            return True
        except CheckpointInvalid:
            return False

    def latest(self, job_id: str) -> Optional[Checkpoint]:
        """Newest VALID checkpoint for the job (attempt desc, round
        desc), skipping — and counting — any that fail validation.
        None means no usable checkpoint: resume falls back to a clean
        restart."""
        for path in reversed(self.checkpoints(job_id)):
            try:
                return self.load(path)
            except CheckpointInvalid:
                if self._metrics is not None:
                    self._metrics.counter(
                        f"{self._prefix}.invalid_checkpoints").inc()
        return None
