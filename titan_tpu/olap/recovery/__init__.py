"""Superstep checkpoint & recovery plane for device-resident OLAP jobs.

Long vertex-program runs (scale-26 BFS, multi-round SSSP/WCC, 50+
-iteration PageRank) on preemptible accelerators are all-or-nothing
without this plane: a worker crash, HBM eviction race, or host
preemption loses the whole run. This package rebuilds Pregel's
superstep-boundary checkpointing (Malewicz et al., SIGMOD 2010 §4.2 —
the canonical BSP fault-tolerance design behind the reference's
Fulgora/VertexProgram contract) on top of the round-boundary hooks the
serving layer already owns (``on_round`` / ``on_level`` vetoes):

* ``store``      — versioned on-disk checkpoints: per-array sha256
                   digests in a manifest, atomic rename-commit, newest-
                   valid-wins ``latest()`` (a torn or corrupted
                   checkpoint is detected and skipped, never adopted).
* ``checkpoint`` — ``JobRecovery``: per-job cadence + fault binding the
                   batcher drives from the round hooks, with
                   ``serving.recovery.*`` metrics.
* ``faults``     — deterministic injector (crash-at-round-k, corrupt-
                   checkpoint, slow-write, snapshot-evicted-mid-job)
                   the test matrix uses to drive every recovery path
                   without flakiness.

Deterministic resume: the round loops are data-deterministic, so a run
crashed at round k and resumed from its newest checkpoint produces
final arrays BIT-EQUAL to an uninterrupted run (property-tested for
BFS, SSSP, WCC and PageRank in tests/test_recovery.py). The scheduler
side (RETRYING state, exponential backoff, retry exhaustion) lives in
olap/serving; docs/recovery.md documents the contract.
"""

from titan_tpu.olap.recovery.checkpoint import JobRecovery       # noqa: F401
from titan_tpu.olap.recovery.faults import (FaultPlan,           # noqa: F401
                                            InjectedFault,
                                            SnapshotEvicted)
from titan_tpu.olap.recovery.store import (Checkpoint,           # noqa: F401
                                           CheckpointInvalid,
                                           CheckpointStore)
