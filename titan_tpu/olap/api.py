"""OLAP contracts: scan jobs, vertex programs, memory.

Re-creation of the reference's OLAP seam (reference: titan-core
diskstorage/keycolumnvalue/scan/ScanJob.java:17-130,
graphdb/olap/VertexScanJob.java:16, TinkerPop VertexProgram +
graphdb/olap/computer/FulgoraMemory.java/FulgoraVertexMemory.java):

* ``ScanJob`` — raw row-level job run by the scanner (storage/scan.py):
  declares the column slices it needs, processes each (key, entries) row.
* ``VertexScanJob`` — vertex-level job; bridged onto ScanJob by the engine.
* ``VertexProgram`` — BSP program executed per vertex per superstep with
  message passing (host computer, olap/computer.py).
* ``DenseProgram`` — the TPU-native program contract: the whole superstep is
  expressed as pure jnp transforms over dense per-vertex state plus a
  gather → per-edge message → segment-combine → apply pipeline, compiled
  once and iterated under ``lax.while_loop`` (olap/tpu/engine.py). This is
  the redesign of FulgoraGraphComputer's scan loop as batched SpMV.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence


class ScanMetrics:
    """(reference: scan/ScanMetrics.java) simple thread-safe counters."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}

    def increment(self, metric: str, delta: int = 1):
        with self._lock:
            self._counts[metric] = self._counts.get(metric, 0) + delta

    def get(self, metric: str) -> int:
        with self._lock:
            return self._counts.get(metric, 0)

    SUCCESS = "success"
    FAILURE = "failure"


class ScanJob(abc.ABC):
    def setup(self, graph, config, metrics: ScanMetrics) -> None:
        pass

    def get_queries(self) -> Sequence:
        """SliceQuery list; the FIRST is the primary query driving iteration
        (reference: ScanJob.getQueries)."""
        raise NotImplementedError

    @abc.abstractmethod
    def process(self, key: bytes, entries_by_query: dict, metrics: ScanMetrics
                ) -> None:
        """``entries_by_query``: SliceQuery -> EntryList for this row."""

    def worker_iteration_start(self, config, metrics: ScanMetrics) -> None:
        pass

    def worker_iteration_end(self, metrics: ScanMetrics) -> None:
        pass


class VertexScanJob(abc.ABC):
    def setup(self, graph, config, metrics: ScanMetrics) -> None:
        pass

    @abc.abstractmethod
    def process(self, vertex, metrics: ScanMetrics) -> None: ...

    def get_queries(self, query_container) -> None:
        """Declare adjacency slices to preload via the QueryContainer."""


class Memory:
    """Global BSP memory (reference: FulgoraMemory.java:131)."""

    def __init__(self):
        self._values: dict[str, Any] = {}
        self.iteration = 0

    def get(self, key: str, default=None):
        return self._values.get(key, default)

    def set(self, key: str, value):
        self._values[key] = value

    def add(self, key: str, value):
        self._values[key] = self._values.get(key, 0) + value

    def keys(self):
        return list(self._values)


class Messenger:
    """Per-vertex message access during execute()."""

    def __init__(self, vertex_memory, vertex_id: int):
        self._vm = vertex_memory
        self._vid = vertex_id

    def receive(self) -> list:
        return self._vm.messages_for(self._vid)

    def send(self, message, target_ids) -> None:
        for t in target_ids:
            self._vm.send(t, message)


class VertexProgram(abc.ABC):
    """Host BSP program (reference: TinkerPop VertexProgram executed by
    FulgoraGraphComputer.java:151-189)."""

    def setup(self, memory: Memory) -> None:
        pass

    @abc.abstractmethod
    def execute(self, vertex, messenger: Messenger, memory: Memory) -> None: ...

    @abc.abstractmethod
    def terminate(self, memory: Memory) -> bool: ...

    def combiner(self) -> Optional[Callable[[Any, Any], Any]]:
        """Optional associative message combiner
        (reference: MessageCombiner)."""
        return None

    @property
    def state_keys(self) -> Sequence[str]:
        """Vertex state property names this program writes."""
        return ()


class MapEmitter:
    """Collects (key, value) pairs from map() (reference:
    FulgoraMapEmitter)."""

    def __init__(self):
        self.pairs: list = []

    def emit(self, key, value) -> None:
        self.pairs.append((key, value))


class ReduceEmitter:
    """Collects (key, value) pairs from combine()/reduce() (reference:
    FulgoraReduceEmitter)."""

    def __init__(self):
        self.pairs: list = []

    def emit(self, key, value) -> None:
        self.pairs.append((key, value))


class MapReduce(abc.ABC):
    """Post-BSP aggregation stage (reference: TinkerPop MapReduce executed
    at FulgoraGraphComputer.java:192-246 — map over all vertices, optional
    per-worker combine, grouped reduce, result stored in Memory under
    ``memory_key``)."""

    memory_key: str = "mapreduce"

    @abc.abstractmethod
    def map(self, vertex, emitter: MapEmitter) -> None: ...

    def has_combine(self) -> bool:
        return type(self).combine is not MapReduce.combine

    def combine(self, key, values: list, emitter: ReduceEmitter) -> None:
        """Optional associative pre-reduce applied per worker chunk."""
        self.reduce(key, values, emitter)

    def has_reduce(self) -> bool:
        return type(self).reduce is not MapReduce.reduce

    def reduce(self, key, values: list, emitter: ReduceEmitter) -> None:
        """Default: pass map output through unchanged."""
        for v in values:
            emitter.emit(key, v)

    def finalize(self, results: dict):
        """Grouped {key: [values]} → the object stored in Memory
        (reference: MapReduce.generateFinalResult)."""
        return results


def execute_map_reduce(mr: MapReduce, vertices, chunk: int = 4096) -> Any:
    """Run one MapReduce over an iterable of vertex views: map → per-chunk
    combine → grouped reduce → finalize. Shared by the host computer and the
    TPU computer's host-side fallback path."""
    combined: dict = {}

    def absorb(pairs):
        if mr.has_combine():
            by_key: dict = {}
            for k, v in pairs:
                by_key.setdefault(k, []).append(v)
            em = ReduceEmitter()
            for k, vs in by_key.items():
                mr.combine(k, vs, em)
            pairs = em.pairs
        for k, v in pairs:
            combined.setdefault(k, []).append(v)

    em = MapEmitter()
    n_in_chunk = 0
    for v in vertices:
        mr.map(v, em)
        n_in_chunk += 1
        if n_in_chunk >= chunk:
            absorb(em.pairs)
            em = MapEmitter()
            n_in_chunk = 0
    absorb(em.pairs)

    if mr.has_reduce():
        rem = ReduceEmitter()
        for k, vs in combined.items():
            mr.reduce(k, vs, rem)
        grouped: dict = {}
        for k, v in rem.pairs:
            grouped.setdefault(k, []).append(v)
    else:
        grouped = combined
    return mr.finalize(grouped)


class DenseMapReduce(abc.ABC):
    """TPU-native post-BSP aggregation: instead of per-vertex map/reduce
    callbacks, one array program over the final dense state (SURVEY §7:
    MapReduce stages → jnp reductions). ``compute`` receives the program's
    output arrays (shape [n]) and must be expressible in numpy/jnp ops."""

    memory_key: str = "mapreduce"

    @abc.abstractmethod
    def compute(self, state: dict, snapshot, params: dict): ...


@dataclass
class EdgeData:
    """Per-edge arrays aligned with the snapshot's edge order."""
    values: dict = field(default_factory=dict)   # name -> np/jnp array [E]


@dataclass
class JobSpec:
    """Declarative vertex-program job for the async serving layer
    (olap/serving — the rebuild of the reference's L7→L4b seam where
    gremlin-server requests feed FulgoraGraphComputer's executor, here
    as an admission-controlled queue over the TPU engine).

    ``kind``: 'bfs' (batchable — same-snapshot BFS jobs fuse into ONE
    [K, n] multi-source device run), 'sssp' | 'pagerank' | 'wcc'
    (frontier kernels, executed singly), 'dense' (a DenseProgram
    instance under ``params['program']``), or 'callable'
    (``params['fn']`` — the host computer's async delegation hook).

    ``deadline`` is an absolute ``time.time()`` by which the job must
    START — jobs still queued past it are EXPIRED by admission control.
    ``timeout_s`` bounds RUNTIME; for batched BFS it is enforced at
    level boundaries through the per-job early-exit mask.
    ``labels``/``edge_keys``/``directed`` select the snapshot the job
    runs against (SnapshotPool parameters; ``directed=False``
    symmetrizes, which the direction-optimizing BFS kernels require).
    For 'dense' jobs the scheduler derives ``edge_keys`` from the
    program's ``edge_keys()`` when unset.

    Recovery plane (olap/recovery): ``max_retries`` lets a RUNNING job
    that dies (worker exception, injected fault, snapshot eviction)
    requeue as RETRYING — with exponential backoff starting at
    ``retry_backoff_s`` — up to that many extra attempts before FAILED;
    ``checkpoint_every > 0`` (with a scheduler-level
    ``checkpoint_dir``) captures the program state every N round
    boundaries so a retried attempt resumes from the newest valid
    checkpoint instead of restarting, bit-equal to an uninterrupted
    run. Cancellation, timeout and param errors never retry.

    Tenancy (olap/serving/tenants): ``tenant`` attributes the job's
    queue-ms / device-seconds / HBM-byte-seconds / replayed-rounds to a
    named tenant, labels its metrics and trace, and subjects it to that
    tenant's quota when the scheduler enforces quotas; unset/empty
    falls back to ``"default"`` everywhere.

    Fleet failover (olap/fleet): ``idempotency_key`` names the LOGICAL
    job across processes — schedulers key this job's checkpoints by it
    (instead of the per-scheduler private namespace), so a redispatch
    of the same logical job onto a surviving replica adopts the dead
    replica's newest checkpoint over the shared store and resumes
    rather than restarts, on its FIRST local attempt."""

    kind: str
    params: dict = field(default_factory=dict)
    priority: int = 0
    deadline: Optional[float] = None
    timeout_s: Optional[float] = None
    labels: Optional[Sequence[str]] = None
    edge_keys: Sequence[str] = ()
    directed: bool = False
    max_retries: int = 0
    checkpoint_every: int = 0
    retry_backoff_s: float = 0.05
    tenant: Optional[str] = None
    idempotency_key: Optional[str] = None


class DenseProgram(abc.ABC):
    """TPU-native vertex program: one compiled superstep, iterated on device.

    State is a dict[str, array] of per-vertex arrays. Each superstep the
    engine computes::

        src_state = {k: state[k][src] for k}            # gather over edges
        msg       = self.message(src_state, edge_data)  # [E] per-edge values
        agg       = segment_<combine>(msg, dst, n)      # combine per vertex
        state'    = self.apply(state, agg, iteration)

    and stops when ``self.done(state, state', agg, iteration)`` is True or
    ``max_iterations`` is reached. All callbacks must be jax-traceable.
    """

    combine: str = "sum"          # 'sum' | 'min' | 'max'
    max_iterations: int = 50

    @abc.abstractmethod
    def init(self, n: int, params: dict) -> dict: ...

    @abc.abstractmethod
    def message(self, src_state: dict, edge_data: dict, params: dict): ...

    @abc.abstractmethod
    def apply(self, state: dict, agg, iteration, params: dict) -> dict: ...

    def identity(self, params: dict):
        import jax.numpy as jnp
        return {"sum": 0.0, "min": jnp.inf, "max": -jnp.inf}[self.combine]

    def done(self, state: dict, new_state: dict, agg, iteration, params: dict):
        import jax.numpy as jnp
        return jnp.array(False)

    def edge_keys(self) -> Sequence[str]:
        """Edge property names required in EdgeData (e.g. ('weight',))."""
        return ()

    def outputs(self, state: dict, params: dict) -> dict:
        """Final state → user-facing arrays (default: identity)."""
        return state
