from titan_tpu.olap.api import (DenseProgram, Memory, Messenger, ScanJob,
                                ScanMetrics, VertexProgram, VertexScanJob)


def graph_computer(graph, backend: str = "tpu", **kwargs):
    """``graph.compute()`` dispatch (reference:
    TitanBlueprintsGraph.compute() graphdb/tinkerpop/TitanBlueprintsGraph.java:143
    choosing FulgoraGraphComputer; here ``computer.backend`` selects the
    thread-pool host executor or the TPU superstep engine)."""
    if backend == "tpu":
        from titan_tpu.olap.tpu.engine import TPUGraphComputer
        return TPUGraphComputer(graph, **kwargs)
    if backend == "host":
        from titan_tpu.olap.computer import HostGraphComputer
        return HostGraphComputer(graph, **kwargs)
    raise ValueError(f"unknown computer backend {backend!r}")


__all__ = ["DenseProgram", "Memory", "Messenger", "ScanJob", "ScanMetrics",
           "VertexProgram", "VertexScanJob", "graph_computer"]
