"""CSR snapshot: bulk-export the edgestore into dense device-ready arrays.

This is the seam the reference fills with ScanJob + StandardScannerExecutor
(reference: titan-core diskstorage/keycolumnvalue/scan/
StandardScannerExecutor.java:85-188 feeding FulgoraGraphComputer) — redesigned
for the TPU: instead of streaming rows through per-vertex Java callbacks, one
ordered scan decodes the adjacency into numpy arrays, vertices are densified
to [0, n) (key order is partition-major, so dense index ranges are exactly
the storage partitions), and edges are sorted by destination for pull-mode
segment reduction on the MXU-adjacent vector units.

The decode hot loop uses the C++ codec when built (native/), else a Python
loop (correct, slower — fine for OLTP-scale graphs; synthetic benchmarks
construct snapshots directly from arrays).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from titan_tpu import native
from titan_tpu.codec import relation_ids as rids
from titan_tpu.core.defs import Direction, RelationCategory
from titan_tpu.storage.api import SliceQuery


@dataclass
class GraphSnapshot:
    """Dense read-only graph image.

    Edges are stored dst-sorted (``dst`` ascending, the pull layout);
    ``indptr_in`` indexes them per destination. ``out_degree`` supports
    degree-normalized programs (PageRank).
    """

    n: int
    vertex_ids: np.ndarray          # [n] int64, original ids, ascending key order
    src: np.ndarray                 # [E] int32 dense indices, dst-sorted
    dst: np.ndarray                 # [E] int32 dense indices, ascending
    indptr_in: np.ndarray           # [n+1] int64
    out_degree: np.ndarray          # [n] int32
    edge_values: dict = field(default_factory=dict)  # name -> [E] array
    labels: Optional[np.ndarray] = None              # [E] int32 label codes
    label_names: dict = field(default_factory=dict)  # code -> label name
    # name -> (values object-array [n], present bool [n]) — dense vertex
    # property columns for the device-compiled traversal subset
    # (attach_vertex_values / olap_compile has()/values() steps)
    vertex_values: dict = field(default_factory=dict)
    # freshness contract (see refresh()): epoch is graph.mutation_epoch at
    # build/refresh time; build() subscribes an in-process change listener
    epoch: int = 0
    _graph: object = None
    _listener_token: int = 0
    _listener: Optional[list] = None
    _build_params: dict = field(default_factory=dict)

    @property
    def num_edges(self) -> int:
        return len(self.src)

    @property
    def stale(self) -> bool:
        """True when commits landed on the source graph after this
        snapshot's epoch (the reference never has this problem — its OLAP
        scans the LIVE store every run, StandardScannerExecutor.java:85-188;
        a build-once device snapshot needs the explicit contract)."""
        g = self._graph
        return g is not None and self.epoch < g.mutation_epoch

    def close(self) -> None:
        """Detach the change listener (stops delta accumulation)."""
        g = self._graph
        if g is not None and self._listener_token:
            g.unsubscribe_changes(self._listener_token)
            self._graph = None
            self._listener = None

    def refresh(self) -> dict:
        """Apply the commits since ``epoch`` to this snapshot IN MEMORY —
        no store re-scan. Pure edge additions take an O(delta + E) merge
        into the dst-sorted arrays; vertex additions/removals or edge
        removals rebuild the CSR from the patched in-memory edge list
        (still host-array work only). Device-layout caches (_out_csr,
        bfs_hybrid's chunked CSR) are invalidated. Returns stats.

        Only commits on THIS graph instance are seen (they are the only
        ones the in-process listener observes); cross-instance writers
        need a rebuild — or wire the durable trigger log into
        ``apply_changes`` via the LogProcessorFramework."""
        g = self._graph
        if g is None:
            raise RuntimeError("snapshot has no source graph "
                               "(built from_arrays or closed)")
        if self.edge_values:
            raise NotImplementedError(
                "refresh() with extracted edge_values: change payloads "
                "don't carry edge properties — rebuild the snapshot")
        q = self._listener
        if getattr(q, "overflowed", False):
            raise RuntimeError(
                "change backlog overflowed (>10k commits since the last "
                "refresh) — delta refresh is unsound; rebuild the "
                "snapshot")
        new_epoch = g.mutation_epoch
        # drain UP TO new_epoch only: a commit that bumped the epoch
        # we read has already queued its payload (push precedes bump,
        # under the commit lock), but a commit racing THIS refresh may
        # queue payloads with epoch > new_epoch — those must stay queued
        # for the next refresh, or its continuity check would find a
        # hole and force a spurious rebuild. Scan-then-slice, not
        # pop(0)-per-payload: against the 10k-commit backlog cap the
        # per-pop list shift made this drain O(backlog^2)
        cut = 0
        while cut < len(q) and (q[cut].get("epoch") is None
                                or q[cut]["epoch"] <= new_epoch):
            cut += 1
        pending = list(q[:cut])
        del q[:cut]
        # continuity: the payloads must cover exactly
        # (self.epoch, new_epoch] — a gap means commits this listener
        # never saw (e.g. they landed during build()'s store scan), and
        # applying around the hole would corrupt the CSR
        epochs = [p.get("epoch") for p in pending]
        covered = [e for e in epochs if e is not None
                   and self.epoch < e <= new_epoch]
        if len(covered) != new_epoch - self.epoch:
            raise RuntimeError(
                f"snapshot delta gap: epochs ({self.epoch}, {new_epoch}] "
                f"but only {len(covered)} payloads — commits landed "
                "concurrently with build()'s scan; rebuild the snapshot")
        stats = self.apply_changes(
            [p for p in pending
             if p.get("epoch") is None or p["epoch"] > self.epoch],
            g.schema, g.idm)
        self.epoch = new_epoch
        return stats

    def rebuild_in_place(self) -> None:
        """Full store re-scan adopted into THIS object: the recovery
        path when delta refresh is unsound (listener overflow, delta
        gap, extracted edge_values). The existing change queue is
        RE-ANCHORED at the rebuilt epoch — cleared, overflow flag
        reset, atomically with the scan's epoch verification — so
        later refresh()es take the delta path again instead of being
        forced into a rebuild forever (ISSUE r9 satellite). Callers
        must guarantee no live device run is reading the arrays (the
        SnapshotPool only takes this path with zero active leases)."""
        g = self._graph
        if g is None:
            raise RuntimeError("snapshot has no source graph "
                               "(built from_arrays or closed)")
        p = self._build_params or {}
        fresh = build(g, labels=p.get("labels"),
                      edge_keys=p.get("edge_keys", ()),
                      directed=p.get("directed", True),
                      _reuse_listener=(self._listener_token,
                                       self._listener))
        self.n = fresh.n
        self.vertex_ids = fresh.vertex_ids
        self.src, self.dst = fresh.src, fresh.dst
        self.indptr_in = fresh.indptr_in
        self.out_degree = fresh.out_degree
        self.edge_values = fresh.edge_values
        self.labels = fresh.labels
        self.label_names = fresh.label_names
        # the vertex set may have changed arbitrarily: every dense
        # column and derived device layout is invalid
        self.vertex_values.clear()
        self._invalidate_layout_caches()
        self.epoch = fresh.epoch
        # fresh shares our listener (reused, not subscribed) — detach it
        # so fresh's GC/close cannot unregister the queue we keep using
        fresh._graph = None
        fresh._listener = None
        fresh._listener_token = 0

    def apply_changes(self, payloads: list, schema, idm) -> dict:
        """Apply change payloads (core/changes.change_payload dicts — from
        the in-process listener or deserialized from the user trigger
        log) to the in-memory CSR."""
        params = self._build_params or {}
        label_ids = params.get("label_ids")
        directed = params.get("directed", True)
        add_src: list = []
        add_dst: list = []
        add_lab: list = []
        removed_edges: list = []
        new_vids: set = set()
        dead_vids: set = set()
        prop_keys: set = set()
        for p in payloads:
            for r in (*p.get("added", ()), *p.get("removed", ())):
                if "in" not in r:          # property mutation
                    prop_keys.add(r.get("type"))
            for vid in p.get("added_vertices", ()):
                new_vids.add(idm.canonical_vertex_id(vid))
            for vid in p.get("removed_vertices", ()):
                dead_vids.add(idm.canonical_vertex_id(vid))
            for r in p.get("added", ()):
                if "in" not in r:
                    continue                      # property, not an edge
                st = schema.get_by_name(r["type"])
                if st is None or (label_ids is not None
                                  and st.id not in label_ids):
                    continue
                add_src.append(idm.canonical_vertex_id(r["out"]))
                add_dst.append(idm.canonical_vertex_id(r["in"]))
                add_lab.append(idm.count(st.id))
                self.label_names.setdefault(idm.count(st.id), st.name)
            for r in p.get("removed", ()):
                if "in" not in r:
                    continue
                st = schema.get_by_name(r["type"])
                if st is None:
                    continue
                removed_edges.append(
                    (idm.canonical_vertex_id(r["out"]),
                     idm.canonical_vertex_id(r["in"]), idm.count(st.id)))
        new_vids -= set(self.vertex_ids.tolist())
        stats = {"added_edges": len(add_src),
                 "removed_edges": len(removed_edges),
                 "added_vertices": len(new_vids),
                 "removed_vertices": len(dead_vids)}
        # property mutations invalidate the dense vertex-property
        # columns even when no edge/vertex changed (a stale column would
        # silently mis-answer compiled has()/values() — pinned by
        # tests/test_olap_compile.py)
        for k in prop_keys:
            self.vertex_values.pop(k, None)
        if not (add_src or removed_edges or new_vids or dead_vids):
            return stats

        self._invalidate_layout_caches()
        need_rebuild = bool(removed_edges or new_vids or dead_vids)
        if need_rebuild:
            # the vertex SET changes: every dense property column's
            # length/alignment is invalidated (edge-only merges keep
            # them — property mutations were already handled above)
            self.vertex_values.clear()
        if not need_rebuild:
            self._merge_edges(np.asarray(add_src, np.int64),
                              np.asarray(add_dst, np.int64),
                              np.asarray(add_lab, np.int32), directed)
            return stats

        # general path: patch the edge list in memory, re-densify, rebuild
        old_ids = self.vertex_ids
        src_ids = old_ids[self.src.astype(np.int64)]
        dst_ids = old_ids[self.dst.astype(np.int64)]
        labs = self.labels if self.labels is not None \
            else np.zeros(len(src_ids), np.int32)
        keep = np.ones(len(src_ids), bool)
        if removed_edges:
            # drop ONE row per removed relation per direction (parallel
            # edges are distinct relations, each contributing one row
            # [+reverse]). Undirected snapshots hold BOTH rows of every
            # relation, so each removal is seeded under both keys —
            # matching one forward AND one reverse row (the old
            # rkey-fallback matched only whichever row scanned first,
            # leaving the mirror row behind and silently
            # de-symmetrizing the CSR)
            from collections import Counter
            want = Counter(removed_edges)
            if not directed:
                want.update((d, s, lb) for s, d, lb in removed_edges)
            for i in range(len(src_ids)):
                key = (int(src_ids[i]), int(dst_ids[i]), int(labs[i]))
                if want.get(key, 0) > 0:
                    want[key] -= 1
                    keep[i] = False
        if dead_vids:
            dead = np.asarray(sorted(dead_vids), np.int64)
            keep &= ~np.isin(src_ids, dead) & ~np.isin(dst_ids, dead)
        src_ids, dst_ids, labs = src_ids[keep], dst_ids[keep], labs[keep]
        if add_src:
            a_s = np.asarray(add_src, np.int64)
            a_d = np.asarray(add_dst, np.int64)
            a_l = np.asarray(add_lab, np.int32)
            if not directed:
                a_s, a_d = (np.concatenate([a_s, a_d]),
                            np.concatenate([a_d, a_s]))
                a_l = np.concatenate([a_l, a_l])
            src_ids = np.concatenate([src_ids, a_s])
            dst_ids = np.concatenate([dst_ids, a_d])
            labs = np.concatenate([labs, a_l])
        ids = np.asarray(sorted((set(old_ids.tolist()) | new_vids)
                                - dead_vids), np.int64)
        si = np.clip(np.searchsorted(ids, src_ids), 0, max(len(ids) - 1, 0))
        di = np.clip(np.searchsorted(ids, dst_ids), 0, max(len(ids) - 1, 0))
        # drop rows whose endpoint is not a live vertex (an added edge
        # can reference a vertex a LATER pending commit removed, or a
        # ghost id): exactly build()'s endpoint validation
        ok = np.ones(len(src_ids), bool)
        if len(ids):
            ok = (ids[si] == src_ids) & (ids[di] == dst_ids)
        si, di, labs = si[ok], di[ok], labs[ok]
        rebuilt = from_arrays(len(ids), si.astype(np.int32),
                              di.astype(np.int32), ids, None, labs,
                              self.label_names)
        self.n = rebuilt.n
        self.vertex_ids = rebuilt.vertex_ids
        self.src, self.dst = rebuilt.src, rebuilt.dst
        self.indptr_in = rebuilt.indptr_in
        self.out_degree = rebuilt.out_degree
        self.labels = rebuilt.labels
        return stats

    def _merge_edges(self, src_ids, dst_ids, labs, directed) -> None:
        """Fast path: merge NEW edges of EXISTING vertices into the
        dst-sorted arrays (one O(E) insert, no re-sort of old rows)."""
        if not directed:
            src_ids, dst_ids = (np.concatenate([src_ids, dst_ids]),
                                np.concatenate([dst_ids, src_ids]))
            labs = np.concatenate([labs, labs])
        si = np.searchsorted(self.vertex_ids, src_ids)
        di = np.searchsorted(self.vertex_ids, dst_ids)
        ok = (si < self.n) & (di < self.n)
        ok &= (self.vertex_ids[np.minimum(si, self.n - 1)] == src_ids) \
            & (self.vertex_ids[np.minimum(di, self.n - 1)] == dst_ids)
        si, di, labs = (si[ok].astype(np.int32), di[ok].astype(np.int32),
                        labs[ok])
        order = np.argsort(di, kind="stable")
        si, di, labs = si[order], di[order], labs[order]
        pos = np.searchsorted(self.dst, di, side="right")
        self.src = np.insert(self.src, pos, si)
        self.dst = np.insert(self.dst, pos, di)
        if self.labels is not None:
            self.labels = np.insert(self.labels, pos, labs)
        counts = np.diff(self.indptr_in)
        np.add.at(counts, di.astype(np.int64), 1)
        self.indptr_in = np.concatenate(
            [np.zeros(1, np.int64), np.cumsum(counts, dtype=np.int64)])
        np.add.at(self.out_degree, si, 1)

    def _invalidate_layout_caches(self) -> None:
        """Drop every derived layout / device-array cache the model
        kernels lazily attach (they rebuild from the refreshed arrays).
        The dense vertex-property columns are NOT cleared here — they
        stay aligned across edge-only merges; apply_changes clears them
        on property mutations (by key) and vertex-set changes (all)."""
        for attr in ("_out_csr", "_out_csr_order", "_hybrid_csr",
                     "_hybrid_csr_rev", "_frontier_shards",
                     "_dev_frontier_sh", "_tiled_shards", "_dev_outdeg",
                     "_dev_frontier"):
            if hasattr(self, attr):
                delattr(self, attr)

    def attach_vertex_values(self, graph, keys) -> None:
        """Build dense vertex property columns through the OLTP tx (one
        batched pass; SINGLE-cardinality keys only) and cache them for
        the device-compiled traversal subset. Keys already attached are
        skipped; unknown keys attach as all-absent columns."""
        from titan_tpu.core.defs import Cardinality

        want = [k for k in keys if k not in self.vertex_values]
        if not want:
            return
        for k in want:
            st = graph.schema.get_by_name(k)
            if st is not None and \
                    graph.schema.cardinality(st.id) is not Cardinality.SINGLE:
                raise ValueError(
                    f"attach_vertex_values: key {k!r} is not "
                    "SINGLE-cardinality; multi-valued columns have no "
                    "dense representation")
        tx = graph.new_transaction(read_only=True)
        try:
            cols = {k: (np.empty(self.n, object), np.zeros(self.n, bool))
                    for k in want}
            # batched: one multi-row property-slice read per id chunk
            # (tx.multi_vertex_properties), not n point reads — the
            # first compiled has()/values() on an OLAP-scale snapshot
            # must not pay minutes of host time
            chunk = 4096
            for c0 in range(0, self.n, chunk):
                ids = [int(v) for v in self.vertex_ids[c0:c0 + chunk]]
                got = tx.multi_vertex_properties(ids, keys=want)
                for j, vid in enumerate(ids):
                    props = got.get(vid)
                    if not props:
                        continue
                    for k, val in props.items():
                        if val is not None:
                            cols[k][0][c0 + j] = val
                            cols[k][1][c0 + j] = True
        finally:
            tx.rollback()
        self.vertex_values.update(cols)

    def dense_of(self, vertex_id: int) -> int:
        i = int(np.searchsorted(self.vertex_ids, vertex_id))
        if i >= self.n or self.vertex_ids[i] != vertex_id:
            raise KeyError(f"vertex {vertex_id} not in snapshot")
        return i

    def out_csr(self) -> tuple[np.ndarray, np.ndarray]:
        """(dst_by_src, indptr_out): edges sorted by SOURCE — the push/
        expansion layout used by frontier-sparse traversal. Computed once
        and cached (the snapshot is immutable). The src-order
        permutation itself is kept as ``_out_csr_order`` (src-order
        position → dst-order row): the live overlay's slot-lookup index
        reads it instead of re-paying the argsort, and ``merge_delta``
        carries both caches across an epoch merge incrementally."""
        cached = getattr(self, "_out_csr", None)
        if cached is None:
            # indptr is just the cumsum of the existing out_degree; the sort
            # takes the native counting-sort path when available (np.add.at
            # at 268M edges costs tens of host seconds)
            indptr_out = np.concatenate(
                [np.zeros(1, np.int64),
                 np.cumsum(self.out_degree, dtype=np.int64)])
            if native.available and self.n > 0 and len(self.src):
                order, _, _ = native.csr_build(self.dst, self.src, self.n)
                dst_by_src = native.gather_i32(self.dst, order)
            else:
                order = np.argsort(self.src, kind="stable")
                dst_by_src = self.dst[order]
            cached = (dst_by_src, indptr_out)
            self._out_csr = cached
            self._out_csr_order = np.asarray(order, np.int64)
        return cached

    def reverse(self) -> "GraphSnapshot":
        """Swap edge direction (push layout / in-degree programs)."""
        return from_arrays(self.n, self.dst, self.src, self.vertex_ids,
                           edge_values=self.edge_values, labels=self.labels,
                           label_names=self.label_names)


def from_arrays(n: int, src, dst, vertex_ids=None, edge_values=None,
                labels=None, label_names=None) -> GraphSnapshot:
    """Build a snapshot from raw (src, dst) dense-index arrays."""
    src = np.asarray(src, dtype=np.int32)
    dst = np.asarray(dst, dtype=np.int32)
    if len(src) and (int(src.min()) < 0 or int(src.max()) >= n
                     or int(dst.min()) < 0 or int(dst.max()) >= n):
        raise IndexError(f"edge endpoint out of range [0, {n})")
    if vertex_ids is None:
        vertex_ids = np.arange(n, dtype=np.int64)
    if native.available and n > 0:
        order, indptr, out_degree = native.csr_build(src, dst, n)
        src_s = native.gather_i32(src, order)
        dst_s = native.gather_i32(dst, order)
    else:
        order = np.argsort(dst, kind="stable")
        src_s, dst_s = src[order], dst[order]
        indptr = np.zeros(n + 1, dtype=np.int64)
        np.add.at(indptr, dst_s + 1, 1)
        np.cumsum(indptr, out=indptr)
        out_degree = np.zeros(n, dtype=np.int32)
        np.add.at(out_degree, src, 1)
    ev = {k: np.asarray(v)[order] for k, v in (edge_values or {}).items()}
    lab = np.asarray(labels, dtype=np.int32)[order] if labels is not None else None
    return GraphSnapshot(n, np.asarray(vertex_ids, dtype=np.int64), src_s,
                         dst_s, indptr, out_degree, ev, lab,
                         dict(label_names or {}))


def merge_delta(snap: GraphSnapshot, keep: np.ndarray, add_src,
                add_dst, add_labels=None) -> GraphSnapshot:
    """Incremental dst-sorted merge: drop the rows where ``keep`` is
    False and insert the added edges, WITHOUT re-sorting the surviving
    rows — bit-equal to ``from_arrays(n, concat(src[keep], add_src),
    concat(dst[keep], add_dst), ...)`` (the full stable sort both the
    native and numpy builders run), because the kept rows stay
    dst-ascending and a stable dst-sort puts equal-dst adds AFTER the
    kept rows in append order, which is exactly a ``side='right'``
    searchsorted insert. O(E) memcpy + O(delta log delta), no O(E log
    E) sort — the epoch compactor's host-durable sync
    (olap/live/compactor.py device merge path) runs this every epoch.
    """
    add_src = np.asarray(add_src, np.int32)
    add_dst = np.asarray(add_dst, np.int32)
    if len(add_src) and (int(add_src.min()) < 0
                        or int(add_src.max()) >= snap.n
                        or int(add_dst.min()) < 0
                        or int(add_dst.max()) >= snap.n):
        raise IndexError(f"edge endpoint out of range [0, {snap.n})")
    order = np.argsort(add_dst, kind="stable")
    a_s, a_d = add_src[order], add_dst[order]
    dst_kept = snap.dst[keep]
    pos = np.searchsorted(dst_kept, a_d, side="right")
    src = np.insert(snap.src[keep], pos, a_s)
    dst = np.insert(dst_kept, pos, a_d)
    labels = None
    if snap.labels is not None:
        a_l = np.asarray(add_labels, np.int32)[order] \
            if add_labels is not None \
            else np.zeros(len(a_s), np.int32)
        labels = np.insert(snap.labels[keep], pos, a_l)
    counts = np.diff(snap.indptr_in)
    dead_dst = snap.dst[~keep].astype(np.int64)
    if len(dead_dst):
        np.add.at(counts, dead_dst, -1)
    if len(a_d):
        np.add.at(counts, a_d.astype(np.int64), 1)
    indptr_in = np.concatenate(
        [np.zeros(1, np.int64), np.cumsum(counts, dtype=np.int64)])
    out_degree = snap.out_degree.copy()
    dead_src = snap.src[~keep].astype(np.int64)
    if len(dead_src):
        np.add.at(out_degree, dead_src, -1)
    if len(a_s):
        np.add.at(out_degree, a_s.astype(np.int64), 1)
    merged = GraphSnapshot(snap.n, snap.vertex_ids, src, dst, indptr_in,
                           out_degree, {}, labels,
                           dict(snap.label_names))
    # ROADMAP #5 residual (ISSUE 11 satellite): the merged epoch's
    # out-CSR — and the src-order permutation the next overlay's
    # slot-lookup index is built from — carry over INCREMENTALLY when
    # the base had them cached (the overlay's own construction always
    # does), so the next DeltaOverlay never re-pays the O(E log E)
    # argsort the device merge path already eliminated everywhere else
    if getattr(snap, "_out_csr", None) is not None \
            and getattr(snap, "_out_csr_order", None) is not None:
        _merge_out_csr(snap, merged, keep, add_src, add_dst, pos)
    return merged


def _merge_out_csr(snap: GraphSnapshot, merged: GraphSnapshot,
                   keep: np.ndarray, add_src: np.ndarray,
                   add_dst: np.ndarray, pos_d: np.ndarray) -> None:
    """Incremental src-sorted layout across ``merge_delta``: build the
    merged snapshot's ``_out_csr`` (dst_by_src, indptr_out) and
    ``_out_csr_order`` from the base's cached pair — O(E) gathers +
    O(delta log delta) sorts, bit-equal to a from-scratch
    ``out_csr()`` on the merged arrays (pinned by
    tests/test_live_compact_device.py).

    Correctness: a stable src-sort preserves dst order within each
    source group (the merged array is dst-ascending), kept rows keep
    their relative order under row drops, and equal-(src, dst) adds
    land AFTER kept rows in append order — exactly a ``side='right'``
    insert on the (src, dst) composite key. ``pos_d`` is the dst-order
    insert-position vector ``merge_delta`` already computed (the adds'
    merged-row indices are ``pos_d + arange``)."""
    dst_by_src_old, _ = snap._out_csr
    order_old = snap._out_csr_order
    n = snap.n
    keep_s = keep[order_old]                  # keep mask, src order
    kept_dst_s = dst_by_src_old[keep_s]
    # src values in src order are just each vertex id repeated by its
    # OLD out-degree — no sort needed
    src_sorted_old = np.repeat(np.arange(n, dtype=np.int64),
                               snap.out_degree.astype(np.int64))
    kept_src_s = src_sorted_old[keep_s]
    # adds in (src, dst, append) order: stable dst-sort then stable
    # src-sort composes to exactly that
    o1 = np.argsort(add_dst, kind="stable")
    o = o1[np.argsort(add_src[o1], kind="stable")]
    as_s, ad_s = add_src[o].astype(np.int64), add_dst[o]
    # composite (src, dst) key: kept rows are sorted under it (groups
    # ascend by src, dst ascends within each group)
    key_kept = kept_src_s * np.int64(n + 1) + kept_dst_s
    key_add = as_s * np.int64(n + 1) + ad_s
    pos_s = np.searchsorted(key_kept, key_add, side="right")
    dst_by_src_new = np.insert(kept_dst_s, pos_s, ad_s)
    indptr_out_new = np.concatenate(
        [np.zeros(1, np.int64),
         np.cumsum(merged.out_degree, dtype=np.int64)])
    # merged-array row index per src-order position: kept row j (in
    # kept-dst order) shifts by the adds inserted at/before it;
    # dst-order add k lands at pos_d[k] + k
    kept_rank = np.cumsum(keep, dtype=np.int64) - 1
    j_kept = kept_rank[order_old[keep_s]]
    merged_idx_kept = j_kept + np.searchsorted(pos_d, j_kept,
                                               side="right")
    merged_idx_add_d = pos_d.astype(np.int64) \
        + np.arange(len(pos_d), dtype=np.int64)
    # map each ORIGINAL add row to its dst-order rank, then read its
    # merged index in the src-sorted visit order
    ord_d = np.argsort(add_dst, kind="stable")
    rank_d = np.empty(len(ord_d), np.int64)
    rank_d[ord_d] = np.arange(len(ord_d), dtype=np.int64)
    merged_idx_add_s = merged_idx_add_d[rank_d[o]]
    order_new = np.insert(merged_idx_kept, pos_s, merged_idx_add_s)
    merged._out_csr = (dst_by_src_new, indptr_out_new)
    merged._out_csr_order = order_new


def _scan_python(graph, rows, exists_q, scan_q, label_ids, key_ids):
    """Per-entry decode via the Python codec (fallback; also the path when
    edge property values must be extracted)."""
    idm, schema, codec = graph.idm, graph.schema, graph.codec
    srcs: list[int] = []
    dsts: list[int] = []
    labs: list[int] = []
    ev: dict[str, list] = {name: [] for name in key_ids.values()}
    vertex_id_list: list[int] = []
    for key, entries in rows:
        vid = idm.id_of_key_bytes(key)
        if not idm.is_user_vertex_id(vid):
            continue
        # vertex-cut rows fold into the canonical vertex (reference:
        # VertexProgramScanJob.java:76-92 canonical-representative aggregation)
        vid = idm.canonical_vertex_id(vid)
        has_exist = False
        for e in entries:
            if exists_q.contains(e.column):
                has_exist = True
            elif scan_q.contains(e.column):
                rc = codec.parse(e, schema)
                if rc.direction is not Direction.OUT or not rc.is_edge:
                    continue
                if schema.system.is_system(rc.type_id):
                    continue
                if label_ids is not None and rc.type_id not in label_ids:
                    continue
                srcs.append(vid)
                dsts.append(rc.other_vertex_id)
                labs.append(idm.count(rc.type_id))
                for kid, name in key_ids.items():
                    ev[name].append(rc.properties.get(kid, 0))
        if has_exist:
            vertex_id_list.append(vid)
    return vertex_id_list, srcs, dsts, labs, ev


def _scan_native(graph, rows, exists_q, label_ids):
    """Bulk decode via the C++ codec (native/): Python only concatenates
    column bytes; head classification and other-vertex varint decode run as
    two vectorized native sweeps. Labels whose columns carry sort keys or
    park the other-vertex id in the value (unique directions) fall back to
    per-entry Python parse — rare, and only for those entries."""
    idm = graph.idm

    cols = bytearray()
    offs: list[int] = [0]
    entry_row: list[int] = []
    entry_refs: list = []
    row_vids: list[int] = []
    for key, entries in rows:
        vid = idm.id_of_key_bytes(key)
        if not idm.is_user_vertex_id(vid):
            continue
        ridx = len(row_vids)
        row_vids.append(vid)
        for e in entries:
            cols += e.column
            offs.append(len(cols))
            entry_row.append(ridx)
            entry_refs.append(e)

    if not entry_refs:
        return [], np.empty(0, np.int64), np.empty(0, np.int64), [], {}

    return _native_classify(
        graph, np.frombuffer(cols, dtype=np.uint8),
        np.asarray(offs, dtype=np.int64),
        np.asarray(entry_row, dtype=np.int64),
        np.asarray(row_vids, dtype=np.int64),
        exists_q, label_ids, lambda i: entry_refs[i])


def _scan_native_packed(graph, packed_rows, exists_q, label_ids):
    """_scan_native over a store's packed row scan (scan_rows_packed,
    features.packed_ops): per-ROW joins and C-speed length maps replace
    the per-Entry Python loop — the entry-wise accumulation measured
    ~3us/cell and dominated benchmark-scale snapshot builds."""
    from titan_tpu.storage.api import Entry
    idm = graph.idm

    chunks: list[bytes] = []
    lens: list[int] = []
    counts: list[int] = []
    row_vids: list[int] = []
    row_refs: list = []
    for key, cols_list, vals_list in packed_rows:
        vid = idm.id_of_key_bytes(key)
        if not idm.is_user_vertex_id(vid):
            continue
        row_vids.append(vid)
        chunks.append(b"".join(cols_list))
        lens.extend(map(len, cols_list))
        counts.append(len(cols_list))
        row_refs.append((cols_list, vals_list))

    if not lens:
        return [], np.empty(0, np.int64), np.empty(0, np.int64), [], {}

    col_buf = np.frombuffer(b"".join(chunks), dtype=np.uint8)
    offs = np.zeros(len(lens) + 1, np.int64)
    np.cumsum(np.asarray(lens, np.int64), out=offs[1:])
    counts_a = np.asarray(counts, np.int64)
    entry_row = np.repeat(np.arange(len(counts_a), dtype=np.int64),
                          counts_a)
    row_start = np.zeros(len(counts_a) + 1, np.int64)
    np.cumsum(counts_a, out=row_start[1:])

    def resolve(i: int) -> Entry:
        r = int(entry_row[i])
        li = i - int(row_start[r])
        cols_list, vals_list = row_refs[r]
        return Entry(cols_list[li], vals_list[li])

    return _native_classify(graph, col_buf, offs, entry_row,
                            np.asarray(row_vids, np.int64), exists_q,
                            label_ids, resolve)


def _native_classify(graph, col_buf, offs, entry_row_a, row_vids_raw,
                     exists_q, label_ids, resolve_entry):
    """Shared tail of the native scan paths: classify column heads,
    bulk-decode other-vertex ids, per-entry-parse the rare slow labels
    (sort keys / unique directions) via ``resolve_entry(i)``."""
    from titan_tpu.ids import IDType
    idm, schema, codec = graph.idm, graph.schema, graph.codec

    kind, tcount, dpos = native.parse_heads(col_buf, offs, exists_q.start)
    # vertex-cut rows fold into the canonical vertex (vectorized analog of
    # the scan job's canonical-representative aggregation)
    row_vids_a = idm.canonicalize_np(row_vids_raw)

    exists_rows = np.unique(entry_row_a[kind == native.KIND_EXISTS])
    vertex_id_list = row_vids_a[exists_rows].tolist()

    edge_mask = kind == native.KIND_OUT_EDGE
    keep_counts, fast_counts = [], []
    for c in np.unique(tcount[edge_mask]).tolist():
        tid = idm.schema_id(IDType.USER_EDGE_LABEL, int(c))
        if label_ids is not None and tid not in label_ids:
            continue
        keep_counts.append(c)
        if (not schema.sort_key(tid)
                and not schema.multiplicity(tid).unique(Direction.OUT)):
            fast_counts.append(c)
    keep = edge_mask & np.isin(tcount, keep_counts)
    fast = keep & np.isin(tcount, fast_counts)

    entry_ends = offs[1:]
    others, _ = native.bulk_read_uvar(col_buf, dpos[fast], entry_ends[fast])
    srcs = row_vids_a[entry_row_a[fast]]
    dsts = others
    labs = tcount[fast].astype(np.int64)

    slow_idx = np.flatnonzero(keep & ~fast)
    if len(slow_idx):
        s_src, s_dst, s_lab = [], [], []
        for i in slow_idx.tolist():
            rc = codec.parse(resolve_entry(i), schema)
            s_src.append(row_vids_a[entry_row_a[i]])
            s_dst.append(rc.other_vertex_id)
            s_lab.append(idm.count(rc.type_id))
        srcs = np.concatenate([srcs, np.asarray(s_src, np.int64)])
        dsts = np.concatenate([dsts, np.asarray(s_dst, np.int64)])
        labs = np.concatenate([labs, np.asarray(s_lab, np.int64)])
    return vertex_id_list, srcs, dsts, labs.tolist(), {}


def build(graph, labels: Optional[Sequence[str]] = None,
          edge_keys: Sequence[str] = (),
          directed: bool = True,
          _reuse_listener: Optional[tuple] = None) -> GraphSnapshot:
    """Scan the edgestore and build the snapshot.

    ``labels``: restrict to these edge labels (None = all user labels).
    ``edge_keys``: edge property names to extract into aligned arrays.
    ``directed=False`` adds the reverse of every edge (symmetrize).
    ``_reuse_listener``: a ``(token, ChangeQueue)`` pair to RE-ANCHOR at
    the scan-verified epoch instead of subscribing a fresh queue —
    ``rebuild_in_place()``'s seam: the queue is cleared and its
    overflow flag reset under the same commit-lock window that proves
    the scan saw a committed prefix, so delta refresh resumes soundly
    after an overflow-forced rebuild.
    """
    idm = graph.idm
    schema = graph.schema
    codec = graph.codec
    label_ids = None
    if labels is not None:
        label_ids = {st.id for name in labels
                     if (st := schema.get_by_name(name)) is not None}
    key_ids = {}
    for name in edge_keys:
        st = schema.get_by_name(name)
        if st is not None:
            key_ids[st.id] = name

    lo, hi = rids.category_bounds(RelationCategory.EDGE, Direction.OUT,
                                  include_system=False)
    scan_q = SliceQuery(lo, hi)

    # Epoch discipline: capture epoch0, scan, then — under the commit
    # lock — verify the epoch did not move during the scan and subscribe
    # atomically. A commit that lands mid-scan may or may not be in the
    # scanned rows (the scan has no store-level snapshot isolation), so
    # its delta payload can't be safely applied OR skipped; retry the
    # scan, and fail loud if writers keep racing. Commits push payload +
    # bump epoch atomically with commit_storage (core/graph.py commit),
    # so an unchanged epoch proves the scan saw a committed prefix.
    import contextlib

    def _scan_once():
        btx = graph.backend.begin_transaction()
        try:
            exists_q = codec.query_type(schema.system.vertex_exists,
                                        Direction.OUT, schema)[0]
            store = graph.backend.edge_store.store
            if native.available and not key_ids:
                if getattr(graph.backend.manager.features, "packed_ops",
                           False):
                    return _scan_native_packed(
                        graph, store.scan_rows_packed(btx.store_tx),
                        exists_q, label_ids)
                return _scan_native(graph,
                                    store.get_keys(SliceQuery(),
                                                   btx.store_tx),
                                    exists_q, label_ids)
            return _scan_python(graph,
                                store.get_keys(SliceQuery(), btx.store_tx),
                                exists_q, scan_q, label_ids, key_ids)
        finally:
            btx.commit()

    def _anchor_locked():
        """Under the commit lock with the scan verified: attach the
        listener — a fresh subscription, or the caller's existing queue
        re-anchored (same atomicity guarantee either way)."""
        if _reuse_listener is not None:
            tok, rq = _reuse_listener
            rq.reanchor()
            return tok, rq
        return graph._subscribe_locked()

    token = q = None
    for attempt in range(3):
        # final attempt scans while HOLDING the commit lock: writers are
        # excluded for one scan, so build() terminates under any write
        # load instead of spinning forever on epoch bumps
        hold = graph._commit_lock if attempt == 2 else \
            contextlib.nullcontext()
        with hold:
            epoch0 = graph.mutation_epoch
            vertex_id_list, srcs, dsts, labs, ev = _scan_once()
            if attempt == 2:
                token, q = _anchor_locked()
                break
        with graph._commit_lock:
            if graph.mutation_epoch == epoch0:
                token, q = _anchor_locked()
                break
    assert token is not None

    vertex_ids = np.array(sorted(vertex_id_list), dtype=np.int64)
    n = len(vertex_ids)
    raw_src = np.array(srcs, dtype=np.int64)
    raw_dst = np.array(dsts, dtype=np.int64)
    # drop edges whose endpoint is missing (ghosts)
    si = np.searchsorted(vertex_ids, raw_src)
    di = np.searchsorted(vertex_ids, raw_dst)
    si = np.clip(si, 0, max(n - 1, 0))
    di = np.clip(di, 0, max(n - 1, 0))
    ok = np.ones(len(raw_src), dtype=bool)
    if n:
        ok = (vertex_ids[si] == raw_src) & (vertex_ids[di] == raw_dst)
    src = si[ok].astype(np.int32)
    dst = di[ok].astype(np.int32)
    labs_arr = np.array(labs, dtype=np.int32)[ok]
    evs = {name: np.array(vals)[ok] for name, vals in ev.items()}
    if not directed:
        src, dst = np.concatenate([src, dst]), np.concatenate([dst, src])
        labs_arr = np.concatenate([labs_arr, labs_arr])
        evs = {name: np.concatenate([v, v]) for name, v in evs.items()}
    label_names = {}
    for code in np.unique(labs_arr).tolist() if len(labs_arr) else []:
        from titan_tpu.ids import IDType
        st = schema.get_type(idm.schema_id(IDType.USER_EDGE_LABEL, code))
        if st is not None:
            label_names[code] = st.name
    snap = from_arrays(n, src, dst, vertex_ids, evs, labs_arr, label_names)
    # freshness contract: stamp the scan-verified epoch and attach the
    # listener subscribed atomically with the epoch check above, so
    # refresh() can catch this snapshot up without a store re-scan
    snap.epoch = epoch0
    snap._graph = graph
    snap._listener_token, snap._listener = token, q
    snap._build_params = {"label_ids": label_ids, "directed": directed,
                          "labels": (tuple(labels)
                                     if labels is not None else None),
                          "edge_keys": tuple(edge_keys)}
    return snap
