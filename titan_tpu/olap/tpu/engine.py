"""The TPU superstep engine: DenseProgram → compiled BSP iteration.

This is the redesign of the reference's OLAP executor (reference: titan-core
graphdb/olap/computer/FulgoraGraphComputer.java:118-189 — scan-all-vertices
supersteps with in-heap message buckets) as batched SpMV on device:

* single-device: the whole BSP loop is ONE ``lax.while_loop`` under ``jit``;
  each superstep is gather(src state) → per-edge message → sorted
  segment-combine per destination → elementwise apply. No host round-trips
  until convergence.
* multi-device: the same loop runs inside ``shard_map`` over a 1D vertex
  mesh. Per-vertex state lives sharded (block per chip); each superstep
  all-gathers the state over ICI, computes messages for locally-owned
  (dst-sharded) edges, segment-combines into the local block and applies.
  Termination is a ``psum``-agreed global predicate, so every chip exits the
  ``while_loop`` on the same iteration.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from titan_tpu.obs import devprof
from titan_tpu.olap.api import DenseProgram
from titan_tpu.olap.tpu.snapshot import GraphSnapshot
from titan_tpu.ops.segment import combine_identity, segment_combine
from titan_tpu.parallel.mesh import VERTEX_AXIS, vertex_mesh
from titan_tpu.parallel.partition import ShardedCSR, shard_csr

#: store job-id under which TPUGraphComputer.run's own checkpoints live
#: (one run per checkpoint directory; the serving layer keys by job id
#: instead)
_RUN_CKPT_ID = "run"


class TPUEngineResult(dict):
    """Final per-vertex arrays + run metadata (+ MapReduce results in
    ``memory``, mirroring the host computer's Memory)."""

    def __init__(self, outputs: dict, iterations: int, n: int):
        super().__init__(outputs)
        self.iterations = iterations
        self.n = n
        self.memory: dict = {}


class _DenseVertexView:
    """Minimal vertex view over dense output arrays for classic MapReduce
    stages run against a TPU result (state reads only; adjacency would need
    the OLTP tx and is out of scope for post-BSP aggregation)."""

    __slots__ = ("_snap", "_state", "_di")

    def __init__(self, snap, state: dict, di: int):
        self._snap = snap
        self._state = state
        self._di = di

    @property
    def id(self) -> int:
        return int(self._snap.vertex_ids[self._di])

    def get_state(self, key: str, default=None):
        arr = self._state.get(key)
        if arr is None:
            return default
        return arr[self._di].item() if arr.ndim == 1 else arr[self._di]

    def value(self, key: str, default=None):
        return self.get_state(key, default)


def _pad_state(state: dict, n: int, n_pad: int) -> dict:
    if n_pad == n:
        return state
    return {k: jnp.concatenate(
        [v, jnp.zeros((n_pad - n,) + v.shape[1:], v.dtype)]) for k, v in state.items()}


class TPUGraphComputer:
    """``graph.compute()`` entry (computer.backend=tpu). Holds a snapshot and
    runs DensePrograms; arbitrary host VertexPrograms fall back to the host
    computer (olap/computer.py)."""

    def __init__(self, graph=None, snapshot: Optional[GraphSnapshot] = None,
                 num_devices: int = 0):
        self.graph = graph
        self._default_snapshot = snapshot
        self._built: dict[tuple, GraphSnapshot] = {}
        self.num_devices = num_devices
        self._scheduler = None

    # -- async serving delegation (olap/serving) ----------------------------

    def scheduler(self, **kwargs):
        """The computer's job scheduler (olap/serving.JobScheduler),
        created lazily and shared by every ``run_async`` call — the
        L4b end of the serving seam: queued/admitted jobs execute
        against this computer's graph through the snapshot pool (so a
        JobSpec's labels/edge_keys/directed select real snapshots),
        with same-snapshot BFS jobs fused into batched runs. Only a
        graph-less computer falls back to its fixed snapshot — that
        pool ignores per-job snapshot parameters (pool contract), so
        the caller owns making the fixed snapshot fit the jobs (e.g.
        symmetrized for BFS)."""
        if self._scheduler is None or self._scheduler.closed:
            from titan_tpu.olap.serving.scheduler import JobScheduler
            self._scheduler = JobScheduler(
                graph=self.graph,
                snapshot=None if self.graph is not None
                else self._default_snapshot,
                **kwargs)
        return self._scheduler

    def run_async(self, spec):
        """Submit a JobSpec (olap/api.py) to this computer's scheduler;
        returns the Job handle immediately."""
        return self.scheduler().submit(spec)

    def snapshot(self, labels=None, edge_keys=(), directed=True) -> GraphSnapshot:
        """Snapshot for the given parameters; cached PER parameter set (a
        cached directed snapshot must never answer a symmetrized request)."""
        default_args = labels is None and not tuple(edge_keys) and directed
        if self._default_snapshot is not None and default_args:
            return self._default_snapshot
        key = (tuple(labels) if labels is not None else None,
               tuple(edge_keys), directed)
        snap = self._built.get(key)
        if snap is None:
            from titan_tpu.olap.tpu import snapshot as snap_mod
            if self.graph is None:
                raise ValueError(
                    "computer holds a fixed snapshot but this request needs "
                    f"different parameters {key}; pass snapshot= explicitly "
                    "or construct the computer from a graph")
            snap = snap_mod.build(self.graph, labels=labels,
                                  edge_keys=edge_keys, directed=directed)
            self._built[key] = snap
        return snap

    def run(self, program: DenseProgram, params: Optional[dict] = None,
            snapshot: Optional[GraphSnapshot] = None,
            map_reduces: Optional[list] = None, *,
            resume_from: Optional[str] = None,
            checkpoint_to: Optional[str] = None,
            checkpoint_every: int = 0) -> TPUEngineResult:
        """Run a DenseProgram; optionally through the checkpoint plane
        (olap/recovery): ``checkpoint_to`` + ``checkpoint_every`` write
        a digest-verified checkpoint directory every N iterations, and
        ``resume_from`` reloads the newest VALID checkpoint under that
        path (torn/corrupted ones are skipped by digest) and continues
        the round loop — bit-equal to an uninterrupted run. Checkpoint
        paths are single-device only (the sharded loop never leaves the
        device mesh mid-run)."""
        if map_reduces:
            # validate BEFORE the expensive BSP run
            from titan_tpu.olap.api import DenseMapReduce, MapReduce
            from titan_tpu.olap.computer import _check_map_reduces
            _check_map_reduces(map_reduces,
                               require=(DenseMapReduce, MapReduce))
        snap = snapshot or self.snapshot(edge_keys=program.edge_keys())
        ndev = self.num_devices
        if ndev <= 0:
            ndev = len(jax.devices())
        if resume_from is None and checkpoint_to is None:
            if ndev == 1:
                result = run_single(program, snap, params)
            else:
                result = run_sharded(program, snap, params,
                                     vertex_mesh(ndev))
        else:
            if ndev != 1:
                raise ValueError(
                    "resume_from/checkpoint_to need the single-device "
                    "engine (set num_devices=1)")
            from titan_tpu.olap.recovery import CheckpointStore
            resume = None
            if resume_from is not None:
                ck = CheckpointStore(resume_from).latest(_RUN_CKPT_ID)
                if ck is not None and ck.kind == "dense":
                    resume = {"state": ck.arrays, "iteration": ck.round}
            ckpt_cb = None
            if checkpoint_to is not None and checkpoint_every > 0:
                wstore = CheckpointStore(checkpoint_to)
                attempt = ck.attempt + 1 if resume is not None else 1

                def ckpt_cb(it, state, _st=wstore, _at=attempt):
                    _st.save(_RUN_CKPT_ID, attempt=_at, round_=it,
                             kind="dense",
                             arrays={k: np.asarray(v)
                                     for k, v in state.items()})
            result = run_single(program, snap, params, resume=resume,
                                checkpoint=ckpt_cb,
                                checkpoint_every=checkpoint_every)
        if map_reduces:
            self._run_map_reduces(map_reduces, result, snap, params or {})
        return result

    def run_batched(self, program: DenseProgram, params_list,
                    snapshot: Optional[GraphSnapshot] = None) -> list:
        """K parameter sets of one DenseProgram as a single [K, n]
        batched device run (single-device path; see
        ``run_single_batched``)."""
        snap = snapshot or self.snapshot(edge_keys=program.edge_keys())
        return run_single_batched(program, snap, params_list)

    def _run_map_reduces(self, map_reduces, result: "TPUEngineResult",
                         snap: GraphSnapshot, params: dict) -> None:
        """Post-BSP MapReduce stages (reference:
        FulgoraGraphComputer.java:192-246). DenseMapReduce runs as one array
        program over the output arrays; classic MapReduce iterates host-side
        vertex views over the dense state."""
        from titan_tpu.olap.api import (DenseMapReduce, MapReduce,
                                        execute_map_reduce)
        host_state = None
        for mr in map_reduces:
            if isinstance(mr, DenseMapReduce):
                result.memory[mr.memory_key] = mr.compute(dict(result), snap,
                                                          params)
                continue
            if host_state is None:
                host_state = {k: np.asarray(v) for k, v in result.items()}
            views = (_DenseVertexView(snap, host_state, di)
                     for di in range(snap.n))
            result.memory[mr.memory_key] = execute_map_reduce(mr, views)


# ---------------------------------------------------------------------------
# single device
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnums=(0,), static_argnames=("n",))
def _iterate_single(program: DenseProgram, state: dict, src, dst, edata: dict,
                    seg_meta: tuple, params: dict, it0, it_end, n: int):
    """BSP iterations [it0, it_end) (both TRACED, so the checkpoint
    plane's chunked calls share one compile); each superstep is a pure
    function of (state, absolute iteration), so chunked execution is
    bit-equal to one monolithic while_loop. Returns (state, iterations
    run so far, done flag) — ``done`` lets the chunking caller stop at
    a mid-chunk convergence."""
    last_idx, seg_has = seg_meta

    def superstep(carry):
        state, it, _ = carry
        src_state = {k: v[src] for k, v in state.items()}
        msg = program.message(src_state, edata, params)
        agg = segment_combine(msg, dst, n, program.combine,
                              last_idx=last_idx, seg_has=seg_has)
        new_state = program.apply(state, agg, it, params)
        done = program.done(state, new_state, agg, it, params)
        return new_state, it + 1, done

    def cond(carry):
        _, it, done = carry
        return jnp.logical_and(it < it_end, jnp.logical_not(done))

    state, iters, done = jax.lax.while_loop(
        cond, superstep,
        (state, jnp.asarray(it0, jnp.int32), jnp.array(False)))
    return state, iters, done


def _device_graph_single(snap: GraphSnapshot):
    """Device-resident edge arrays, uploaded once per snapshot (cached on the
    snapshot object — repeated runs must not re-pay host→HBM transfer)."""
    cached = getattr(snap, "_dev_single", None)
    if cached is None:
        from titan_tpu.ops.segment import segment_metadata
        li, sh = segment_metadata(snap.indptr_in)
        devprof.count_h2d(
            "engine.graph",
            snap.src.nbytes + snap.dst.nbytes + li.nbytes + sh.nbytes
            + sum(v.nbytes for v in snap.edge_values.values()))
        cached = (jnp.asarray(snap.src), jnp.asarray(snap.dst),
                  {k: jnp.asarray(v) for k, v in snap.edge_values.items()},
                  (jnp.asarray(li), jnp.asarray(sh)))
        snap._dev_single = cached
    return cached


def run_single(program: DenseProgram, snap: GraphSnapshot,
               params: Optional[dict] = None, *,
               resume: Optional[dict] = None, checkpoint=None,
               checkpoint_every: int = 0) -> TPUEngineResult:
    """One DenseProgram run on a single device.

    Checkpoint plane (olap/recovery): with ``checkpoint_every > 0`` the
    while_loop runs in cadence-aligned chunks and
    ``checkpoint(iteration, state)`` fires at each boundary (state is
    the device dict; the callback owns readback/persistence).
    ``resume={"state": {...}, "iteration": i}`` continues from a
    captured boundary — chunked and resumed runs are bit-equal to a
    monolithic run because each superstep is a pure function of
    (state, absolute iteration)."""
    params = dict(params or {})
    n = snap.n
    if resume is not None:
        state = {k: jnp.asarray(v) for k, v in resume["state"].items()}
        it = int(resume["iteration"])
    else:
        state = {k: jnp.asarray(v)
                 for k, v in program.init(n, params).items()}
        it = 0
    src, dst, edata, seg_meta = _device_graph_single(snap)
    edata = {k: edata[k] for k in program.edge_keys()} if program.edge_keys() \
        else edata
    tparams = _traceable(params)
    max_iter = program.max_iterations
    every = int(checkpoint_every or 0)
    if checkpoint is None or every <= 0:
        state, iters, _ = devprof.profiled(
            "engine.iterate_single", _iterate_single, program, state,
            src, dst, edata, seg_meta, tparams, it, max_iter, n=n)
        it = int(iters)
    else:
        done = False
        while it < max_iter and not done:
            # next cadence boundary (cadence-aligned regardless of the
            # resume point, so checkpoint rounds are stable identifiers)
            it_end = min(max_iter, (it // every + 1) * every)
            state, iters, done_dev = devprof.profiled(
                "engine.iterate_single", _iterate_single, program,
                state, src, dst, edata, seg_meta, tparams, it, it_end,
                n=n)
            it = int(iters)
            done = bool(done_dev)
            checkpoint(it, state)
    outputs = program.outputs(state, params)
    devprof.count_d2h("engine.outputs",
                      sum(getattr(v, "nbytes", 0)
                          for v in outputs.values()))
    return TPUEngineResult({k: np.asarray(v) for k, v in outputs.items()},
                           it, n)


# ---------------------------------------------------------------------------
# batched multi-job execution (serving layer: K jobs, [K, ...] state)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnums=(0,), static_argnames=("max_iter", "n"))
def _iterate_batched(program: DenseProgram, state: dict, src, dst,
                     edata: dict, seg_meta: tuple, params: dict,
                     max_iter: int, n: int):
    """Multi-job BSP: every state leaf carries a leading job axis
    [K, ...] and the superstep is vmapped over it — the edge arrays are
    closed over, so the graph stays a single device-resident copy shared
    by every job. Jobs that report done freeze (their state stops
    changing) while the rest iterate; the loop exits when all are done.
    ``it_done[k]`` records the iteration at which job k converged (0 if
    it ran to max_iter — the caller patches that from ``iters``)."""
    last_idx, seg_has = seg_meta

    def job_step(st, pr, it):
        src_state = {k: v[src] for k, v in st.items()}
        msg = program.message(src_state, edata, pr)
        agg = segment_combine(msg, dst, n, program.combine,
                              last_idx=last_idx, seg_has=seg_has)
        new = program.apply(st, agg, it, pr)
        return new, program.done(st, new, agg, it, pr)

    def superstep(carry):
        state, it, done, it_done = carry
        new_state, jd = jax.vmap(
            lambda st, pr: job_step(st, pr, it))(state, params)
        new_state = {
            k: jnp.where(done.reshape((-1,) + (1,) * (v.ndim - 1)),
                         state[k], v)
            for k, v in new_state.items()}
        jd = jd | done
        it_done = jnp.where(jd & ~done, it + 1, it_done)
        return new_state, it + 1, jd, it_done

    def cond(carry):
        _, it, done, _ = carry
        return jnp.logical_and(it < max_iter, jnp.logical_not(done.all()))

    K = next(iter(state.values())).shape[0]
    state, iters, done, it_done = jax.lax.while_loop(
        cond, superstep,
        (state, jnp.int32(0), jnp.zeros((K,), bool),
         jnp.zeros((K,), jnp.int32)))
    return state, iters, it_done


def run_single_batched(program: DenseProgram, snap: GraphSnapshot,
                       params_list) -> list:
    """Run ONE DenseProgram for K parameter sets (e.g. K BFS sources) as
    a single batched device run with state widened to [K, n]: one
    compiled while_loop, per-job done flags, graph read once per
    superstep. Per-job results are bit-equal to ``run_single`` with the
    same params (the vmapped superstep evaluates identical expressions
    per job). Params must be numeric (int/float/bool/ndarray) and share
    a key set — they are stacked along the job axis and vmapped.

    Returns a list of TPUEngineResult, one per job (MapReduce stages are
    not run here — the serving layer aggregates per job if needed)."""
    params_list = [dict(p or {}) for p in params_list]
    if not params_list:
        raise ValueError("run_single_batched needs >= 1 params set")
    keys = set(params_list[0])
    for p in params_list[1:]:
        if set(p) != keys:
            raise ValueError("batched jobs must share a params key set")
    for p in params_list:
        for k, v in p.items():
            if not isinstance(v, (int, float, bool, np.ndarray)):
                raise TypeError(
                    f"run_single_batched params must be numeric; "
                    f"{k!r} is {type(v).__name__}")
    n = snap.n
    states = [{k: jnp.asarray(v)
               for k, v in program.init(n, p).items()} for p in params_list]
    state = {k: jnp.stack([s[k] for s in states]) for k in states[0]}
    src, dst, edata, seg_meta = _device_graph_single(snap)
    edata = {k: edata[k] for k in program.edge_keys()} \
        if program.edge_keys() else edata
    vparams = {k: jnp.stack([jnp.asarray(p[k]) for p in params_list])
               for k in keys}
    state, iters, it_done = devprof.profiled(
        "engine.iterate_batched", _iterate_batched,
        program, state, src, dst, edata, seg_meta, vparams,
        max_iter=program.max_iterations, n=n)
    it_done_h = np.asarray(it_done)
    iters_h = int(iters)
    results = []
    for i, p in enumerate(params_list):
        out = program.outputs({k: v[i] for k, v in state.items()}, p)
        devprof.count_d2h("engine.outputs",
                          sum(getattr(v, "nbytes", 0)
                              for v in out.values()))
        results.append(TPUEngineResult(
            {k: np.asarray(v) for k, v in out.items()},
            int(it_done_h[i]) or iters_h, n))
    return results


# ---------------------------------------------------------------------------
# multi device (shard_map over the vertex axis)
# ---------------------------------------------------------------------------

def run_sharded(program: DenseProgram, snap: GraphSnapshot,
                params: Optional[dict], mesh: Mesh) -> TPUEngineResult:
    params = dict(params or {})
    ndev = mesh.devices.size
    cache = getattr(snap, "_dev_sharded", None)
    if cache is None:
        cache = {}
        snap._dev_sharded = cache
    sharded = cache.get(ndev)
    if sharded is None:
        sharded = shard_csr(snap, ndev)
        cache[ndev] = sharded
    return _run_sharded_csr(program, sharded, params, mesh)


def _run_sharded_csr(program: DenseProgram, sc: ShardedCSR, params: dict,
                     mesh: Mesh) -> TPUEngineResult:
    n, n_pad, block = sc.n, sc.n_pad, sc.block
    state0 = _pad_state({k: jnp.asarray(v)
                         for k, v in program.init(n, params).items()}, n, n_pad)
    tparams = _traceable(params)

    vspec = P(VERTEX_AXIS)
    espec = P(VERTEX_AXIS, None)

    edge_keys = tuple(program.edge_keys())
    wanted_edata = {k for k in sc.edge_values if not edge_keys or k in edge_keys}

    def per_device(state, src_g, dst_l, valid, last_idx, seg_has, edata):
        # state arrays come in as [block]; edge arrays as [1, e_block]
        src_g = src_g[0]
        dst_l = dst_l[0]
        valid = valid[0]
        last_idx = last_idx[0]
        seg_has = seg_has[0]
        edata = {k: v[0] for k, v in edata.items()}

        def superstep(carry):
            state, it, _ = carry
            full = {k: jax.lax.all_gather(v, VERTEX_AXIS, tiled=True)
                    for k, v in state.items()}
            src_state = {k: v[src_g] for k, v in full.items()}
            msg = program.message(src_state, edata, tparams)
            ident = combine_identity(program.combine, msg.dtype)
            msg = jnp.where(valid, msg, ident)
            agg = segment_combine(msg, dst_l, block + 1, program.combine,
                                  last_idx=last_idx, seg_has=seg_has)[:block]
            new_state = program.apply(state, agg, it, tparams)
            local_done = program.done(state, new_state, agg, it, tparams)
            not_done = jax.lax.psum(
                jnp.where(local_done, 0, 1), VERTEX_AXIS)
            return new_state, it + 1, not_done == 0

        def cond(carry):
            _, it, done = carry
            return jnp.logical_and(it < program.max_iterations,
                                   jnp.logical_not(done))

        state, iters, _ = jax.lax.while_loop(
            cond, superstep, (state, jnp.int32(0), jnp.array(False)))
        return state, iters

    from titan_tpu.parallel.mesh import shard_map_compat
    mapped = jax.jit(shard_map_compat(
        per_device, mesh=mesh,
        in_specs=({k: vspec for k in state0}, espec, espec, espec, espec,
                  espec, {k: espec for k in sorted(wanted_edata)}),
        out_specs=({k: vspec for k in state0}, P())))

    dev = getattr(sc, "_dev", None)
    if dev is None:
        dev = (jnp.asarray(sc.src_global), jnp.asarray(sc.dst_local),
               jnp.asarray(sc.valid), jnp.asarray(sc.last_idx),
               jnp.asarray(sc.seg_has), {})
        sc._dev = dev
    src_g, dst_l, valid, last_idx_d, seg_has_d, edata_cache = dev
    # edge properties upload lazily, only the ones this program reads
    edata = {}
    for k in sorted(wanted_edata):
        if k not in edata_cache:
            edata_cache[k] = jnp.asarray(sc.edge_values[k])
        edata[k] = edata_cache[k]
    state, iters = devprof.profiled(
        "engine.iterate_sharded", mapped, state0, src_g, dst_l, valid,
        last_idx_d, seg_has_d, edata)
    outputs = program.outputs({k: v[:n] for k, v in state.items()}, params)
    return TPUEngineResult({k: np.asarray(v) for k, v in outputs.items()},
                           int(iters), n)


def _traceable(params: dict) -> dict:
    """Array-ify numeric params so they're jit-stable."""
    out = {}
    for k, v in params.items():
        if isinstance(v, (int, float, bool)) or isinstance(v, np.ndarray):
            out[k] = jnp.asarray(v)
        else:
            out[k] = v
    return out
