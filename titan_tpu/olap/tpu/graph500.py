"""Graph500 benchmark-graph pipeline: generate, build, cache, upload.

Through the axon tunnel D2H runs at ~0.01 GB/s (H2D at ~0.9 GB/s), so the
benchmark graph is generated and CSR-built on the HOST (native C++:
``tt_rmat_gen`` + ``tt_sym_chunked_csr``), cached on disk, and uploaded
once per process; the BFS then reads back only scalar stats. At scale 26
the symmetrized graph is exactly 2^31 directed edges — one over the int32
limit — so the builder dedups per-vertex adjacency (and drops self-loops),
which is standard Graph500 practice; TEPS accounting still uses the
PRE-dedup degrees (``deg_orig``), per the official TEPS definition
(counts every input edge tuple incl. multiples and self-loops).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

DEFAULT_CACHE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))),
    ".bench_cache")


def load_or_build(scale: int, edge_factor: int = 16, seed: int = 2,
                  cache_dir: str | None = None, verbose: bool = True
                  ) -> dict:
    """Host-side chunked Graph500 CSR, disk-cached.

    Returns numpy dict: ``dstT`` int32 [8, Q] (transposed 8-aligned
    chunked CSR, pad = n+1), ``colstart`` int32 [n+1], ``deg`` int32 [n]
    (post-dedup), ``deg_orig`` int32 [n], plus ``n``, ``q_total``,
    ``m_input`` (generated directed edge count before symmetrization).
    """
    from titan_tpu import native

    cache_dir = cache_dir or DEFAULT_CACHE
    tag = f"g500_s{scale}_ef{edge_factor}_seed{seed}"
    meta_path = os.path.join(cache_dir, tag + ".json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        # the native and numpy generators produce DIFFERENT edge sets for
        # the same (scale, ef, seed); a numpy-built cache is upgraded once
        # the native module appears so benchmark identity stays stable
        if not (native.available
                and meta.get("generator", "native") == "numpy"):
            out = {k: np.load(os.path.join(cache_dir, f"{tag}_{k}.npy"),
                              mmap_mode="r")
                   for k in ("dstT", "colstart", "deg", "deg_orig")}
            out.update(meta)
            return out

    n = 1 << scale
    m = n * edge_factor
    t0 = time.time()
    if native.available:
        src, dst = native.rmat_gen(m, scale, seed=seed)
        t1 = time.time()
        flat, colstart64, deg, deg_orig = native.sym_chunked_csr(src, dst,
                                                                 n)
        del src, dst
    else:
        # pure-numpy fallback (no C++ toolchain): fine for CI scales,
        # far too slow for scale 26
        from titan_tpu.olap.tpu.rmat import rmat_edges
        src, dst = rmat_edges(scale, edge_factor, seed=seed)
        t1 = time.time()
        flat, colstart64, deg, deg_orig = _sym_chunked_csr_numpy(src, dst,
                                                                 n)
        del src, dst
    t2 = time.time()
    q_total = flat.shape[0]
    # the kernels index COLUMNS (q_total) and vertices only — never flat
    # slot positions — so int32 safety needs q_total < 2^31, not slots;
    # scale-26 has ~2.26B slots but only ~282M columns
    if q_total >= (1 << 31):
        raise NotImplementedError(
            f"chunked CSR has {q_total} columns >= 2^31; needs sharding")
    dstT = np.ascontiguousarray(flat.T)
    del flat
    colstart = colstart64.astype(np.int32)
    t3 = time.time()
    if verbose:
        print(f"graph500 s{scale}: gen {t1-t0:.1f}s build {t2-t1:.1f}s "
              f"transpose {t3-t2:.1f}s  q_total={q_total} "
              f"dedup_edges={int(colstart64[-1])*8 - int(((8 - deg % 8) % 8).sum())}")
    meta = {"n": n, "q_total": int(q_total), "m_input": m,
            "generator": "native" if native.available else "numpy",
            "scale": scale, "edge_factor": edge_factor, "seed": seed,
            "e_dedup": int(deg.sum(dtype=np.int64)),
            "e_sym": int(deg_orig.sum(dtype=np.int64))}
    os.makedirs(cache_dir, exist_ok=True)
    for k, v in (("dstT", dstT), ("colstart", colstart), ("deg", deg),
                 ("deg_orig", deg_orig)):
        np.save(os.path.join(cache_dir, f"{tag}_{k}.npy"), v)
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    out = {"dstT": dstT, "colstart": colstart, "deg": deg,
           "deg_orig": deg_orig}
    out.update(meta)
    return out


def _sym_chunked_csr_numpy(src, dst, n: int):
    """Numpy mirror of native.sym_chunked_csr (symmetrize, per-vertex
    sort-dedup incl. self-loop drop, 8-aligned chunk layout)."""
    v = np.concatenate([src, dst]).astype(np.int64)
    w = np.concatenate([dst, src]).astype(np.int64)
    deg_orig = np.bincount(v, minlength=n).astype(np.int32)
    packed = np.unique(v * (n + 1) + w)
    pv = (packed // (n + 1)).astype(np.int64)
    pw = (packed % (n + 1)).astype(np.int64)
    keep = pv != pw
    pv, pw = pv[keep], pw[keep]
    deg = np.bincount(pv, minlength=n).astype(np.int32)
    degc = -(-deg.astype(np.int64) // 8)
    colstart64 = np.zeros(n + 1, np.int64)
    np.cumsum(degc, out=colstart64[1:])
    q_total = int(colstart64[-1]) + 1
    flat = np.full(q_total * 8, n + 1, np.int32)
    starts8 = colstart64[:n] * 8
    pos = np.repeat(starts8 - np.concatenate(
        [[0], np.cumsum(deg.astype(np.int64))])[:n], deg) \
        + np.arange(len(pw), dtype=np.int64)
    flat[pos] = pw
    return flat.reshape(q_total, 8), colstart64, deg, deg_orig


def pipelined_upload(arr, chunk_cols: int = 1 << 24):
    """Host->HBM upload of a [8, Q] (or any 2D) array in column chunks,
    overlapping disk/memory page-in with the transfer (SURVEY 2.7 PP row:
    DataPuller->Processor pipelining, restructured as async H2D).

    jnp.asarray of a 9GB memmap serializes page-in with the copy
    (~0.4 GB/s observed); chunked dispatch lets jax's async transfers
    overlap the next chunk's page-in. Each chunk lands in a donated
    device buffer via dynamic_update_slice, so peak device memory is
    size + one chunk."""
    import functools

    import jax
    import jax.numpy as jnp

    rows, cols = arr.shape
    if cols <= chunk_cols:
        return jnp.asarray(np.asarray(arr))

    # `at` is a traced operand (NOT static): one compile serves every
    # chunk — a static index would recompile per chunk, minutes of tunnel
    # compile time for a 9GB upload
    @functools.partial(jax.jit, donate_argnums=(0,))
    def place(buf, chunk, at):
        return jax.lax.dynamic_update_slice(
            buf, chunk, (jnp.int32(0), at))

    buf = jnp.zeros((rows, cols), arr.dtype)
    for c0 in range(0, cols, chunk_cols):
        if c0 + chunk_cols > cols:
            # final short chunk: shift the window back so the shape stays
            # static; the overlap rewrites identical real data (padding
            # with zeros instead would clobber the previous chunk's tail)
            c0 = cols - chunk_cols
        chunk = np.ascontiguousarray(arr[:, c0:c0 + chunk_cols])
        buf = place(buf, jnp.asarray(chunk), jnp.int32(c0))
    return buf


def to_device(host_graph: dict) -> dict:
    """Upload a ``load_or_build`` result as a hybrid-BFS device graph
    (the dict form ``frontier_bfs_hybrid`` accepts)."""
    import jax.numpy as jnp

    n = host_graph["n"]
    deg = np.asarray(host_graph["deg"])
    degc = -(-deg // 8)
    return {
        "dstT": pipelined_upload(host_graph["dstT"]),
        "colstart": jnp.asarray(np.asarray(host_graph["colstart"])),
        "degc": jnp.asarray(
            np.concatenate([degc, [0]]).astype(np.int32)),
        "deg": jnp.asarray(
            np.concatenate([deg, [0]]).astype(np.int32)),
        "q_total": host_graph["q_total"],
        "n": n,
    }


def device_degrees(deg_orig: np.ndarray, chunk: int = 4096):
    """Upload (once) the pre-dedup degrees padded to a chunk multiple,
    for reachable_edge_sum."""
    import jax.numpy as jnp

    pad = (-len(deg_orig)) % chunk
    return jnp.asarray(np.concatenate(
        [np.asarray(deg_orig, np.int32), np.zeros(pad, np.int32)]))


def _parts_fn():
    import functools

    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames=("n_", "inf", "chunk"))
    def parts(dist, deg_pad, n_: int, inf: int, chunk: int):
        reach = dist[:n_] < inf
        pad = (-n_) % chunk
        rp = jnp.concatenate(
            [reach, jnp.zeros((pad,), bool)]).reshape(-1, chunk)
        dp = deg_pad.reshape(-1, chunk)
        psums = jnp.where(rp, dp, 0).sum(axis=1, dtype=jnp.int32)
        return psums, reach.sum(dtype=jnp.int32)
    return parts


def reachable_edge_sum(dist_dev, deg_orig, inf: int,
                       chunk: int = 4096, deg_dev=None) -> tuple[int, int]:
    """Graph500 TEPS numerator on device: sum of PRE-dedup degrees over
    reachable vertices (and the reachable count). The total exceeds int32
    and x64 is disabled, so the device produces per-chunk int32 partial
    sums (each < 2^31) and the host adds them exactly. Pass ``deg_dev``
    (from device_degrees) to amortize the upload across calls."""
    from titan_tpu.utils.jitcache import jit_once
    parts = jit_once("graph500_reachable_parts", _parts_fn)
    n = len(deg_orig)
    if deg_dev is None:
        deg_dev = device_degrees(deg_orig, chunk)
    psums, nreach = parts(dist_dev, deg_dev, n_=n, inf=inf, chunk=chunk)
    return int(np.asarray(psums, dtype=np.int64).sum()), int(nreach)
