"""R-MAT / Graph500-style Kronecker edge-list generator.

(BASELINE configs #3-#5 use LiveJournal/Twitter/Graph500 graphs; with zero
egress we generate Graph500's synthetic R-MAT (A,B,C,D)=(.57,.19,.19,.05)
power-law graphs of the same scale instead. Vectorized numpy, chunked so
scale-26 generation stays in bounded memory.)
"""

from __future__ import annotations

import numpy as np


def rmat_edges(scale: int, edge_factor: int = 16, seed: int = 1,
               a: float = 0.57, b: float = 0.19, c: float = 0.19,
               chunk: int = 1 << 24) -> tuple[np.ndarray, np.ndarray]:
    """Returns (src, dst) int32/int64 arrays of 2^scale-vertex R-MAT edges."""
    n_edges = (1 << scale) * edge_factor
    rng = np.random.default_rng(seed)
    dtype = np.int32 if scale < 31 else np.int64
    src = np.empty(n_edges, dtype=dtype)
    dst = np.empty(n_edges, dtype=dtype)
    ab = a + b
    a_norm = a / ab
    c_norm = c / (1.0 - ab)
    for start in range(0, n_edges, chunk):
        m = min(chunk, n_edges - start)
        s = np.zeros(m, dtype=dtype)
        t = np.zeros(m, dtype=dtype)
        for bit in range(scale):
            # two float32 draws per bit: one for the row half, one shared
            # for the column (its threshold is selected by `down`, and
            # conditioned on `down` the uniform is independent — same
            # distribution as three draws at ~1/3 the rng cost)
            down = rng.random(m, dtype=np.float32) > ab
            u = rng.random(m, dtype=np.float32)
            right = np.where(down, u > c_norm, u > a_norm)
            s |= (down.astype(dtype) << bit)
            t |= (right.astype(dtype) << bit)
        # scramble to break locality (Graph500 permutes vertex ids)
        src[start:start + m] = s
        dst[start:start + m] = t
    perm = _scramble(1 << scale, seed, dtype)
    return perm[src], perm[dst]


def _scramble(n: int, seed: int, dtype) -> np.ndarray:
    rng = np.random.default_rng(seed + 0xC0FFEE)
    perm = np.arange(n, dtype=dtype)
    rng.shuffle(perm)
    return perm
