"""Distributed scan execution: ScanJobs over key splits in worker processes.

The Hadoop-analog tier (reference: titan-hadoop-core
scan/HadoopScanMapper.java:33-110 — any ScanJob runs as a Hadoop Mapper:
the job is reconstructed from serialized config in each mapper, every input
split re-slices its rows exactly like the in-process scanner, and
ScanMetrics map onto Hadoop counters; CassandraHadoopScanRunner /
HBaseHadoopScanRunner drive it; titan-test's SimpleScanJobRunner abstracts
"execute this ScanJob somehow" so one assertion suite runs both in-process
and distributed).

TPU-native restructuring: input splits ARE the id-partition key ranges —
partition bits sit in the key MSBs (IDManager.key_of), so each split is one
contiguous range that a worker process scans independently against its own
storage connection. No Hadoop: workers are OS processes (the multi-host
story runs one runner per host over its local partition ranges, with the
TPU engine consuming each host's CSR shard).

Contract: the job is shipped as a ``ScanJobSpec`` — an importable factory
``module:callable`` called as ``factory(graph, **kwargs)`` in each worker —
mirroring HadoopScanMapper.setup's reconstruct-from-config, because live
jobs hold graph handles that cannot cross process boundaries.
"""

from __future__ import annotations

import importlib
from concurrent.futures import ProcessPoolExecutor, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Optional, Sequence

from titan_tpu.olap.api import ScanMetrics


@dataclass(frozen=True)
class ScanJobSpec:
    """Serializable job description: ``factory`` is ``"module:callable"``,
    invoked as ``factory(graph, **kwargs)`` inside each worker."""
    factory: str
    kwargs: dict = field(default_factory=dict)

    def build(self, graph):
        mod, _, fn = self.factory.partition(":")
        if not fn:
            raise ValueError(f"spec factory must be 'module:callable', "
                             f"got {self.factory!r}")
        return getattr(importlib.import_module(mod), fn)(graph, **self.kwargs)


def key_splits(idm, num_splits: int) -> list[tuple[bytes, bytes]]:
    """Contiguous key ranges covering the id space, aligned to partition
    boundaries (the key order is partition-major, so storage partitions are
    the natural input splits — the reference's region/token-range splits)."""
    num_partitions = idm.num_partitions
    num_splits = max(1, min(num_splits, num_partitions))
    per = num_partitions // num_splits
    extra = num_partitions % num_splits
    out = []
    p = 0
    for i in range(num_splits):
        width = per + (1 if i < extra else 0)
        start, _ = idm.partition_key_range(p)
        _, end = idm.partition_key_range(p + width - 1)
        out.append((start, end))
        p += width
    return out


def _merge_metrics(target: ScanMetrics, counts: dict) -> None:
    for k, v in counts.items():
        target.increment(k, v)


def _run_split(graph_config: dict, spec: ScanJobSpec,
               key_range: tuple, store: str, num_threads: int,
               attempts: int = 5) -> dict:
    """One worker: own graph connection, one key split, merged counters.
    Top-level so it pickles under the spawn start method. Retries on
    TemporaryBackendError (multi-process write contention during open or
    flush) — split work is idempotent, like re-run Hadoop mappers."""
    import random
    import time

    import titan_tpu
    from titan_tpu.errors import TemporaryBackendError
    from titan_tpu.storage.scan import StandardScanner

    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            graph = titan_tpu.open(dict(graph_config))
        except TemporaryBackendError as e:
            last = e
            time.sleep(0.05 * (2 ** attempt) * (1 + random.random()))
            continue
        try:
            job = spec.build(graph)
            backend = graph.backend
            st = backend.index_store if store == "graphindex" else \
                backend.edge_store
            scanner = StandardScanner(st.store, backend.manager)
            metrics = scanner.execute(job, graph=graph,
                                      num_threads=num_threads,
                                      key_range=key_range)
            return dict(metrics._counts)
        except TemporaryBackendError as e:
            last = e
            time.sleep(0.05 * (2 ** attempt) * (1 + random.random()))
        finally:
            graph.close()
    raise last  # type: ignore[misc]


class DistributedScanRunner:
    """Executes a ScanJobSpec over all key splits in separate OS processes,
    each with its own storage connection (requires a multi-process-capable
    backend, e.g. sqlite). The coordinator merges per-split ScanMetrics —
    the reference's counter aggregation across mappers."""

    def __init__(self, graph_config: dict, num_workers: int = 4,
                 store: str = "edgestore", threads_per_worker: int = 2):
        self.graph_config = dict(graph_config)
        self.num_workers = num_workers
        self.store = store
        self.threads_per_worker = threads_per_worker

    def run(self, spec: ScanJobSpec,
            idm=None) -> ScanMetrics:
        if idm is None:
            import titan_tpu
            g = titan_tpu.open(dict(self.graph_config))
            try:
                idm = g.idm
            finally:
                g.close()
        splits = key_splits(idm, self.num_workers)
        metrics = ScanMetrics()
        # spawn, never fork: the coordinator process has JAX (and sqlite)
        # threads — forking a multithreaded process deadlocks
        import multiprocessing as mp
        with ProcessPoolExecutor(max_workers=self.num_workers,
                                 mp_context=mp.get_context("spawn")) as pool:
            futures = [pool.submit(_run_split, self.graph_config, spec, r,
                                   self.store, self.threads_per_worker)
                       for r in splits]
            for f in futures:
                _merge_metrics(metrics, f.result())
        return metrics


class InProcessSplitRunner:
    """Same split contract, same assertions, no processes: scans each key
    split on a thread against a SHARED graph (titan-test's
    SimpleScanJobRunner duality — in-process vs distributed execution of
    the identical job). Works on every backend including inmemory."""

    def __init__(self, graph, num_workers: int = 4,
                 store: str = "edgestore"):
        self.graph = graph
        self.num_workers = num_workers
        self.store = store

    def run(self, spec: ScanJobSpec, idm=None) -> ScanMetrics:
        if not isinstance(spec, ScanJobSpec):
            # a live job instance would be SHARED by the worker threads —
            # concurrent setup()/process() on one stateful job corrupts it
            raise TypeError(
                "InProcessSplitRunner needs a ScanJobSpec (one job instance "
                "is built per split); got "
                f"{type(spec).__name__}")
        from titan_tpu.storage.scan import StandardScanner
        graph = self.graph
        splits = key_splits(graph.idm, self.num_workers)
        backend = graph.backend
        st = backend.index_store if self.store == "graphindex" else \
            backend.edge_store
        scanner = StandardScanner(st.store, backend.manager)
        metrics = ScanMetrics()

        def one(key_range):
            job = spec.build(graph)
            m = scanner.execute(job, graph=graph, num_threads=1,
                                key_range=key_range)
            return dict(m._counts)

        with ThreadPoolExecutor(max_workers=self.num_workers) as pool:
            for counts in pool.map(one, splits):
                _merge_metrics(metrics, counts)
        return metrics


# ---------------------------------------------------------------------------
# distributed index management (MapReduceIndexManagement analog)
# ---------------------------------------------------------------------------

def make_repair_job(graph, index_name: str):
    """Worker-side factory for REINDEX (importable by ScanJobSpec)."""
    from titan_tpu.indexing.jobs import IndexRepairJob
    idx = graph.management().get_graph_index(index_name)
    if idx is None:
        raise ValueError(f"unknown index {index_name!r}")
    return IndexRepairJob(graph, idx)


def distributed_reindex(graph_config: dict, index_name: str,
                        num_workers: int = 4) -> ScanMetrics:
    """Drive SchemaAction.REINDEX across worker processes (reference:
    titan-hadoop MapReduceIndexManagement.updateIndex:50-110 — REINDEX as
    an MR job over the edgestore). The caller is responsible for the
    REGISTER → REINDEX → ENABLE lifecycle transitions around it."""
    runner = DistributedScanRunner(graph_config, num_workers=num_workers)
    spec = ScanJobSpec("titan_tpu.olap.distributed:make_repair_job",
                       {"index_name": index_name})
    return runner.run(spec)
