"""Multi-host distributed scan: HTTP scan workers + a failover coordinator.

Completes the distributed-OLAP tier past one host (reference:
titan-hadoop-core scan/HadoopScanMapper.java:33-110 runs any ScanJob in
YARN containers across a cluster; MapReduceIndexManagement.java:50 drives
REINDEX/REMOVE that way). Here the container role is a long-lived
**scan worker node** (``python -m titan_tpu.olap.scan_worker``) on each
host: the coordinator splits the key space on partition boundaries
(olap/distributed.key_splits), ships each split as a ScanJobSpec over
HTTP, and merges the returned ScanMetrics — with re-dispatch of a dead
worker's splits to the survivors, the Hadoop re-run-failed-mapper
semantics (split scans are idempotent).

Workers open their own graph connection per request from the shipped
config, exactly like HadoopScanMapper.setup reconstructs the job from
serialized config; pointing that config at a ``remote``/``remote-cluster``
backend gives a true multi-host scan against shared storage nodes.

Observability (ISSUE 14 satellite): the path used to merge
``ScanMetrics`` and say nothing else — a dead worker's splits were
silently re-dispatched. It now reports through the registry
(``scan.remote.*``, docs/monitoring.md — visible on ``GET /metrics``):
splits dispatched / merged / re-dispatched, per-``{url}`` worker
failures, and splits served on the worker side; pass ``tracer=`` (an
``obs.tracing.Tracer``) to additionally journal one span per split
under the reserved trace id ``"scan"`` (url, key-range size, ok/error
— the re-dispatch timeline end to end).

Cross-process tracing (ISSUE 18): with a tracer attached the
coordinator also stamps a W3C-style ``traceparent`` into every split
request; the worker runs its OWN tracer, parents its split / execute /
serialize spans under the propagated context, and ships the completed
spans back in the split response — the coordinator splices them into
the owning trace with clock-skew normalization
(``Tracer.ingest``), so ``GET /trace`` renders ONE tree spanning both
processes. Workers additionally expose ``GET /metrics`` (Prometheus
text), ``GET /healthz``, and a bounded ``POST /trace/drain`` for
fire-and-forget span pickup; ``obs.federate.Federator`` scrapes those
into the coordinator's ``GET /metrics?federate=1``. Propagation is
opt-out (``propagate=False``) and changes no scan results — only what
the trace can show (docs/observability.md "Cross-process tracing").
"""

from __future__ import annotations

import base64
import itertools
import queue
import threading
import time
from typing import Optional, Sequence

from titan_tpu.errors import PermanentBackendError, TemporaryBackendError
from titan_tpu.obs.tracing import (INGEST_MAX_SPANS, Tracer,
                                   make_traceparent, parse_traceparent)
from titan_tpu.olap.api import ScanMetrics
from titan_tpu.olap.distributed import (ScanJobSpec, _merge_metrics,
                                        _run_split, key_splits)
from titan_tpu.utils.httpnode import JsonNode, TextResponse, json_call
from titan_tpu.utils.metrics import MetricManager


def _b(x: bytes) -> str:
    return base64.b64encode(x).decode()


def _ub(x: str) -> bytes:
    return base64.b64decode(x)


class ScanWorkerServer(JsonNode):
    """One scan worker: executes shipped splits against its own graph
    connection (opened per request from the shipped config).

    The shipped ``factory`` ("module:callable") is code selection, so the
    worker gates it twice: the JsonNode bearer token (TITAN_TPU_NODE_TOKEN
    or ``auth_token=``) authenticates the caller, and ``factory_allow``
    restricts resolution to registered prefixes (default: the built-in
    ``titan_tpu.`` jobs; extend via the TITAN_TPU_SCAN_FACTORIES env var,
    comma-separated module prefixes)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 auth_token: Optional[str] = None,
                 factory_allow: Optional[Sequence[str]] = None,
                 metrics: Optional[MetricManager] = None,
                 tracer: Optional[Tracer] = None):
        super().__init__(self._dispatch, host, port, name="scan-worker",
                         auth_token=auth_token)
        self._metrics = metrics or MetricManager.instance()
        # the worker's OWN span journal: split requests that carry a
        # traceparent journal under a per-request key and drain into
        # the response; without one the worker records nothing
        self.tracer = tracer or Tracer()
        self._req_ids = itertools.count(1)
        if factory_allow is None:
            import os
            extra = [p.strip() for p in
                     os.environ.get("TITAN_TPU_SCAN_FACTORIES",
                                    "").split(",") if p.strip()]
            factory_allow = ["titan_tpu."] + extra
        self.factory_allow = list(factory_allow)

    def _factory_allowed(self, factory: str) -> bool:
        # dot-anchored only: an allowlist entry "myjobs" must not also
        # admit sibling modules like "myjobs_evil"
        mod = factory.split(":", 1)[0]
        return any(mod == p.rstrip(".")
                   or mod.startswith(p.rstrip(".") + ".")
                   for p in self.factory_allow)

    def _dispatch(self, path: str, req: dict):
        path = path.split("?", 1)[0]
        if path == "/ping":
            return {"ok": True}
        if path == "/scan":
            return self._scan(req)
        if path == "/trace/drain":
            # bounded pickup for fire-and-forget spans: anything a
            # worker journaled that never rode a response (the caller
            # names the trace key it handed out)
            tid = str(req.get("trace") or "")
            if not tid:
                raise ValueError("trace/drain needs {'trace': <id>}")
            cap = min(int(req.get("max_spans", INGEST_MAX_SPANS)),
                      INGEST_MAX_SPANS)
            spans, dropped = self.tracer.drain(tid, max_spans=cap)
            return {"spans": spans, "dropped": dropped,
                    "t_now": time.time()}
        if path == "/metrics":
            # the federation scrape surface (obs/federate): this
            # worker's whole registry in Prometheus text
            from titan_tpu.obs.promexport import (CONTENT_TYPE,
                                                  render_prometheus)
            return TextResponse(render_prometheus(self._metrics),
                                CONTENT_TYPE)
        if path == "/healthz":
            return {"live": True, "ready": True, "role": "scan-worker",
                    "splits_served": int(self._metrics.counter_value(
                        "scan.remote.splits_served"))}
        raise ValueError(f"unknown path {path!r}")

    def _scan(self, req: dict) -> dict:
        t_recv = time.time()
        if not self._factory_allowed(str(req["factory"])):
            raise PermanentBackendError(
                f"factory {req['factory']!r} not in the worker's "
                "allowlist (TITAN_TPU_SCAN_FACTORIES)")
        # propagated trace context → journal this split's spans under a
        # per-request key (concurrent splits of one trace must not
        # drain each other's spans) and ship them back in the response
        ctx = parse_traceparent(req.get("traceparent"))
        tracer = self.tracer if ctx is not None and \
            self.tracer is not None and self.tracer.enabled else None
        root = ex = None
        wkey = None
        if tracer is not None:
            # the propagated parent span id lives in the COORDINATOR's
            # id space (numerically colliding with this worker's own
            # ids), so the worker's root ships parentless — ingest
            # attaches unshipped parents under the coordinator's split
            # span, which IS the propagated parent
            wkey = f"{ctx[0]}#w{next(self._req_ids)}"
            root = tracer.start(wkey, "split",
                                factory=str(req["factory"]))
            ex = tracer.start(wkey, "execute", parent=root)
        spec = ScanJobSpec(req["factory"], dict(req.get("kwargs") or {}))
        key_range = (_ub(req["key_start"]), _ub(req["key_end"]))
        counts = _run_split(dict(req["graph_config"]), spec, key_range,
                            req.get("store", "edgestore"),
                            int(req.get("num_threads", 2)))
        self._metrics.counter("scan.remote.splits_served").inc()
        if tracer is None:
            return {"counts": {k: int(v) for k, v in counts.items()}}
        tracer.end(ex)
        ser = tracer.start(wkey, "serialize", parent=root)
        out = {"counts": {k: int(v) for k, v in counts.items()}}
        tracer.end(ser)
        tracer.end(root)
        spans, dropped = tracer.drain(wkey)
        out["trace"] = {"spans": spans, "dropped": dropped,
                        "t_recv": t_recv, "t_send": time.time()}
        return out


class RemoteScanRunner:
    """Coordinator: dispatches key splits to HTTP scan workers with
    failover. ``workers``: ["host:port", ...]."""

    def __init__(self, workers: Sequence[str], graph_config: dict,
                 store: str = "edgestore", threads_per_worker: int = 2,
                 splits_per_worker: int = 2, timeout: float = 600.0,
                 metrics: Optional[MetricManager] = None,
                 tracer=None, trace_id: str = "scan",
                 propagate: bool = True):
        if not workers:
            raise ValueError("RemoteScanRunner needs at least one worker")
        self.workers = [w if "://" in w else f"http://{w}" for w in workers]
        self.graph_config = dict(graph_config)
        self.store = store
        self.threads_per_worker = threads_per_worker
        self.splits_per_worker = splits_per_worker
        self.timeout = timeout
        self._metrics = metrics or MetricManager.instance()
        # optional span journal (obs/tracing.Tracer): one span per
        # split attempt under ``trace_id`` (default: the reserved
        # "scan" trace); with ``propagate`` the split's span id also
        # rides the request as a traceparent and the worker's spans
        # come back spliced under it (Tracer.ingest)
        self._tracer = tracer
        self.trace_id = trace_id
        self.propagate = bool(propagate)

    def _start_split(self, url: str):
        """Open the per-attempt ``split`` span (None without a tracer)
        — a dead worker's re-dispatch stays a visible timeline, not an
        inference from totals."""
        if self._tracer is None or not self._tracer.enabled:
            return None
        return self._tracer.start(self.trace_id, "split", url=url)

    def _end_split(self, span, **attrs) -> None:
        if span is not None:
            self._tracer.end(span, **attrs)

    def _ingest_trace(self, res: dict, span, url: str,
                      t0: float, t1: float) -> None:
        """Splice the worker's shipped spans under this attempt's split
        span. Skew anchor: the coordinator knows it sent at ``t0`` and
        received at ``t1``; the worker stamped its own receive/send —
        the NTP-style midpoint difference is the remote→local offset,
        and (t0, t1) is the clamp window that keeps the stitched tree
        monotonic even when that estimate is off."""
        wire = res.get("trace") if isinstance(res, dict) else None
        if wire is None or span is None:
            return
        try:
            offset = ((t0 + t1) - (float(wire["t_recv"])
                                   + float(wire["t_send"]))) / 2.0
        except (KeyError, TypeError, ValueError):
            offset = 0.0
        self._tracer.ingest(
            self.trace_id, wire.get("spans") or [],
            parent_id=span.span_id, offset=offset, window=(t0, t1),
            instance=url, extra_dropped=int(wire.get("dropped") or 0),
            metrics=self._metrics)

    def run(self, spec: ScanJobSpec, idm=None) -> ScanMetrics:
        if idm is None:
            import titan_tpu
            g = titan_tpu.open(dict(self.graph_config))
            try:
                idm = g.idm
            finally:
                g.close()
        splits = key_splits(idm,
                            len(self.workers) * self.splits_per_worker)
        pending: "queue.Queue" = queue.Queue()
        for s in splits:
            pending.put(s)
        results: list[dict] = []
        errors: list[BaseException] = []
        fatal: list[BaseException] = []
        done = threading.Event()
        lock = threading.Lock()
        remaining = [len(splits)]
        alive = [len(self.workers)]

        def serve(url: str):
            """One drain loop per worker: keep polling until every split
            has completed (another worker's failed split may be re-queued
            AFTER this worker first sees an empty queue, so idle workers
            must wait, not exit); a worker retires only on its own
            failure (re-run-mapper semantics). A PermanentBackendError is
            the JOB's fault (e.g. an unresolvable factory) — retrying on
            other workers cannot help, so the whole run aborts."""
            m = self._metrics
            while not done.is_set():
                try:
                    key_range = pending.get(timeout=0.2)
                except queue.Empty:
                    continue
                m.counter("scan.remote.splits_dispatched").inc()
                span = self._start_split(url)
                # skew anchors in the TRACER's clock domain (injectable
                # clock preserved): the NTP-style offset in
                # _ingest_trace maps worker wall time into whatever
                # clock this tracer runs on
                t0 = span.t_start if span is not None else time.time()
                payload = {
                    "graph_config": self.graph_config,
                    "factory": spec.factory, "kwargs": spec.kwargs,
                    "key_start": _b(key_range[0]),
                    "key_end": _b(key_range[1]),
                    "store": self.store,
                    "num_threads": self.threads_per_worker,
                }
                if span is not None and self.propagate:
                    payload["traceparent"] = make_traceparent(
                        self.trace_id, span.span_id)
                try:
                    res = json_call(url, "/scan", payload,
                                    timeout=self.timeout)
                except PermanentBackendError as e:
                    self._end_split(span, error=f"permanent: {e}")
                    with lock:
                        fatal.append(e)
                        done.set()
                    return
                except Exception as e:   # noqa: BLE001 — retire worker
                    # the split is idempotent: back on the queue for a
                    # survivor — COUNTED, so a flapping worker's
                    # re-dispatch churn shows on GET /metrics instead
                    # of hiding inside a slower wall clock
                    pending.put(key_range)
                    m.counter("scan.remote.splits_redispatched").inc()
                    m.counter("scan.remote.worker_failures",
                              labels={"url": url}).inc()
                    self._end_split(span, redispatched=True,
                                    error=f"{type(e).__name__}: {e}")
                    with lock:
                        errors.append(e)
                        alive[0] -= 1
                        if alive[0] == 0:
                            done.set()   # no one left to drain the queue
                    return
                t1 = self._tracer.clock() if span is not None \
                    else time.time()
                m.counter("scan.remote.splits_merged").inc()
                self._ingest_trace(res, span, url, t0, t1)
                self._end_split(span, ok=True)
                with lock:
                    results.append(res["counts"])
                    remaining[0] -= 1
                    if remaining[0] == 0:
                        done.set()

        threads = [threading.Thread(target=serve, args=(u,), daemon=True)
                   for u in self.workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if fatal:
            raise fatal[0]
        if remaining[0] > 0:
            raise TemporaryBackendError(
                f"{remaining[0]} split(s) undispatchable; all workers "
                f"failed (last errors: {[str(e) for e in errors[-3:]]})")
        metrics = ScanMetrics()
        for counts in results:
            _merge_metrics(metrics, counts)
        return metrics


def distributed_reindex_remote(workers: Sequence[str], graph_config: dict,
                               index_name: str) -> ScanMetrics:
    """REINDEX across HTTP scan workers (the MapReduceIndexManagement
    role at multi-host scale)."""
    runner = RemoteScanRunner(workers, graph_config)
    spec = ScanJobSpec("titan_tpu.olap.distributed:make_repair_job",
                       {"index_name": index_name})
    return runner.run(spec)


def main(argv: Optional[list] = None) -> None:
    import sys
    args = list(sys.argv[1:] if argv is None else argv)
    port = int(args[0]) if args else 0
    # localhost by default: exposing the worker beyond the host is an
    # explicit decision and should come with a bearer token
    host = args[1] if len(args) > 1 else "127.0.0.1"
    node = ScanWorkerServer(host, port).start()
    if host not in ("127.0.0.1", "localhost") and node.auth_token is None:
        print("WARNING: scan-worker bound to a non-local interface with "
              "no TITAN_TPU_NODE_TOKEN set — any peer can submit scan "
              "jobs", file=sys.stderr)
    print(f"scan-worker serving on {node.url}")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        node.stop()


if __name__ == "__main__":
    main()
