"""Multi-host distributed scan: HTTP scan workers + a failover coordinator.

Completes the distributed-OLAP tier past one host (reference:
titan-hadoop-core scan/HadoopScanMapper.java:33-110 runs any ScanJob in
YARN containers across a cluster; MapReduceIndexManagement.java:50 drives
REINDEX/REMOVE that way). Here the container role is a long-lived
**scan worker node** (``python -m titan_tpu.olap.scan_worker``) on each
host: the coordinator splits the key space on partition boundaries
(olap/distributed.key_splits), ships each split as a ScanJobSpec over
HTTP, and merges the returned ScanMetrics — with re-dispatch of a dead
worker's splits to the survivors, the Hadoop re-run-failed-mapper
semantics (split scans are idempotent).

Workers open their own graph connection per request from the shipped
config, exactly like HadoopScanMapper.setup reconstructs the job from
serialized config; pointing that config at a ``remote``/``remote-cluster``
backend gives a true multi-host scan against shared storage nodes.

Observability (ISSUE 14 satellite): the path used to merge
``ScanMetrics`` and say nothing else — a dead worker's splits were
silently re-dispatched. It now reports through the registry
(``scan.remote.*``, docs/monitoring.md — visible on ``GET /metrics``):
splits dispatched / merged / re-dispatched, per-``{url}`` worker
failures, and splits served on the worker side; pass ``tracer=`` (an
``obs.tracing.Tracer``) to additionally journal one span per split
under the reserved trace id ``"scan"`` (url, key-range size, ok/error
— the re-dispatch timeline end to end).
"""

from __future__ import annotations

import base64
import queue
import threading
import time
from typing import Optional, Sequence

from titan_tpu.errors import PermanentBackendError, TemporaryBackendError
from titan_tpu.olap.api import ScanMetrics
from titan_tpu.olap.distributed import (ScanJobSpec, _merge_metrics,
                                        _run_split, key_splits)
from titan_tpu.utils.httpnode import JsonNode, json_call
from titan_tpu.utils.metrics import MetricManager


def _b(x: bytes) -> str:
    return base64.b64encode(x).decode()


def _ub(x: str) -> bytes:
    return base64.b64decode(x)


class ScanWorkerServer(JsonNode):
    """One scan worker: executes shipped splits against its own graph
    connection (opened per request from the shipped config).

    The shipped ``factory`` ("module:callable") is code selection, so the
    worker gates it twice: the JsonNode bearer token (TITAN_TPU_NODE_TOKEN
    or ``auth_token=``) authenticates the caller, and ``factory_allow``
    restricts resolution to registered prefixes (default: the built-in
    ``titan_tpu.`` jobs; extend via the TITAN_TPU_SCAN_FACTORIES env var,
    comma-separated module prefixes)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 auth_token: Optional[str] = None,
                 factory_allow: Optional[Sequence[str]] = None,
                 metrics: Optional[MetricManager] = None):
        super().__init__(self._dispatch, host, port, name="scan-worker",
                         auth_token=auth_token)
        self._metrics = metrics or MetricManager.instance()
        if factory_allow is None:
            import os
            extra = [p.strip() for p in
                     os.environ.get("TITAN_TPU_SCAN_FACTORIES",
                                    "").split(",") if p.strip()]
            factory_allow = ["titan_tpu."] + extra
        self.factory_allow = list(factory_allow)

    def _factory_allowed(self, factory: str) -> bool:
        # dot-anchored only: an allowlist entry "myjobs" must not also
        # admit sibling modules like "myjobs_evil"
        mod = factory.split(":", 1)[0]
        return any(mod == p.rstrip(".")
                   or mod.startswith(p.rstrip(".") + ".")
                   for p in self.factory_allow)

    def _dispatch(self, path: str, req: dict):
        if path == "/ping":
            return {"ok": True}
        if path == "/scan":
            if not self._factory_allowed(str(req["factory"])):
                raise PermanentBackendError(
                    f"factory {req['factory']!r} not in the worker's "
                    "allowlist (TITAN_TPU_SCAN_FACTORIES)")
            spec = ScanJobSpec(req["factory"], dict(req.get("kwargs") or {}))
            key_range = (_ub(req["key_start"]), _ub(req["key_end"]))
            counts = _run_split(dict(req["graph_config"]), spec, key_range,
                                req.get("store", "edgestore"),
                                int(req.get("num_threads", 2)))
            self._metrics.counter("scan.remote.splits_served").inc()
            return {"counts": {k: int(v) for k, v in counts.items()}}
        raise ValueError(f"unknown path {path!r}")


class RemoteScanRunner:
    """Coordinator: dispatches key splits to HTTP scan workers with
    failover. ``workers``: ["host:port", ...]."""

    def __init__(self, workers: Sequence[str], graph_config: dict,
                 store: str = "edgestore", threads_per_worker: int = 2,
                 splits_per_worker: int = 2, timeout: float = 600.0,
                 metrics: Optional[MetricManager] = None,
                 tracer=None):
        if not workers:
            raise ValueError("RemoteScanRunner needs at least one worker")
        self.workers = [w if "://" in w else f"http://{w}" for w in workers]
        self.graph_config = dict(graph_config)
        self.store = store
        self.threads_per_worker = threads_per_worker
        self.splits_per_worker = splits_per_worker
        self.timeout = timeout
        self._metrics = metrics or MetricManager.instance()
        # optional span journal (obs/tracing.Tracer): one event per
        # split attempt under the reserved "scan" trace id
        self._tracer = tracer

    def _split_event(self, url: str, t0: float, **attrs) -> None:
        """One completed ``split`` span under the reserved ``"scan"``
        trace id (when a tracer is attached) — dispatch→outcome wall
        time with the worker url, so a dead worker's re-dispatch is a
        visible timeline, not an inference from totals."""
        if self._tracer is not None:
            self._tracer.event("scan", "split", t0=t0, t1=time.time(),
                               url=url, **attrs)

    def run(self, spec: ScanJobSpec, idm=None) -> ScanMetrics:
        if idm is None:
            import titan_tpu
            g = titan_tpu.open(dict(self.graph_config))
            try:
                idm = g.idm
            finally:
                g.close()
        splits = key_splits(idm,
                            len(self.workers) * self.splits_per_worker)
        pending: "queue.Queue" = queue.Queue()
        for s in splits:
            pending.put(s)
        results: list[dict] = []
        errors: list[BaseException] = []
        fatal: list[BaseException] = []
        done = threading.Event()
        lock = threading.Lock()
        remaining = [len(splits)]
        alive = [len(self.workers)]

        def serve(url: str):
            """One drain loop per worker: keep polling until every split
            has completed (another worker's failed split may be re-queued
            AFTER this worker first sees an empty queue, so idle workers
            must wait, not exit); a worker retires only on its own
            failure (re-run-mapper semantics). A PermanentBackendError is
            the JOB's fault (e.g. an unresolvable factory) — retrying on
            other workers cannot help, so the whole run aborts."""
            m = self._metrics
            while not done.is_set():
                try:
                    key_range = pending.get(timeout=0.2)
                except queue.Empty:
                    continue
                m.counter("scan.remote.splits_dispatched").inc()
                t0 = time.time()
                try:
                    res = json_call(url, "/scan", {
                        "graph_config": self.graph_config,
                        "factory": spec.factory, "kwargs": spec.kwargs,
                        "key_start": _b(key_range[0]),
                        "key_end": _b(key_range[1]),
                        "store": self.store,
                        "num_threads": self.threads_per_worker,
                    }, timeout=self.timeout)
                except PermanentBackendError as e:
                    self._split_event(url, t0, error=f"permanent: {e}")
                    with lock:
                        fatal.append(e)
                        done.set()
                    return
                except Exception as e:   # noqa: BLE001 — retire worker
                    # the split is idempotent: back on the queue for a
                    # survivor — COUNTED, so a flapping worker's
                    # re-dispatch churn shows on GET /metrics instead
                    # of hiding inside a slower wall clock
                    pending.put(key_range)
                    m.counter("scan.remote.splits_redispatched").inc()
                    m.counter("scan.remote.worker_failures",
                              labels={"url": url}).inc()
                    self._split_event(url, t0, redispatched=True,
                                      error=f"{type(e).__name__}: {e}")
                    with lock:
                        errors.append(e)
                        alive[0] -= 1
                        if alive[0] == 0:
                            done.set()   # no one left to drain the queue
                    return
                m.counter("scan.remote.splits_merged").inc()
                self._split_event(url, t0, ok=True)
                with lock:
                    results.append(res["counts"])
                    remaining[0] -= 1
                    if remaining[0] == 0:
                        done.set()

        threads = [threading.Thread(target=serve, args=(u,), daemon=True)
                   for u in self.workers]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if fatal:
            raise fatal[0]
        if remaining[0] > 0:
            raise TemporaryBackendError(
                f"{remaining[0]} split(s) undispatchable; all workers "
                f"failed (last errors: {[str(e) for e in errors[-3:]]})")
        metrics = ScanMetrics()
        for counts in results:
            _merge_metrics(metrics, counts)
        return metrics


def distributed_reindex_remote(workers: Sequence[str], graph_config: dict,
                               index_name: str) -> ScanMetrics:
    """REINDEX across HTTP scan workers (the MapReduceIndexManagement
    role at multi-host scale)."""
    runner = RemoteScanRunner(workers, graph_config)
    spec = ScanJobSpec("titan_tpu.olap.distributed:make_repair_job",
                       {"index_name": index_name})
    return runner.run(spec)


def main(argv: Optional[list] = None) -> None:
    import sys
    args = list(sys.argv[1:] if argv is None else argv)
    port = int(args[0]) if args else 0
    # localhost by default: exposing the worker beyond the host is an
    # explicit decision and should come with a bearer token
    host = args[1] if len(args) > 1 else "127.0.0.1"
    node = ScanWorkerServer(host, port).start()
    if host not in ("127.0.0.1", "localhost") and node.auth_token is None:
        print("WARNING: scan-worker bound to a non-local interface with "
              "no TITAN_TPU_NODE_TOKEN set — any peer can submit scan "
              "jobs", file=sys.stderr)
    print(f"scan-worker serving on {node.url}")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        node.stop()


if __name__ == "__main__":
    main()
