"""Batch-loading ingest: benchmark-scale writes through the storage plane.

The reference ships a bulk-loading mode (reference: titan-core
graphdb/configuration/GraphDatabaseConfiguration.java `storage.batch-loading`
+ docs/bulkloading.txt) that bypasses per-element consistency work so tens of
millions of elements can be loaded in reasonable time. This module is the
TPU-framework equivalent: vertex/relation ids are claimed in ONE authority
block each (the claim-column protocol, same as normal allocation — just one
big block, the reference's "increase ids.block-size for bulk loads" advice),
edge rows are encoded VECTORIZED (numpy varint sweeps instead of per-relation
DataOutput calls — the role the reference's EdgeSerializer hot loop plays,
EdgeSerializer.java:222-315), and the rows land through the ordinary KCVS
``mutate`` SPI, so everything downstream (scan, snapshot, OLAP) sees a
perfectly normal edgestore.

Wire-format compatibility with codec/edges.py is pinned by
tests/test_bulk_load.py (bulk-written rows parse back through
``EdgeCodec.parse`` and the native scan identically to tx-written rows).
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from titan_tpu.codec import relation_ids as rids
from titan_tpu.codec.dataio import DataOutput
from titan_tpu.core.defs import Direction, Multiplicity, RelationCategory

_STOP = 0x80
_MASK = 0x7F


def _uvar_lengths(v: np.ndarray) -> np.ndarray:
    """Byte length of each value's MSB-first unsigned varint."""
    v = v.astype(np.uint64)
    n = np.ones(v.shape, np.int64)
    for k in range(1, 10):
        n += v >= np.uint64(1 << (7 * k))
    return n


def _write_uvars(out: np.ndarray, starts: np.ndarray, lens: np.ndarray,
                 v: np.ndarray, backward: bool = False) -> None:
    """Scatter the varint bytes of ``v[i]`` at ``out[starts[i]:...+lens[i]]``.

    Forward form: MSB-first groups, stop bit on the LAST byte
    (utils/varint.write_positive). Backward form: same group order but the
    stop bit moves to the FIRST byte (write_positive_backward)."""
    v = v.astype(np.uint64)
    maxb = int(lens.max()) if len(lens) else 0
    for k in range(maxb):          # k = byte index counted from the END
        sel = lens > k
        pos = starts[sel] + (lens[sel] - 1 - k)
        b = ((v[sel] >> np.uint64(7 * k)) & np.uint64(_MASK)).astype(np.uint8)
        if not backward and k == 0:
            b |= np.uint8(_STOP)
        out[pos] = b
    if backward and maxb:
        first = lens > 0
        out[starts[first]] |= np.uint8(_STOP)


def encode_out_edge_columns(prefix: bytes, others: np.ndarray,
                            relids: np.ndarray
                            ) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized MULTI-edge OUT columns: ``prefix ⋅ uvar(other) ⋅
    uvar(relid)`` (codec/edges.py layout row 'EDGE multi', empty sort key).
    Returns (flat uint8 buffer, int64 offsets [m+1])."""
    others = np.asarray(others, np.int64)
    relids = np.asarray(relids, np.int64)
    l1 = _uvar_lengths(others)
    l2 = _uvar_lengths(relids)
    P = len(prefix)
    lens = P + l1 + l2
    offs = np.zeros(len(lens) + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    out = np.empty(int(offs[-1]), np.uint8)
    pb = np.frombuffer(prefix, np.uint8)
    for j in range(P):
        out[offs[:-1] + j] = pb[j]
    _write_uvars(out, offs[:-1] + P, l1, others)
    _write_uvars(out, offs[:-1] + P + l1, l2, relids)
    return out, offs


def encode_backward_uvars(prefix: bytes, relids: np.ndarray
                          ) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized ``prefix ⋅ backward-uvar(relid)`` buffers (the VALUE of a
    SINGLE-cardinality property row, codec/edges.py 'PROPERTY single')."""
    relids = np.asarray(relids, np.int64)
    l1 = _uvar_lengths(relids)
    P = len(prefix)
    lens = P + l1
    offs = np.zeros(len(lens) + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    out = np.empty(int(offs[-1]), np.uint8)
    pb = np.frombuffer(prefix, np.uint8)
    for j in range(P):
        out[offs[:-1] + j] = pb[j]
    _write_uvars(out, offs[:-1] + P, l1, relids, backward=True)
    return out, offs


def _claim_counts(authority, namespace: bytes, k: int,
                  chunk: int = 1 << 26) -> np.ndarray:
    """~k id counts straight from the authority (contiguous blocks)."""
    got: list[np.ndarray] = []
    have = 0
    while have < k:
        want = min(k - have, chunk)
        block = authority.get_id_block(namespace, want, 120.0)
        got.append(np.arange(block.start, block.end, dtype=np.int64))
        have += len(block)
    return np.concatenate(got)[:k]


def bulk_load_adjacency(graph, src: np.ndarray, dst: np.ndarray,
                        n: Optional[int] = None, label: str = "related",
                        partition: int = 0) -> dict:
    """Load ``n`` vertices + the directed edges (src[i] -> dst[i], dense
    [0, n) indices) through the KCVS SPI. Returns
    {"vertex_ids": int64 [n] (ascending), "n", "m", seconds...}.

    One OUT row entry per edge (the reference writes both endpoint rows;
    bulk adjacency for OLAP needs only the OUT side — snapshot.build scans
    OUT columns, snapshot.py:544). Vertex existence rows are written so
    the scan's exists filter sees every vertex, isolated-ones included.
    """
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    if n is None:
        n = int(max(src.max(), dst.max())) + 1 if len(src) else 0
    m = len(src)
    t0 = time.time()

    schema, idm, codec = graph.schema, graph.idm, graph.codec
    st = schema.get_by_name(label)
    if st is None:
        st = graph.management().make_edge_label(label, Multiplicity.MULTI)
    label_id = st.id

    # --- id allocation: one authority block per namespace ---------------
    authority = graph.backend.id_authority
    from titan_tpu.ids.idmanager import TYPE_BITS, IDType
    vcounts = _claim_counts(authority, b"partition%d" % partition, n)
    rcounts = _claim_counts(authority, b"relation", n + m)
    # vectorized make_id(NORMAL_VERTEX, count, partition): count in the
    # MSBs keeps id order == count order (ids/idmanager.py:124-132)
    shift = TYPE_BITS + idm.partition_bits
    vids = ((vcounts << shift) | (partition << TYPE_BITS)
            | int(IDType.NORMAL_VERTEX))
    # relation ids are bare counters (idmanager.relation_id)
    exists_relids = rcounts[:n]
    edge_relids = rcounts[n:]

    # --- encode -----------------------------------------------------------
    # row keys: key_of moves partition above count; one vectorized pack +
    # a single big-endian byte view sliced per key
    from titan_tpu.ids.idmanager import TOTAL_BITS
    keys64 = ((np.int64(partition) << (TOTAL_BITS - idm.partition_bits))
              | (vcounts << TYPE_BITS) | int(IDType.NORMAL_VERTEX))
    key_bytes = keys64.astype(">i8").tobytes()

    exists_id = schema.system.vertex_exists
    exists_col = rids.type_prefix(exists_id, idm, RelationCategory.PROPERTY,
                                  Direction.OUT)
    vp = DataOutput()
    graph.serializer.write_value(vp, True)
    exists_vals, ev_offs = encode_backward_uvars(vp.getvalue(), exists_relids)

    edge_prefix = rids.type_prefix(label_id, idm, RelationCategory.EDGE,
                                   Direction.OUT)
    # group edges by source (stable): per-vertex contiguous segments
    order = np.argsort(src, kind="stable")
    src_s = src[order]
    other_vids = vids[dst[order]]
    relids_s = edge_relids[order]
    indptr = np.zeros(n + 1, np.int64)
    np.add.at(indptr, src_s + 1, 1)
    np.cumsum(indptr, out=indptr)
    cols_buf, col_offs = encode_out_edge_columns(edge_prefix, other_vids,
                                                 relids_s)
    cols_bytes = cols_buf.tobytes()
    ev_bytes = exists_vals.tobytes()
    encode_s = time.time() - t0

    # --- mutate through the SPI ------------------------------------------
    t1 = time.time()
    from titan_tpu.storage.api import Entry
    store = graph.backend.edge_store.store
    txh = graph.backend.manager.begin_transaction()
    empty_val = b"\x80"          # uvar(0): zero non-sort-key properties
    packed = getattr(graph.backend.manager.features, "packed_ops", False)
    P = len(edge_prefix)
    if packed:
        starts = col_offs[:-1]
        lens = np.diff(col_offs)
        K = int(lens.max() - P) if m else 0
    if packed and K <= 16:
        # the packed path slots the exists column before/after ALL edge
        # columns by one byte-compare — only sound while category codes
        # are prefix-free AND differ in their first byte (a codec change
        # that shares the leading byte would interleave edge columns
        # around the exists column, and mutate_row_packed adopts rows
        # verbatim, silently breaking sliced reads — ADVICE r5 #4)
        if exists_col[:1] == edge_prefix[:1]:
            raise AssertionError(
                "packed bulk path: vertex-exists and edge category "
                "prefixes share their first byte "
                f"({exists_col[:1]!r}) — within-row byte order is no "
                "longer decided by the category slot; fix the codec "
                "prefixes or disable features.packed_ops")
        # packed bulk path: rows are adopted whole, so columns must
        # arrive byte-sorted. All edge columns share the category
        # prefix, so the within-row order is decided by the <=16
        # post-prefix bytes — two big-endian u64 sort keys accumulated
        # byte-at-a-time with 1-D gathers (a padded [m, K] byte matrix
        # would transiently cost ~11GB of host RAM at the bench's
        # scale-22 target), then one stable lexsort groups by row and
        # orders within it. The exists column's category prefix
        # differs in its FIRST byte (prefixed-varint encodings are
        # prefix-free per category), so its slot is UNIFORM per row.
        key_hi = np.zeros(m, np.uint64)
        key_lo = np.zeros(m, np.uint64)
        base = starts + P
        limit = max(len(cols_buf) - 1, 0)
        for j in range(K):
            b = cols_buf[np.minimum(base + j, limit)].astype(np.uint64)
            b = np.where(P + j < lens, b, 0)
            if j < 8:
                key_hi = (key_hi << np.uint64(8)) | b
            else:
                key_lo = (key_lo << np.uint64(8)) | b
        order2 = np.lexsort((key_lo, key_hi, src_s))
        sstart_a = starts[order2]
        slen_a = lens[order2]
        del key_hi, key_lo, order2
        exists_first = exists_col < edge_prefix
        ev_o = ev_offs.tolist()
        ip = indptr.tolist()
        mrp = store.mutate_row_packed
        for i in range(n):
            ex_val = ev_bytes[ev_o[i]:ev_o[i + 1]]
            e0, e1 = ip[i], ip[i + 1]
            # per-row tolist keeps peak memory at row scale (a global
            # 67M-int tolist holds ~2.5GB of boxed ints per array)
            ecols = [cols_bytes[s:s + l] for s, l in
                     zip(sstart_a[e0:e1].tolist(),
                         slen_a[e0:e1].tolist())]
            evals = [empty_val] * (e1 - e0)
            if exists_first:
                cols_l = [exists_col] + ecols
                vals_l = [ex_val] + evals
            else:
                cols_l = ecols + [exists_col]
                vals_l = evals + [ex_val]
            mrp(key_bytes[8 * i:8 * i + 8], cols_l, vals_l, txh)
    else:
        for i in range(n):
            adds = [Entry(exists_col,
                          ev_bytes[ev_offs[i]:ev_offs[i + 1]])]
            e0, e1 = indptr[i], indptr[i + 1]
            if e1 > e0:
                o = col_offs[e0:e1 + 1]
                adds.extend(Entry(cols_bytes[o[j]:o[j + 1]], empty_val)
                            for j in range(e1 - e0))
            store.mutate(key_bytes[8 * i:8 * i + 8], adds, [], txh)
    txh.commit()
    mutate_s = time.time() - t1
    return {"vertex_ids": vids, "n": n, "m": m,
            "encode_s": encode_s, "mutate_s": mutate_s,
            "ingest_s": time.time() - t0}


def ingest_rmat_store(scale: int, edge_factor: int = 16, seed: int = 2,
                      backend: str = "inmemory",
                      directory: Optional[str] = None) -> dict:
    """Bench-stage helper: generate an R-MAT edge list, bulk-load it into a
    fresh graph's edgestore, scan it back into a symmetrized snapshot.
    Returns {"graph", "snapshot", "n", "m", "ingest_s", "scan_s"}."""
    import titan_tpu
    from titan_tpu.olap.tpu import snapshot as snap_mod
    from titan_tpu.olap.tpu.rmat import rmat_edges
    from titan_tpu import native

    n = 1 << scale
    m = n * edge_factor
    if native.available:
        src, dst = native.rmat_gen(m, scale, seed=seed)
    else:
        src, dst = rmat_edges(scale, edge_factor, seed=seed)

    conf = {"storage.backend": backend}
    if directory:
        conf["storage.directory"] = directory
    g = titan_tpu.open(conf)
    res = bulk_load_adjacency(g, src, dst, n=n)
    del src, dst
    t0 = time.time()
    # directed=False symmetrizes the scanned OUT rows — BFS distances then
    # match the generated-graph chunked CSR exactly (duplicate edges and
    # self-loops don't move BFS levels)
    snap = snap_mod.build(g, directed=False)
    scan_s = time.time() - t0
    return {"graph": g, "snapshot": snap, "n": res["n"], "m": res["m"],
            "ingest_s": res["ingest_s"], "scan_s": scan_s}


def dist_match(dist_a, dist_b, inf: int) -> bool:
    """Device-side BFS-distance equality (a D2H of a scale-22 dist array
    costs seconds through the axon tunnel; a scalar readback does not).
    Unreached stays unreached: values >= inf compare as inf."""
    import jax.numpy as jnp

    a = jnp.minimum(dist_a, inf)
    b = jnp.minimum(dist_b, inf)
    if a.shape != b.shape:
        return False
    return bool(int(np.asarray((a != b).sum())) == 0)
