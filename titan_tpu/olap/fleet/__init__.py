"""Replica fleet serving tier (docs/fleet.md, ISSUE 19).

One :class:`~titan_tpu.olap.fleet.router.FleetRouter` process owns the
public job plane and dispatches to N replica processes (each a full
GraphServer + JobScheduler over the same store, ``python -m
titan_tpu.olap.fleet.replica``). Membership is health-checked through
the Federator; routing is a quota/SLO-aware weighted pick over
in-flight depth, HBM headroom and epoch freshness; failover
re-dispatches a dead replica's jobs under an unchanged idempotency key
so the survivor resumes from the shared checkpoint store.
"""

from titan_tpu.olap.fleet.membership import FleetMembership
from titan_tpu.olap.fleet.router import ROUTE_SIGNALS, FleetRouter

__all__ = ["FleetMembership", "FleetRouter", "ROUTE_SIGNALS"]
