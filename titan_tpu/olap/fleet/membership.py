"""Fleet membership: health-checked replicas + routing signals.

The membership plane is the Federator (obs/federate) pointed at replica
``GraphServer`` processes instead of scan workers: each replica already
serves ``GET /metrics`` (Prometheus text) and ``GET /healthz``, so
health checking, consecutive-failure eviction and un-evict on recovery
come for free — this module adds the ROUTING read on top. From each
scrape round it extracts the per-replica signals the router's weighted
pick consumes (docs/fleet.md "Routing policy"):

* **in-flight depth** — ``serving_queue_depth`` from the replica's own
  exposition (the router adds its own live dispatch ledger on top,
  because scraped depth is one round stale);
* **HBM headroom** — ``serving_hbm_resident_bytes`` (a loaded replica
  with resident graph images is cheaper to route TO for the same
  snapshot, but an HBM-saturated one should shed);
* **epoch freshness lag** — the replica's ``GET /live`` freshness block
  (``lag_epochs``), best-effort: a replica without a live plane reads
  as lag 0.

Signal extraction parses the SAME scraped exposition text the federated
``/metrics`` view re-exports (``obs.federate._parse_families``), so
routing and observability can never disagree about what a replica
reported.
"""

from __future__ import annotations

import json
from typing import Optional

from titan_tpu.obs.federate import Federator, _parse_families
from titan_tpu.utils.httpnode import text_get
from titan_tpu.utils.metrics import MetricManager

#: exposition sample names the router reads (sanitized Prometheus
#: names — promexport maps metric-name dots to underscores)
_DEPTH_SAMPLE = "serving_queue_depth"
_HBM_SAMPLE = "serving_hbm_resident_bytes"


def _unlabeled_value(fams: dict, name: str) -> Optional[float]:
    """The unlabeled parent sample of ``name`` from a parsed
    exposition, or None when the replica never registered it."""
    fam = fams.get(name)
    if not fam:
        return None
    for line in fam["samples"]:
        head, _, rest = line.partition(" ")
        if head == name:        # the unlabeled parent, not a child
            try:
                return float(rest.split()[0])
            except (ValueError, IndexError):
                return None
    return None


class FleetMembership:
    """Replica set + routing-signal reads for the FleetRouter.

    ``fetch(url, path) -> text`` is injectable (tests); the default is
    ``utils.httpnode.text_get`` carrying the mesh bearer token, exactly
    like the Federator's."""

    def __init__(self, metrics: Optional[MetricManager] = None,
                 clock=None, fetch=None, *, timeout: float = 5.0,
                 max_failures: int = 3, token: Optional[str] = None):
        self._metrics = metrics or MetricManager.instance()
        self._fetch = fetch or (lambda url, path: text_get(
            url, path, timeout=timeout, token=token))
        self.federator = Federator(
            metrics=self._metrics, clock=clock, fetch=self._fetch,
            timeout=timeout, max_failures=max_failures, token=token)
        # per-scrape-round lag memo: the routing pick runs per submit,
        # and freshness moves per scrape, not per job — one /live fetch
        # per replica per round, not per routing decision
        self._lag_cache: dict = {}

    # -- membership ----------------------------------------------------------

    def add_replica(self, url: str,
                    instance: Optional[str] = None) -> str:
        return self.federator.add_peer(url, instance=instance)

    def remove_replica(self, instance: str) -> bool:
        return self.federator.remove_peer(instance)

    def scrape(self) -> dict:
        """One health/metrics round over every replica (failure
        counting + eviction + un-evict live in the Federator)."""
        self._lag_cache.clear()
        return self.federator.scrape()

    def fleet(self) -> dict:
        """The ``GET /fleet`` roll-up (per-replica up/evicted/failure
        state), straight from the Federator."""
        return self.federator.fleet()

    # -- routing signals -----------------------------------------------------

    def signals(self) -> dict:
        """``{instance: {"up", "url", "queue_depth",
        "hbm_resident_bytes", "lag_epochs"}}`` from the LAST scrape
        round — call :meth:`scrape` first. Signal reads are
        best-effort: a replica that answered its scrape but exposes
        none of the serving families routes on depth 0 (new replicas
        must be routable before their first job)."""
        out: dict = {}
        for peer in self.federator.peers():
            up = (not peer.evicted and peer.failures == 0
                  and peer.last_ok is not None)
            row = {"up": up, "url": peer.url, "queue_depth": 0.0,
                   "hbm_resident_bytes": 0.0, "lag_epochs": 0.0}
            if peer.text:
                fams = _parse_families(peer.text)
                d = _unlabeled_value(fams, _DEPTH_SAMPLE)
                if d is not None:
                    row["queue_depth"] = max(0.0, d)
                h = _unlabeled_value(fams, _HBM_SAMPLE)
                if h is not None:
                    row["hbm_resident_bytes"] = max(0.0, h)
            if up:
                lag = self._lag_cache.get(peer.instance)
                if lag is None:
                    lag = self._lag_epochs(peer.url)
                    self._lag_cache[peer.instance] = lag
                row["lag_epochs"] = lag
            out[peer.instance] = row
        return out

    def _lag_epochs(self, url: str) -> float:
        """Epoch freshness lag from the replica's ``GET /live``; 0 for
        replicas without a live plane (or mid-death — the health plane
        owns liveness, this read must never evict anyone)."""
        try:
            live = json.loads(self._fetch(url, "/live"))
        except Exception:   # noqa: BLE001 — best-effort signal
            return 0.0
        if not isinstance(live, dict) or not live.get("enabled"):
            return 0.0
        fresh = live.get("freshness") or {}
        try:
            return max(0.0, float(fresh.get("lag_epochs", 0)))
        except (TypeError, ValueError):
            return 0.0
