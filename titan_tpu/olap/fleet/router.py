"""FleetRouter: the front-door job plane over N replica processes.

ROADMAP #2's missing tier (reference: the titan-dist deployment — a
load balancer in front of N gremlin-server processes over shared
storage): one router process owns the public ``/jobs`` / ``/traverse``
/ ``/metrics?federate=1`` / ``/fleet`` surface and dispatches to
replica ``GraphServer`` processes, each a full ``JobScheduler`` over
the same store. docs/fleet.md documents the topology; the pieces:

* **membership** (:class:`~titan_tpu.olap.fleet.membership.
  FleetMembership`) — Federator-backed health checks with
  consecutive-failure eviction + un-evict on recovery, plus the
  routing signals scraped from each replica's own exposition;
* **routing** — quota/SLO-aware weighted pick: per-replica in-flight
  depth (router ledger + scraped ``serving.queue.depth``), HBM
  headroom (``serving.hbm.resident_bytes``), epoch freshness lag, each
  normalized across the live set and weighted by the autotune fleet
  knob (``fleet.routing_weight.*``, journaled through the existing
  Controller rules — ``GET /controller`` explains every weight move);
* **failover** — every admitted job carries an **idempotency key**
  (the router's logical job id). A dead replica's in-flight jobs are
  re-dispatched to a survivor under the SAME key, so the survivor's
  scheduler adopts the newest checkpoint from the shared store
  (olap/recovery) and RESUMES rather than restarts — bit-equal to an
  uninterrupted run. ``serving.jobs.submitted`` is counted ONCE at
  router admission; a re-dispatch counts ``serving.fleet.redispatches``
  instead (the double-count regression, tests/test_fleet.py);
* **trace splice** — the router opens one trace per logical job
  (``GET /trace?job=<id>``) with a ``dispatch`` span per attempt; each
  pump round progressively drains every in-flight replica's
  ``GET /trace/export`` and splices the spans under the attempt's
  dispatch span with NTP-style skew normalization (``Tracer.ingest``,
  the scan_worker idiom) — after a SIGKILL the stitched tree shows the
  dead replica's partial spans BESIDE the redispatch span.

Metrics: ``serving.fleet.*`` (docs/monitoring.md) on the router's own
registry; ``?federate=1`` merges every replica's registry under
``instance`` labels for one fleet-wide scrape target.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
import uuid
from typing import Optional, Sequence
from urllib.parse import parse_qs

from titan_tpu.errors import TemporaryBackendError
from titan_tpu.obs.tracing import Tracer
from titan_tpu.olap.fleet.membership import FleetMembership
from titan_tpu.utils.httpnode import (JsonNode, TextResponse, json_call,
                                      text_get)
from titan_tpu.utils.metrics import MetricManager

#: routing signal names; each has an implicit weight of 1.0 unless the
#: controller's fleet knob (fleet.routing_weight.<signal>) moved it
ROUTE_SIGNALS = ("depth", "hbm", "lag")

_TERMINAL = ("done", "failed", "timeout", "cancelled", "expired")


class _FleetJob:
    """Router-side record of one LOGICAL job. ``id`` doubles as the
    idempotency key and the router-side trace id; ``remote_id`` is the
    replica scheduler's own job id for the current attempt."""

    __slots__ = ("id", "body", "kind", "tenant", "instance", "url",
                 "remote_id", "attempts", "state", "wire", "t_submit",
                 "t_dead", "root", "dispatch")

    def __init__(self, jid: str, body: dict, now: float):
        self.id = jid
        self.body = dict(body)
        self.kind = str(body.get("kind", "bfs"))
        self.tenant = str(body.get("tenant") or "default")
        self.instance: Optional[str] = None
        self.url: Optional[str] = None
        self.remote_id: Optional[str] = None
        self.attempts = 0
        self.state = "queued"
        self.wire: dict = {}
        self.t_submit = now
        self.t_dead: Optional[float] = None
        self.root = None
        self.dispatch = None

    def to_wire(self) -> dict:
        out = {"job": self.id, "kind": self.kind, "tenant": self.tenant,
               "state": self.state, "replica": self.instance,
               "remote_job": self.remote_id, "attempts": self.attempts}
        if self.wire:
            out["remote"] = self.wire
        return out


class FleetRouter(JsonNode):
    """See module doc. ``replicas``: ["host:port" | url, ...]; more can
    join later via :meth:`add_replica`. ``autotune`` follows the
    scheduler's modes ("off" | "shadow" | "enforce") for the fleet
    routing-weight knob; ``autopump=False`` (tests, bench) disables the
    background maintenance thread — call :meth:`pump` directly."""

    def __init__(self, replicas: Sequence[str] = (), *,
                 host: str = "127.0.0.1", port: int = 0,
                 metrics: Optional[MetricManager] = None,
                 tracer: Optional[Tracer] = None, clock=None,
                 fetch=None, token: Optional[str] = None,
                 auth_token: Optional[str] = None,
                 autotune: Optional[str] = "shadow",
                 autotune_tick_s: Optional[float] = None,
                 max_failures: int = 3, call_timeout_s: float = 30.0,
                 pump_interval_s: float = 0.25, autopump: bool = True):
        super().__init__(self._route, host, port, name="fleet-router",
                         auth_token=auth_token)
        self._metrics = metrics or MetricManager.instance()
        self.tracer = tracer or Tracer(clock=clock)
        self._clock = clock or time.time
        self._token = token
        self.call_timeout_s = float(call_timeout_s)
        self.membership = FleetMembership(
            metrics=self._metrics, clock=clock, fetch=fetch,
            timeout=call_timeout_s, max_failures=max_failures,
            token=token)
        self._ids = itertools.count(1)
        self._jobs: dict[str, _FleetJob] = {}
        self._inflight: dict[str, int] = {}
        self._lock = threading.RLock()
        self._pump_interval_s = float(pump_interval_s)
        self._pump_stop = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None
        self._autopump = bool(autopump)
        # no-network read: the Federator's stored peer state, NOT a
        # fresh signal round — gauges run on every scrape
        self._up_fn = lambda: float(self.membership.fleet()["up"])
        self._up_gauge = self._metrics.gauge(
            "serving.fleet.replicas_up", fn=self._up_fn)
        from titan_tpu.olap.serving.autotune import (Controller,
                                                     resolve_mode)
        mode = resolve_mode(autotune)
        self.controller = None
        if mode != "off":
            self.controller = Controller(
                mode=mode, metrics=self._metrics, tracer=self.tracer,
                clock=clock, tick_s=autotune_tick_s,
                signals=self._fleet_signals)
        for r in replicas:
            self.add_replica(r)

    # -- membership ----------------------------------------------------------

    def add_replica(self, url: str,
                    instance: Optional[str] = None) -> str:
        return self.membership.add_replica(url, instance=instance)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetRouter":
        super().start()
        self.membership.scrape()       # first routing round up front
        if self._autopump:
            self._pump_thread = threading.Thread(
                target=self._pump_loop, daemon=True,
                name="fleet-router-pump")
            self._pump_thread.start()
        return self

    def stop(self) -> None:
        self._pump_stop.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=5.0)
            self._pump_thread = None
        if self.controller is not None:
            self.controller.detach_gauges()
        # identity-checked detach (the controller's idiom): a stopped
        # router must not keep reading dead membership on every scrape
        if self._up_gauge.fn is self._up_fn:
            self._up_gauge.fn = None
        super().stop()

    def _pump_loop(self) -> None:
        while not self._pump_stop.wait(self._pump_interval_s):
            try:
                self.pump()
            except Exception:   # noqa: BLE001 — the pump must survive
                pass

    # -- HTTP dispatch -------------------------------------------------------

    def _route(self, path: str, req: dict):
        route, _, query = path.partition("?")
        q = parse_qs(query)
        if route == "/jobs":
            # POST with a body submits; a GET (empty request dict)
            # lists the router's logical job table
            if req:
                return self._submit(req)
            with self._lock:
                jobs = [rec.to_wire() for rec in self._jobs.values()]
            return {"jobs": jobs, "inflight": dict(self._inflight)}
        if route.startswith("/jobs/"):
            return self._job_status(route[len("/jobs/"):])
        if route == "/traverse":
            return self._traverse(req)
        if route == "/metrics":
            from titan_tpu.obs.promexport import (CONTENT_TYPE,
                                                  render_prometheus)
            body = render_prometheus(self._metrics)
            if (q.get("federate") or ["0"])[0] not in ("0", "",
                                                       "false"):
                # scrape-then-render: one coherent round, fleet-wide
                self.membership.scrape()
                body = self.membership.federator.render(body)
            return TextResponse(body, CONTENT_TYPE)
        if route == "/fleet":
            return self._fleet_view()
        if route == "/trace":
            tid = (q.get("job") or [None])[0]
            if tid is None:
                raise ValueError("trace needs ?job=<id>")
            tree = self.tracer.tree(tid)
            if tree is None:
                raise ValueError(f"unknown trace {tid!r}")
            return tree
        if route == "/controller":
            if self.controller is None:
                return {"enabled": False}
            return {"enabled": True, **self.controller.state()}
        if route == "/healthz":
            up = int(self.membership.fleet()["up"])
            return {"live": True, "ready": up > 0,
                    "role": "fleet-router", "replicas_up": up}
        if route == "/pump":
            return self.pump()
        raise ValueError(f"unknown path {path!r}")

    # -- admission + routing -------------------------------------------------

    def _submit(self, body: dict) -> dict:
        """Admit one logical job: route, dispatch, count. The submitted
        counter increments HERE exactly once per logical job — the
        replica's own registry also counts its local submit, but under
        ``?federate=1`` those re-export under ``instance`` labels and
        never fold into the router's series."""
        now = self._clock()
        jid = f"f{next(self._ids):04d}-{uuid.uuid4().hex[:6]}"
        rec = _FleetJob(jid, body, now)
        rec.root = self.tracer.start(jid, "job", kind=rec.kind,
                                     tenant=rec.tenant)
        if not self._dispatch_job(rec):
            self.tracer.end(rec.root, error="no replica accepted")
            self.tracer.discard(jid)
            raise TemporaryBackendError(
                "no replica accepted the job (fleet down?)")
        self._metrics.counter(
            "serving.jobs.submitted",
            labels={"kind": rec.kind, "tenant": rec.tenant}).inc()
        with self._lock:
            self._jobs[jid] = rec
        return rec.to_wire()

    def _dispatch_job(self, rec: _FleetJob,
                      exclude: Optional[set] = None) -> bool:
        """One dispatch walk over the live set (weighted-pick order):
        POST the job body + the logical idempotency key to a replica,
        falling through to the next pick when one refuses. Returns
        False when no replica accepted."""
        tried = set(exclude or ())
        while True:
            pick = self._pick(exclude=tried)
            if pick is None:
                return False
            inst, url = pick
            tried.add(inst)
            span = self.tracer.start(rec.id, "dispatch",
                                     parent=rec.root, instance=inst,
                                     attempt=rec.attempts + 1)
            payload = dict(rec.body)
            payload["idempotency_key"] = rec.id
            try:
                wire = json_call(url, "/jobs", payload,
                                 timeout=self.call_timeout_s,
                                 token=self._token)
            except Exception as e:   # noqa: BLE001 — replica boundary
                self.tracer.end(span,
                                error=f"{type(e).__name__}: {e}")
                continue
            rec.instance, rec.url = inst, url
            rec.remote_id = wire.get("job")
            rec.attempts += 1
            rec.state = wire.get("status", "queued")
            rec.wire = wire
            rec.dispatch = span
            with self._lock:
                self._inflight[inst] = self._inflight.get(inst, 0) + 1
            self._metrics.counter("serving.fleet.routed",
                                  labels={"instance": inst}).inc()
            return True

    def _weights(self) -> dict:
        w = {s: 1.0 for s in ROUTE_SIGNALS}
        if self.controller is not None:
            w.update(self.controller.routing_weights())
        return w

    def _pick(self, exclude=()) -> Optional[tuple]:
        """The weighted pick: min weighted sum of normalized signals
        over the live set (lower = roomier), deterministic tie-break by
        instance name."""
        sig = self.membership.signals()
        with self._lock:
            rows = []
            for inst, s in sig.items():
                if not s["up"] or inst in exclude:
                    continue
                depth = (self._inflight.get(inst, 0)
                         + float(s["queue_depth"]))
                rows.append((inst, s["url"], depth,
                             float(s["hbm_resident_bytes"]),
                             float(s["lag_epochs"])))
        if not rows:
            return None
        w = self._weights()
        maxes = [max(1.0, max(r[i] for r in rows)) for i in (2, 3, 4)]
        best = None
        for inst, url, depth, hbm, lag in sorted(rows):
            score = (w["depth"] * depth / maxes[0]
                     + w["hbm"] * hbm / maxes[1]
                     + w["lag"] * lag / maxes[2])
            if best is None or score < best[0]:
                best = (score, inst, url)
        return best[1], best[2]

    # -- interactive proxy ---------------------------------------------------

    def _traverse(self, body: dict) -> dict:
        """Route one interactive point query. Traversals are read-only
        and carry no idempotency state, so a refused/failed replica
        simply falls through to the next pick."""
        tried: set = set()
        last: Optional[BaseException] = None
        while True:
            pick = self._pick(exclude=tried)
            if pick is None:
                if last is not None:
                    raise last
                raise TemporaryBackendError("no replica up")
            inst, url = pick
            tried.add(inst)
            try:
                out = json_call(url, "/traverse", dict(body),
                                timeout=self.call_timeout_s,
                                token=self._token)
            except TemporaryBackendError as e:
                last = e
                continue
            self._metrics.counter("serving.fleet.routed",
                                  labels={"instance": inst}).inc()
            out["replica"] = inst
            return out

    # -- status + pump -------------------------------------------------------

    def _get_json(self, url: str, path: str) -> dict:
        return json.loads(text_get(url, path,
                                   timeout=self.call_timeout_s,
                                   token=self._token))

    def _job_status(self, jid: str) -> dict:
        with self._lock:
            rec = self._jobs.get(jid)
        if rec is None:
            raise ValueError(f"unknown job {jid!r}")
        if rec.state not in _TERMINAL and rec.url is not None:
            try:
                rec.wire = self._get_json(rec.url,
                                          f"/jobs/{rec.remote_id}")
                rec.state = rec.wire.get("status", rec.state)
            except Exception:   # noqa: BLE001 — pump owns failover
                pass
        return rec.to_wire()

    def _fleet_view(self) -> dict:
        fl = self.membership.fleet()
        with self._lock:
            inflight = dict(self._inflight)
            total = len(self._jobs)
        return {"enabled": True, **fl,
                "routing": {
                    "weights": self._weights(),
                    "inflight": inflight,
                    "decisions": int(self._metrics.counter_value(
                        "serving.fleet.routed"))},
                "jobs": {"total": total,
                         "redispatches": int(
                             self._metrics.counter_value(
                                 "serving.fleet.redispatches"))}}

    def _fleet_signals(self) -> dict:
        """The router controller's signal source: only the ``fleet``
        block (no scheduler registries behind this controller), so of
        the rule table exactly ``_rule_fleet`` can ever fire."""
        sig: dict = {"t": self._clock()}
        depths: dict = {}
        up: list = []
        for inst, s in self.membership.signals().items():
            with self._lock:
                d = self._inflight.get(inst, 0)
            depths[inst] = d
            if s["up"]:
                up.append(d)
        fleet: dict = {"depths": depths, "replicas_up": len(up)}
        if len(up) >= 2:
            mean = sum(up) / len(up)
            fleet["depth_spread"] = (
                round((max(up) - min(up)) / mean, 4) if mean > 0
                else 0.0)
        sig["fleet"] = fleet
        return sig

    def pump(self) -> dict:
        """One maintenance round: scrape membership (evict/un-evict),
        tick the fleet controller, poll every in-flight job, drain its
        replica-side spans into the stitched trace, and fail over jobs
        whose replica is down. Runs on the background pump thread (or
        directly from tests/bench via ``POST /pump``)."""
        out = {"polled": 0, "completed": 0, "redispatched": 0,
               "orphaned": 0}
        self.membership.scrape()
        if self.controller is not None:
            try:
                self.controller.maybe_tick()
            except Exception:   # noqa: BLE001 — advisory plane
                pass
        rows = {r["instance"]: r
                for r in self.membership.fleet()["peers"]}
        with self._lock:
            live = [rec for rec in self._jobs.values()
                    if rec.state not in _TERMINAL]
        for rec in live:
            if rec.dispatch is None:
                # orphaned on an earlier round (no survivor then) —
                # keep trying until a replica comes back
                if self._failover(rec, why="orphaned: no survivor"):
                    out["redispatched"] += 1
                else:
                    out["orphaned"] += 1
                continue
            try:
                wire = self._get_json(rec.url,
                                      f"/jobs/{rec.remote_id}")
            except Exception as e:   # noqa: BLE001 — replica boundary
                row = rows.get(rec.instance)
                if row is None or not row.get("up"):
                    # the health plane agrees the replica is down —
                    # this is a death, not a blip
                    if self._failover(
                            rec, why=f"{type(e).__name__}: {e}"):
                        out["redispatched"] += 1
                    else:
                        out["orphaned"] += 1
                continue
            out["polled"] += 1
            self._drain_trace(rec)
            rec.wire = wire
            rec.state = wire.get("status", rec.state)
            if rec.state in _TERMINAL:
                out["completed"] += 1
                with self._lock:
                    self._inflight[rec.instance] = max(
                        0, self._inflight.get(rec.instance, 0) - 1)
                self.tracer.end(rec.dispatch, state=rec.state)
                self.tracer.end(rec.root, state=rec.state)
        return out

    def _failover(self, rec: _FleetJob, why: str) -> bool:
        """Re-dispatch one in-flight job off a dead replica under its
        UNCHANGED idempotency key: the survivor's scheduler finds the
        dead replica's checkpoints in the shared store (keyed
        ``idem-<key>``) and resumes. Counts
        ``serving.fleet.redispatches`` — NEVER a second
        ``serving.jobs.submitted``."""
        dead = rec.instance
        if rec.dispatch is not None:
            self.tracer.end(rec.dispatch, error=why,
                            redispatched=True)
            rec.dispatch = None
            rec.t_dead = self._clock()
            with self._lock:
                self._inflight[dead] = max(
                    0, self._inflight.get(dead, 0) - 1)
        if not self._dispatch_job(rec, exclude={dead}):
            return False
        self._metrics.counter("serving.fleet.redispatches").inc()
        if rec.t_dead is not None:
            self._metrics.histogram(
                "serving.fleet.redispatch_latency_ms").update(
                (self._clock() - rec.t_dead) * 1e3)
        return True

    def _drain_trace(self, rec: _FleetJob) -> None:
        """Progressively pop the replica's completed spans for this
        attempt and splice them under the dispatch span (scan_worker's
        NTP-midpoint skew + clamp-window idiom). Progressive draining
        is what makes a dead replica's PARTIAL spans visible: whatever
        rode earlier pump rounds is already in the stitched tree when
        the replica dies."""
        if not self.tracer.enabled or rec.dispatch is None:
            return
        t0 = self._clock()
        try:
            res = self._get_json(
                rec.url, f"/trace/export?job={rec.remote_id}")
        except Exception:   # noqa: BLE001 — next round retries
            return
        t1 = self._clock()
        spans = res.get("spans") or []
        dropped = int(res.get("dropped") or 0)
        if not spans and not dropped:
            return
        try:
            offset = ((t0 + t1) - (float(res["t_recv"])
                                   + float(res["t_send"]))) / 2.0
        except (KeyError, TypeError, ValueError):
            offset = 0.0
        self.tracer.ingest(
            rec.id, spans, parent_id=rec.dispatch.span_id,
            offset=offset, window=(t0, t1), instance=rec.instance,
            extra_dropped=dropped, metrics=self._metrics)
