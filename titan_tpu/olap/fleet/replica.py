"""Replica serving process: one full GraphServer + JobScheduler.

The fleet's unit of capacity (docs/fleet.md): ``python -m
titan_tpu.olap.fleet.replica '<json config>'`` opens the SHARED graph
storage, builds a :class:`~titan_tpu.olap.serving.scheduler.
JobScheduler` over it and serves the whole GraphServer surface —
``/jobs``, ``/traverse``, ``/metrics``, ``/healthz``, ``/live``,
``/trace/export`` — on its own port. The router never speaks anything a
plain replica doesn't already serve, so a replica is independently
debuggable with curl.

Config keys (JSON object on argv[1], or ``-`` to read stdin):

``graph``
    the ``titan_tpu.open`` config dict — MUST point at the same
    storage backend on every replica (shared store = shared epochs =
    adoptable checkpoints);
``checkpoint_dir``
    SHARED checkpoint directory. Failover depends on it: a redispatched
    job's idempotency key resolves to the same ``idem-<key>`` record
    from any replica, so the survivor resumes from the dead replica's
    newest checkpoint instead of restarting (olap/recovery);
``host`` / ``port``
    bind address (default 127.0.0.1:0 — the banner prints the real
    port); ``instance`` names the replica in federated metrics;
``auth_token``
    optional bearer token (else TITAN_TPU_NODE_TOKEN applies);
``scheduler``
    optional kwargs forwarded to the JobScheduler ctor (quotas,
    autotune mode, checkpoint cadence...).
"""

from __future__ import annotations

import json
import sys
import threading
from typing import Optional


def build(config: dict):
    """Build (graph, scheduler, server) from one replica config —
    importable seam so tests and bench can run an in-process replica
    from the exact config the process entry uses."""
    import titan_tpu
    from titan_tpu.olap.serving.scheduler import JobScheduler
    from titan_tpu.server import GraphServer

    graph = titan_tpu.open(dict(config["graph"]))
    sched_kw = dict(config.get("scheduler") or {})
    if config.get("checkpoint_dir"):
        sched_kw.setdefault("checkpoint_dir", config["checkpoint_dir"])
    scheduler = JobScheduler(graph=graph, **sched_kw)
    server = GraphServer(
        graph, host=config.get("host", "127.0.0.1"),
        port=int(config.get("port", 0)),
        auth_token=config.get("auth_token"),
        scheduler=scheduler)
    return graph, scheduler, server


def main(argv: Optional[list] = None) -> None:
    args = list(sys.argv[1:] if argv is None else argv)
    if not args:
        print("usage: python -m titan_tpu.olap.fleet.replica "
              "'<json config>' (or - for stdin)", file=sys.stderr)
        raise SystemExit(2)
    raw = sys.stdin.read() if args[0] == "-" else args[0]
    config = json.loads(raw)
    graph, scheduler, server = build(config)
    server.start()
    host = config.get("host", "127.0.0.1")
    if host not in ("127.0.0.1", "localhost") \
            and server.auth_token is None:
        print("WARNING: replica bound to a non-local interface with no "
              "auth token set — any peer can submit jobs",
              file=sys.stderr)
    # the exact banner the fleet smoke + router tooling parse for the
    # bound port (mirrors scan_worker's)
    print(f"replica serving on http://{server.host}:{server.port}",
          flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        scheduler.close()
        server.stop()


if __name__ == "__main__":
    main()
