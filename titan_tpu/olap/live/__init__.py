"""Live graph plane: serve OLAP traffic while the graph is being written.

The OLTP→OLAP freshness pipeline (ISSUE r9 tentpole; reference seam:
titan-core's trigger-log/LogProcessor machinery, docs/TitanBus.md §3 —
rebuilt TPU-native so freshness costs neither a snapshot rebuild nor an
HBM re-upload):

* ``feed.ChangeFeed`` — tails the durable user trigger log with a
  resumable named read marker; payloads become columnar
  ``DeltaBatch``es (cross-instance writers reach the OLAP plane here);
* ``overlay.DeltaOverlay`` — device-resident padded COO add-buffer +
  tombstone bitmap over base-CSR edge slots, pow-2 capacity buckets
  (no recompile on append), HBM-ledger accounted; the frontier kernels
  consume immutable ``OverlayView``s through their overlay-aware
  expansion seams (models/frontier.py, models/bfs_hybrid.py);
* ``compactor.EpochCompactor`` — folds overlay into base when fill or
  tombstone budget trips, republishing a new epoch to the serving pool;
* ``plane.LiveGraphPlane`` — orchestration: dual-lane ingest (in-process
  listener + durable feed), epoch/lease consistency, ``serving.live.*``
  metrics surfaced by ``GET /live``.

See docs/live.md for the architecture and the freshness/epoch contract.
"""

from titan_tpu.olap.live.compactor import EpochCompactor
from titan_tpu.olap.live.feed import ChangeFeed, DeltaBatch
from titan_tpu.olap.live.overlay import DeltaOverlay, OverlayView
from titan_tpu.olap.live.plane import LiveGraphPlane

__all__ = ["ChangeFeed", "DeltaBatch", "DeltaOverlay", "OverlayView",
           "EpochCompactor", "LiveGraphPlane"]
