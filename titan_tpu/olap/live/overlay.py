"""DeltaOverlay: device-resident COO add-buffer + base-edge tombstones.

The freshness half of the live plane's cost model: applying a delta to
the HOST snapshot (``GraphSnapshot.apply_changes``) invalidates every
device-layout cache and forces the next run to re-upload the full
chunked CSR (11.6 GB at bfs_heavy scale) through the H2D tunnel.  The
overlay instead keeps the base CSR device arrays UNTOUCHED and layers
the delta next to them:

* **adds** — a padded COO buffer ``(src, dst)`` of dense indices (pad =
  ``n+1``, the kernels' scatter-drop sentinel), sized in power-of-two
  capacity buckets so appends never change the compiled kernel shapes
  (no recompile on append — the same discipline as the frontier list
  caps);
* **tombstones** — a bitmap over base edge SLOTS in the chunked-CSR
  layout (slot = column*8 + lane, exactly the id ``frontier.py`` hashes
  for SSSP weights): masked slots stop counting as parents/targets in
  the overlay-aware kernels. The bitmap is updated by scattering only
  the touched bytes, so a removal costs O(changed bytes) H2D, not a
  re-upload.

Delta-page uploads (ISSUE 9): ``view()`` ships only the CHANGED device
bytes — the appended row range (plus any in-place-killed rows) is
scattered into the resident add buffers, and only the dirtied tombstone
bytes hit the bitmap. Buffer establishment and capacity growth are
device-side pad fills (``jnp.full`` / pad-extension), so they cost no
H2D at all. Every byte that does cross the tunnel — scatter payloads
AND the int32 index words the scatters ship — is counted on
``serving.live.upload_bytes`` when a ``metrics`` manager is attached,
so the H2D cost of freshness is directly observable
(docs/monitoring.md, the ``live_refresh`` bench stage).

Views are immutable: :meth:`view` freezes the current device arrays +
counters into an :class:`OverlayView`; a running job keeps reading its
leased view while the plane appends to fresh arrays (jax arrays are
immutable, so the old view stays consistent — the "(snapshot, overlay)
pair at a consistent epoch" lease contract).

HBM accounting: the overlay's device bytes (2·4·cap + q_total tomb
bytes) are reserved through the serving ``HBMLedger`` when one is
attached, so admission sees the delta as resident state, not free
lunch.

Thread safety: the overlay is owned and locked by the LiveGraphPlane;
methods here assume external synchronization.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

#: smallest add-buffer capacity bucket (power of two)
MIN_CAP = 1024


def _next_pow2(x: int) -> int:
    return 1 << max(x - 1, 1).bit_length()


class OverlayView:
    """Immutable device-side view of the overlay at one delta seq."""

    __slots__ = ("n", "cap", "count", "src_dev", "dst_dev", "tomb_dev",
                 "tomb_count", "seq", "slot_base")

    def __init__(self, n, cap, count, src_dev, dst_dev, tomb_dev,
                 tomb_count, seq, slot_base):
        self.n = n
        self.cap = cap
        self.count = count
        self.src_dev = src_dev
        self.dst_dev = dst_dev
        self.tomb_dev = tomb_dev
        self.tomb_count = tomb_count
        self.seq = seq
        self.slot_base = slot_base

    @property
    def empty(self) -> bool:
        return self.count == 0 and self.tomb_count == 0

    @property
    def has_tombstones(self) -> bool:
        return self.tomb_count > 0


class DeltaOverlay:
    """See module doc. Built against ONE base snapshot epoch; the
    compactor folds it into the base and starts a fresh overlay."""

    def __init__(self, snapshot, *, min_cap: int = MIN_CAP,
                 ledger=None, ledger_key=None, metrics=None):
        self.snap = snapshot
        self.n = int(snapshot.n)
        deg = snapshot.out_degree.astype(np.int64)
        degc = -(-deg // 8)
        colstart = np.zeros(self.n + 1, np.int64)
        np.cumsum(degc, out=colstart[1:])
        # q_total matches models/bfs_hybrid.build_chunked_csr exactly —
        # slot ids must agree with the device layout (+1 pad column)
        self.q_total = int(colstart[-1]) + 1
        self._colstart = colstart
        self._deg = deg
        # out-CSR host view for slot lookup on removals
        self._dst_by_src, self._indptr_out = snapshot.out_csr()
        self._labels_by_src: Optional[np.ndarray] = None
        # add buffer (host mirror; device arrays built lazily per view)
        self.cap = int(min_cap)
        self._min_cap = int(min_cap)
        self._h_src = np.full(self.cap, self.n + 1, np.int32)
        self._h_dst = np.full(self.cap, self.n + 1, np.int32)
        self._h_lab = np.zeros(self.cap, np.int32)
        self.count = 0
        self.dead_adds = 0             # appended rows later tombstoned
        # tombstone state: slot bitmap (device mirror) + per-base-ROW
        # mask (host only — the compactor filters snapshot rows with it)
        self._h_tomb = np.zeros(self.q_total, np.uint8)
        self.tomb_row_mask = np.zeros(snapshot.num_edges, bool)
        self.tomb_count = 0
        self.seq = 0                   # bumps on every mutation
        # device state: rows [0, _clean_rows) of the add buffers are
        # already device-resident and accurate; rows the writer killed
        # IN PLACE below that watermark collect in _dirty_add_rows.
        # view() scatters only (watermark tail + dirty rows) — the
        # delta pages; buffer establishment and capacity growth are
        # device-side pad fills (jnp.full / concatenate), so they cost
        # ZERO H2D — only changed rows/bytes ever cross the tunnel.
        self._d_src = None
        self._d_dst = None
        self._d_tomb = None
        self._clean_rows = 0
        self._dirty_add_rows: set = set()
        self._dirty_tomb_bytes: set = set()
        self._metrics = metrics
        self._ledger = ledger
        self._ledger_key = ledger_key if ledger_key is not None \
            else ("live-overlay", id(self))
        self._reserved = 0
        self._reserve()

    # -- HBM accounting ------------------------------------------------------

    def device_bytes(self) -> int:
        return 2 * 4 * self.cap + self.q_total

    def _reserve(self) -> None:
        if self._ledger is None:
            return
        need = self.device_bytes()
        if need == self._reserved:
            return
        self._ledger.release(self._ledger_key)
        self._ledger.reserve(self._ledger_key, need)  # stays pinned
        self._reserved = need

    def close(self) -> None:
        if self._ledger is not None:
            self._ledger.release(self._ledger_key)
            self._reserved = 0

    # -- mutation ------------------------------------------------------------

    def _grow(self, need: int) -> None:
        new_cap = _next_pow2(max(need, self._min_cap))
        if new_cap <= self.cap:
            return
        for name in ("_h_src", "_h_dst", "_h_lab"):
            old = getattr(self, name)
            fill = self.n + 1 if name != "_h_lab" else 0
            fresh = np.full(new_cap, fill, np.int32)
            fresh[:self.count] = old[:self.count]
            setattr(self, name, fresh)
        self.cap = new_cap    # device buffers pad-extend at next view()
        self._reserve()       # raises AdmissionError when HBM is tight
                              # — the plane responds by compacting

    def append_edges(self, src_dense, dst_dense, labs) -> int:
        """Append dense-index edge rows (caller symmetrizes for
        undirected snapshots). Returns rows appended."""
        src_dense = np.asarray(src_dense, np.int32)
        dst_dense = np.asarray(dst_dense, np.int32)
        labs = np.asarray(labs, np.int32)
        k = len(src_dense)
        if k == 0:
            return 0
        if self.count + k > self.cap:
            self._grow(self.count + k)
        sl = slice(self.count, self.count + k)
        self._h_src[sl] = src_dense
        self._h_dst[sl] = dst_dense
        self._h_lab[sl] = labs
        self.count += k          # the [_clean_rows, count) tail is the
        self.seq += 1            # delta page view() scatters — no flag
        return k

    def _labels_src_order(self) -> Optional[np.ndarray]:
        if self.snap.labels is None:
            return None
        if self._labels_by_src is None:
            self._labels_by_src = self.snap.labels[self._base_order()]
        return self._labels_by_src

    def _base_order(self) -> np.ndarray:
        """src-order permutation of the base rows (slot → dst-order
        row). The snapshot caches it beside its out-CSR — ``__init__``
        already forced that build — and ``merge_delta`` carries both
        across epoch merges incrementally, so this is a read, not an
        O(E log E) argsort re-paid per epoch (ROADMAP #5 residual)."""
        if getattr(self, "_order", None) is None:
            order = getattr(self.snap, "_out_csr_order", None)
            if order is None:
                self.snap.out_csr()
                order = getattr(self.snap, "_out_csr_order", None)
            self._order = order if order is not None \
                else np.argsort(self.snap.src, kind="stable")
        return self._order

    def remove_edge(self, u: int, v: int, lab: Optional[int]) -> bool:
        """Tombstone ONE live row (u→v[, label]) — first a base-CSR
        slot, else a live overlay add. Returns False when no live row
        matches (caller may ignore: a rebuild would not see the edge
        either)."""
        labs_src = self._labels_src_order()
        p0 = int(self._indptr_out[u])
        p1 = p0 + int(self._deg[u])
        for p in range(p0, p1):
            if int(self._dst_by_src[p]) != v:
                continue
            if lab is not None and labs_src is not None \
                    and int(labs_src[p]) != lab:
                continue
            slot = int(self._colstart[u]) * 8 + (p - p0)
            byte, bit = slot >> 3, slot & 7
            if self._h_tomb[byte] & (1 << bit):
                continue               # this row is already dead
            self._h_tomb[byte] |= (1 << bit)
            self._dirty_tomb_bytes.add(byte)
            self.tomb_row_mask[self._base_order()[p]] = True
            self.tomb_count += 1
            self.seq += 1
            return True
        # not in the base: kill a live overlay add
        for i in range(self.count):
            if int(self._h_src[i]) == u and int(self._h_dst[i]) == v \
                    and (lab is None or int(self._h_lab[i]) == lab):
                self._h_src[i] = self.n + 1
                self._h_dst[i] = self.n + 1
                self.dead_adds += 1
                if i < self._clean_rows:
                    self._dirty_add_rows.add(i)
                self.seq += 1
                return True
        return False

    # -- observation ---------------------------------------------------------

    def fill_fraction(self) -> float:
        return self.count / max(self.cap, 1)

    def tombstone_fraction(self) -> float:
        return self.tomb_count / max(self.snap.num_edges, 1)

    def live_adds(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(src, dst, lab) dense host arrays of the LIVE appended rows
        (killed rows excluded) — the compactor's merge input."""
        s = self._h_src[:self.count]
        alive = s <= self.n
        return (s[alive].copy(), self._h_dst[:self.count][alive].copy(),
                self._h_lab[:self.count][alive].copy())

    def stats(self) -> dict:
        return {"capacity": self.cap, "adds": self.count,
                "dead_adds": self.dead_adds,
                "tombstones": self.tomb_count,
                "fill": round(self.fill_fraction(), 4),
                "tombstone_fraction":
                    round(self.tombstone_fraction(), 6),
                "device_bytes": self.device_bytes(), "seq": self.seq}

    # -- device sync / views -------------------------------------------------

    def _count_upload(self, nbytes: int) -> None:
        if self._metrics is not None and nbytes:
            self._metrics.counter("serving.live.upload_bytes") \
                .inc(int(nbytes))
        # device-cost mirror (obs/devprof, ISSUE 10): the same delta
        # pages on the process-wide device.xfer.h2d_bytes family, so
        # the profiler's transfer story includes live-plane traffic
        from titan_tpu.obs import devprof
        devprof.count_h2d("overlay.delta", int(nbytes))

    def view(self) -> OverlayView:
        """Freeze the current state into an immutable device view.
        ONLY delta pages cross the tunnel: the appended tail (plus any
        in-place-killed rows) scatters into the resident add buffers,
        and only dirtied bytes hit the tombstone bitmap. Buffer
        establishment and capacity growth are device-side pad fills —
        never an upload. Every byte that does ship counts on
        ``serving.live.upload_bytes``."""
        import jax.numpy as jnp

        pad = jnp.int32(self.n + 1)
        if self._d_src is None:
            # device-side constant fill: 0 bytes H2D; the scatter
            # below ships rows [0, count) — the actual delta
            self._d_src = jnp.full((self.cap,), pad, jnp.int32)
            self._d_dst = jnp.full((self.cap,), pad, jnp.int32)
            self._clean_rows = 0
        elif self._d_src.shape[0] != self.cap:
            # capacity bucket grew: pad-extend ON DEVICE (device-to-
            # device copy, 0 bytes H2D); resident rows stay valid —
            # in-place kills are tracked in _dirty_add_rows
            ext = jnp.full((self.cap - self._d_src.shape[0],), pad,
                           jnp.int32)
            self._d_src = jnp.concatenate([self._d_src, ext])
            self._d_dst = jnp.concatenate([self._d_dst, ext])
        if self._dirty_add_rows or self._clean_rows < self.count:
            rows = sorted(self._dirty_add_rows)
            rows.extend(range(self._clean_rows, self.count))
            idx = jnp.asarray(np.asarray(rows, np.int32))
            # .at[].set returns NEW arrays — frozen views keep theirs
            self._d_src = self._d_src.at[idx].set(
                jnp.asarray(self._h_src[rows]))
            self._d_dst = self._d_dst.at[idx].set(
                jnp.asarray(self._h_dst[rows]))
            self._clean_rows = self.count
            self._dirty_add_rows.clear()
            # 2 int32 payloads + the int32 scatter-index array (shipped
            # once, reused by both scatters) — index words are H2D too
            self._count_upload((2 * 4 + 4) * len(rows))
        if self._d_tomb is None:
            # all-zero bitmap: device-side fill, 0 bytes H2D (every
            # set byte since construction is in _dirty_tomb_bytes)
            self._d_tomb = jnp.zeros((self.q_total,), jnp.uint8)
        if self._dirty_tomb_bytes:
            idx = np.fromiter(self._dirty_tomb_bytes, np.int64,
                              len(self._dirty_tomb_bytes))
            self._d_tomb = self._d_tomb.at[
                jnp.asarray(idx.astype(np.int32))].set(
                jnp.asarray(self._h_tomb[idx]))
            self._dirty_tomb_bytes.clear()
            # 1 payload byte + 4 index bytes per dirtied bitmap byte
            self._count_upload(5 * len(idx))
        return OverlayView(self.n, self.cap, self.count, self._d_src,
                           self._d_dst, self._d_tomb, self.tomb_count,
                           self.seq, slot_base=self.q_total * 8)
