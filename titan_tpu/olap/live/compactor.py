"""EpochCompactor: fold the delta overlay back into the base CSR.

Compaction is the live plane's epoch boundary: the overlay's live adds
and tombstoned base rows are merged into a fresh dst-sorted snapshot,
the new epoch is republished to the serving pool (running jobs keep
their leased (snapshot, overlay-view) pair; new jobs lease the merged
base with an empty overlay), and only THEN do the device-layout caches
of the old base die — the acceptance contract that a refresh under
writes never evicts or re-uploads the base CSR until the compactor
republishes.

Two merge implementations (ISSUE 9):

* **device** (default) — the next epoch's chunked CSR is computed
  entirely in HBM by ``ops/epoch_merge.merge_chunked_csr`` from the
  base CSR device arrays + the overlay view (both already resident),
  and the host-durable snapshot is synced from delta pages
  (``snapshot.merge_delta`` — O(E) memcpy, no O(E log E) sort, no
  download). Epochs are double-buffered through the HBM ledger: the
  next epoch's CSR bytes are reserved BESIDE the current epoch before
  the merge runs, the merged snapshot is published with its device CSR
  pre-attached (no re-upload), and the old epoch's reservation is
  released by the pool's retire path. Per-epoch H2D cost: zero beyond
  the delta pages the overlay already shipped incrementally.
* **host** — the oracle: filter + concatenate + ``from_arrays``'s full
  stable sort, leaving a snapshot with NO device CSR (the next run
  re-uploads the whole image — charged eagerly to
  ``serving.live.upload_bytes``). This is the fallback whenever the
  device path cannot run, and every fallback is LOUD:
  ``serving.live.device_merge_fallbacks`` counts it and ``stats()``
  records the reason (``GET /live``).

Policy: compact when the overlay's add-buffer fill or its tombstone
fraction crosses budget (defaults 0.5 / 0.05 — configurable per plane
since ISSUE 9, no longer module-constant-only), when a delta cannot be
expressed in the overlay at all (vertex-set changes, edges to unknown
vertices — the general ``apply_changes`` path handles those on the
merged snapshot), or when the HBM ledger refuses an overlay growth.
"""

from __future__ import annotations

import time

import numpy as np

#: default thresholds — fill is fraction of the CURRENT capacity bucket
#: (so small overlays compact before jumping buckets), tombstones are a
#: fraction of base edge rows (dead slots cost gather bandwidth every
#: round until compacted)
MAX_FILL = 0.5
MAX_TOMB_FRACTION = 0.05


class EpochCompactor:
    """Merge policy + merge implementation. Mode/fallback telemetry is
    instance state (one compactor per plane); byte/fallback counters go
    through the ``metrics`` manager the plane passes per call."""

    def __init__(self, max_fill: float = MAX_FILL,
                 max_tomb_fraction: float = MAX_TOMB_FRACTION,
                 *, device_merge: bool = True,
                 verify_device: bool = False):
        self.max_fill = float(max_fill)
        self.max_tomb_fraction = float(max_tomb_fraction)
        self.device_merge = bool(device_merge)
        # paranoia knob: download the device-merged dstT (D2H charged
        # to serving.live.download_bytes) and compare it to the
        # host-synced mirror; a mismatch degrades to the host oracle
        self.verify_device = bool(verify_device)
        self.device_merges = 0
        self.host_merges = 0
        self.last_mode: str = "none"
        self.fallbacks: dict = {}      # reason -> count

    def policy(self) -> dict:
        """The active policy + merge-mode telemetry — surfaced by
        ``LiveGraphPlane.stats()`` under ``GET /live``."""
        return {"max_fill": self.max_fill,
                "max_tomb_fraction": self.max_tomb_fraction,
                "device_merge": self.device_merge,
                "verify_device": self.verify_device,
                "merge_mode": self.last_mode,
                "device_merges": self.device_merges,
                "host_merges": self.host_merges,
                "fallbacks": dict(self.fallbacks)}

    def should_compact(self, overlay) -> bool:
        if overlay.count == 0 and overlay.tomb_count == 0:
            return False
        return (overlay.fill_fraction() >= self.max_fill
                or overlay.tombstone_fraction() >= self.max_tomb_fraction)

    # -- host oracle ---------------------------------------------------------

    def merge(self, snapshot, overlay):
        """Base + overlay → a fresh snapshot over the SAME vertex set
        (vertex-set changes ride the subsequent ``apply_changes`` call
        on the merged object). Pure host-array work — the full stable
        re-sort; the old snapshot's arrays are left untouched for jobs
        still leasing them. This is the ORACLE the device path is
        pinned bit-equal to (tests/test_live_compact_device.py) and the
        fallback it degrades to."""
        from titan_tpu.olap.tpu import snapshot as snap_mod

        keep = ~overlay.tomb_row_mask
        src = snapshot.src[keep]
        dst = snapshot.dst[keep]
        labs = snapshot.labels[keep] if snapshot.labels is not None \
            else None
        a_src, a_dst, a_lab = overlay.live_adds()
        if len(a_src):
            src = np.concatenate([src, a_src])
            dst = np.concatenate([dst, a_dst])
            if labs is not None:
                labs = np.concatenate([labs, a_lab])
        merged = snap_mod.from_arrays(
            snapshot.n, src, dst, snapshot.vertex_ids,
            labels=labs, label_names=snapshot.label_names)
        return self._carry_over(snapshot, merged)

    @staticmethod
    def _carry_over(snapshot, merged):
        # dense vertex-property columns stay aligned (same vertex set);
        # carry them over so compiled has()/values() keep working
        merged.vertex_values = dict(snapshot.vertex_values)
        merged._build_params = dict(snapshot._build_params or {})
        merged.epoch = snapshot.epoch
        return merged

    # -- device path ---------------------------------------------------------

    def _fallback(self, reason: str, metrics) -> None:
        self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1
        if metrics is not None:
            metrics.counter(
                "serving.live.device_merge_fallbacks").inc()

    def compact(self, snapshot, overlay, *, ledger=None, metrics=None,
                host_only: bool = False, on_resident=None):
        """One epoch boundary: returns ``(merged_snapshot, mode)`` with
        ``mode in ("device", "host")``.

        Device path preconditions — any miss degrades LOUDLY to the
        host oracle (fallback reason recorded, counter bumped):

        * ``host_only`` is False (vertex-set changes take the general
          ``apply_changes`` path, which invalidates device caches — a
          device merge would be wasted work);
        * the base chunked CSR is device-resident (otherwise there is
          nothing in HBM to merge against and the host path is
          strictly cheaper) and non-empty;
        * int32 slot ids can express both layouts;
        * the HBM ledger (when attached) can hold the NEXT epoch's CSR
          beside the current one — the double-buffer reservation.

        ``on_resident(merged)`` (when given) registers the published
        snapshot with the ledger owner's eviction map so a later
        eviction of the unpinned epoch actually drops its device CSR.
        """
        from titan_tpu.ops import epoch_merge

        if host_only:
            return self._host("apply-path", snapshot, overlay,
                              metrics)
        if not self.device_merge:
            return self._host(None, snapshot, overlay, metrics)
        csr = getattr(snapshot, "_hybrid_csr", None)
        if csr is None:
            return self._host("base-not-resident", snapshot,
                              overlay, metrics)
        if snapshot.num_edges == 0:
            return self._host("empty-base", snapshot, overlay,
                              metrics)
        deg, degc, colstart, q_new = \
            epoch_merge.merged_degrees_host(snapshot, overlay)
        if not (epoch_merge.fits_int32(int(csr["q_total"]))
                and epoch_merge.fits_int32(q_new)):
            return self._host("int32-overflow", snapshot,
                              overlay, metrics)
        reserve_key = None
        nbytes = 0
        if ledger is not None:
            from titan_tpu.olap.serving.hbm import (AdmissionError,
                                                    chunked_csr_bytes)
            nbytes = chunked_csr_bytes(snapshot.n, q_new)
            reserve_key = ("live-epoch-next", id(self))
            try:
                # the double-buffer: next epoch's CSR beside the
                # current one. AdmissionError = the ledger cannot hold
                # two epochs → loud host degrade.
                ledger.reserve(reserve_key, nbytes)
            except AdmissionError:
                return self._host("ledger-full", snapshot,
                                  overlay, metrics)
        try:
            return self._device(snapshot, overlay, csr, deg, degc,
                                colstart, q_new, ledger, reserve_key,
                                nbytes, metrics, on_resident)
        except Exception as e:
            # ANY kernel failure degrades to the host oracle — not
            # just the int32/layout ValueErrors the CPU path can hit:
            # on real hardware the merge can die with an
            # XlaRuntimeError (HBM allocator RESOURCE_EXHAUSTED under
            # fragmentation the ledger model didn't predict), and
            # letting it escape would leak the pinned double-buffer
            # reservation and skip the epoch entirely
            if ledger is not None:
                ledger.release(reserve_key)
            return self._host(f"kernel: {type(e).__name__}: {e}",
                              snapshot, overlay, metrics)

    def _device(self, snapshot, overlay, csr, deg, degc, colstart,
                q_new, ledger, reserve_key, nbytes, metrics,
                on_resident):
        import jax

        from titan_tpu.olap.tpu import snapshot as snap_mod
        from titan_tpu.ops import epoch_merge

        view = overlay.view()
        t0 = time.time()
        out = epoch_merge.merge_chunked_csr(
            csr, view, q_total_new=q_new, e_base=snapshot.num_edges)
        jax.block_until_ready(out["dstT"])
        device_ms = (time.time() - t0) * 1e3
        # host-durable sync from delta pages: drop tombstoned rows,
        # insert the adds — O(E) memcpy + O(delta log delta), never the
        # full re-sort, never a device download
        a_src, a_dst, a_lab = overlay.live_adds()
        merged = self._carry_over(snapshot, snap_mod.merge_delta(
            snapshot, ~overlay.tomb_row_mask, a_src, a_dst, a_lab))
        out["_host"] = epoch_merge.LazyHostMirror(
            merged, colstart, degc)
        if self.verify_device:
            # D2H readback (charged) + bit-compare vs the host mirror
            got = np.asarray(out["dstT"])
            if metrics is not None:
                metrics.counter("serving.live.download_bytes").inc(
                    got.nbytes)
            if not (got == out["_host"]["dstT"]).all():
                if ledger is not None:
                    ledger.release(reserve_key)
                return self._host("verify-mismatch", snapshot,
                                  overlay, metrics)
        merged._hybrid_csr = out
        if ledger is not None:
            # re-key the double-buffer reservation onto the published
            # snapshot's identity: the scheduler's per-run reserve()
            # pins this same entry, and the pool's retire path releases
            # it — exactly the lifecycle of an uploaded image. Resident
            # but unpinned (the warm-cache state) until a job runs.
            from titan_tpu.olap.serving.hbm import AdmissionError
            ledger.release(reserve_key)
            try:
                ledger.reserve(id(merged), nbytes)
                ledger.unpin(id(merged))
            except AdmissionError:
                pass   # accounting catches up on the next job's reserve
        if on_resident is not None:
            on_resident(merged)
        if metrics is not None:
            metrics.histogram(
                "serving.live.compact_device_ms").update(device_ms)
        self.device_merges += 1
        self.last_mode = "device"
        return merged, "device"

    def _host(self, fallback_reason, snapshot, overlay, metrics):
        if fallback_reason is not None:
            self._fallback(fallback_reason, metrics)
        merged = self.merge(snapshot, overlay)
        if metrics is not None:
            # the host path leaves no device CSR: the next run
            # re-uploads the whole image — charge the epoch for it so
            # upload_bytes reflects what the boundary commits through
            # the tunnel either way
            from titan_tpu.olap.serving.hbm import snapshot_csr_bytes
            metrics.counter("serving.live.upload_bytes").inc(
                snapshot_csr_bytes(merged))
        self.host_merges += 1
        self.last_mode = "host"
        return merged, "host"
