"""EpochCompactor: fold the delta overlay back into the base CSR.

Compaction is the live plane's epoch boundary: the overlay's live adds
and tombstoned base rows are merged into a fresh dst-sorted snapshot
(``olap/tpu/snapshot.from_arrays`` — the same CSR builder the scan path
uses), the new epoch is republished to the serving pool (running jobs
keep their leased (snapshot, overlay-view) pair; new jobs lease the
merged base with an empty overlay), and only THEN do the device-layout
caches of the old base die — the acceptance contract that a refresh
under writes never evicts or re-uploads the base CSR until the
compactor republishes.

Policy: compact when the overlay's add-buffer fill or its tombstone
fraction crosses budget (defaults 0.5 / 0.05), when a delta cannot be
expressed in the overlay at all (vertex-set changes, edges to unknown
vertices — the general ``apply_changes`` path handles those on the
merged snapshot), or when the HBM ledger refuses an overlay growth.
"""

from __future__ import annotations

import numpy as np

#: default thresholds — fill is fraction of the CURRENT capacity bucket
#: (so small overlays compact before jumping buckets), tombstones are a
#: fraction of base edge rows (dead slots cost gather bandwidth every
#: round until compacted)
MAX_FILL = 0.5
MAX_TOMB_FRACTION = 0.05


class EpochCompactor:
    """Merge policy + merge implementation (host-array work only)."""

    def __init__(self, max_fill: float = MAX_FILL,
                 max_tomb_fraction: float = MAX_TOMB_FRACTION):
        self.max_fill = float(max_fill)
        self.max_tomb_fraction = float(max_tomb_fraction)

    def should_compact(self, overlay) -> bool:
        if overlay.count == 0 and overlay.tomb_count == 0:
            return False
        return (overlay.fill_fraction() >= self.max_fill
                or overlay.tombstone_fraction() >= self.max_tomb_fraction)

    def merge(self, snapshot, overlay):
        """Base + overlay → a fresh snapshot over the SAME vertex set
        (vertex-set changes ride the subsequent ``apply_changes`` call
        on the merged object). Pure host-array work; the old snapshot's
        arrays are left untouched for jobs still leasing them."""
        from titan_tpu.olap.tpu import snapshot as snap_mod

        keep = ~overlay.tomb_row_mask
        src = snapshot.src[keep]
        dst = snapshot.dst[keep]
        labs = snapshot.labels[keep] if snapshot.labels is not None \
            else None
        a_src, a_dst, a_lab = overlay.live_adds()
        if len(a_src):
            src = np.concatenate([src, a_src])
            dst = np.concatenate([dst, a_dst])
            if labs is not None:
                labs = np.concatenate([labs, a_lab])
        merged = snap_mod.from_arrays(
            snapshot.n, src, dst, snapshot.vertex_ids,
            labels=labs, label_names=snapshot.label_names)
        # dense vertex-property columns stay aligned (same vertex set);
        # carry them over so compiled has()/values() keep working
        merged.vertex_values = dict(snapshot.vertex_values)
        merged._build_params = dict(snapshot._build_params or {})
        merged.epoch = snapshot.epoch
        return merged
