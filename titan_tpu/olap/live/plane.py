"""LiveGraphPlane: serve OLAP under writes without rebuilds or re-uploads.

The orchestration layer over the three live primitives (feed.py /
overlay.py / compactor.py): one base ``GraphSnapshot`` whose device CSR
stays resident, one :class:`DeltaOverlay` absorbing committed deltas,
and an :class:`EpochCompactor` that folds the overlay into a republished
base when it crosses budget. Ingest has two lanes, unified on the
``change_payload`` shape:

* **local** — the base snapshot's atomically-subscribed in-process
  change queue (adopted from ``build()``), drained with the same
  epoch-continuity discipline as ``GraphSnapshot.refresh()``;
* **cross-instance** — a :class:`ChangeFeed` tailing the durable user
  trigger log (writers tag transactions with ``log_identifier`` — the
  TitanBus contract); the feed drops this instance's own messages and
  enforces seq continuity.

Epoch/lease contract: ``lease_state()`` returns ``(snapshot,
OverlayView, epoch_info)`` captured under one lock — a consistent pair.
``epoch_info`` carries the compaction ``epoch``, the overlay delta
``seq`` and the applied local mutation epoch; jobs report it so results
are attributable to an exact graph state. Deltas the overlay cannot
express (vertex adds/removals, edges to unknown vertices) trigger an
immediate compaction whose merged snapshot takes the general
``apply_changes`` path; listener overflow or a feed gap triggers a full
store re-scan (``resync``) that re-anchors the change queue.

Metrics (``serving.live.*`` — see docs/monitoring.md): deltas_applied,
edges_added, edges_tombstoned, compactions, resyncs, feed_batches,
backpressure, upload_bytes, download_bytes, device_merge_fallbacks
counters; apply_ms / compact_ms / compact_device_ms histograms;
freshness lag (epochs + seconds), overlay fill and tombstone fraction,
and the active compaction policy + merge mode via ``stats()`` →
``GET /live``.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np

from titan_tpu.olap.live.compactor import EpochCompactor
from titan_tpu.olap.live.feed import ChangeFeed
from titan_tpu.olap.live.overlay import MIN_CAP, DeltaOverlay
from titan_tpu.utils.metrics import MetricManager


#: the plane's ``serving.live.*`` counter family — ONE definition
#: shared by stats() and the metric-name doc-drift guard
#: (tests/test_docs_metrics.py). upload_bytes / download_bytes /
#: device_merge_fallbacks are the ISSUE 9 byte-accounting surface:
#: delta pages + host-merge re-upload charges, verify-mode readback,
#: and loud device→host degrades.
_LIVE_COUNTERS = ("deltas_applied", "edges_added", "edges_tombstoned",
                  "compactions", "resyncs", "feed_batches",
                  "backpressure", "upload_bytes", "download_bytes",
                  "device_merge_fallbacks")


class LiveGraphPlane:
    """See module doc. One plane serves one snapshot parameter set
    (``labels`` + ``directed``; extracted edge_keys are unsupported —
    change payloads carry no edge property values)."""

    def __init__(self, graph, *, labels=None, directed: bool = False,
                 log_identifier: Optional[str] = None,
                 feed: Optional[ChangeFeed] = None,
                 reader_id: Optional[str] = None,
                 min_cap: int = MIN_CAP,
                 compactor: Optional[EpochCompactor] = None,
                 max_fill: Optional[float] = None,
                 max_tomb_fraction: Optional[float] = None,
                 device_merge: bool = True,
                 verify_device: bool = False,
                 ledger=None,
                 metrics: Optional[MetricManager] = None,
                 poll_interval_s: Optional[float] = None):
        from titan_tpu.olap.live.compactor import (MAX_FILL,
                                                   MAX_TOMB_FRACTION)
        from titan_tpu.olap.tpu import snapshot as snap_mod

        self.graph = graph
        self.labels = tuple(labels) if labels is not None else None
        self.directed = bool(directed)
        self._metrics = metrics or MetricManager.instance()
        # obs seam: the owning JobScheduler lends its tracer (like the
        # ledger) so apply/compaction epochs land on the reserved
        # "live" trace id; None = no tracing
        self._tracer = None
        # serving seam: the owning JobScheduler registers published
        # epochs in its HBM eviction map through this hook
        self._on_resident = None
        self._lock = threading.RLock()
        self._min_cap = int(min_cap)
        self._ledger = ledger
        # compaction policy is plane/server configuration (ISSUE 9
        # satellite), not module constants: pass a prebuilt compactor
        # OR the individual knobs
        self.compactor = compactor or EpochCompactor(
            max_fill if max_fill is not None else MAX_FILL,
            max_tomb_fraction if max_tomb_fraction is not None
            else MAX_TOMB_FRACTION,
            device_merge=device_merge, verify_device=verify_device)

        # the feed starts BEFORE the build scan and the ingest floor is
        # stamped before it too: a remote commit racing the scan is
        # never LOST (at-least-once — it may duplicate a parallel edge
        # in the window, harmless to reachability-class results and
        # resolved by the next resync; exactly-once would need txid
        # bookkeeping in the scan, future work)
        self.feed = feed
        if self.feed is None and log_identifier is not None:
            self.feed = ChangeFeed(graph, log_identifier,
                                   reader_id=reader_id,
                                   start_time=None,
                                   metrics=self._metrics)
        self._feed_seq = 0
        self._ingest_floor = graph.backend.times.time()

        snap = snap_mod.build(graph, labels=labels, directed=directed)
        # adopt the snapshot's atomically-subscribed listener as the
        # plane's local ingest queue; published snapshots are plain
        # array objects (the plane owns freshness, not refresh())
        self._queue = snap._listener
        self._token = snap._listener_token
        self.applied_epoch = snap.epoch
        self._label_ids = (snap._build_params or {}).get("label_ids")
        self._detach(snap)
        self.snapshot = snap
        self.overlay = self._new_overlay(snap)
        self.epoch = 0                 # compaction epoch
        self._republish = None         # pool hook: fn(old, new)
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        if poll_interval_s is not None:
            self.start(poll_interval_s)

    # -- plumbing ------------------------------------------------------------

    @staticmethod
    def _detach(snap) -> None:
        """Published snapshots must not own the plane's listener: their
        close() (pool retirement) would unsubscribe the queue the plane
        keeps draining."""
        snap._graph = None
        snap._listener = None
        snap._listener_token = 0

    def _new_overlay(self, snap) -> DeltaOverlay:
        return DeltaOverlay(snap, min_cap=self._min_cap,
                            ledger=self._ledger,
                            ledger_key=("live-overlay", id(self)),
                            metrics=self._metrics)

    @property
    def pool_key(self) -> tuple:
        from titan_tpu.olap.serving.pool import SnapshotPool
        return SnapshotPool.key_of(self.labels, (), self.directed)

    def start(self, poll_interval_s: float = 0.05) -> "LiveGraphPlane":
        """Background pump so freshness does not depend on lease
        traffic."""
        if self._thread is not None and self._thread.is_alive():
            return self

        def loop():
            while not self._closed:
                try:
                    self.pump()
                except Exception:
                    pass               # next tick retries; pump states
                self._wake.wait(poll_interval_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="live-plane-pump")
        self._thread.start()
        return self

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        if self.feed is not None:
            self.feed.close()
        self.overlay.close()
        self.graph.unsubscribe_changes(self._token)

    # -- ingest --------------------------------------------------------------

    def pump(self) -> None:
        """Drain both ingest lanes into the overlay (or through a
        compaction). Idempotent, cheap when idle; called by the
        background loop and on every lease."""
        with self._lock:
            if self._closed:
                return
            self._pump_local()
            self._pump_feed()

    def _pump_local(self) -> None:
        g, q = self.graph, self._queue
        if q.overflowed:
            self._resync("listener overflow")
            return
        new_epoch = g.mutation_epoch
        if new_epoch == self.applied_epoch:
            return
        # same drain discipline as GraphSnapshot.refresh(): scan-then-
        # slice up to new_epoch; racing payloads stay queued
        cut = 0
        while cut < len(q) and (q[cut].get("epoch") is None
                                or q[cut]["epoch"] <= new_epoch):
            cut += 1
        pending = list(q[:cut])
        del q[:cut]
        covered = [e for e in (p.get("epoch") for p in pending)
                   if e is not None
                   and self.applied_epoch < e <= new_epoch]
        if len(covered) != new_epoch - self.applied_epoch:
            self._resync("local delta gap")
            return
        self._apply_payloads(
            [p for p in pending
             if p.get("epoch") is None
             or p["epoch"] > self.applied_epoch])
        self.applied_epoch = new_epoch

    def _pump_feed(self) -> None:
        if self.feed is None:
            return
        batches = self.feed.poll()
        if not batches:
            return
        for batch in batches:
            if batch.seq != self._feed_seq + 1:
                # continuity broke: the store re-scan covers every
                # committed batch, so the rest of this poll is dropped
                # (applying it on top would double-apply)
                self._feed_seq = batches[-1].seq
                self._resync(f"feed seq gap (expected "
                             f"{self._feed_seq + 1})")
                return
            self._feed_seq = batch.seq
            if batch.timestamp <= self._ingest_floor:
                continue               # covered by the base build scan
            self._apply_payloads([batch.to_payload()])

    # -- delta application ---------------------------------------------------

    def _resolve(self, name: str):
        """Edge/property type by name; remote writers may have created
        it after our schema cache warmed — expire once and retry."""
        st = self.graph.schema.get_by_name(name)
        if st is None:
            try:
                self.graph.schema.expire()
            except Exception:
                return None
            st = self.graph.schema.get_by_name(name)
        return st

    def _payload_fits_overlay(self, p: dict) -> bool:
        if p.get("added_vertices") or p.get("removed_vertices"):
            return False
        idm = self.graph.idm
        vids = self.snapshot.vertex_ids
        for r in p.get("added", ()):
            if "in" not in r:
                continue
            st = self._resolve(r["type"])
            if st is None or (self._label_ids is not None
                              and st.id not in self._label_ids):
                continue
            for vid in (r["out"], r["in"]):
                cv = idm.canonical_vertex_id(vid)
                i = int(np.searchsorted(vids, cv))
                if i >= len(vids) or vids[i] != cv:
                    return False       # edge to an unknown vertex
        return True

    def _apply_payloads(self, payloads: list) -> None:
        if not payloads:
            return
        for i, p in enumerate(payloads):
            if not self._payload_fits_overlay(p):
                # flush what the overlay can absorb, then fold the rest
                # through the merged snapshot's general apply path
                self._overlay_apply(payloads[:i])
                self._compact(payloads[i:], why="vertex-set change")
                return
        self._overlay_apply(payloads)
        if self.compactor.should_compact(self.overlay):
            self._compact([], why="budget")

    def _append(self, a_s, a_d, a_l) -> int:
        """Overlay append with the HBM-admission fallback: a refused
        growth triggers a compaction (frees the overlay) and ONE
        retry against the fresh minimum-capacity buffer."""
        try:
            return self.overlay.append_edges(a_s, a_d, a_l)
        except Exception:
            self._compact([], why="hbm admission")
            return self.overlay.append_edges(a_s, a_d, a_l)

    def _overlay_apply(self, payloads: list) -> None:
        if not payloads:
            return
        t0 = time.time()
        idm = self.graph.idm
        snap = self.snapshot
        vids = snap.vertex_ids
        added = tombed = 0
        for p in payloads:
            # adds land before this payload's removals so a remove in a
            # later commit (or the same one) can target them; multiset
            # semantics make within-payload order immaterial
            a_s: list = []
            a_d: list = []
            a_l: list = []
            for r in p.get("added", ()):
                if "in" not in r:      # property mutation: the dense
                    snap.vertex_values.pop(r.get("type"), None)
                    continue           # columns go stale, arrays don't
                st = self._resolve(r["type"])
                if st is None or (self._label_ids is not None
                                  and st.id not in self._label_ids):
                    continue
                u = int(np.searchsorted(
                    vids, idm.canonical_vertex_id(r["out"])))
                v = int(np.searchsorted(
                    vids, idm.canonical_vertex_id(r["in"])))
                code = idm.count(st.id)
                snap.label_names.setdefault(code, st.name)
                a_s.append(u)
                a_d.append(v)
                a_l.append(code)
            if a_s:
                s = np.asarray(a_s, np.int32)
                d = np.asarray(a_d, np.int32)
                lb = np.asarray(a_l, np.int32)
                if not self.directed:
                    s, d = (np.concatenate([s, d]),
                            np.concatenate([d, s]))
                    lb = np.concatenate([lb, lb])
                added += self._append(s, d, lb)
                # the append may have compacted: re-bind the published
                # base (same vertex set, so dense indices stay valid)
                snap = self.snapshot
                vids = snap.vertex_ids
            for r in p.get("removed", ()):
                if "in" not in r:
                    snap.vertex_values.pop(r.get("type"), None)
                    continue
                st = self._resolve(r["type"])
                if st is None:
                    continue
                cu = idm.canonical_vertex_id(r["out"])
                cv = idm.canonical_vertex_id(r["in"])
                iu = int(np.searchsorted(vids, cu))
                iv = int(np.searchsorted(vids, cv))
                if iu >= len(vids) or vids[iu] != cu \
                        or iv >= len(vids) or vids[iv] != cv:
                    continue           # ghost endpoints: rebuild would
                lab = idm.count(st.id)  # not see the edge either
                if self.overlay.remove_edge(iu, iv, lab):
                    tombed += 1
                # undirected bases hold the mirror row too
                if not self.directed \
                        and self.overlay.remove_edge(iv, iu, lab):
                    tombed += 1
        if added:
            self._metrics.counter("serving.live.edges_added").inc(added)
        if tombed:
            self._metrics.counter(
                "serving.live.edges_tombstoned").inc(tombed)
        self._metrics.counter("serving.live.deltas_applied").inc(
            len(payloads))
        self._metrics.histogram("serving.live.apply_ms").update(
            (time.time() - t0) * 1e3)
        if self._tracer is not None:
            self._tracer.event("live", "apply", t0=t0,
                               payloads=len(payloads),
                               edges_added=added, tombstoned=tombed,
                               epoch=self.epoch, seq=self.overlay.seq)

    # -- epoch boundaries ----------------------------------------------------

    def _publish(self, merged) -> None:
        old = self.snapshot
        self._detach(merged)
        self.snapshot = merged
        self.overlay.close()
        self.overlay = self._new_overlay(merged)
        self.epoch += 1
        if self._republish is not None:
            self._republish(old, merged)

    def _compact(self, extra_payloads: list, why: str = "") -> None:
        t0 = time.time()
        # device merge by default: next epoch's CSR is computed in HBM
        # beside the current one (double-buffered through the ledger)
        # and published pre-attached — no serving gap, no re-upload.
        # Payloads the overlay can't express force the host path (their
        # apply_changes invalidates device caches anyway).
        merged, mode = self.compactor.compact(
            self.snapshot, self.overlay, ledger=self._ledger,
            metrics=self._metrics, host_only=bool(extra_payloads),
            on_resident=self._on_resident)
        if extra_payloads:
            merged.apply_changes(extra_payloads, self.graph.schema,
                                 self.graph.idm)
        self._publish(merged)
        self._metrics.counter("serving.live.compactions").inc()
        self._metrics.histogram("serving.live.compact_ms").update(
            (time.time() - t0) * 1e3)
        if self._tracer is not None:
            self._tracer.event("live", "compact", t0=t0, why=why,
                               mode=mode, epoch=self.epoch)

    def compact_if_dirty(self) -> bool:
        """Force-fold the overlay (dense/PageRank's documented
        compact-before-run fallback). Returns True when a compaction
        happened."""
        return self.compact_now(why="compact-before-run")

    def compact_now(self, why: str = "controller") -> bool:
        """Externally-triggered epoch fold — the autotune controller's
        predicted-merge-cost seam (olap/serving/autotune): compact the
        overlay NOW instead of waiting for the fixed fill/tombstone
        thresholds. Pumps first so the fold covers every visible
        commit; a clean overlay is a no-op. Returns True when a
        compaction happened."""
        with self._lock:
            if self._closed:
                return False
            self._pump_local()
            self._pump_feed()
            if self.overlay.count == 0 and self.overlay.tomb_count == 0:
                return False
            self._compact([], why=why)
            return True

    def _resync(self, why: str) -> None:
        """Full store re-scan: the recovery path when delta continuity
        broke (listener overflow / gap, feed gap). Re-anchors the SAME
        change queue at the scan-verified epoch (core/changes
        ``ChangeQueue.reanchor`` — the overflow flag resets, so delta
        ingest resumes instead of resyncing forever)."""
        from titan_tpu.olap.tpu import snapshot as snap_mod

        # floor first: feed batches older than the re-scan are covered
        # by it (the at-least-once boundary, see __init__)
        self._ingest_floor = self.graph.backend.times.time()
        fresh = snap_mod.build(self.graph, labels=self.labels,
                               directed=self.directed,
                               _reuse_listener=(self._token,
                                                self._queue))
        self.applied_epoch = fresh.epoch
        self._label_ids = (fresh._build_params or {}).get("label_ids")
        self._publish(fresh)
        self._metrics.counter("serving.live.resyncs").inc()

    # -- leases / observation ------------------------------------------------

    def lease_state(self) -> tuple:
        """(snapshot, OverlayView, epoch_info) captured atomically — the
        consistent pair new jobs run against. Pumps first, so the local
        lane is as fresh as every commit visible before this call."""
        with self._lock:
            self._pump_local()
            self._pump_feed()
            view = self.overlay.view()
            info = {"epoch": self.epoch, "seq": view.seq,
                    "applied_epoch": self.applied_epoch}
            # convenience for direct model calls on the leased object
            # (serving passes the view explicitly per lease)
            self.snapshot._live_overlay = view
            return self.snapshot, view, info

    def stats(self) -> dict:
        with self._lock:
            g = self.graph
            lag_epochs = max(g.mutation_epoch - self.applied_epoch, 0)
            feed_pending = self.feed.pending() if self.feed else 0
            lag_s = self.feed.lag_seconds() if self.feed else 0.0
            m = self._metrics
            return {
                "epoch": self.epoch,
                "applied_epoch": self.applied_epoch,
                "seq": self.overlay.seq,
                "freshness": {
                    "lag_epochs": lag_epochs + feed_pending,
                    "lag_seconds": round(lag_s, 4),
                    "feed_pending": feed_pending,
                },
                "overlay": self.overlay.stats(),
                # active thresholds + merge mode (device/host) +
                # fallback reasons — the ISSUE 9 GET /live surface
                "compactor": self.compactor.policy(),
                "counters": {
                    k: m.counter_value(f"serving.live.{k}")
                    for k in _LIVE_COUNTERS},
                "apply_ms": m.histogram("serving.live.apply_ms")
                             .to_dict(),
                "compact_ms": m.histogram("serving.live.compact_ms")
                               .to_dict(),
                "compact_device_ms":
                    m.histogram("serving.live.compact_device_ms")
                     .to_dict(),
            }
