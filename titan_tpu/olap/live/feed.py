"""ChangeFeed: tail the durable user trigger log into columnar deltas.

The OLTP→OLAP freshness seam (reference: titan-core docs/TitanBus.md §3 —
``ulog_<id>`` trigger logs + StandardLogProcessorFramework): transactions
tagged with ``log_identifier`` stream their change set to the durable
log at commit; this feed registers a processor through
``core/changes.LogProcessorFramework`` with a RESUMABLE named read
marker (storage/log.KCVSLog per-bucket cursors), so a restarted feed
continues where it stopped instead of replaying history or skipping
writes.

Each delivered ``ChangeState`` becomes one :class:`DeltaBatch` — the
payload re-shaped into columnar numpy arrays (edge adds, edge/vertex
tombstones, property keys) ready for the device overlay — tagged with a
feed-local contiguous ``seq`` so the consumer can verify continuity
(a gap means batches were dropped and the base must resync).

Delivery is at-least-once (the marker is saved AFTER the callback), so
the feed deduplicates by per-sender ``(timestamp, txid)`` watermark;
messages from this instance's own rid are dropped by default — the
in-process listener already delivered them (``skip_sender``).

Backpressure: when more than ``high_watermark`` batches are pending the
log reader thread BLOCKS inside the processor until the consumer drains
below ``low_watermark`` — the durable cursor stops advancing, so no
message is lost while ingest outruns compaction; every stall increments
``serving.live.backpressure``.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from titan_tpu.core.changes import LogProcessorFramework
from titan_tpu.utils.metrics import MetricManager


@dataclass
class DeltaBatch:
    """One committed transaction's change set in columnar form."""

    seq: int                      # feed-local contiguous sequence number
    txid: int
    timestamp: int                # backend time units (commit time)
    sender: Optional[bytes]
    received_at: float            # wall clock at ingest
    # edge adds / removes: original vertex ids + edge type names
    add_out: np.ndarray = field(default_factory=lambda: np.empty(0, np.int64))
    add_in: np.ndarray = field(default_factory=lambda: np.empty(0, np.int64))
    add_type: list = field(default_factory=list)
    del_out: np.ndarray = field(default_factory=lambda: np.empty(0, np.int64))
    del_in: np.ndarray = field(default_factory=lambda: np.empty(0, np.int64))
    del_type: list = field(default_factory=list)
    # vertex adds / tombstones
    vtx_add: np.ndarray = field(default_factory=lambda: np.empty(0, np.int64))
    vtx_del: np.ndarray = field(default_factory=lambda: np.empty(0, np.int64))
    # property type names mutated (dense vertex-column invalidation)
    prop_keys: set = field(default_factory=set)

    @property
    def empty(self) -> bool:
        return not (len(self.add_out) or len(self.del_out)
                    or len(self.vtx_add) or len(self.vtx_del)
                    or self.prop_keys)

    @classmethod
    def from_state(cls, seq: int, state) -> "DeltaBatch":
        """Columnarize a ``core/changes.ChangeState``."""
        a_out: list = []
        a_in: list = []
        a_ty: list = []
        d_out: list = []
        d_in: list = []
        d_ty: list = []
        props: set = set()
        for r in state.added_relations():
            if "in" in r:
                a_out.append(r["out"])
                a_in.append(r["in"])
                a_ty.append(r["type"])
            else:
                props.add(r["type"])
        for r in state.removed_relations():
            if "in" in r:
                d_out.append(r["out"])
                d_in.append(r["in"])
                d_ty.append(r["type"])
            else:
                props.add(r["type"])
        return cls(
            seq=seq, txid=state.txid, timestamp=state.timestamp,
            sender=getattr(state, "sender", None),
            received_at=time.time(),
            add_out=np.asarray(a_out, np.int64),
            add_in=np.asarray(a_in, np.int64), add_type=a_ty,
            del_out=np.asarray(d_out, np.int64),
            del_in=np.asarray(d_in, np.int64), del_type=d_ty,
            vtx_add=np.asarray(state.added_vertices(), np.int64),
            vtx_del=np.asarray(state.removed_vertices(), np.int64),
            prop_keys=props)

    def to_payload(self) -> dict:
        """The ``core/changes.change_payload`` dict shape — what
        ``GraphSnapshot.apply_changes`` consumes. This is the
        unification seam: a batch read off the DURABLE log feeds the
        same delta-apply path the in-process listener uses, so
        refresh-style catch-up finally works for cross-instance
        writers."""
        added = [{"type": t, "out": int(o), "in": int(i)}
                 for t, o, i in zip(self.add_type, self.add_out,
                                    self.add_in)]
        added += [{"type": k, "out": 0, "value": None}
                  for k in sorted(self.prop_keys)]
        removed = [{"type": t, "out": int(o), "in": int(i)}
                   for t, o, i in zip(self.del_type, self.del_out,
                                      self.del_in)]
        return {"txid": self.txid, "time": self.timestamp,
                "added_vertices": self.vtx_add.tolist(),
                "removed_vertices": self.vtx_del.tolist(),
                "added": added, "removed": removed}


class ChangeFeed:
    """Durable change-log tail with a resumable cursor (see module doc).

    ``identifier``: the trigger-log name — writers must open their
    transactions with ``graph.new_transaction(log_identifier=...)`` for
    their commits to reach this feed (the TitanBus contract).
    ``reader_id``: names the durable read marker; None starts from
    ``start_time`` (default 0 = log head) without persistence.
    ``skip_sender``: rid bytes whose messages are dropped (defaults to
    the tailing graph's own rid — local commits arrive through the
    in-process listener instead; pass ``b""`` to keep everything).
    """

    def __init__(self, graph, identifier: str, *,
                 reader_id: Optional[str] = None,
                 start_time: Optional[int] = 0,
                 read_interval_ms: int = 50,
                 skip_sender: Optional[bytes] = None,
                 high_watermark: int = 512,
                 low_watermark: Optional[int] = None,
                 metrics: Optional[MetricManager] = None):
        self.graph = graph
        self.identifier = identifier
        self._metrics = metrics or MetricManager.instance()
        self._lock = threading.Lock()
        self._space = threading.Condition(self._lock)
        self._pending: list[DeltaBatch] = []
        self._seq = 0
        self._drained_seq = 0          # highest seq handed to poll()
        self._high = int(high_watermark)
        self._low = int(low_watermark if low_watermark is not None
                        else max(high_watermark // 2, 1))
        self._closed = False
        self._watermarks: dict = {}    # sender -> (timestamp, txid)
        if skip_sender is None:
            skip_sender = getattr(graph.backend.log_manager, "_rid", None)
        self._skip_sender = skip_sender
        self._framework = LogProcessorFramework(graph)
        builder = self._framework.add_log_processor(identifier) \
            .set_read_interval_ms(read_interval_ms) \
            .add_processor(self._on_state)
        if reader_id is not None:
            builder = builder.set_processor_identifier(reader_id)
        if start_time is not None:
            builder = builder.set_start_time(start_time)
        builder.build()

    # -- ingest (log reader thread) ------------------------------------------

    def _on_state(self, graph, txid, state) -> None:
        sender = getattr(state, "sender", None)
        if self._skip_sender and sender == self._skip_sender:
            return
        with self._lock:
            if self._closed:
                return
            # at-least-once dedup: per-sender (timestamp, txid) watermark
            # — bucket scans deliver time-ordered per sender, so a
            # redelivered message compares <= the watermark
            mark = (state.timestamp, txid)
            last = self._watermarks.get(sender)
            if last is not None and mark <= last:
                return
            self._watermarks[sender] = mark
            # backpressure: hold the reader (and therefore the durable
            # cursor) until the consumer drains — ingest must not
            # outrun compaction unboundedly
            if len(self._pending) >= self._high:
                self._metrics.counter("serving.live.backpressure").inc()
                while len(self._pending) >= self._low \
                        and not self._closed:
                    self._space.wait(0.25)
                if self._closed:
                    return
            self._seq += 1
            self._pending.append(DeltaBatch.from_state(self._seq, state))
            self._metrics.counter("serving.live.feed_batches").inc()

    # -- consumption ---------------------------------------------------------

    def poll(self, max_batches: Optional[int] = None) -> list[DeltaBatch]:
        """Pop pending batches in seq order (contiguous — the consumer
        checks ``batch.seq == last + 1`` for continuity)."""
        with self._lock:
            if max_batches is None or max_batches >= len(self._pending):
                out, self._pending = self._pending, []
            else:
                out = self._pending[:max_batches]
                del self._pending[:max_batches]
            if out:
                self._drained_seq = out[-1].seq
            self._space.notify_all()
        return out

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def lag_seconds(self) -> float:
        """Age of the oldest undrained batch (0 when drained)."""
        with self._lock:
            if not self._pending:
                return 0.0
            return max(time.time() - self._pending[0].received_at, 0.0)

    def drain_into(self, snapshot, schema, idm) -> dict:
        """Apply every pending batch to ``snapshot`` through
        ``apply_changes`` — the host-CSR catch-up path for
        cross-instance writers (device-layout caches are invalidated;
        the overlay path in plane.py avoids that). Returns the combined
        apply stats."""
        batches = self.poll()
        totals = {"added_edges": 0, "removed_edges": 0,
                  "added_vertices": 0, "removed_vertices": 0,
                  "batches": len(batches)}
        if batches:
            stats = snapshot.apply_changes(
                [b.to_payload() for b in batches], schema, idm)
            for k, v in stats.items():
                totals[k] = totals.get(k, 0) + v
        return totals

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._space.notify_all()
        # the underlying KCVSLog is shared/cached by the backend's log
        # manager; its readers stop when the graph closes the manager
