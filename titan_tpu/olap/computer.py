"""Host BSP graph computer: thread-pool scan execution of VertexPrograms.

(reference: titan-core graphdb/olap/computer/FulgoraGraphComputer.java:48-401
— per-iteration scan over all vertices executing the program, message
exchange through an in-heap vertex memory with optional combiners, loop until
``terminate``, then write mutated vertex state back in batched transactions.
This is the generality fallback; DensePrograms take the TPU engine.)
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from titan_tpu.olap.api import (MapReduce, Memory, Messenger, ScanMetrics,
                                VertexProgram, execute_map_reduce)


def _check_map_reduces(map_reduces, require=None) -> None:
    """Reject wrong stage types up front and duplicate memory keys (two
    stages sharing a key would silently overwrite each other's result)."""
    if not map_reduces:
        return
    seen = set()
    for mr in map_reduces:
        if require is not None and not isinstance(mr, require):
            names = ([r.__name__ for r in require]
                     if isinstance(require, tuple) else [require.__name__])
            raise TypeError(
                f"{type(mr).__name__} is not a supported MapReduce stage "
                f"here (need {'/'.join(names)}; DenseMapReduce runs on the "
                "TPU computer only)")
        if mr.memory_key in seen:
            raise ValueError(
                f"duplicate MapReduce memory_key {mr.memory_key!r}")
        seen.add(mr.memory_key)


class VertexMemory:
    """(reference: FulgoraVertexMemory.java:24-120) per-vertex message
    buckets with optional combiner, double-buffered across supersteps."""

    def __init__(self, combiner=None):
        self._combiner = combiner
        self._incoming: dict[int, list] = {}
        self._outgoing: dict[int, list] = {}
        self._state: dict[int, dict] = {}
        self._lock = threading.Lock()

    def send(self, target: int, message) -> None:
        with self._lock:
            if self._combiner is not None:
                cur = self._outgoing.get(target)
                if cur is None:
                    self._outgoing[target] = [message]
                else:
                    cur[0] = self._combiner(cur[0], message)
            else:
                self._outgoing.setdefault(target, []).append(message)

    def messages_for(self, vid: int) -> list:
        return self._incoming.get(vid, [])

    def complete_iteration(self) -> None:
        self._incoming = self._outgoing
        self._outgoing = {}

    def get_state(self, vid: int) -> dict:
        st = self._state.get(vid)
        if st is None:
            st = {}
            with self._lock:
                self._state.setdefault(vid, st)
                st = self._state[vid]
        return st

    def all_states(self) -> dict:
        return self._state


class ComputerVertex:
    """Vertex view handed to programs: adjacency from the tx + a mutable
    compute-state dict (reference: PreloadedVertex)."""

    __slots__ = ("_v", "_vm")

    def __init__(self, v, vm: VertexMemory):
        self._v = v
        self._vm = vm

    @property
    def id(self):
        return self._v.id

    def label(self):
        return self._v.label()

    def value(self, key, default=None):
        return self._v.value(key, default)

    def edges(self, direction, *labels):
        return self._v.edges(direction, *labels)

    def vertices(self, direction, *labels):
        return self._v.vertices(direction, *labels)

    def out(self, *labels):
        return self._v.out(*labels)

    def in_(self, *labels):
        return self._v.in_(*labels)

    def both(self, *labels):
        return self._v.both(*labels)

    def degree(self, direction, *labels):
        return self._v.degree(direction, *labels)

    # compute-scoped state
    def set_state(self, key, value):
        self._vm.get_state(self._v.id)[key] = value

    def get_state(self, key, default=None):
        return self._vm.get_state(self._v.id).get(key, default)


class HostComputerResult:
    def __init__(self, memory: Memory, states: dict, iterations: int):
        self.memory = memory
        self.states = states
        self.iterations = iterations

    def state_of(self, vid: int) -> dict:
        return self.states.get(vid, {})


class HostGraphComputer:
    def __init__(self, graph, num_threads: int = 0):
        self.graph = graph
        import os
        self.num_threads = num_threads or min(32, (os.cpu_count() or 4))

    def run_async(self, program: VertexProgram, scheduler,
                  max_iterations: int = 100, write_back: bool = False,
                  map_reduces: Optional[list] = None):
        """Delegate a host BSP run to the serving scheduler: the job
        queues behind (and shares admission with) the TPU jobs, and its
        result is this computer's HostComputerResult. Returns the Job
        handle immediately."""
        from titan_tpu.olap.api import JobSpec

        def _run():
            return self.run(program, max_iterations=max_iterations,
                            write_back=write_back,
                            map_reduces=map_reduces)
        return scheduler.submit(JobSpec(kind="callable",
                                        params={"fn": _run}))

    def run(self, program: VertexProgram, max_iterations: int = 100,
            write_back: bool = False,
            map_reduces: Optional[list] = None, *,
            checkpoint=None, checkpoint_every: int = 0,
            resume: Optional[dict] = None) -> HostComputerResult:
        """Run a host BSP program; optionally through the checkpoint
        plane (olap/recovery): ``checkpoint(iteration, payload)`` fires
        every ``checkpoint_every`` completed supersteps with the FULL
        host state (vertex states + pending messages + global memory —
        Python objects; the store persists them as a digest-checked
        pickle payload), and ``resume`` restores such a payload to
        continue the superstep loop. Host programs run per-vertex
        callbacks in a thread pool, so unlike the device kernels the
        continuation is deterministic only if the program's message
        combining is order-independent."""
        # validate BEFORE the expensive BSP loop
        _check_map_reduces(map_reduces, require=MapReduce)
        memory = Memory()
        vm = VertexMemory(program.combiner())
        program.setup(memory)
        iterations = 0
        if resume is not None:
            vm._state = dict(resume["states"])
            vm._incoming = dict(resume["messages"])
            memory._values = dict(resume["memory"])
            iterations = int(resume["iteration"])
        while True:
            memory.iteration = iterations
            tx = self.graph.new_transaction(read_only=True)
            try:
                vertices = [ComputerVertex(v, vm) for v in tx.vertices()]
                with ThreadPoolExecutor(max_workers=self.num_threads) as pool:
                    list(pool.map(
                        lambda cv: program.execute(
                            cv, Messenger(vm, cv.id), memory), vertices))
            finally:
                tx.rollback()
            vm.complete_iteration()
            iterations += 1
            terminated = (program.terminate(memory)
                          or iterations >= max_iterations)
            if (checkpoint is not None and checkpoint_every > 0
                    and not terminated
                    and iterations % checkpoint_every == 0):
                checkpoint(iterations, {"states": vm.all_states(),
                                        "messages": vm._incoming,
                                        "memory": memory._values,
                                        "iteration": iterations})
            if terminated:
                break
        # MapReduce stages over the final vertex states (reference:
        # FulgoraGraphComputer.java:192-246)
        for mr in (map_reduces or ()):
            tx = self.graph.new_transaction(read_only=True)
            try:
                memory.set(mr.memory_key, execute_map_reduce(
                    mr, (ComputerVertex(v, vm) for v in tx.vertices())))
            finally:
                tx.rollback()
        if write_back and program.state_keys:
            self._write_back(program, vm)
        return HostComputerResult(memory, vm.all_states(), iterations)

    def _write_back(self, program: VertexProgram, vm: VertexMemory,
                    batch: int = 5000) -> None:
        """Persist program state as vertex properties in batched txs
        (reference: FulgoraGraphComputer.java:248-305)."""
        items = list(vm.all_states().items())
        for i in range(0, len(items), batch):
            tx = self.graph.new_transaction()
            try:
                for vid, state in items[i:i + batch]:
                    v = tx.vertex(vid)
                    if v is None:
                        continue
                    for key in program.state_keys:
                        if key in state:
                            v.property(key, state[key])
                tx.commit()
            except BaseException:
                tx.rollback()
                raise
