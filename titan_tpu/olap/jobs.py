"""System maintenance scan jobs.

(reference: titan-core graphdb/olap/job/GhostVertexRemover.java — removes
half-deleted "ghost" vertices left by races on eventually-consistent stores:
rows that still carry relations but lost their vertex-exists marker;
IndexRepairJob/IndexRemoveJob land with the index lifecycle in
titan_tpu/index/jobs.py.)
"""

from __future__ import annotations

from titan_tpu.core.defs import Direction
from titan_tpu.olap.api import ScanJob, ScanMetrics
from titan_tpu.storage.api import SliceQuery


class GhostVertexRemover(ScanJob):
    REMOVED = "ghost-removed"

    def __init__(self, graph):
        self.graph = graph
        [self._exists_q] = graph.codec.query_type(
            graph.schema.system.vertex_exists, Direction.OUT, graph.schema)
        self._all_q = SliceQuery()
        self._pending: list[tuple[bytes, list]] = []

    def get_queries(self):
        # primary = full row; the existence check re-slices it
        return [self._all_q]

    def process(self, key: bytes, entries_by_query: dict, metrics: ScanMetrics):
        entries = entries_by_query[self._all_q]
        if not entries:
            return
        vid = self.graph.idm.id_of_key_bytes(key)
        if not self.graph.idm.is_user_vertex_id(vid):
            return
        if any(self._exists_q.contains(e.column) for e in entries):
            return  # alive
        # ghost: relations without existence — delete everything in the row
        self._pending.append((key, [e.column for e in entries]))
        metrics.increment(self.REMOVED)

    def worker_iteration_end(self, metrics: ScanMetrics):
        if not self._pending:
            return
        batch, self._pending = self._pending, []
        backend = self.graph.backend
        txh = backend.manager.begin_transaction()
        try:
            for key, columns in batch:
                backend.edge_store.store.mutate(key, [], columns, txh)
                backend.edge_store.invalidate(key)
            txh.commit()
        except BaseException:
            txh.rollback()
            raise


class VertexCountJob(ScanJob):
    """Counts live user vertices and their OUT edges — the smallest useful
    ScanJob, and the shared fixture for the split-runner suites (reference:
    titan-test diskstorage/SimpleScanJob.java:25 — the configurable job run
    both in-process and on MapReduce)."""

    VERTICES = "vertex-count"
    EDGES = "edge-count"

    def __init__(self, graph):
        self.graph = graph
        [self._exists_q] = graph.codec.query_type(
            graph.schema.system.vertex_exists, Direction.OUT, graph.schema)
        self._all_q = SliceQuery()

    def get_queries(self):
        return [self._all_q, self._exists_q]

    def process(self, key: bytes, entries_by_query: dict,
                metrics: ScanMetrics) -> None:
        from titan_tpu.core.defs import RelationCategory
        vid = self.graph.idm.id_of_key_bytes(key)
        if not self.graph.idm.is_user_vertex_id(vid):
            return
        if not entries_by_query[self._exists_q]:
            return
        metrics.increment(self.VERTICES)
        for e in entries_by_query[self._all_q]:
            rc = self.graph.codec.parse(e, self.graph.schema)
            if rc.category is RelationCategory.EDGE and \
                    rc.direction is Direction.OUT and \
                    not self.graph.schema.system.is_system(rc.type_id):
                metrics.increment(self.EDGES)


def make_vertex_count_job(graph):
    """Worker-side factory for the split runners (ScanJobSpec target)."""
    return VertexCountJob(graph)


def remove_ghost_vertices(graph, num_threads: int = 2) -> int:
    """Run the ghost remover over the edgestore; returns vertices removed."""
    from titan_tpu.storage.scan import StandardScanner
    job = GhostVertexRemover(graph)
    metrics = StandardScanner(graph.backend.edge_store.store,
                              graph.backend.manager).execute(
        job, graph=graph, num_threads=num_threads)
    return metrics.get(GhostVertexRemover.REMOVED)
