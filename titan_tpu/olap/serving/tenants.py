"""Per-tenant attribution + quota admission for the serving plane.

The dimension ISSUE 8 adds under the job scheduler: every job belongs
to a tenant (``JobSpec.tenant``; absent/empty falls back to
``"default"`` everywhere — wire envelopes, traces, metrics — never a
KeyError), and the scheduler accounts the resources its execution
actually consumed to that tenant:

* **queue-ms** — submit → first start, sampled once per job;
* **device-seconds** — batch wall time split evenly across the K fused
  jobs (the shared level loop serves all K at once, so an even split is
  the amortization-aware attribution);
* **HBM byte-seconds** — the leased graph image's ledger bytes × batch
  wall time, split across the K jobs sharing the image;
* **replayed rounds** — recovery-plane work re-executed on the tenant's
  behalf after crashes.

``TenantAccounting`` is the authoritative store behind ``GET /tenants``
(the labeled metric children mirror the countable parts into the
Prometheus plane). ``TenantQuota`` holds per-tenant admission limits,
checked at ``submit()`` BEHIND A FLAG (``JobScheduler(
enforce_quotas=True)``, default off): with enforcement off a violating
submit is still admitted but counted ``serving.tenant.throttled``
(shadow mode — admission control lands observable-first); with it on
the submit raises ``QuotaExceeded`` (HTTP 429) and counts
``serving.tenant.rejected``.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

#: the tenant every unattributed job belongs to
DEFAULT_TENANT = "default"


def effective_tenant(value) -> str:
    """``JobSpec.tenant`` → the accounting/label tenant: absent or
    empty falls back to ``DEFAULT_TENANT``; anything else is
    stringified (the wire may send numbers)."""
    if value is None or value == "":
        return DEFAULT_TENANT
    return str(value)


class QuotaExceeded(ValueError):
    """Submit refused by a tenant quota (only with enforcement on).
    A ValueError so in-process callers get the admission-error
    taxonomy; the HTTP layer maps it to 429 + ``retryable: true`` —
    the same request may succeed once the tenant's load drains."""


@dataclass(frozen=True)
class TenantQuota:
    """Per-tenant admission limits; ``None`` = unlimited.

    ``max_in_flight`` caps concurrently admitted (non-terminal) jobs;
    ``max_hbm_bytes`` refuses NEW submits while the tenant's running
    jobs hold more than this many ledger bytes (attributed per batch
    share); ``max_device_seconds`` is a cumulative budget — once the
    tenant has burned it, further submits are refused until the
    scheduler (and its accounting) is recreated."""

    max_in_flight: Optional[int] = None
    max_hbm_bytes: Optional[float] = None
    max_device_seconds: Optional[float] = None

    def to_wire(self) -> dict:
        return {"max_in_flight": self.max_in_flight,
                "max_hbm_bytes": self.max_hbm_bytes,
                "max_device_seconds": self.max_device_seconds}


def _row() -> dict:
    return {"in_flight": 0, "submitted": 0, "rejected": 0,
            "throttled": 0, "queue_ms": 0.0, "device_seconds": 0.0,
            "hbm_byte_seconds": 0.0, "hbm_running_bytes": 0.0,
            "rounds_replayed": 0, "by_state": {}}


class TenantAccounting:
    """Thread-safe per-tenant resource ledger (see module doc)."""

    def __init__(self):
        self._t: dict[str, dict] = {}
        self._lock = threading.Lock()

    def _get(self, tenant: str) -> dict:
        return self._t.setdefault(tenant, _row())

    # -- lifecycle ----------------------------------------------------------

    def admit(self, tenant: str, quota: Optional["TenantQuota"],
              enforce: bool) -> Optional[str]:
        """Atomic quota-check-and-admit: under ONE lock hold, evaluate
        the tenant's quota and either reserve the admission (submitted
        + in-flight move together) or — violating with ``enforce`` —
        count the rejection and reserve nothing. Returns the violation
        reason (None when within quota). The check and the reservation
        MUST be one critical section: concurrent submits racing a
        max_in_flight limit would otherwise both read "below limit"
        and both admit (the HTTP server runs handlers concurrently).
        In shadow mode (``enforce=False``) a violating submit is still
        admitted, counted throttled."""
        with self._lock:
            r = self._get(tenant)
            why = self._violation_locked(r, quota)
            if why is not None:
                if enforce:
                    r["rejected"] += 1
                    return why
                r["throttled"] += 1
            r["submitted"] += 1
            r["in_flight"] += 1
            return why

    def unadmit(self, tenant: str) -> None:
        """Back out an ``admit`` reservation for a job that was never
        actually accepted (closed-scheduler refusal lands AFTER the
        quota gate) — without polluting ``by_state``."""
        with self._lock:
            r = self._get(tenant)
            r["submitted"] = max(0, r["submitted"] - 1)
            r["in_flight"] = max(0, r["in_flight"] - 1)

    def finished(self, tenant: str, state: str,
                 rounds_replayed: int = 0) -> None:
        with self._lock:
            r = self._get(tenant)
            r["in_flight"] = max(0, r["in_flight"] - 1)
            r["by_state"][state] = r["by_state"].get(state, 0) + 1
            r["rounds_replayed"] += int(rounds_replayed)


    # -- resource attribution -----------------------------------------------

    def queue_ms(self, tenant: str, ms: float) -> None:
        with self._lock:
            self._get(tenant)["queue_ms"] += float(ms)

    def device_seconds(self, tenant: str, seconds: float) -> None:
        with self._lock:
            self._get(tenant)["device_seconds"] += float(seconds)

    def hbm_byte_seconds(self, tenant: str, byte_s: float) -> None:
        with self._lock:
            self._get(tenant)["hbm_byte_seconds"] += float(byte_s)

    def hold_hbm(self, tenant: str, nbytes: float) -> None:
        with self._lock:
            self._get(tenant)["hbm_running_bytes"] += float(nbytes)

    def drop_hbm(self, tenant: str, nbytes: float) -> None:
        with self._lock:
            r = self._get(tenant)
            r["hbm_running_bytes"] = max(
                0.0, r["hbm_running_bytes"] - float(nbytes))

    # -- reads --------------------------------------------------------------

    def violation(self, tenant: str,
                  quota: Optional[TenantQuota]) -> Optional[str]:
        """Human-readable reason the tenant's NEXT submit violates its
        quota, or None. Read-only probe (tests/diagnostics); the
        admission path uses ``admit`` so check and reservation share
        one critical section."""
        with self._lock:
            return self._violation_locked(
                self._t.get(tenant) or _row(), quota)

    @staticmethod
    def _violation_locked(r: dict,
                          quota: Optional[TenantQuota]) -> Optional[str]:
        if quota is None:
            return None
        if quota.max_in_flight is not None \
                and r["in_flight"] >= quota.max_in_flight:
            return (f"in-flight limit reached "
                    f"({r['in_flight']} >= {quota.max_in_flight})")
        if quota.max_hbm_bytes is not None \
                and r["hbm_running_bytes"] > quota.max_hbm_bytes:
            return (f"HBM limit exceeded "
                    f"({r['hbm_running_bytes']:.0f} > "
                    f"{quota.max_hbm_bytes:.0f} bytes held by running "
                    f"jobs)")
        if quota.max_device_seconds is not None \
                and r["device_seconds"] >= quota.max_device_seconds:
            return (f"device-seconds budget burned "
                    f"({r['device_seconds']:.3f}s >= "
                    f"{quota.max_device_seconds:.3f}s)")
        return None

    def stats(self) -> dict:
        """Deep-copied per-tenant rows (wire-safe)."""
        with self._lock:
            return {t: {**r, "by_state": dict(r["by_state"])}
                    for t, r in sorted(self._t.items())}
