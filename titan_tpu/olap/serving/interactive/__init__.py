"""Interactive traversal lane: OLTP-shaped point reads on the OLAP plane.

Millions of users asking ``g.V(x).out().out()``-class questions get a
dedicated sub-millisecond lane (ROADMAP #3): bounded-depth dsl chains
compile onto the batched ``[K, n]`` frontier machinery
(``compile.py`` → ``models/bfs_hybrid.frontier_bfs_batched``
``mode="hops"``), a deadline-driven micro-batcher fuses concurrent
point queries into one device dispatch (``collector.py``), and a
low-latency lane bypasses the heavy OLAP queue while flowing through
tenant quotas, tracing and the device-cost profiler
(``scheduler.py``). Batched personalized PageRank
(``models/pagerank.pagerank_personalized_batched``) rides the same
lane as the flagship recommendation workload. Wire surface: ``POST
/traverse`` (server.py); metrics: ``serving.interactive.*``
(docs/monitoring.md); unsupported chains fall back LOUDLY to the
``traversal/dsl.py`` interpreter.
"""

from titan_tpu.olap.serving.interactive.collector import (  # noqa: F401
    Collector, InteractiveRequest)
from titan_tpu.olap.serving.interactive.compile import (  # noqa: F401
    DEFAULT_MAX_DEPTH, FallbackToInterpreter, PPRPlan, TraversalPlan,
    compile_steps, compile_traversal, plan_from_wire,
    traversal_from_plan)
from titan_tpu.olap.serving.interactive.scheduler import (  # noqa: F401
    InteractiveLane)
