"""Deadline-driven micro-batcher: pack point queries into fused runs.

The low-latency queue of the interactive lane. Concurrent ``POST
/traverse`` requests land here; requests whose plans share a
``fuse_key()`` (same snapshot selection + workload family —
``interactive/compile.py``) collect into ONE pending group. A group
flushes to the lane's worker when EITHER

* it fills to ``max_fuse`` members (flushed immediately — a full
  ``[K, n]`` batch gains nothing by waiting), or
* its fuse window (``window_s``, a few ms) expires — the deadline that
  bounds the latency a lone query pays for fusion.

This is deliberately NOT the heavy OLAP heap (olap/serving/scheduler):
no priorities, no deadlines-before-start, no retry plane — a point
query that fails answers its caller with the error and is gone. The
caller's thread BLOCKS on its request event (the endpoint is
synchronous; sub-ms device time + a few-ms window), so the queue depth
is bounded by the HTTP server's handler pool.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

#: default fuse window — long enough to catch a concurrent burst from
#: many users, short enough to stay invisible next to interpreter-era
#: latencies
DEFAULT_WINDOW_S = 0.002
DEFAULT_MAX_FUSE = 16


class InteractiveRequest:
    """One caller's blocking request: plan + identity + rendezvous."""

    __slots__ = ("plan", "tenant", "submitted_at", "result", "error",
                 "wait_ms", "_done")

    def __init__(self, plan, tenant: str):
        self.plan = plan
        self.tenant = tenant
        self.submitted_at = time.time()
        self.result: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.wait_ms: float = 0.0
        self._done = threading.Event()

    def finish(self, result: Optional[dict] = None,
               error: Optional[BaseException] = None) -> None:
        self.result = result
        self.error = error
        self._done.set()

    def wait(self, timeout: Optional[float]) -> bool:
        return self._done.wait(timeout)


class _Group:
    __slots__ = ("key", "members", "due_at")

    def __init__(self, key, due_at: float):
        self.key = key
        self.members: list = []
        self.due_at = due_at


class Collector:
    """See module doc. Thread-safe; ``pop_due`` is the single worker's
    blocking drain."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S,
                 max_fuse: int = DEFAULT_MAX_FUSE):
        self.window_s = float(window_s)
        self.max_fuse = int(max_fuse)
        self._cv = threading.Condition()
        self._pending: dict = {}        # fuse_key -> _Group
        self._ready: deque = deque()    # full groups, FIFO
        self._closed = False

    def submit(self, req: InteractiveRequest) -> None:
        key = req.plan.fuse_key()
        with self._cv:
            if self._closed:
                raise RuntimeError("interactive lane is closed")
            grp = self._pending.get(key)
            if grp is None:
                grp = _Group(key, time.time() + self.window_s)
                self._pending[key] = grp
            grp.members.append(req)
            if len(grp.members) >= self.max_fuse:
                # full: flush now, don't wait out the window
                del self._pending[key]
                self._ready.append(grp)
            self._cv.notify()

    def pop_due(self) -> Optional[_Group]:
        """Block until a group is due (full, or window expired); None
        once closed AND drained — close() lets queued callers get
        answers instead of hanging."""
        with self._cv:
            while True:
                if self._ready:
                    return self._ready.popleft()
                if self._closed:
                    if self._pending:
                        _k, grp = self._pending.popitem()
                        return grp
                    return None
                now = time.time()
                due_key, earliest = None, None
                for key, grp in self._pending.items():
                    if now >= grp.due_at:
                        due_key = key
                        break
                    if earliest is None or grp.due_at < earliest:
                        earliest = grp.due_at
                if due_key is not None:
                    return self._pending.pop(due_key)
                self._cv.wait(None if earliest is None
                              else max(earliest - now, 1e-4))

    def depth(self) -> int:
        with self._cv:
            return sum(len(g.members) for g in self._pending.values()) \
                + sum(len(g.members) for g in self._ready)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
