"""InteractiveLane: the low-latency execution lane for point queries.

A dedicated worker + micro-batcher that BYPASSES the heavy OLAP heap
(no priorities, no retry plane, no checkpoints — a point query answers
in milliseconds or answers with its error) while still flowing through
the owning ``JobScheduler``'s shared planes:

* **snapshot pool + HBM ledger** — groups lease epoch-consistent
  ``(snapshot, overlay)`` pairs from the SAME pool the heavy queue
  uses, and the graph image (plus the ``out()``-orientation's reversed
  CSR) is reserved/pinned on the same ledger for the run;
* **tenant quotas** — every request passes ``TenantAccounting.admit``
  under the scheduler's quota table and enforce flag (shadow mode
  counts ``serving.tenant.throttled``, enforced violations are
  ``serving.tenant.rejected`` + ``QuotaExceeded`` → HTTP 429), and the
  fused batch wall is attributed to member tenants split over K;
* **tracing** — one trace per executed batch (trace id
  ``traverse-<seq>``, readable at ``GET /trace?job=traverse-<seq>``)
  with fuse/run spans and the shared device-cost event;
* **device-cost profiler** — each batch executes inside a profiler
  window; its compile/exec/transfer deltas land on the batch trace.

Metrics (``serving.interactive.*`` — docs/monitoring.md):
  serving.interactive.requests     admitted lane requests ({tenant})
  serving.interactive.fallbacks    loud interpreter fallbacks
                                   (uncompilable chain or a runtime
                                   FallbackToInterpreter)
  serving.interactive.batches      executed fused device runs
  serving.interactive.fuse_k       histogram: members per executed
                                   batch (occupancy — the fusion
                                   evidence)
  serving.interactive.wait_ms      histogram: fuse-window wait per
                                   request
  serving.interactive.latency_ms   histogram ({tenant}): submit →
                                   reply for compiled requests — the
                                   lane's p95 SLO SLI
                                   (``obs/slo.SLO(metric=...)``)
  serving.interactive.ppr_users    personalized-PageRank source rows
                                   served
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Optional

import numpy as np

from titan_tpu.olap.serving.interactive.collector import (
    DEFAULT_MAX_FUSE, DEFAULT_WINDOW_S, Collector, InteractiveRequest)
from titan_tpu.olap.serving.interactive.compile import (
    DEFAULT_MAX_DEPTH, FallbackToInterpreter, PPRPlan, TraversalPlan,
    reversed_chunked_csr)
from titan_tpu.olap.serving.tenants import (QuotaExceeded,
                                            effective_tenant)

_batch_seq = itertools.count(1)


class InteractiveLane:
    """See module doc. One lane per JobScheduler
    (``JobScheduler.interactive()``); independently constructible for
    tests."""

    def __init__(self, scheduler, *,
                 window_s: float = DEFAULT_WINDOW_S,
                 max_fuse: int = DEFAULT_MAX_FUSE,
                 max_depth: int = DEFAULT_MAX_DEPTH,
                 autostart: bool = True):
        self.sched = scheduler
        self._metrics = scheduler._metrics
        self.max_depth = int(max_depth)
        self.collector = Collector(window_s=window_s, max_fuse=max_fuse)
        self._closed = False
        self._worker: Optional[threading.Thread] = None
        if autostart:
            self.start()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "InteractiveLane":
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(target=self._run,
                                            name="serving-interactive",
                                            daemon=True)
            self._worker.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        self._closed = True
        self.collector.close()
        if self._worker is not None:
            self._worker.join(timeout)

    @property
    def closed(self) -> bool:
        return self._closed

    def stats(self) -> dict:
        return {"queue_depth": self.collector.depth(),
                "window_s": self.collector.window_s,
                "max_fuse": self.collector.max_fuse,
                "max_depth": self.max_depth}

    # -- submission ----------------------------------------------------------

    def submit(self, plan, tenant: Optional[str] = None,
               timeout_s: float = 30.0) -> dict:
        """Blocking point-query execution. Returns the response
        envelope; raises QuotaExceeded (enforced quota violation),
        FallbackToInterpreter (the LOUD unsupported-at-runtime path —
        the caller reruns on the dsl interpreter), or the member's
        parameter error."""
        if self._closed:
            raise RuntimeError("interactive lane is closed")
        tenant = self._admit(tenant)
        req = InteractiveRequest(plan, tenant)
        state = "failed"
        try:
            if isinstance(plan, TraversalPlan) \
                    and plan.depth > self.max_depth:
                # inside the admitted section: a depth-ceiling
                # fallback is still this tenant's traffic
                self._metrics.counter(
                    "serving.interactive.fallbacks").inc()
                state = "fallback"
                raise FallbackToInterpreter(
                    f"depth {plan.depth} past the lane ceiling "
                    f"{self.max_depth} — an analytics-depth chain "
                    "belongs on the heavy queue or the interpreter")
            self.collector.submit(req)
            if not req.wait(timeout_s):
                raise RuntimeError(
                    f"interactive request timed out after {timeout_s}s")
            if req.error is not None:
                if isinstance(req.error, FallbackToInterpreter):
                    state = "fallback"
                    self._metrics.counter(
                        "serving.interactive.fallbacks").inc()
                raise req.error
            state = "completed"
            self._metrics.histogram(
                "serving.interactive.latency_ms",
                labels={"tenant": tenant}).update(
                (time.time() - req.submitted_at) * 1e3)
            self._metrics.histogram(
                "serving.interactive.wait_ms").update(req.wait_ms)
            return req.result
        finally:
            self.sched.tenants.finished(tenant, state)

    def _admit(self, tenant: Optional[str]) -> str:
        """The lane's quota gate (shared by compiled submits and
        interpreter fallbacks): atomic tenant admission under the
        scheduler's quota table — enforced violations raise
        QuotaExceeded (HTTP 429), shadow-mode ones count throttled.
        Returns the effective tenant; the caller MUST balance with
        ``tenants.finished``."""
        tenant = effective_tenant(tenant)
        sched = self.sched
        # an enforcing autotune controller's tenant shed scales the
        # configured quota HERE too — a shed tenant must not dodge the
        # throttle by switching its flood to point queries
        quota = sched.quotas.get(tenant)
        if sched.controller is not None:
            quota = sched.controller.scaled_quota(tenant, quota)
        why = sched.tenants.admit(tenant, quota, sched.enforce_quotas)
        if why is not None and sched.enforce_quotas:
            self._metrics.counter("serving.tenant.rejected",
                                  labels={"tenant": tenant}).inc()
            raise QuotaExceeded(f"tenant {tenant!r}: {why}")
        if why is not None:
            self._metrics.counter("serving.tenant.throttled",
                                  labels={"tenant": tenant}).inc()
        self._metrics.counter("serving.interactive.requests",
                              labels={"tenant": tenant}).inc()
        return tenant

    def account_fallback(self, tenant: Optional[str] = None):
        """Admission + accounting for a COMPILE-TIME interpreter
        fallback (the server routes chains outside the compilable
        subset to the dsl interpreter): same quota gate as compiled
        submits — a tenant over its enforced quota gets 429 for
        uncompilable traffic too, not a free interpreter ride. Counts
        the fallback and returns a ``done(state)`` callable the caller
        MUST invoke exactly once after the interpreter run."""
        tenant = self._admit(tenant)
        self._metrics.counter("serving.interactive.fallbacks").inc()

        def done(state: str = "fallback") -> None:
            self.sched.tenants.finished(tenant, state)
        return done

    # -- worker --------------------------------------------------------------

    def _run(self) -> None:
        while True:
            grp = self.collector.pop_due()
            if grp is None:
                return
            try:
                self._execute(grp)
            except Exception as e:
                # NOTHING may kill the lane worker: answer every
                # member with the error and keep serving
                for r in grp.members:
                    if not r._done.is_set():
                        r.finish(error=e)

    def _execute(self, grp) -> None:
        sched = self.sched
        members = grp.members
        t_exec0 = time.time()
        for r in members:
            r.wait_ms = (t_exec0 - r.submitted_at) * 1e3
        batch_id = f"traverse-{next(_batch_seq)}"
        trace = None
        if sched.tracer.enabled:
            kind = grp.key[0]
            trace = sched.tracer.start(batch_id, "interactive",
                                       kind=kind, k=len(members))
            sched.tracer.event(batch_id, "fuse", parent=trace,
                               k=len(members),
                               window_ms=round(
                                   max(r.wait_ms for r in members), 3))
        w = sched.profiler.window() if sched.profiler is not None \
            else None
        err = None
        dispatched = False
        try:
            if isinstance(members[0].plan, PPRPlan):
                dispatched = self._run_ppr(members, batch_id)
            else:
                dispatched = self._run_traverse(members, batch_id)
        except Exception as e:
            err = e
            raise
        finally:
            wall = time.time() - t_exec0
            if err is None and dispatched:
                # executed device runs only: a group that fell back,
                # died, or had no resolvable members is not fusion
                # evidence
                self._metrics.counter(
                    "serving.interactive.batches").inc()
                self._metrics.histogram("serving.interactive.fuse_k") \
                    .update(float(len(members)))
            share = wall / len(members)
            for r in members:
                sched.tenants.device_seconds(r.tenant, share)
            if trace is not None:
                if w is not None:
                    cost = w.close()
                    w = None
                    if cost["calls"]:
                        sched.tracer.event(
                            batch_id, "device_cost", parent=trace,
                            k=len(members),
                            kernel_calls=cost["calls"],
                            compiles=cost["compiles"],
                            exec_ms=round(cost["exec_s"] * 1e3, 3),
                            h2d_bytes=cost["h2d_bytes"],
                            d2h_bytes=cost["d2h_bytes"])
                sched.tracer.end(trace,
                                 wall_ms=round(wall * 1e3, 3),
                                 **({"error": type(err).__name__}
                                    if err is not None else {}))
            if w is not None:
                w.close()
            if sched.recorder is not None:
                sched.recorder.metric_delta()

    # -- traversal groups ----------------------------------------------------

    def _run_traverse(self, members: list, batch_id: str) -> bool:
        from titan_tpu.core.defs import Direction
        from titan_tpu.models.bfs_hybrid import build_chunked_csr
        from titan_tpu.olap.serving.hbm import snapshot_csr_bytes

        sched = self.sched
        plan0: TraversalPlan = members[0].plan
        direction = plan0.direction
        labels = list(plan0.labels) if plan0.labels else None
        lease = sched.pool.acquire(labels=labels,
                                   directed=direction
                                   is not Direction.BOTH)
        with lease as snap:
            overlay = lease.overlay
            if overlay is None:
                overlay = getattr(snap, "_live_overlay", None)
            if overlay is not None and overlay.empty:
                overlay = None
            if overlay is not None and direction is not Direction.BOTH:
                # the overlay's slot bitmap and add-COO orientation
                # belong to the symmetrized live base; a directed
                # chain under live writes falls back LOUDLY
                raise FallbackToInterpreter(
                    "directed chain over a live overlay: the overlay "
                    "seam serves the symmetrized (both) orientation")
            if overlay is not None and plan0.hop_labels is not None:
                # per-level label masks ride the tombstone-bitmap seam,
                # and the overlay's add-COO edges carry labels the slot
                # mask cannot filter — mixed-label chains under live
                # writes fall back LOUDLY (frontier_bfs_batched raises
                # on the combination too; this keeps the error a
                # fallback, not a batch failure)
                raise FallbackToInterpreter(
                    "mixed-label chain over a live overlay: compact "
                    "the overlay first")
            epoch_info = lease.epoch_info \
                or {"epoch": getattr(snap, "epoch", 0)}
            # seeds: V(ids) skips unknown vertices, like the
            # interpreter's tx.vertex(i) None-filter
            runnable: list = []
            seeds: list = []
            for r in members:
                ds = []
                for vid in r.plan.start_ids:
                    try:
                        ds.append(snap.dense_of(int(vid)))
                    except (KeyError, TypeError, ValueError):
                        pass
                if ds:
                    runnable.append(r)
                    seeds.append(ds)
                else:
                    r.finish(result=self._empty_result(
                        r.plan, batch_id, len(members), epoch_info))
            if not runnable:
                return False
            # HBM admission FIRST, build second (the heavy queue's
            # order): the layout this run reads is sized host-side —
            # forward graph image for in_/both, the REVERSED layout
            # (the only resident one) for out(), its q_total a cheap
            # O(n) cumsum over in-degrees — and reserved BEFORE any
            # device bytes move, so the ledger can evict or refuse
            # while refusal is still free. An AdmissionError fails the
            # group; the finally unpins exactly what was reserved
            from titan_tpu.olap.serving.hbm import (AdmissionError,
                                                    chunked_csr_bytes)
            if direction is Direction.OUT:
                key = ("interactive-rev", id(snap))
                deg_in = np.diff(snap.indptr_in)
                q_rev = int((-(-deg_in // 8)).sum()) + 1
                nbytes = chunked_csr_bytes(snap.n, q_rev)
                handle = (snap, "_hybrid_csr_rev")
            else:
                key = id(snap)
                nbytes = snapshot_csr_bytes(snap)
                handle = snap
            try:
                sched.ledger.reserve(key, nbytes)
            except AdmissionError as e:
                for r in runnable:
                    r.finish(error=e)
                return False
            sched._evictable.setdefault(key, handle)
            g = reversed_chunked_csr(snap) \
                if direction is Direction.OUT \
                else build_chunked_csr(snap)
            # mixed-label chain (ISSUE 13): per-hop slot bitmaps over
            # the union-label lease — one bitmap per distinct hop label
            # set, threaded through the kernels as per-level masks
            level_masks = None
            if plan0.hop_labels is not None:
                from titan_tpu.olap.serving.interactive.compile import \
                    hop_label_masks
                level_masks = hop_label_masks(snap, plan0, direction)
            # per-tenant HBM accounting, exactly like the heavy
            # queue: the image bytes are HELD against each member's
            # tenant while the run is in flight (the max_hbm_bytes
            # quota view) and converted to byte-seconds after
            share = nbytes / len(runnable)
            for r in runnable:
                sched.tenants.hold_hbm(r.tenant, share)
            t0 = time.time()
            try:
                self._sweep(runnable, seeds, g, overlay, snap,
                            batch_id, len(members), epoch_info,
                            level_masks=level_masks)
            finally:
                wall = time.time() - t0
                for r in runnable:
                    sched.tenants.drop_hbm(r.tenant, share)
                    sched.tenants.hbm_byte_seconds(
                        r.tenant, share * wall)
                sched.ledger.unpin(key)
            return True

    def _sweep(self, runnable, seeds, g, overlay, snap, batch_id,
               fused_k, epoch_info, level_masks=None) -> None:
        import jax.numpy as jnp

        from titan_tpu.models.bfs import _next_pow2
        from titan_tpu.models.bfs_hybrid import frontier_bfs_batched
        from titan_tpu.ops.compaction import compact_ids

        n = g["n"]
        depths = [r.plan.depth for r in runnable]
        D = max(depths)
        K = len(runnable)
        # pad the batch to its power-of-two capacity bucket so fuse
        # occupancy never mints a fresh XLA shape; pad rows carry
        # depth 0 — the level-1 keep mask retires them before any sweep
        Kp = 1 << max(K - 1, 1).bit_length() if K > 1 else 1
        depths_p = depths + [0] * (Kp - K)

        def on_level(level, nf):
            keep = np.asarray([level <= d for d in depths_p])
            return keep if not keep.all() else None

        t0 = time.time()
        if all(len(ds) == 1 for ds in seeds):
            # the common point-query shape (one start vertex): seed on
            # DEVICE through the kernel's sources path — no [Kp, n]
            # host init array, no O(n) H2D per query
            srcs = [ds[0] for ds in seeds] + [0] * (Kp - K)
            dist, _levels, _completed = frontier_bfs_batched(
                g, srcs, max_levels=D + 1, start_level=1,
                on_level=on_level, overlay=overlay, mode="hops",
                level_masks=level_masks, return_device=True)
        else:
            # multi-start members (V(id1, id2, ...)): rarer — pay the
            # dense init upload
            init = np.zeros((Kp, n), np.int32)
            for k, ds in enumerate(seeds):
                init[k, ds] = 1
            dist, _levels, _completed = frontier_bfs_batched(
                g, [0] * Kp, max_levels=D + 1, start_level=1,
                init_dist=init, on_level=on_level, overlay=overlay,
                mode="hops", level_masks=level_masks,
                return_device=True)
        # hop-set extraction stays DEVICE-side: one [Kp] size readback,
        # then a compacted index list per id/values member — never the
        # O(n) dist row (a scale-26 row is ~270 MB through the tunnel)
        want = jnp.asarray(np.asarray(depths_p, np.int32) + 1)
        masks = dist == want[:, None]
        sizes = np.asarray(masks.sum(axis=1, dtype=jnp.int32))
        from titan_tpu.obs import devprof
        devprof.count_d2h("interactive.sizes", int(sizes.nbytes))
        exec_ms = (time.time() - t0) * 1e3
        for k, r in enumerate(runnable):
            plan: TraversalPlan = r.plan
            count = int(sizes[k])
            try:
                if plan.terminal == "count":
                    result = count
                elif count == 0:
                    result = []
                else:
                    cap = min(_next_pow2(max(count, 2)),
                              _next_pow2(max(n, 2)))
                    _c, ids_dev = compact_ids(masks[k], cap, n)
                    hopset = np.asarray(ids_dev)[:count]
                    devprof.count_d2h("interactive.hopset",
                                      int(hopset.nbytes))
                    result = self._terminal(plan, snap, hopset)
            except FallbackToInterpreter as e:
                r.finish(error=e)
                continue
            r.finish(result={"result": result, "batch": batch_id,
                             "fused_k": fused_k, "hops": plan.depth,
                             "wait_ms": round(r.wait_ms, 3),
                             "exec_ms": round(exec_ms, 3),
                             "epoch": epoch_info})

    def _empty_result(self, plan, batch_id, fused_k, epoch_info) -> dict:
        empty = 0 if plan.terminal == "count" else []
        return {"result": empty, "batch": batch_id, "fused_k": fused_k,
                "hops": plan.depth, "wait_ms": 0.0, "exec_ms": 0.0,
                "epoch": epoch_info}

    def _terminal(self, plan: TraversalPlan, snap, hopset):
        if plan.terminal == "count":
            return int(len(hopset))
        if plan.terminal == "id":
            return [int(snap.vertex_ids[i]) for i in hopset]
        key = plan.terminal[1]
        vals, present = self._vertex_column(snap, key)
        return [vals[i] for i in hopset if present[i]]

    def _vertex_column(self, snap, key: str):
        """Dense property column for a values() terminal — attached
        from the pool's graph when safe, FallbackToInterpreter when
        the snapshot can't answer faithfully (unbound snapshot, stale
        epoch, non-SINGLE cardinality — mirrors
        traversal/olap_compile's dataset-consistency guards)."""
        got = snap.vertex_values.get(key)
        if got is not None:
            return got
        graph = self.sched.pool.graph
        if graph is None or getattr(snap, "_graph", None) is None:
            raise FallbackToInterpreter(
                f"snapshot carries no {key!r} column and is not bound "
                "to a graph to build one from")
        if snap.stale:
            raise FallbackToInterpreter(
                f"snapshot went stale before the {key!r} column was "
                "attached")
        try:
            snap.attach_vertex_values(graph, [key])
        except ValueError as e:
            raise FallbackToInterpreter(str(e)) from e
        return snap.vertex_values[key]

    # -- personalized PageRank groups ---------------------------------------

    def _run_ppr(self, members: list, batch_id: str) -> bool:
        from titan_tpu.models.pagerank import (
            pagerank_personalized_batched, top_k_per_user)
        from titan_tpu.olap.serving.hbm import snapshot_csr_bytes

        sched = self.sched
        plan0: PPRPlan = members[0].plan
        labels = list(plan0.labels) if plan0.labels else None
        # dense window sweeps have no overlay seam: compacted=True
        # folds the live overlay first (the heavy queue's documented
        # pagerank/dense fallback)
        lease = sched.pool.acquire(labels=labels,
                                   directed=plan0.directed,
                                   compacted=True)
        with lease as snap:
            epoch_info = lease.epoch_info \
                or {"epoch": getattr(snap, "epoch", 0)}
            runnable, sources = [], []
            for r in members:
                try:
                    sources.append(snap.dense_of(int(r.plan.source)))
                    runnable.append(r)
                except (KeyError, TypeError, ValueError) as e:
                    r.finish(error=ValueError(
                        f"unknown ppr source {r.plan.source!r}: {e}"))
            if not runnable:
                return False
            from titan_tpu.olap.serving.hbm import AdmissionError
            key = id(snap)
            nbytes = snapshot_csr_bytes(snap)
            try:
                sched.ledger.reserve(key, nbytes)
            except AdmissionError as e:
                for r in runnable:
                    r.finish(error=e)
                return False
            sched._evictable.setdefault(key, snap)
            # per-tenant HBM hold + byte-seconds, like the heavy queue
            share = nbytes / len(runnable)
            for r in runnable:
                sched.tenants.hold_hbm(r.tenant, share)
            try:
                t0 = time.time()
                ranks, iters = pagerank_personalized_batched(
                    snap, sources, iterations=plan0.iterations,
                    damping=plan0.damping, overlay=lease.overlay)
                exec_ms = (time.time() - t0) * 1e3
            finally:
                wall = time.time() - t0
                for r in runnable:
                    sched.tenants.drop_hbm(r.tenant, share)
                    sched.tenants.hbm_byte_seconds(r.tenant,
                                                   share * wall)
                sched.ledger.unpin(key)
            self._metrics.counter("serving.interactive.ppr_users") \
                .inc(len(runnable))
            for s, r in enumerate(runnable):
                plan: PPRPlan = r.plan
                recs = top_k_per_user(
                    ranks[s:s + 1], snap.vertex_ids, k=plan.top_k,
                    exclude=[None if plan.include_source
                             else sources[s]])[0]
                r.finish(result={
                    "result": [[vid, rank] for vid, rank in recs],
                    "batch": batch_id, "fused_k": len(members),
                    "iterations": int(iters),
                    "wait_ms": round(r.wait_ms, 3),
                    "exec_ms": round(exec_ms, 3),
                    "epoch": epoch_info})
            return True
