"""Micro-traversal → batched-frontier compilation (the interactive lane).

The OLTP-shaped read lane on the OLAP plane (ROADMAP #3): bounded-depth
Gremlin point queries — ``g.V(x).out().out().dedup().id_()``-class
chains from ``traversal/dsl.py`` — lower onto the batched ``[K, n]``
frontier machinery (``models/bfs_hybrid.frontier_bfs_batched``,
``mode="hops"``) so MANY users' micro-queries fuse into ONE device
dispatch sharing every plan and edge-chunk gather.

Semantics: hops mode computes exact per-hop frontier SETS (a vertex
reached at hop h is reached again at hop h' > h when a path exists —
what BFS levels cannot express), so the compilable subset is the
set-semantics one:

    V(id, ...)                       >= 1 explicit start id
    .out(*L) | .in_(*L) | .both(*L)  1..max_depth hops, ONE direction
                                     and ONE label set for the chain
                                     (labels select a label-filtered
                                     snapshot from the pool)
    [.repeat(<hop>).times(k)]        expands to k copies of the hop
    .dedup()                         REQUIRED — the terminal dedup is
                                     what makes set semantics equal the
                                     interpreter's bulked multiset
    .id_() | .count() | .values(k)   terminal

Everything else — mixed directions, per-hop label changes, missing
dedup (path-multiplicity counts), predicates, paths — returns ``None``
from :func:`compile_steps` and the caller falls back LOUDLY to the
``dsl.py`` interpreter (``serving.interactive.fallbacks``; the seam is
``traversal/olap_compile.FallbackToInterpreter``, raised at run time
when the leased snapshot cannot answer a compiled plan faithfully).

Direction lowering: the hops-mode sweep is bottom-up — candidate ``w``
joins the next hop when one of w's CSR chunk neighbors is in the
frontier — so ``both()`` runs on the symmetrized lease's forward CSR
(overlay-aware: the live plane's key), ``in_()`` on the directed
lease's forward CSR (w's out-neighbors ARE its in_-expansion parents),
and ``out()`` on the REVERSED layout, which is free to build: the
snapshot's dst-sorted arrays are already the in-CSR
(:func:`reversed_chunked_csr` — no argsort, one O(E) layout pass).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Union

import numpy as np

from titan_tpu.core.defs import Direction
from titan_tpu.traversal.olap_compile import FallbackToInterpreter

__all__ = ["TraversalPlan", "PPRPlan", "compile_steps",
           "compile_traversal", "plan_from_wire", "traversal_from_plan",
           "reversed_chunked_csr", "hop_label_masks",
           "FallbackToInterpreter", "DEFAULT_MAX_DEPTH"]

#: default bounded-depth ceiling (LDBC IS3 is a 4-hop; anything deeper
#: is an analytics job for the heavy queue, not a point query)
DEFAULT_MAX_DEPTH = 4

_DIR_NAMES = {"out": Direction.OUT, "in": Direction.IN,
              "both": Direction.BOTH}
_NAME_OF_DIR = {v: k for k, v in _DIR_NAMES.items()}


@dataclass(frozen=True)
class TraversalPlan:
    """One compiled point query: fuses with plans sharing
    :meth:`fuse_key` (snapshot selection — direction + labels; DEPTH is
    NOT part of the key, shallower members deactivate early through the
    kernel's per-job keep mask).

    Mixed-label chains (ISSUE 13): ``hop_labels`` — a per-hop tuple of
    label tuples (length == depth) when the chain changes labels
    between hops. ``labels`` is then the UNION (the snapshot the lane
    leases) and each hop masks the union layout down to its own set
    through the kernel's per-level slot bitmaps
    (:func:`hop_label_masks` → ``frontier_bfs_batched(level_masks=)``).
    Mixed chains fuse only with identical chains (the masks are shared
    batch-wide), so ``hop_labels`` joins the fuse key."""

    start_ids: tuple
    direction: Direction
    labels: Optional[tuple]          # None = all labels (union if mixed)
    depth: int
    terminal: Union[str, tuple]      # "id" | "count" | ("values", key)
    hop_labels: Optional[tuple] = None   # per-hop label tuples (mixed)

    def fuse_key(self) -> tuple:
        return ("traverse", self.direction, self.labels,
                self.hop_labels)

    def describe(self) -> str:
        hop = _NAME_OF_DIR[self.direction]
        if self.hop_labels is not None:
            hops = "".join(f".{hop}({','.join(ls)})"
                           for ls in self.hop_labels)
            term = self.terminal if isinstance(self.terminal, str) \
                else f"values({self.terminal[1]})"
            return (f"V({','.join(str(i) for i in self.start_ids)})"
                    f"{hops}.dedup().{term}")
        labs = ",".join(self.labels) if self.labels else ""
        term = self.terminal if isinstance(self.terminal, str) \
            else f"values({self.terminal[1]})"
        return (f"V({','.join(str(i) for i in self.start_ids)})"
                f".{hop}({labs})x{self.depth}.dedup().{term}")


@dataclass(frozen=True)
class PPRPlan:
    """One user's personalized-PageRank recommendation query: fuses
    with plans sharing the iteration budget / damping / snapshot
    selection into one ``[S, n]`` vmapped run
    (``models/pagerank.pagerank_personalized_batched``)."""

    source: int                      # original vertex id
    iterations: int = 20
    damping: float = 0.85
    top_k: int = 10
    labels: Optional[tuple] = None
    directed: bool = False
    include_source: bool = False

    def fuse_key(self) -> tuple:
        return ("ppr", self.iterations, round(float(self.damping), 9),
                self.labels, self.directed)

    def describe(self) -> str:
        return (f"ppr({self.source}, it={self.iterations}, "
                f"d={self.damping}, top{self.top_k})")


def _expand_hops(steps: list, i: int, max_depth: int):
    """Consume the hop run at ``steps[i:]``: plain vsteps and
    repeat(<single vstep>).times(k). Returns (hops, next_i) or None."""
    hops: list = []
    while i < len(steps):
        name, args = steps[i][0], steps[i][1]
        if name == "vstep":
            direction, labels, kind = args
            if kind != "vertex":
                return None
            hops.append((direction, tuple(labels)))
            i += 1
        elif name == "repeat" and i + 1 < len(steps) \
                and steps[i + 1][0] == "times":
            sub, times = args[0], steps[i + 1][1][0]
            body = []
            for sname, sargs in sub._steps:
                if sname != "vstep" or sargs[2] != "vertex":
                    return None
                body.append((sargs[0], tuple(sargs[1])))
            if times < 1:
                return None
            hops.extend(h for _ in range(times) for h in body)
            i += 2
        else:
            break
        if len(hops) > max_depth:
            return None
    return hops, i


def compile_steps(steps: list,
                  max_depth: int = DEFAULT_MAX_DEPTH
                  ) -> Optional[TraversalPlan]:
    """Match a folded dsl step list against the compilable subset;
    None = interpret instead (the LOUD fallback is the caller's)."""
    if not steps or steps[0][0] != "V" or not steps[0][1]:
        return None
    got = _expand_hops(steps, 1, max_depth)
    if got is None:
        return None
    hops, i = got
    if not hops:
        return None
    directions = {h[0] for h in hops}
    label_sets = {h[1] for h in hops}
    if len(directions) != 1:
        # mixed directions would need a different CSR orientation per
        # level — the interpreter's job
        return None
    hop_labels = None
    if len(label_sets) != 1:
        # per-hop label changes compile since ISSUE 13: lease the
        # UNION-label snapshot and mask each level down to its hop's
        # set through the kernel's per-level slot bitmaps — but an
        # all-labels hop (empty set) inside a labeled chain would need
        # the unfiltered snapshot, whose extra edges no union lease
        # carries; that stays with the interpreter
        if any(not h[1] for h in hops):
            return None
        hop_labels = tuple(h[1] for h in hops)
    if i >= len(steps) or steps[i][0] != "dedup":
        # no terminal dedup = path-multiplicity semantics, which a
        # frontier SET machine cannot carry (olap_compile's count
        # vectors can — that path still exists on the tpu computer)
        return None
    i += 1
    if i >= len(steps):
        return None
    name, args = steps[i][0], steps[i][1]
    if name == "count" and i == len(steps) - 1:
        terminal = "count"
    elif name == "id" and i == len(steps) - 1:
        terminal = "id"
    elif name == "values" and i == len(steps) - 1 \
            and len(args[0]) == 1:
        terminal = ("values", args[0][0])
    else:
        return None
    if hop_labels is not None:
        labels = tuple(sorted({name for ls in hop_labels
                               for name in ls}))
    else:
        labels = label_sets.pop() or None
    return TraversalPlan(tuple(steps[0][1]), directions.pop(), labels,
                         len(hops), terminal, hop_labels=hop_labels)


def compile_traversal(t, max_depth: int = DEFAULT_MAX_DEPTH
                      ) -> Optional[TraversalPlan]:
    """Compile a dsl ``Traversal`` (folds has-into-start first, exactly
    like the execution path, so ``V(ids)``-rooted chains normalize the
    same way)."""
    from titan_tpu.traversal.dsl import Traversal
    steps = Traversal._fold_has_into_start(list(t._steps))
    return compile_steps(steps, max_depth)


def plan_from_wire(body: dict):
    """Structured ``POST /traverse`` body → plan. Raises ValueError on
    malformed requests (the 400 path). Depth is NOT gated here: the
    lane's ceiling raises FallbackToInterpreter at submit, so a
    too-deep chain still answers (loudly) via the interpreter."""
    kind = body.get("kind", "traverse")
    if kind == "ppr":
        if "source" not in body:
            raise ValueError("ppr needs 'source' (vertex id)")
        iterations = int(body.get("iterations", 20))
        if not 1 <= iterations <= 1000:
            raise ValueError("iterations must be in [1, 1000], "
                             f"got {iterations}")
        damping = float(body.get("damping", 0.85))
        if not 0.0 <= damping < 1.0:
            raise ValueError(f"damping must be in [0, 1), got {damping}")
        top_k = int(body.get("top_k", 10))
        if not 1 <= top_k <= 1000:
            # a negative/huge k would answer with (almost) the whole
            # graph — a recommendation query is bounded by contract
            raise ValueError(f"top_k must be in [1, 1000], got {top_k}")
        labels = _wire_labels(body)
        return PPRPlan(int(body["source"]),
                       iterations=iterations,
                       damping=damping,
                       top_k=top_k,
                       labels=labels,
                       directed=bool(body.get("directed", False)),
                       include_source=bool(
                           body.get("include_source", False)))
    if kind != "traverse":
        raise ValueError(f"unknown interactive kind {kind!r} "
                         "(traverse | ppr)")
    start = body.get("start")
    if not isinstance(start, (list, tuple)):
        # scalar form: a bare vertex id (0 is a valid id — no falsy
        # shortcut)
        start = [start] if start is not None else []
    if not start:
        raise ValueError("traverse needs 'start': [vertex id, ...]")
    dir_name = body.get("dir", "out")
    if dir_name not in _DIR_NAMES:
        raise ValueError(f"dir must be out|in|both, got {dir_name!r}")
    hops = int(body.get("hops", 1))
    if not 1 <= hops <= 32:
        # deeper than the lane ceiling still answers (interpreter
        # fallback), but an unbounded value would build an unbounded
        # step chain host-side — 32 is already analytics territory
        raise ValueError(f"hops must be in [1, 32], got {hops}")
    term = body.get("terminal", "id")
    if isinstance(term, dict) and "values" in term:
        terminal = ("values", str(term["values"]))
    elif term in ("id", "count"):
        terminal = term
    else:
        raise ValueError("terminal must be 'id', 'count' or "
                         "{'values': <key>}")
    labels = body.get("labels")
    hop_labels = None
    if isinstance(labels, (list, tuple)) and labels \
            and all(isinstance(x, (list, tuple)) for x in labels):
        # per-hop label form: "labels": [["a"], ["b"]] — one label set
        # per hop (the mixed-label chain seam, ISSUE 13)
        if len(labels) != hops:
            raise ValueError(
                f"per-hop labels must list one set per hop "
                f"({hops}), got {len(labels)}")
        sets = []
        for ls in labels:
            if not ls or not all(isinstance(x, str) for x in ls):
                raise ValueError(
                    "each per-hop label set must be a non-empty list "
                    f"of label names, got {ls!r}")
            sets.append(tuple(ls))
        if len(set(sets)) > 1:
            hop_labels = tuple(sets)
            wire_labels = tuple(sorted({n for ls in sets for n in ls}))
        else:
            wire_labels = sets[0]
    else:
        wire_labels = _wire_labels(body)
    return TraversalPlan(tuple(int(v) for v in start),
                         _DIR_NAMES[dir_name],
                         wire_labels,
                         hops, terminal, hop_labels=hop_labels)


def _wire_labels(body: dict) -> Optional[tuple]:
    """``labels`` must be a list of names — a bare string would
    tuple() into per-character labels the snapshot build silently
    drops, answering every query from an EMPTY edge set with 200."""
    labels = body.get("labels")
    if labels is None or labels == []:
        return None
    if not isinstance(labels, (list, tuple)) \
            or not all(isinstance(x, str) for x in labels):
        raise ValueError("labels must be a list of label names, got "
                         f"{labels!r}")
    return tuple(labels)


def traversal_from_plan(plan: TraversalPlan, g):
    """Rebuild the equivalent dsl traversal (the interpreter-fallback
    executor and the bit-equality property tests both run it)."""
    t = g.V(*plan.start_ids)
    step = {"out": "out", "in": "in_", "both": "both"}[
        _NAME_OF_DIR[plan.direction]]
    if plan.hop_labels is not None:
        for ls in plan.hop_labels:
            t = getattr(t, step)(*ls)
    else:
        labels = plan.labels or ()
        for _ in range(plan.depth):
            t = getattr(t, step)(*labels)
    t = t.dedup()
    if plan.terminal == "count":
        return t.count()
    if plan.terminal == "id":
        return t.id_()
    return t.values(plan.terminal[1])


# -- reversed device layout ---------------------------------------------------

def reversed_chunked_csr(snap) -> dict:
    """Chunked CSR of the REVERSED edges — the ``out()``-expansion
    orientation (candidate w's chunks must hold w's IN-neighbors).

    Free of any sort: the snapshot's arrays are dst-sorted, so
    ``snap.src`` IS the in-CSR payload and ``snap.indptr_in`` its
    index — one O(E) layout scatter into the 8-aligned transposed
    form, cached on the snapshot (``_hybrid_csr_rev``, dropped by
    ``_invalidate_layout_caches`` with the other device layouts)."""
    cached = getattr(snap, "_hybrid_csr_rev", None)
    if cached is not None:
        return cached
    import jax.numpy as jnp

    from titan_tpu.models.bfs_hybrid import chunked_layout

    n = snap.n
    deg = np.diff(snap.indptr_in).astype(np.int64)       # in-degree
    dstT, colstart, degc, q_total = chunked_layout(
        snap.src, snap.indptr_in, deg, n)
    from titan_tpu.obs import devprof
    devprof.count_h2d("interactive.rev_csr",
                      dstT.nbytes + 3 * (n + 1) * 4)
    out = {
        "dstT": jnp.asarray(dstT),
        "colstart": jnp.asarray(colstart.astype(np.int32)),
        "degc": jnp.asarray(np.concatenate(
            [degc, [0]]).astype(np.int32)),
        "deg": jnp.asarray(np.concatenate(
            [deg, [0]]).astype(np.int32)),
        "q_total": q_total,
        "n": n,
    }
    snap._hybrid_csr_rev = out
    return out


# -- per-hop label masks (mixed-label chains, ISSUE 13) -----------------------


def hop_label_masks(snap, plan: TraversalPlan, direction) -> list:
    """Per-hop edge-slot bitmaps for a mixed-label chain over the
    UNION-label lease: hop h's bitmap sets the bit of every slot whose
    edge label is NOT in hop h's set (1 = not a parent this level —
    the same packing as the overlay tombstone bitmap, byte = chunk
    column / bit = lane), ready for
    ``frontier_bfs_batched(level_masks=)``.

    Built on whichever layout the chain sweeps — the forward chunked
    CSR (``both``/``in_``: payload in ``out_csr`` order, labels
    permuted through the cached ``_out_csr_order``) or the REVERSED
    layout (``out()``: payload in the snapshot's native dst-sorted
    order, labels align directly). Hops sharing a label set share one
    bitmap; masks cache on the snapshot per (direction, hop chain) and
    upload once (the devprof ``interactive.label_masks`` H2D site).

    Raises FallbackToInterpreter when the lease carries no label codes
    (an unlabeled snapshot cannot answer a label-filtered chain
    faithfully)."""
    if snap.labels is None:
        raise FallbackToInterpreter(
            "mixed-label chain over a snapshot without label codes")
    cache = getattr(snap, "_hop_label_masks", None)
    if cache is None:
        cache = snap._hop_label_masks = {}
    key = (direction, plan.hop_labels)
    got = cache.get(key)
    if got is not None:
        return got
    import jax.numpy as jnp

    n = snap.n
    from titan_tpu.models.bfs_hybrid import layout_slot_positions
    if direction is Direction.OUT:
        # reversed layout: payload is snap.src in native dst-sorted
        # order — labels align 1:1
        deg = np.diff(snap.indptr_in).astype(np.int64)
        pos, colstart, _degc = layout_slot_positions(
            snap.indptr_in, deg, n)
        labs = snap.labels
    else:
        _dst_by_src, indptr_out = snap.out_csr()
        deg = snap.out_degree.astype(np.int64)
        pos, colstart, _degc = layout_slot_positions(
            indptr_out, deg, n)
        labs = snap.labels[snap._out_csr_order]
    q_total = int(colstart[-1]) + 1
    name_of = snap.label_names
    code_of = {v: k for k, v in name_of.items()}
    masks: list = []
    by_set: dict = {}
    total_bytes = 0
    for ls in plan.hop_labels:
        dev = by_set.get(ls)
        if dev is None:
            codes = [code_of[name] for name in ls if name in code_of]
            dead = ~np.isin(labs, np.asarray(codes, np.int32))
            tomb = np.zeros(q_total, np.uint8)
            p = pos[dead]
            np.bitwise_or.at(tomb, p >> 3,
                             np.uint8(1) << (p & 7).astype(np.uint8))
            dev = jnp.asarray(tomb)
            by_set[ls] = dev
            total_bytes += tomb.nbytes
        masks.append(dev)
    if total_bytes:
        from titan_tpu.obs import devprof
        devprof.count_h2d("interactive.label_masks", total_bytes)
    cache[key] = masks
    return masks
