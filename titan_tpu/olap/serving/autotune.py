"""Closed-loop autotuning: the telemetry plane drives the serving knobs.

ROADMAP #4 — PR 6/8/10 built the signals (SLO burn rates, per-tenant
device-seconds, per-kernel device cost, queue depth, overlay fill,
replay cost) but every control knob was static YAML. This module closes
the loop with a :class:`Controller` the :class:`JobScheduler` owns: on
a fixed tick (injectable clock, like ``obs/slo.py``) it reads its
signals EXCLUSIVELY through the existing metric/SLO registries and
applies bounded, hysteresis-guarded rules to the knobs:

* **batch K** (``batcher.target_k``) — grow the batcher's target K
  while recent batch occupancy runs near the current target and no p95
  burn is spending budget; shrink it back when occupancy collapses.
  Steps are multiplicative (×2 / ÷2), clamped to ``[k_min, k_cap]``,
  one move per cooldown window.
* **tenant shed / restore** (``tenant.quota_scale.<tenant>``) — when an
  SLO burn spikes past ``shed_burn``, halve the quota scale of the
  biggest recent device-seconds consumer that no objective protects
  (quotas already answer retryable 429s — the controller flips a SCALE
  on the configured quota, never hard state); when every burn recedes
  under ``restore_burn``, scales double back toward 1.0, one tenant per
  tick.
* **compaction trigger** (``live.compact``) — predict the device-merge
  wall from devprof-measured per-row merge cost × (base + overlay)
  rows, weigh it against the overlay scan penalty the current job rate
  pays per tick, and trigger the epoch fold when deferring costs more
  than merging — instead of waiting for the plane's fixed fill
  fraction.
* **checkpoint cadence** (``recovery.checkpoint_every``, stretch) —
  Young's approximation ``every ≈ sqrt(2 · c · R)`` from the measured
  checkpoint commit cost ``c`` (in rounds, via the device round wall)
  and the measured replay-per-failure ``R``; applied as the default
  cadence for retryable jobs that did not pick their own.

**Shadow mode is the default** (``JobScheduler(autotune=...)`` /
``TITAN_TPU_AUTOTUNE``; ``"enforce"`` opts in): decisions are computed,
journaled and exported, but NO knob moves — serving behavior and every
pre-existing metric family stay byte-identical with the controller off
(regression-pinned in tests/test_autotune.py). Signal reads are
strictly non-creating (``MetricManager.histogram_stats`` & friends) so
shadow observation cannot mint registry entries either.

**Every decision is explainable from the journal alone**: each entry
carries the full signal snapshot the rules consumed (knob state
included), the rule id, old→new value, the rule parameters and the
cooldown it armed — :func:`replay` re-runs the SAME pure rule functions
on a journaled entry and must reproduce its decision (the
"explainable" guarantee, pinned by the replay test). The journal is
bounded (oldest dropped, counted); it surfaces via ``GET /controller``,
rides in flight-recorder postmortem bundles (``state.controller``), is
stitched as ``controller`` spans into the traces of jobs running under
freshly-applied decisions, and exports as ``controller.*`` labeled
metrics (docs/monitoring.md).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Optional

from titan_tpu.olap.serving.tenants import TenantQuota
from titan_tpu.utils.metrics import MetricManager

MODES = ("shadow", "enforce")

#: knob identifiers (journal ``knob`` field; tenant scales append the
#: tenant: ``tenant.quota_scale.<tenant>``)
KNOB_K = "batcher.target_k"
KNOB_SCALE = "tenant.quota_scale"
KNOB_COMPACT = "live.compact"
KNOB_CKPT = "recovery.checkpoint_every"
KNOB_FLEET = "fleet.routing_weight"

#: rule parameter defaults. Every decision records the EFFECTIVE params
#: it was evaluated under, so a journaled entry replays bit-equal even
#: after the controller is reconfigured.
DEFAULT_PARAMS = {
    # batch-K rule
    "k_min": 1,
    "k_cap": 32,
    "grow_occupancy": 0.9,     # recent mean K >= frac * target → grow
    "shrink_occupancy": 0.25,  # recent mean K <= frac * target → shrink
    "burn_ceiling": 1.0,       # any p95 burn above this blocks growth
    "k_cooldown_s": 10.0,
    # tenant shed/restore rule
    "shed_burn": 2.0,          # fast-window burn that triggers a shed
    "restore_burn": 0.5,       # every burn under this → restore
    "scale_min": 0.25,         # shed floor (scales halve per decision)
    "shed_cooldown_s": 10.0,
    # compaction rule
    "compact_min_rows": 64,    # overlay rows before the rule engages
    "compact_cooldown_s": 5.0,
    "overlay_us_per_row": 0.5,  # per-job overlay scan penalty model
    "merge_us_per_row": 0.05,   # merge-cost fallback when unmeasured
    # checkpoint-cadence rule
    "ckpt_min_every": 1,
    "ckpt_max_every": 64,
    "ckpt_cooldown_s": 30.0,
    # fleet routing-weight rule (olap/fleet: the router's controller
    # feeds a "fleet" signal block; the scheduler-side controller never
    # produces one, so this rule is inert there)
    "fleet_spread_high": 1.0,   # (max-min)/mean depth that biases harder
    "fleet_spread_low": 0.25,   # spread under which the bias decays back
    "fleet_weight_cap": 8.0,
    "fleet_cooldown_s": 5.0,
}

DEFAULT_TICK_S = 1.0
DEFAULT_JOURNAL_CAP = 256


def resolve_mode(value) -> str:
    """``JobScheduler(autotune=)`` / TITAN_TPU_AUTOTUNE → a mode:
    ``"off"`` (no controller), ``"shadow"`` (default) or
    ``"enforce"``."""
    if value is None or value == "":
        return "shadow"
    v = str(value).strip().lower()
    if v in ("0", "false", "off", "none", "disabled"):
        return "off"
    if v in ("1", "true", "on", "enforce", "enforced"):
        return "enforce"
    if v in ("shadow", "default"):
        return "shadow"
    raise ValueError(f"autotune mode {value!r} not in "
                     f"('off', 'shadow', 'enforce')")


# -- pure rules --------------------------------------------------------------
#
# Each rule is a pure function of (signals, knob state, params) →
# proposals. tick() and replay() call the SAME functions — this is what
# makes every journal entry reconstructible from its snapshot alone.


def _rule_batch_k(sig: dict, knobs: dict, p: dict) -> list:
    occ = sig.get("occupancy") or {}
    recent = occ.get("recent_mean")
    if recent is None:
        return []                 # no executed batch since last tick
    k = int(knobs["target_k"])
    burn = float(sig.get("burn_max") or 0.0)
    if recent >= p["grow_occupancy"] * k and burn <= p["burn_ceiling"] \
            and k < p["k_cap"]:
        return [{"rule": "batch_k.grow", "knob": KNOB_K, "old": k,
                 "new": min(int(p["k_cap"]), k * 2),
                 "why": (f"recent occupancy {recent:.2f} >= "
                         f"{p['grow_occupancy']:.2f}*K={k} and max burn "
                         f"{burn:.3f} <= {p['burn_ceiling']:.2f}")}]
    if recent <= p["shrink_occupancy"] * k and k > p["k_min"]:
        return [{"rule": "batch_k.shrink", "knob": KNOB_K, "old": k,
                 "new": max(int(p["k_min"]), k // 2),
                 "why": (f"recent occupancy {recent:.2f} <= "
                         f"{p['shrink_occupancy']:.2f}*K={k}")}]
    return []


def _rule_tenant(sig: dict, knobs: dict, p: dict) -> list:
    burn = float(sig.get("burn_max") or 0.0)
    scales = knobs.get("scales") or {}
    if burn >= p["shed_burn"]:
        protected = set(sig.get("protected_tenants") or ())
        deltas = sig.get("tenant_device_s_delta") or {}
        tens = sig.get("tenants") or {}
        cands = []
        for t, row in tens.items():
            if t in protected or scales.get(t, 1.0) <= p["scale_min"]:
                continue
            d = float(deltas.get(t, 0.0))
            if d > 0 or row.get("in_flight", 0) > 0:
                cands.append((-d, t))
        if not cands:
            return []
        cands.sort()              # biggest recent consumer, then name
        t = cands[0][1]
        old = scales.get(t, 1.0)
        return [{"rule": "tenant.shed", "knob": f"{KNOB_SCALE}.{t}",
                 "old": old, "new": max(p["scale_min"], old / 2),
                 "tenant": t,
                 "why": (f"burn {burn:.3f} ({sig.get('burn_max_slo')}) "
                         f">= shed_burn {p['shed_burn']:.2f}; tenant "
                         f"{t!r} is the largest unprotected consumer "
                         f"(+{float((sig.get('tenant_device_s_delta') or {}).get(t, 0.0)):.4f} dev-s)")}]
    if burn <= p["restore_burn"]:
        for t in sorted(scales):
            old = scales[t]
            if old < 1.0:
                return [{"rule": "tenant.restore",
                         "knob": f"{KNOB_SCALE}.{t}", "old": old,
                         "new": min(1.0, old * 2), "tenant": t,
                         "why": (f"max burn {burn:.3f} <= restore_burn "
                                 f"{p['restore_burn']:.2f}")}]
    return []


def _rule_compact(sig: dict, knobs: dict, p: dict) -> list:
    live = sig.get("live")
    if not live:
        return []
    rows = int(live.get("overlay_rows") or 0) \
        + int(live.get("tombs") or 0)
    if rows < p["compact_min_rows"]:
        return []
    merge_us = live.get("merge_us_per_row")
    if merge_us is None:
        merge_us = p["merge_us_per_row"]
    base = int(live.get("base_edges") or 0)
    merge_ms = float(merge_us) * (base + rows) / 1e3
    jobs = int(sig.get("jobs_delta") or 0)
    defer_ms = rows * p["overlay_us_per_row"] / 1e3 * jobs
    if defer_ms >= merge_ms:
        return [{"rule": "live.compact", "knob": KNOB_COMPACT,
                 "old": "deferred", "new": "compact",
                 "why": (f"predicted merge {merge_ms:.3f}ms "
                         f"({merge_us:.4f}us/row x {base + rows} rows) "
                         f"<= one tick's overlay scan penalty "
                         f"{defer_ms:.3f}ms ({rows} rows x {jobs} "
                         f"jobs)")}]
    return []


def _rule_ckpt(sig: dict, knobs: dict, p: dict) -> list:
    rec = sig.get("recovery") or {}
    if not rec.get("retries_delta"):
        return []                 # cadence updates only on failure news
    c_ms = rec.get("checkpoint_ms_mean")
    r_ms = rec.get("round_ms_mean")
    retries = int(rec.get("retries_delta") or 0)
    replayed = int(rec.get("replayed_delta") or 0)
    if not c_ms or not r_ms or retries <= 0 or replayed <= 0:
        return []
    cost_rounds = float(c_ms) / float(r_ms)     # checkpoint cost, rounds
    replay_per_failure = replayed / retries     # measured MTBF proxy
    every = int(round(math.sqrt(2.0 * cost_rounds * replay_per_failure)))
    every = max(int(p["ckpt_min_every"]),
                min(int(p["ckpt_max_every"]), every))
    old = int(knobs.get("checkpoint_every") or 0)
    if every == old:
        return []
    return [{"rule": "recovery.cadence", "knob": KNOB_CKPT, "old": old,
             "new": every,
             "why": (f"Young: sqrt(2 x {cost_rounds:.3f} ckpt-rounds x "
                     f"{replay_per_failure:.1f} replay/failure) -> "
                     f"every {every}")}]


def _rule_fleet(sig: dict, knobs: dict, p: dict) -> list:
    """Fleet routing-weight rule (olap/fleet, ISSUE 19): the router's
    controller injects a ``fleet`` signal block — per-replica in-flight
    ``depth_spread`` ((max-min)/mean). A wide spread means the weighted
    pick is not steering hard enough toward idle replicas: double the
    ``depth`` weight (capped); a collapsed spread decays it back toward
    the neutral 1.0. Scheduler-side controllers never collect a
    ``fleet`` block, so the rule is inert there by construction."""
    fl = sig.get("fleet")
    if not fl:
        return []
    spread = fl.get("depth_spread")
    if spread is None:
        return []
    spread = float(spread)
    weights = knobs.get("fleet_weights") or {}
    w = float(weights.get("depth", 1.0))
    if spread >= p["fleet_spread_high"] and w < p["fleet_weight_cap"]:
        new = min(float(p["fleet_weight_cap"]), w * 2)
        return [{"rule": "fleet.rebalance",
                 "knob": f"{KNOB_FLEET}.depth", "old": w, "new": new,
                 "signal": "depth",
                 "why": (f"in-flight depth spread {spread:.2f} >= "
                         f"{p['fleet_spread_high']:.2f}: bias routing "
                         f"harder toward idle replicas")}]
    if spread <= p["fleet_spread_low"] and w > 1.0:
        return [{"rule": "fleet.relax",
                 "knob": f"{KNOB_FLEET}.depth", "old": w,
                 "new": max(1.0, w / 2), "signal": "depth",
                 "why": (f"depth spread {spread:.2f} <= "
                         f"{p['fleet_spread_low']:.2f}: decay the "
                         f"routing bias back toward neutral")}]
    return []


#: rule id prefix → (evaluator, cooldown param) — tick and replay
#: dispatch through this one table
_RULES = (
    (_rule_batch_k, "k_cooldown_s"),
    (_rule_tenant, "shed_cooldown_s"),
    (_rule_compact, "compact_cooldown_s"),
    (_rule_ckpt, "ckpt_cooldown_s"),
    (_rule_fleet, "fleet_cooldown_s"),
)


def evaluate(sig: dict, knobs: dict, params: dict) -> list:
    """Run every rule over one signal snapshot — pure, cooldown-blind.
    Returns proposal dicts (rule / knob / old / new / why)."""
    out = []
    for fn, cool in _RULES:
        for prop in fn(sig, knobs, params):
            prop["cooldown_s"] = float(params[cool])
            out.append(prop)
    return out


def replay(entry: dict) -> Optional[dict]:
    """Re-derive a journaled decision from its own snapshot — the
    explainability contract. Returns the matching proposal (or None if
    the snapshot no longer produces one, which the replay test treats
    as a failure)."""
    sig = entry["signals"]
    props = evaluate(sig, sig["knobs"], entry["params"])
    for prop in props:
        if prop["rule"] == entry["rule"] and prop["knob"] == entry["knob"]:
            return prop
    return None


class Controller:
    """See module doc. One controller per scheduler (``scheduler`` may
    be None for pure-simulation tests driving injected ``signals``)."""

    def __init__(self, scheduler=None, *, mode: str = "shadow",
                 clock=None, tick_s: Optional[float] = None,
                 journal_cap: int = DEFAULT_JOURNAL_CAP,
                 metrics: Optional[MetricManager] = None,
                 tracer=None, signals=None, k_init: Optional[int] = None,
                 **params):
        if mode not in MODES:
            raise ValueError(f"mode {mode!r} not in {MODES}")
        unknown = set(params) - set(DEFAULT_PARAMS)
        if unknown:
            raise ValueError(f"unknown autotune params: {sorted(unknown)}")
        self.scheduler = scheduler
        self.mode = mode
        self.clock = clock or time.time
        self.tick_s = float(tick_s if tick_s is not None
                            else DEFAULT_TICK_S)
        self.journal_cap = int(journal_cap)
        self.params = {**DEFAULT_PARAMS, **params}
        if metrics is not None:
            self.metrics = metrics
        elif scheduler is not None:
            self.metrics = scheduler._metrics
        else:
            self.metrics = MetricManager.instance()
        self.tracer = tracer if tracer is not None else (
            scheduler.tracer if scheduler is not None else None)
        self._signals_fn = signals or self._collect
        # knob state — tracked in BOTH modes (the journal shows the
        # full trajectory either way); the system only moves in enforce
        self.target_k = int(k_init if k_init is not None
                            else scheduler.max_batch
                            if scheduler is not None else 16)
        self.scales: dict[str, float] = {}
        self.checkpoint_every = 0
        # fleet routing-weight multipliers (signal name → weight); only
        # populated on a router-owned controller whose signal source
        # injects a "fleet" block — read back via routing_weights()
        self.fleet_weights: dict[str, float] = {}
        self.ticks = 0
        self._cooldowns: dict[str, float] = {}
        self._journal: list[dict] = []
        self._dropped = 0
        self._seq = 0
        self._last_tick = self.clock()
        self._prev: dict = {}
        self._lock = threading.RLock()
        self._gauges: list = []
        self._register_gauges()

    # -- gauges --------------------------------------------------------------

    def _register_gauges(self) -> None:
        # the gauges read EFFECTIVE values (what the system actually
        # runs), never the shadow trajectory — an operator debugging
        # batch shapes must not read a K the scheduler never used
        for knob, fn in ((KNOB_K,
                          lambda: self._effective_knobs()[KNOB_K]),
                         (KNOB_CKPT,
                          lambda: self._effective_knobs()[KNOB_CKPT])):
            g = self.metrics.gauge("controller.knob.value", fn=fn,
                                   labels={"knob": knob})
            self._gauges.append((g, fn))

    def detach_gauges(self) -> None:
        """Identity-checked detach, like the SLO engine's — a closed
        scheduler's controller must not keep reading dead state on
        every scrape."""
        for g, fn in self._gauges:
            if g.fn is fn:
                g.fn = None
                g.set(0.0)
        self._gauges = []

    # -- signal collection (non-creating reads only) -------------------------

    def _collect(self) -> dict:
        """One signal snapshot off the registries. EVERY read here must
        be non-creating (``counter_value`` / ``histogram_stats`` /
        plain attribute reads): in shadow mode the controller observes,
        and observation must not mint metric entries the autotune-off
        twin would lack (the byte-identical regression)."""
        now = self.clock()
        sched = self.scheduler
        m = self.metrics
        prev = self._prev
        sig: dict = {"t": now}
        occ = m.histogram_stats("serving.batch.occupancy")
        if occ is not None:
            dc = occ["count"] - prev.get("occ_count", 0)
            dt = occ["total"] - prev.get("occ_total", 0.0)
            prev["occ_count"] = occ["count"]
            prev["occ_total"] = occ["total"]
            sig["occupancy"] = {
                "recent_mean": round(dt / dc, 4) if dc > 0 else None,
                "batches": dc, "cum_mean": round(occ["mean"], 4)}
        else:
            sig["occupancy"] = {"recent_mean": None, "batches": 0}
        sig["queue_depth"] = m.counter_value("serving.queue.depth")
        burn: dict = {}
        burn_max = 0.0
        burn_max_slo = None
        protected: list = []
        slo = sched.slo if sched is not None else None
        if slo is not None:
            for o in slo.objectives:
                if o.tenant is not None:
                    protected.append(o.tenant)
                w = min(o.windows)
                r = slo.burn_rate(o.name, w)
                burn[o.name] = {f"{w:g}s": round(r, 6)}
                if r > burn_max:
                    burn_max, burn_max_slo = r, o.name
        sig["burn"] = burn
        sig["burn_max"] = round(burn_max, 6)
        sig["burn_max_slo"] = burn_max_slo
        sig["protected_tenants"] = sorted(set(protected))
        tens: dict = {}
        deltas: dict = {}
        if sched is not None:
            for t, r in sched.tenants.stats().items():
                tens[t] = {"in_flight": r["in_flight"],
                           "device_seconds": round(r["device_seconds"],
                                                   6)}
                d = r["device_seconds"] - prev.get(("dev", t), 0.0)
                deltas[t] = round(max(0.0, d), 6)
                prev[("dev", t)] = r["device_seconds"]
        sig["tenants"] = tens
        sig["tenant_device_s_delta"] = deltas
        comp = m.counter_value("serving.jobs.completed")
        sig["jobs_delta"] = comp - prev.get("jobs", 0)
        prev["jobs"] = comp
        prof = sched.profiler if sched is not None else None
        if prof is not None:
            sig["device"] = prof.stats()
        live = sched.live if sched is not None else None
        if live is not None:
            with live._lock:
                ov = live.overlay
                base = live.snapshot.num_edges
                lv = {"overlay_rows": ov.count, "tombs": ov.tomb_count,
                      "fill": round(ov.fill_fraction(), 6),
                      "tomb_fraction": round(ov.tombstone_fraction(), 6),
                      "base_edges": int(base),
                      "fallbacks": m.counter_value(
                          "serving.live.device_merge_fallbacks")}
            cd = m.histogram_stats("serving.live.compact_device_ms")
            lv["merge_us_per_row"] = (
                round(cd["mean"] * 1e3 / max(base, 1), 6)
                if cd is not None and cd["count"] else None)
            sig["live"] = lv
        ck = m.histogram_stats("serving.recovery.checkpoint_ms")
        ex = m.histogram_stats("device.exec.ms")
        retries = m.counter_value("serving.recovery.retries")
        replayed = m.counter_value("serving.recovery.rounds_replayed")
        sig["recovery"] = {
            "retries": retries, "rounds_replayed": replayed,
            "retries_delta": retries - prev.get("retries", 0),
            "replayed_delta": replayed - prev.get("replayed", 0),
            "checkpoint_ms_mean": round(ck["mean"], 4)
            if ck is not None and ck["count"] else None,
            "round_ms_mean": round(ex["mean"], 4)
            if ex is not None and ex["count"] else None}
        prev["retries"] = retries
        prev["replayed"] = replayed
        # the knob snapshot rides IN the signals so replay() can
        # reconstruct candidate selection (scales) and diffs (old K)
        sig["knobs"] = {"target_k": self.target_k,
                        "scales": dict(self.scales),
                        "checkpoint_every": self.checkpoint_every,
                        "fleet_weights": dict(self.fleet_weights)}
        return sig

    # -- tick ----------------------------------------------------------------

    def maybe_tick(self) -> list:
        """Worker-loop entry: tick if the interval elapsed, else
        nothing. Never raises past itself — the caller is the one
        serving worker."""
        now = self.clock()
        with self._lock:
            if now - self._last_tick < self.tick_s:
                return []
        return self.tick()

    def tick(self, force: bool = False) -> list:
        """One control evaluation: collect signals, run the rules, gate
        on cooldowns, journal every decision, apply in enforce mode.
        Returns the new journal entries."""
        now = self.clock()
        applies: list = []
        with self._lock:
            if not force and now - self._last_tick < self.tick_s \
                    and self.ticks > 0:
                return []
            self._last_tick = now
            self.ticks += 1
            self.metrics.counter("controller.tick.count").inc()
            sig = self._signals_fn()
            if "knobs" not in sig:
                # injected signal sources (tests, simulations) may omit
                # the knob snapshot — stamp it in, because replay()
                # reconstructs candidate selection from it and every
                # journaled snapshot must be self-contained
                sig["knobs"] = {"target_k": self.target_k,
                                "scales": dict(self.scales),
                                "checkpoint_every": self.checkpoint_every,
                                "fleet_weights": dict(
                                    self.fleet_weights)}
            knobs = sig["knobs"]
            entries = []
            for prop in evaluate(sig, knobs, self.params):
                until = self._cooldowns.get(prop["knob"], 0.0)
                if now < until:
                    continue      # hysteresis: the knob is cooling down
                entry = self._decide(prop, sig, now)
                entries.append(entry)
                applies.append(entry)
        # enforce-mode application OUTSIDE the controller lock: a
        # compaction can hold the live plane's lock for a while, and
        # GET /controller must stay answerable meanwhile
        if self.mode == "enforce":
            for entry in applies:
                self._apply(entry)
        return entries

    def _decide(self, prop: dict, sig: dict, now: float) -> dict:
        self._seq += 1
        applied = self.mode == "enforce"
        entry = {"seq": self._seq, "t": now, "rule": prop["rule"],
                 "knob": prop["knob"], "old": prop["old"],
                 "new": prop["new"], "why": prop["why"],
                 "mode": "enforced" if applied else "shadow",
                 "applied": applied,
                 "cooldown_s": prop["cooldown_s"],
                 "cooldown_until": now + prop["cooldown_s"],
                 "params": dict(self.params),
                 "signals": sig}
        self._cooldowns[prop["knob"]] = entry["cooldown_until"]
        # knob state advances in BOTH modes so shadow journals the same
        # trajectory enforcement would walk (restore sequencing,
        # hysteresis); only _apply moves the actual system
        rule = prop["rule"]
        if rule.startswith("batch_k."):
            self.target_k = int(prop["new"])
        elif rule in ("tenant.shed", "tenant.restore"):
            t = prop["tenant"]
            if prop["new"] >= 1.0:
                self.scales.pop(t, None)
            else:
                self.scales[t] = float(prop["new"])
        elif rule == "recovery.cadence":
            self.checkpoint_every = int(prop["new"])
        elif rule.startswith("fleet."):
            s = prop["signal"]
            if prop["new"] <= 1.0:
                self.fleet_weights.pop(s, None)
            else:
                self.fleet_weights[s] = float(prop["new"])
        self._journal.append(entry)
        if len(self._journal) > self.journal_cap:
            del self._journal[0]
            self._dropped += 1
            self.metrics.counter("controller.journal.dropped").inc()
        name = "controller.decisions.applied" if applied \
            else "controller.decisions.shadowed"
        self.metrics.counter(name, labels={"rule": rule}).inc()
        if self.tracer is not None:
            # the reserved "controller" trace id holds the decision
            # timeline (like "live" holds the plane's) — enforced
            # decisions are ALSO stitched into affected job traces by
            # the scheduler's execute path
            self.tracer.event("controller", "decision", rule=rule,
                              knob=entry["knob"], old=entry["old"],
                              new=entry["new"], mode=entry["mode"],
                              why=entry["why"])
        return entry

    def _apply(self, entry: dict) -> None:
        """Move the actual knob (enforce mode only). Tenant scales are
        read by the scheduler's quota gate via :meth:`scaled_quota`;
        compaction pokes the live plane; K and cadence write scheduler
        state the worker thread owns."""
        sched = self.scheduler
        rule = entry["rule"]
        if sched is None:
            return
        if rule.startswith("batch_k."):
            sched.max_batch = int(entry["new"])
            sched.batcher.max_batch = int(entry["new"])
        elif rule == "live.compact" and sched.live is not None:
            try:
                sched.live.compact_now(why="controller")
            except Exception:
                pass              # the plane's own fallbacks are loud

    # -- knob reads (scheduler seams) ----------------------------------------

    def scaled_quota(self, tenant: str, quota):
        """The quota the admission gate should check for ``tenant``:
        the configured one, scaled down by the shed state — enforce
        mode only (shadow must not change admission), and only when a
        quota is configured (the controller scales limits, it never
        invents them)."""
        if self.mode != "enforce" or quota is None:
            return quota
        s = self.scales.get(tenant, 1.0)
        if s >= 1.0:
            return quota
        return TenantQuota(
            # floor of 1: a shed HALVES a tenant's admission, it never
            # zeroes it — int() truncation on a small limit would turn
            # "throttle" into a total outage no restore could be
            # observed through
            max_in_flight=max(1, int(quota.max_in_flight * s))
            if quota.max_in_flight is not None else None,
            max_hbm_bytes=quota.max_hbm_bytes * s
            if quota.max_hbm_bytes is not None else None,
            max_device_seconds=quota.max_device_seconds * s
            if quota.max_device_seconds is not None else None)

    def routing_weights(self) -> dict:
        """Fleet routing-weight multipliers for the olap/fleet router's
        weighted pick (signal name → weight; absent = 1.0). Empty
        outside enforce mode — shadow journals the trajectory, the
        router must keep routing neutrally."""
        if self.mode != "enforce":
            return {}
        with self._lock:
            return dict(self.fleet_weights)

    def checkpoint_every_hint(self) -> int:
        """The adaptive default cadence for retryable jobs that did not
        set their own ``checkpoint_every`` — 0 (no hint) outside
        enforce mode or before a cadence decision."""
        return self.checkpoint_every if self.mode == "enforce" else 0

    # -- observation surface -------------------------------------------------

    def journal(self) -> list:
        with self._lock:
            return list(self._journal)

    def decisions_since(self, seq: int) -> list:
        """Journal entries newer than ``seq`` (the scheduler's stitch
        watermark)."""
        with self._lock:
            return [e for e in self._journal if e["seq"] > seq]

    def _effective_knobs(self) -> dict:
        """What the SYSTEM is actually running. In enforce mode the
        controller's internal state IS the applied state; in shadow
        the real knobs never moved, so this reads the scheduler's
        live values (and no tenant is actually scaled)."""
        if self.mode == "enforce" or self.scheduler is None:
            return {KNOB_K: self.target_k,
                    KNOB_SCALE: dict(self.scales),
                    KNOB_CKPT: self.checkpoint_every,
                    KNOB_FLEET: dict(self.fleet_weights)}
        return {KNOB_K: self.scheduler.max_batch,
                KNOB_SCALE: {}, KNOB_CKPT: 0, KNOB_FLEET: {}}

    def state(self) -> dict:
        """The ``GET /controller`` envelope + the flight-recorder
        bundle's ``state.controller`` section. ``knobs`` is the
        EFFECTIVE state; in shadow mode the would-be trajectory the
        journal walked is reported separately as ``shadow_knobs`` so
        the two can never be confused."""
        with self._lock:
            out = {"mode": self.mode, "tick_s": self.tick_s,
                   "ticks": self.ticks,
                   "knobs": self._effective_knobs(),
                   "cooldowns": {k: v for k, v in
                                 sorted(self._cooldowns.items())
                                 if v > self.clock()},
                   "journal_dropped": self._dropped,
                   "decisions": list(self._journal)}
            if self.mode != "enforce":
                out["shadow_knobs"] = {
                    KNOB_K: self.target_k,
                    KNOB_SCALE: dict(self.scales),
                    KNOB_CKPT: self.checkpoint_every,
                    KNOB_FLEET: dict(self.fleet_weights)}
            return out
