"""Epoch-aware snapshot pool: jobs share snapshots, never stale ones.

One ``GraphSnapshot`` per (labels, edge_keys, directed) parameter set is
shared by every concurrent job, leased out under the snapshot
epoch/refresh() freshness contract (olap/tpu/snapshot.py):

* fresh → lease it directly;
* stale with NO active leases → ``refresh()`` in place (the delta-apply
  path — no store re-scan); a refresh that raises (delta gap racing
  build()'s scan, listener overflow, extracted edge_values) falls back
  to a full rebuild — the same retry discipline as build()'s
  epoch-verified scan;
* stale with active leases → the leased object's arrays must NOT mutate
  under a live device run, so the pool builds a REPLACEMENT snapshot and
  retires the old one (closed when its last lease is released).

The hand-out guarantee (pinned by tests/test_serving_pool.py): the
snapshot returned by ``acquire()`` has ``epoch >= graph.mutation_epoch``
as sampled at the call's entry — a new job can never observe pre-acquire
commits missing from its snapshot, no matter how writers race the
refresh.
"""

from __future__ import annotations

import threading
from typing import Optional, Sequence


class Lease:
    """Context-managed snapshot lease; ``release()`` (or ``with``) must
    run exactly once. ``overlay``/``epoch_info`` are set on LIVE leases
    (olap/live): the overlay view frozen at the same epoch as the
    snapshot — the consistent pair jobs run against — and the epoch
    descriptor reported by ``GET /jobs``."""

    __slots__ = ("snapshot", "_release", "_done", "overlay",
                 "epoch_info")

    def __init__(self, snapshot, release):
        self.snapshot = snapshot
        self._release = release
        self._done = False
        self.overlay = None
        self.epoch_info = None

    def release(self) -> None:
        if not self._done:
            self._done = True
            self._release(self.snapshot)

    def __enter__(self):
        return self.snapshot

    def __exit__(self, *exc):
        self.release()
        return False


class SnapshotPool:
    """See module doc. ``graph=None, snapshot=...`` pins one fixed
    snapshot (array-built or externally managed) that is always returned
    as-is — the epoch machinery needs a source graph.

    ``live=`` attaches a ``olap/live.LiveGraphPlane``: acquires whose
    key matches the plane's (labels, no edge_keys, directed) lease the
    plane's current (snapshot, overlay-view) pair at a consistent epoch
    instead of building/refreshing; compactions REPUBLISH — the old base
    retires when its last lease drops, exactly like the
    replace-when-leased path. Other keys fall through to the normal
    build/refresh machinery."""

    def __init__(self, graph=None, snapshot=None, on_close=None,
                 live=None):
        if live is not None and graph is None:
            graph = live.graph
        if graph is None and snapshot is None:
            raise ValueError("SnapshotPool needs a graph, a snapshot "
                             "or a live plane")
        self.graph = graph
        self._live = live
        if live is not None:
            live._republish = self._live_republish
        self._fixed = snapshot
        self._entries: dict = {}      # key -> current snapshot
        self._leases: dict = {}       # id(snap) -> count
        self._retired: dict = {}      # id(snap) -> snap awaiting close
        self._keylocks: dict = {}     # key -> builder lock (slow path)
        self._lock = threading.Lock()
        self._closed = False
        # called with each snapshot the pool permanently discards
        # (retire-close / rebuild-close / pool close) — the scheduler
        # uses it to drop the snapshot's HBM-ledger entry and device
        # caches, so dead snapshots don't stay "resident"
        self.on_close = on_close

    def _close_snap(self, snap) -> None:
        if self.on_close is not None:
            try:
                self.on_close(snap)
            except Exception:
                pass
        snap.close()

    @staticmethod
    def key_of(labels: Optional[Sequence[str]] = None,
               edge_keys: Sequence[str] = (),
               directed: bool = False) -> tuple:
        return (tuple(labels) if labels is not None else None,
                tuple(edge_keys), bool(directed))

    # -- lease plumbing -----------------------------------------------------

    def _release(self, snap) -> None:
        to_close = None
        with self._lock:
            sid = id(snap)
            left = self._leases.get(sid, 1) - 1
            if left > 0:
                self._leases[sid] = left
            else:
                self._leases.pop(sid, None)
                to_close = self._retired.pop(sid, None)
        if to_close is not None:
            self._close_snap(to_close)

    def _lease_locked(self, snap) -> Lease:
        self._leases[id(snap)] = self._leases.get(id(snap), 0) + 1
        return Lease(snap, self._release)

    # -- acquisition --------------------------------------------------------

    def _live_republish(self, old, new) -> None:
        """Plane compaction/resync hook: the previous base snapshot
        leaves the serving plane — retired while leases hold it, closed
        outright otherwise (on_close drops its HBM ledger entry and
        device caches either way)."""
        to_close = None
        with self._lock:
            if self._leases.get(id(old), 0) > 0:
                self._retired[id(old)] = old
            else:
                to_close = old
        if to_close is not None:
            self._close_snap(to_close)

    def _acquire_live(self, compacted: bool) -> Lease:
        plane = self._live
        # plane lock → pool lock is the global order (republish runs
        # under the plane lock and takes the pool lock); holding it
        # across the lease keeps the (snapshot, view) pair and the
        # lease count atomic with any concurrent compaction
        with plane._lock:
            if compacted:
                plane.compact_if_dirty()
            snap, view, info = plane.lease_state()
            with self._lock:
                if self._closed:
                    raise RuntimeError("pool is closed")
                lease = self._lease_locked(snap)
                lease.overlay = view
                lease.epoch_info = info
                return lease

    def acquire(self, labels: Optional[Sequence[str]] = None,
                edge_keys: Sequence[str] = (),
                directed: bool = False,
                compacted: bool = False) -> Lease:
        """Lease a snapshot for the given parameters whose epoch covers
        every commit visible before this call.

        Locking: the pool lock guards only the maps (so ``stats()`` and
        fast-path acquires never block behind a store scan); the SLOW
        work — build() / refresh(), minutes at bench scale — runs under
        a per-key builder lock only. A concurrent fast-path acquire
        cannot lease a snapshot mid-refresh: its epoch is stamped last,
        so the snapshot reads as stale until the refresh completes."""
        if self._fixed is not None:
            with self._lock:
                if self._closed:
                    raise RuntimeError("pool is closed")
                return self._lease_locked(self._fixed)
        from titan_tpu.olap.tpu import snapshot as snap_mod

        key = self.key_of(labels, edge_keys, directed)
        if self._live is not None and key == self._live.pool_key:
            return self._acquire_live(compacted)
        e0 = self.graph.mutation_epoch
        with self._lock:
            if self._closed:
                raise RuntimeError("pool is closed")
            snap = self._entries.get(key)
            if snap is not None and snap.epoch >= e0 and not snap.stale:
                return self._lease_locked(snap)     # fast path
            klock = self._keylocks.setdefault(key, threading.Lock())
        with klock:
            while True:
                rebuild_close = None
                with self._lock:
                    if self._closed:
                        raise RuntimeError("pool is closed")
                    snap = self._entries.get(key)
                    if snap is not None and snap.epoch >= e0 \
                            and not snap.stale:
                        return self._lease_locked(snap)
                    if snap is not None \
                            and self._leases.get(id(snap), 0) > 0:
                        # live runs hold the old arrays: retire, rebuild
                        self._retired[id(snap)] = snap
                        self._entries.pop(key, None)
                        snap = None
                if snap is None:
                    new = snap_mod.build(self.graph, labels=labels,
                                         edge_keys=edge_keys,
                                         directed=directed)
                    with self._lock:
                        self._entries[key] = new
                        # build()'s epoch-verified scan stamps an epoch
                        # >= e0 (it started after e0 was sampled)
                        return self._lease_locked(new)
                try:
                    snap.refresh()
                except (RuntimeError, NotImplementedError):
                    # delta gap / backlog overflow / edge_values: degrade
                    # to a full rebuild, NEVER a job failure. With no
                    # leases out (we hold the key lock, so no new lease
                    # can appear for this key) the rebuild happens IN
                    # PLACE — keeping the object identity AND
                    # re-anchoring its change queue at the rebuilt epoch,
                    # so a single overflow doesn't force every future
                    # refresh into a rebuild (ISSUE r9 satellite);
                    # otherwise retire-and-replace as usual.
                    with self._lock:
                        leased = self._leases.get(id(snap), 0) > 0
                    if not leased:
                        try:
                            snap.rebuild_in_place()
                            continue
                        except Exception:
                            pass     # fall through: replace wholesale
                    rebuild_close = snap
                    with self._lock:
                        if self._entries.get(key) is snap:
                            self._entries.pop(key)
                    self._close_snap(rebuild_close)
                    continue
                if snap.epoch >= e0:
                    with self._lock:
                        return self._lease_locked(snap)
                # a commit landed inside refresh(): loop and re-check

    def ready(self) -> tuple:
        """Readiness probe (``GET /healthz``, ISSUE 10): can this pool
        hand out a current-epoch snapshot right now? (ok, why) — True
        when the pool is open and holds a snapshot source: a live plane
        publishing its epoch, a fixed snapshot, or a graph to
        build/refresh from."""
        with self._lock:
            if self._closed:
                return False, "pool closed"
            if self._live is not None:
                return True, f"live plane at epoch {self._live.epoch}"
            if self._fixed is not None:
                return True, "fixed snapshot resident"
            if self.graph is not None:
                return True, (f"graph-backed "
                              f"({len(self._entries)} resident)")
            return False, "no snapshot source"

    def stats(self) -> dict:
        with self._lock:
            out = {"entries": len(self._entries),
                   # resident snapshots incl. a fixed one (the
                   # serving.pool.snapshots gauge; "entries" predates
                   # it and counts only the keyed build cache)
                   "snapshots": len(self._entries)
                   + (1 if self._fixed is not None else 0),
                   "active_leases": sum(self._leases.values()),
                   "retired": len(self._retired)}
        if self._live is not None:
            out["live_epoch"] = self._live.epoch
        return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            snaps = list(self._entries.values()) \
                + list(self._retired.values())
            self._entries.clear()
            self._retired.clear()
            self._leases.clear()
        for s in snaps:
            if s is not self._fixed:
                self._close_snap(s)
