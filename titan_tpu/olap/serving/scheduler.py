"""Concurrent job scheduler: priority queue + admission + batch worker.

The control half of the serving layer (reference seam: gremlin-server's
request executor feeding FulgoraGraphComputer — rebuilt as an explicit
queue because a TPU graph engine is throughput-bound on device
residency, not thread-bound):

* submit() enqueues a JobSpec by (priority desc, deadline asc, FIFO);
* the single worker drains batches: it pops the head job, gathers up to
  ``max_batch - 1`` more QUEUED jobs with the same batch key
  (same-snapshot BFS today), leases the snapshot from the epoch-aware
  pool, admits the group against the HBM ledger (the graph image is
  pinned for the run, largest-first eviction of idle images), and hands
  the group to the Batcher;
* cancellation (queued: immediate; running: level-boundary early-exit),
  deadlines (EXPIRED before start) and timeouts are job-level paths, so
  one stuck caller never wedges the queue;
* recovery (olap/recovery, ``checkpoint_dir=``): a RUNNING job that
  dies retryably goes RETRYING (Job.fail), requeues after its
  exponential backoff gate (``Job.not_before`` — deferred entries stay
  heap-resident and are skipped until due), and its next attempt
  resumes from the newest valid checkpoint; retries exhausted → FAILED.

Metrics (utils/metrics.MetricManager):
  serving.jobs.{submitted,completed,failed,cancelled,expired,timeout}
  serving.jobs.rejected          (submits refused by admission — closed
                                  scheduler / unknown kind; NOT counted
                                  as submitted)
  serving.queue.depth            (gauge-flagged counter, inc on enqueue
                                  / dec on pop; labeled children break
                                  the depth out by priority class so
                                  head-of-line blocking is visible)
  serving.job.latency_ms         (histogram: submit → terminal, p50/p95)
  serving.job.queue_ms           (histogram: submit → start)
  serving.batch.occupancy        (histogram: K per executed batch)
  serving.recovery.checkpoints / .checkpoint_bytes / .checkpoint_ms
  serving.recovery.invalid_checkpoints (digest-rejected at resume)
  serving.recovery.resumes / .rounds_replayed
  serving.recovery.retries / .retries_exhausted
  serving.tenant.{rejected,throttled}  (quota admissions, by tenant)
  serving.hbm.{resident_bytes,pinned_bytes} + serving.pool.snapshots
                                 (callback gauges over the ledger/pool)

Device-cost observability (titan_tpu/obs/devprof + flightrec, ISSUE
10): the scheduler installs a process-wide DeviceCostProfiler by
default (``profiling=False`` / TITAN_TPU_PROFILING=0 removes it) —
XLA compiles per static shape bucket, per-kernel device wall and
H2D/D2H bytes land on the ``device.*`` families, and each executed
batch's device cost is stitched into its jobs' traces as a
``device_cost`` span (split over K, like the device-seconds
accounting). ``flight_dir=`` (or TITAN_TPU_FLIGHT_DIR) attaches a
FlightRecorder: a bounded ring journals spans / device events /
counter deltas, and a job that entered execution and ended FAILED /
TIMEOUT / CANCELLED — or its first RETRYING transition — writes a
self-contained postmortem bundle (``job.dump_path``, ``GET
/debug/dumps``, on-demand via ``dump_debug``).

Tenancy (olap/serving/tenants, ISSUE 8): every job belongs to a tenant
(``spec.tenant``, falling back to "default"); the per-job counters and
latency/queue histograms write through {kind, tenant}-labeled children
that sum exactly into the unlabeled parents, and the scheduler accounts
queue-ms / device-seconds (batch wall split across the K fused jobs) /
HBM byte-seconds / replayed-rounds per tenant (``tenant_stats()`` →
``GET /tenants``). Per-tenant quotas check at submit() behind
``enforce_quotas`` (default OFF: violations are admitted but counted as
throttled — observable-first); ``slos=[obs.slo.SLO(...)]`` attaches the
SLO engine (``slo_report()`` → ``GET /slo``, burn-rate gauges).

Autotuning (olap/serving/autotune, ROADMAP #4): a ``Controller`` owned
by this scheduler reads the registries above on a fixed tick and
journals bounded knob decisions (batch K, tenant quota scaling,
compaction triggers, checkpoint cadence). Shadow by default —
``autotune="enforce"`` / TITAN_TPU_AUTOTUNE=enforce lets them move the
knobs; ``autotune="off"`` removes the plane. ``GET /controller`` serves
the journal; ``controller.*`` metric families export the decision flow.

Tracing (titan_tpu/obs, ISSUE r10): one trace per job (trace id ==
job id) — ``submit`` / ``queue`` / per-attempt ``attempt`` spans open
here; ``fuse`` / ``run`` / per-round ``round`` / ``checkpoint`` spans
in the batcher and recovery hooks; the terminal state stamps the root.
``GET /trace?job=<id>`` renders the tree; ``tracing=False`` (or
TITAN_TPU_TRACING=0) removes the whole plane.
"""

from __future__ import annotations

import heapq
import itertools
import os
import threading
import time
from typing import Optional

from titan_tpu.obs import devprof
from titan_tpu.obs.flightrec import FlightRecorder
from titan_tpu.obs.tracing import TraceHandle, Tracer
from titan_tpu.olap.api import JobSpec
from titan_tpu.olap.serving.batcher import Batcher, batch_key
from titan_tpu.olap.serving.hbm import (DEFAULT_BUDGET_BYTES,
                                        AdmissionError, HBMLedger,
                                        snapshot_csr_bytes)
from titan_tpu.olap.serving.jobs import Job, JobState
from titan_tpu.olap.serving.pool import SnapshotPool
from titan_tpu.olap.serving.tenants import (QuotaExceeded,
                                            TenantAccounting,
                                            effective_tenant)
from titan_tpu.utils.metrics import MetricManager

#: job kinds that execute against a pooled snapshot (everything except
#: host 'callable' delegations)
_SNAPSHOT_KINDS = ("bfs", "sssp", "pagerank", "wcc", "dense")

_KNOWN_KINDS = _SNAPSHOT_KINDS + ("callable",)


class JobScheduler:
    """One queue + one worker over one graph (or fixed snapshot)."""

    def __init__(self, graph=None, snapshot=None, *, max_batch: int = 16,
                 mesh=None,
                 hbm_budget_bytes: float = DEFAULT_BUDGET_BYTES,
                 metrics: Optional[MetricManager] = None,
                 autostart: bool = True,
                 checkpoint_dir: Optional[str] = None,
                 live=None, tracer: Optional[Tracer] = None,
                 tracing: Optional[bool] = None,
                 quotas: Optional[dict] = None,
                 enforce_quotas: bool = False,
                 slos=None, slo_clock=None,
                 profiling: Optional[bool] = None,
                 profiler=None,
                 flight_dir: Optional[str] = None,
                 flight_capacity: int = 4096,
                 interactive_window_s: Optional[float] = None,
                 interactive_max_fuse: Optional[int] = None,
                 interactive_max_depth: Optional[int] = None,
                 autotune: Optional[str] = None,
                 autotune_tick_s: Optional[float] = None,
                 autotune_clock=None,
                 autotune_params: Optional[dict] = None):
        # observability plane (titan_tpu/obs): one tracer per scheduler,
        # one trace per job (trace id == job id) — submit/queue/attempt
        # spans here, fuse/run/round/checkpoint spans in the batcher &
        # recovery hooks, all host-side. ``tracing=False`` (or env
        # TITAN_TPU_TRACING=0) removes it wholesale: jobs carry no
        # TraceHandle and every hook is a single None check.
        if tracer is None:
            if tracing is None:
                tracing = os.environ.get("TITAN_TPU_TRACING", "1") \
                    .lower() not in ("0", "false", "off")
            tracer = Tracer(enabled=tracing)
        self.tracer = tracer
        self._metrics = metrics or MetricManager.instance()
        # flight recorder (obs/flightrec): only exists when a dump
        # directory is configured — no ring, no taps, no files without
        # one. The tracer tap journals every completed span into the
        # bounded ring (round-mass tuples ride in round-span attrs)
        self.recorder = None
        if flight_dir is None:
            flight_dir = os.environ.get("TITAN_TPU_FLIGHT_DIR") or None
        if flight_dir:
            self.recorder = FlightRecorder(flight_dir,
                                           capacity=flight_capacity,
                                           metrics=self._metrics)
            self.tracer.tap = self.recorder.span_tap
        # device-cost profiler (obs/devprof): process-wide interception
        # of the jit entry points (jitcache shim + engine seams) —
        # compile-per-bucket, per-kernel device wall, H2D/D2H bytes as
        # device.* metric families; default ON, one flag removes it
        self.profiler = None
        self._own_profiler = False
        if profiler is not None:
            self.profiler = profiler
        else:
            if profiling is None:
                profiling = os.environ.get(
                    "TITAN_TPU_PROFILING", "1").lower() \
                    not in ("0", "false", "off")
            if profiling:
                self.profiler = devprof.DeviceCostProfiler(
                    metrics=self._metrics, recorder=self.recorder)
                self._own_profiler = True
        if self._own_profiler:
            self.profiler.install()
        # live plane (olap/live): jobs lease (snapshot, overlay) pairs
        # at a consistent epoch instead of refresh/rebuild churn; the
        # scheduler OWNS the plane's lifecycle once attached (close()
        # closes it) and lends it the HBM ledger so overlay growth is
        # admission-controlled
        self.live = live
        self.pool = SnapshotPool(graph, snapshot, live=live)
        # the evictable map must exist BEFORE the ledger (whose
        # on_evict callback reads it) and before the live plane's
        # hooks: the plane's pump thread is already running and can
        # fire a device-merged compaction mid-__init__
        self._evictable: dict = {}    # ledger key -> snapshot (cache drop)
        self.ledger = HBMLedger(hbm_budget_bytes, on_evict=self._evict)
        if live is not None and live._ledger is None:
            live._ledger = self.ledger
        if live is not None and getattr(live, "_tracer", None) is None:
            # the plane records apply/compaction epochs under the
            # reserved "live" trace id (GET /trace?job=live)
            live._tracer = self.tracer
        if live is not None:
            # device-merged epochs arrive ledger-resident with their
            # CSR pre-attached (no upload); register them in the
            # eviction map so an HBM eviction of the unpinned epoch
            # actually drops the device arrays
            live._on_resident = (
                lambda snap: self._evictable.setdefault(id(snap), snap))
        # mesh-aware batch placement (ISSUE 13): with a multi-device
        # mesh, batched BFS cohorts place their [K, n] state sharded
        # over "v" (K replicated) and the edge image's chunk columns
        # shard over the mesh — parallel/partition.place_batched_csr;
        # the HBM ledger (a PER-DEVICE budget) then charges the
        # per-device share (hbm.meshed_snapshot_csr_bytes)
        self.mesh = mesh
        self.batcher = Batcher(max_batch=max_batch, mesh=mesh)
        self.max_batch = max_batch
        # (self._metrics was bound before the recorder/profiler above)
        # tenancy plane (olap/serving/tenants): authoritative per-tenant
        # attribution behind GET /tenants; quotas check at submit()
        # behind the enforce flag (default OFF = shadow mode: violations
        # admitted but counted throttled)
        self.tenants = TenantAccounting()
        self.quotas = dict(quotas or {})
        self.enforce_quotas = bool(enforce_quotas)
        # first-class gauges (utils/metrics.Gauge): HBM residency and
        # pool size as live callback views. queue depth stays a counter
        # (its counter_value contract predates gauges) flagged
        # bidirectional so the Prometheus exposition types it gauge.
        # The (gauge, fn) pairs are kept so close() can neutralize the
        # callbacks: the registry may be process-global, and a closed
        # scheduler's closures would otherwise pin its pool/ledger
        # forever and keep scraping dead residency numbers
        self._metrics.counter("serving.queue.depth", gauge=True)
        self._gauges = []
        for name, fn in (
                ("serving.hbm.resident_bytes",
                 self.ledger.resident_bytes),
                ("serving.hbm.pinned_bytes", self.ledger.pinned_bytes),
                ("serving.pool.snapshots",
                 lambda: self.pool.stats()["snapshots"])):
            self._gauges.append((self._metrics.gauge(name, fn), fn))
        # SLO engine (obs/slo): declarative objectives over the labeled
        # children this scheduler writes; burn rates export as gauges
        self.slo = None
        if slos:
            from titan_tpu.obs.slo import SLOEngine
            self.slo = SLOEngine(self._metrics, slos,
                                 clock=slo_clock)
            self.slo.register_gauges()
        # closed-loop autotuning (olap/serving/autotune, ROADMAP #4):
        # the controller reads its signals off THIS scheduler's
        # registries on a fixed tick (driven from the worker loop) and
        # journals bounded, hysteresis-guarded knob decisions. Shadow
        # mode is the default — decisions are computed and journaled
        # but nothing moves; autotune="enforce" (or
        # TITAN_TPU_AUTOTUNE=enforce) lets them drive batch K, tenant
        # quota scaling, compaction triggers and checkpoint cadence.
        # autotune="off" removes the plane (no controller.* metrics).
        self.controller = None
        self._ctl_stitch_seq = 0
        if autotune is None:
            autotune = os.environ.get("TITAN_TPU_AUTOTUNE")
        from titan_tpu.olap.serving.autotune import resolve_mode
        mode = resolve_mode(autotune)
        if mode != "off":
            from titan_tpu.olap.serving.autotune import Controller
            self.controller = Controller(
                self, mode=mode, tick_s=autotune_tick_s,
                clock=autotune_clock, **(autotune_params or {}))
        # recovery plane: one store for every job's checkpoints, keyed
        # by a per-scheduler nonce + job id (job ids restart at job-1
        # per process while the store persists on disk — a restarted
        # server must never resume an OLD process's checkpoint for an
        # unrelated job); None disables capture, retries restart clean
        self.ckpt_store = None
        if checkpoint_dir is not None:
            import uuid

            from titan_tpu.olap.recovery import CheckpointStore
            self.ckpt_store = CheckpointStore(checkpoint_dir,
                                              metrics=self._metrics)
            self._ckpt_ns = uuid.uuid4().hex[:12]
        # interactive lane (olap/serving/interactive, ISSUE 11):
        # constructed lazily on the first point query — the fuse
        # window / occupancy / depth ceiling are scheduler config so a
        # server-injected scheduler pins batching for tests
        self._interactive = None
        self._interactive_cfg = {
            k: v for k, v in (("window_s", interactive_window_s),
                              ("max_fuse", interactive_max_fuse),
                              ("max_depth", interactive_max_depth))
            if v is not None}
        self._jobs: dict[str, Job] = {}
        self._heap: list = []
        self._seq = itertools.count()
        self._cv = threading.Condition()
        self._stop = False
        self._running_batch = 0
        # retired/closed snapshots must not stay ledger-resident
        self.pool.on_close = self._forget_snapshot
        self._worker: Optional[threading.Thread] = None
        if autostart:
            self.start()

    # -- lifecycle ----------------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._stop

    def start(self) -> "JobScheduler":
        if self._worker is None or not self._worker.is_alive():
            self._stop = False
            self._worker = threading.Thread(target=self._run,
                                            name="serving-scheduler",
                                            daemon=True)
            self._worker.start()
        return self

    def interactive(self):
        """The scheduler's interactive point-query lane
        (olap/serving/interactive.InteractiveLane), created on first
        use — ``POST /traverse``'s executor. Shares this scheduler's
        pool, ledger, tenant quotas, tracer and profiler."""
        with self._cv:
            if self._stop:
                raise RuntimeError("scheduler is closed")
            if self._interactive is None or self._interactive.closed:
                from titan_tpu.olap.serving.interactive import \
                    InteractiveLane
                self._interactive = InteractiveLane(
                    self, **self._interactive_cfg)
            return self._interactive

    def close(self, timeout: float = 10.0) -> None:
        with self._cv:
            # under the cv: interactive() creates the lane under this
            # same lock and refuses once _stop is set, so no lane can
            # be constructed after this read and escape the close
            self._stop = True
            lane = self._interactive
            self._cv.notify_all()
        if lane is not None:
            lane.close()
        if self._worker is not None:
            self._worker.join(timeout)
        # queued jobs fail loudly rather than hang their waiters
        # (permanent: a closing scheduler must not re-enter RETRYING)
        for job in self.jobs():
            if not job.state.terminal:
                job.fail("scheduler closed", permanent=True)
                self._finalize_metrics(job)
        self.pool.close()
        if self.live is not None:
            self.live.close()
        # detach OUR gauge callbacks (identity-checked: a successor
        # scheduler that already re-registered over the same names
        # must not be clobbered) — the gauges read 0.0 afterwards
        for g, fn in self._gauges:
            if g.fn is fn:
                g.fn = None
                g.set(0.0)
        if self.slo is not None:
            self.slo.detach_gauges()
        if self.controller is not None:
            self.controller.detach_gauges()
        # detach OUR process-wide profiler (a caller-provided one stays
        # the caller's to uninstall)
        if self._own_profiler and self.profiler is not None:
            self.profiler.uninstall()

    def _evict(self, key) -> None:
        """HBM eviction: drop the snapshot's cached device CSR (arrays
        free when the last jax reference dies). An ``(obj, attr)``
        entry drops that attribute instead — the interactive lane's
        reversed-orientation layout registers itself this way."""
        snap = self._evictable.pop(key, None)
        if isinstance(snap, tuple):
            obj, attr = snap
            if hasattr(obj, attr):
                delattr(obj, attr)
        elif snap is not None and hasattr(snap, "_hybrid_csr"):
            delattr(snap, "_hybrid_csr")

    def _forget_snapshot(self, snap) -> None:
        """Pool close hook: a retired/rebuilt snapshot leaves the HBM
        ledger (and the evictable map) instead of counting as resident
        forever — including the interactive lane's reversed-orientation
        layout riding on the same snapshot."""
        key = id(snap)
        self._evictable.pop(key, None)
        self.ledger.release(key)
        rev_key = ("interactive-rev", key)
        self._evictable.pop(rev_key, None)
        self.ledger.release(rev_key)

    # -- submission surface --------------------------------------------------

    def _job_labels(self, job: Job) -> dict:
        """The {kind, tenant} label set the per-job metric children
        carry — bounded: kind is validated at admission, tenant
        cardinality is capped by the registry's MAX_CHILDREN guard."""
        return {"kind": job.spec.kind, "tenant": job.tenant}

    def submit(self, spec: JobSpec) -> Job:
        tenant = effective_tenant(getattr(spec, "tenant", None))
        # rejected submits must NOT count as submitted (the counter
        # moves only after admission): unknown kinds and closed-
        # scheduler refusals are serving.jobs.rejected instead
        if spec.kind not in _KNOWN_KINDS:
            self._metrics.counter(
                "serving.jobs.rejected",
                labels={"kind": "unknown", "tenant": tenant}).inc()
            raise ValueError(f"unknown job kind {spec.kind!r} "
                             f"(known: {', '.join(_KNOWN_KINDS)})")
        faults = spec.params.get("faults") \
            if isinstance(spec.params, dict) else None
        if faults is not None:
            from titan_tpu.olap.recovery import FaultPlan
            if not isinstance(faults, FaultPlan):
                # an arbitrary wire value here would detonate inside
                # the fused batch's level callback and fail every
                # batchmate — reject it at admission instead
                self._metrics.counter(
                    "serving.jobs.rejected",
                    labels={"kind": spec.kind, "tenant": tenant}).inc()
                raise ValueError("params['faults'] must be a "
                                 "recovery.FaultPlan (test harness "
                                 "only, not wire-settable)")
        # tenant quota gate (olap/serving/tenants): check + reservation
        # are ONE atomic step (concurrent submits racing a max_in_flight
        # limit must not both read "below limit" and both admit).
        # Enforcement is flagged, default off — a violating submit in
        # shadow mode is admitted but counted, so admission control
        # lands observable-first. An ENFORCING autotune controller may
        # scale the configured quota down (tenant shedding) — the gate
        # checks the scaled limit, the journal explains why.
        quota = self.quotas.get(tenant)
        if self.controller is not None:
            quota = self.controller.scaled_quota(tenant, quota)
        why = self.tenants.admit(tenant, quota, self.enforce_quotas)
        if why is not None:
            if self.enforce_quotas:
                self._metrics.counter("serving.tenant.rejected",
                                      labels={"tenant": tenant}).inc()
                raise QuotaExceeded(f"tenant {tenant!r}: {why}")
            self._metrics.counter("serving.tenant.throttled",
                                  labels={"tenant": tenant}).inc()
        # from here the tenant holds an in-flight reservation: ANY
        # raise before the job is actually accepted (closed scheduler,
        # junk deadline type, recovery-plan construction, ...) must
        # back it out, or failed submits pin quota slots forever
        try:
            return self._submit_admitted(spec, faults)
        except BaseException:
            self.tenants.unadmit(tenant)
            raise

    def _submit_admitted(self, spec: JobSpec, faults) -> Job:
        """Post-quota-gate tail of ``submit``: the caller owns the
        tenant's admission reservation and backs it out if we raise."""
        job = Job(spec)
        if self.tracer.enabled:
            root = self.tracer.start(job.id, "job", kind=spec.kind,
                                     priority=spec.priority,
                                     tenant=job.tenant)
            job.trace = TraceHandle(self.tracer, job.id, root)
            job.trace.event("submit", parent=root)
        # checkpoint cadence: the spec's own setting wins; a retryable
        # job that did not pick one adopts the autotune controller's
        # measured-cost cadence when enforcement is on (hint() is 0
        # otherwise — shadow mode never changes capture behavior)
        every = spec.checkpoint_every
        if every <= 0 and spec.max_retries > 0 \
                and self.controller is not None:
            every = self.controller.checkpoint_every_hint()
        store = self.ckpt_store \
            if self.ckpt_store is not None \
            and (every > 0 or spec.idempotency_key) \
            else None
        if store is not None or faults is not None:
            from titan_tpu.olap.recovery import JobRecovery
            # fleet failover: an idempotency key names the LOGICAL job
            # across processes, so its checkpoints bypass the
            # per-scheduler nonce namespace — a redispatch of the same
            # key on another replica finds them and resumes
            key = None
            if store is not None:
                key = f"idem-{spec.idempotency_key}" \
                    if spec.idempotency_key \
                    else f"{self._ckpt_ns}-{job.id}"
            job.recovery = JobRecovery(
                store, job, every=every, faults=faults,
                metrics=self._metrics, key=key)
        if spec.deadline is not None and time.time() > spec.deadline:
            # tenant admission was already reserved by tenants.admit
            self._metrics.counter(
                "serving.jobs.submitted",
                labels=self._job_labels(job)).inc()
            job.expire()
            self._finalize_metrics(job)
            with self._cv:
                self._jobs[job.id] = job
            return job
        with self._cv:
            if self._stop:
                self._metrics.counter(
                    "serving.jobs.rejected",
                    labels=self._job_labels(job)).inc()
                # the job was never admitted: drop its just-opened
                # trace (or rejected submits would pile never-ending
                # root spans into the tracer's LRU); the quota
                # reservation is backed out by submit()'s except
                self.tracer.discard(job.id)
                raise RuntimeError("scheduler is closed")
            self._metrics.counter(
                "serving.jobs.submitted",
                labels=self._job_labels(job)).inc()
            self._jobs[job.id] = job
            if job.trace is not None:
                job.trace.queue = job.trace.start(
                    "queue", parent=job.trace.root)
            self._push_locked(job)
        return job

    def _depth(self, job: Job, n: int) -> None:
        """Queue-depth move, labeled by the job's priority class — the
        child rolls up into the unlabeled total, and the per-priority
        breakout makes head-of-line blocking visible on /metrics."""
        self._metrics.counter(
            "serving.queue.depth",
            labels={"priority": str(job.spec.priority)}).inc(n)

    def _push_locked(self, job: Job) -> None:
        """Heap insert (priority desc, deadline asc, FIFO) + depth/
        notify — under the cv lock; shared by submit() and _requeue()
        so the ordering key has exactly one definition."""
        heapq.heappush(self._heap,
                       (-job.spec.priority,
                        job.spec.deadline
                        if job.spec.deadline is not None
                        else float("inf"),
                        next(self._seq), job))
        self._depth(job, 1)
        self._cv.notify()

    def get(self, job_id: str) -> Optional[Job]:
        with self._cv:
            return self._jobs.get(job_id)

    def cancel(self, job_id: str) -> bool:
        job = self.get(job_id)
        if job is None:
            return False
        # RETRYING cancels like QUEUED: immediately, off the worker path
        was_queued = job.state in (JobState.QUEUED, JobState.RETRYING)
        ok = job.cancel()
        if ok and was_queued and job.state is JobState.CANCELLED:
            self._finalize_metrics(job)
        return ok

    def jobs(self) -> list[Job]:
        with self._cv:
            return list(self._jobs.values())

    def live_stats(self) -> Optional[dict]:
        """The live plane's freshness/overlay/compaction stats
        (``GET /live``); None when no plane is attached."""
        return self.live.stats() if self.live is not None else None

    def tenant_stats(self) -> dict:
        """Per-tenant attribution + quota view (``GET /tenants``):
        the accounting rows (queue-ms, device-seconds, HBM
        byte-seconds, replayed rounds, in-flight, admissions) plus the
        configured quotas and the enforcement flag."""
        return {"enforce_quotas": self.enforce_quotas,
                "tenants": self.tenants.stats(),
                "quotas": {t: q.to_wire()
                           for t, q in sorted(self.quotas.items())}}

    def slo_report(self) -> Optional[dict]:
        """The SLO engine's full evaluation (``GET /slo``): per
        objective, the current SLI and the multi-window error-budget
        burn rates; None when no objectives are attached."""
        return self.slo.evaluate() if self.slo is not None else None

    def trace_summary(self, job_id: str) -> Optional[dict]:
        """Per-job trace digest (queue_ms / fuse_ms / device_ms /
        rounds) for the ``GET /jobs`` envelope; None when tracing is
        disabled or the trace was evicted."""
        from titan_tpu.obs.tracing import trace_summary
        return trace_summary(self.tracer, job_id)

    # -- postmortems (obs/flightrec) ----------------------------------------

    def _dump_config(self) -> dict:
        """The scheduler's effective configuration for the bundle —
        enough to reproduce the serving posture without the process."""
        return {"max_batch": self.max_batch,
                "mesh_devices": int(self.mesh.devices.size)
                if self.mesh is not None else None,
                "hbm_budget_bytes": self.ledger.budget_bytes,
                "tracing": self.tracer.enabled,
                "profiling": self.profiler is not None,
                "checkpoints": self.ckpt_store is not None,
                "live": self.live is not None,
                "autotune": self.controller.mode
                if self.controller is not None else "off",
                "enforce_quotas": self.enforce_quotas,
                "quotas": {t: q.to_wire()
                           for t, q in sorted(self.quotas.items())}}

    def _dump(self, job: Optional[Job], reason: str) -> Optional[str]:
        """Write a postmortem bundle for ``job`` (or a whole-system
        snapshot when None); never raises into the worker path."""
        if self.recorder is None:
            return None
        try:
            path = self.recorder.dump(
                reason=reason,
                job=job.to_wire() if job is not None else None,
                span_tree=self.tracer.tree(job.id)
                if job is not None and self.tracer.enabled else None,
                state={"scheduler": self.stats(),
                       "ledger": {
                           "resident_bytes":
                               self.ledger.resident_bytes(),
                           "pinned_bytes": self.ledger.pinned_bytes(),
                           "budget_bytes": self.ledger.budget_bytes},
                       "pool": self.pool.stats(),
                       "live": self.live_stats(),
                       # the decision journal rides in every bundle:
                       # a postmortem must show what the controller
                       # was doing to the knobs beforehand
                       "controller": self.controller.state()
                       if self.controller is not None else None},
                config=self._dump_config(),
                profiler=self.profiler)
        except Exception:
            # dump.errors already counted by the recorder; a broken
            # dump directory must never take the worker down
            return None
        if job is not None:
            job.dump_path = path
        return path

    def dump_debug(self, job_id: Optional[str] = None,
                   reason: str = "manual") -> str:
        """On-demand postmortem (``POST /debug/dump``): dump the ring +
        state now, optionally anchored to a job. Raises ValueError for
        an unknown job id or when no flight recorder is attached."""
        if self.recorder is None:
            raise ValueError("flight recorder disabled — construct the "
                             "scheduler with flight_dir= (or set "
                             "TITAN_TPU_FLIGHT_DIR)")
        job = None
        if job_id is not None:
            job = self.get(job_id)
            if job is None:
                raise ValueError(f"unknown job {job_id!r}")
        path = self._dump(job, reason=reason)
        if path is None:
            raise RuntimeError("postmortem dump failed (see "
                               "flightrec.dump.errors)")
        return path

    def stats(self) -> dict:
        with self._cv:
            depth = sum(1 for *_x, j in self._heap
                        if j.state in (JobState.QUEUED,
                                       JobState.RETRYING))
            running = self._running_batch
            jobs = list(self._jobs.values())
        by_state: dict = {}
        for j in jobs:
            by_state[j.state.value] = by_state.get(j.state.value, 0) + 1
        return {"queue_depth": depth, "running_batch": running,
                "jobs_total": len(jobs), "by_state": by_state,
                "hbm_resident_bytes": self.ledger.resident_bytes(),
                **{f"pool_{k}": v for k, v in self.pool.stats().items()}}

    # -- worker --------------------------------------------------------------

    _STATE_COUNTER = {JobState.DONE: "completed",
                      JobState.FAILED: "failed",
                      JobState.TIMEOUT: "timeout",
                      JobState.CANCELLED: "cancelled",
                      JobState.EXPIRED: "expired"}

    def _finalize_metrics(self, job: Job) -> None:
        """Record a terminal job's state counter + latency sample,
        exactly once per job (cancel vs worker completion can race)."""
        if not job.state.terminal or not job.metered_once():
            return
        name = self._STATE_COUNTER[job.state]
        h = job.trace
        if h is not None:
            # close whatever is still open (a job cancelled while
            # queued never started; an expired one never ran) and stamp
            # the terminal state as the tree's last child
            if h.attempt is not None:
                h.end(h.attempt, state=job.state.value)
                h.attempt = None
            if h.queue is not None and h.queue.open:
                h.end(h.queue)
            h.event(job.state.value, parent=h.root)
            h.end(h.root, status=job.state.value,
                  **({"error": job.error} if job.error else {}))
        self._metrics.counter(f"serving.jobs.{name}",
                              labels=self._job_labels(job)).inc()
        # tenant attribution closes out here: the job leaves in-flight,
        # its terminal state lands in the per-tenant row, and any
        # recovery-plane replay it caused is charged to its tenant
        self.tenants.finished(job.tenant, name,
                              rounds_replayed=job.rounds_replayed)
        if job.retries_exhausted:
            self._metrics.counter(
                "serving.recovery.retries_exhausted").inc()
        if job.finished_at is not None and job.started_at is not None:
            # jobs that never entered execution (cancelled while
            # queued, expired at submit) record NO latency sample:
            # their ~0ms "latencies" would drag the p95 down and
            # dilute the SLO engine's latency SLI — a tenant flooding
            # expired jobs must not mask its real jobs' breaches
            self._metrics.histogram(
                "serving.job.latency_ms",
                labels=self._job_labels(job)).update(
                (job.finished_at - job.submitted_at) * 1e3)
        # postmortem (obs/flightrec): a job that ENTERED execution and
        # ended abnormally — FAILED, TIMEOUT, or a mid-flight kill —
        # writes its bundle now, AFTER the terminal span stamped above,
        # so the dump's span tree matches GET /trace exactly
        if self.recorder is not None and job.started_at is not None \
                and job.state in (JobState.FAILED, JobState.TIMEOUT,
                                  JobState.CANCELLED):
            self._dump(job, reason=job.state.value)

    def _pop_group(self) -> list[Job]:
        """Under the cv lock: pop the head runnable job + compatible
        batchmates; drop cancelled/expired entries on the way. RETRYING
        entries are runnable but gated by their backoff (``not_before``)
        — not-yet-due ones go back on the heap untouched."""
        group: list[Job] = []
        leftovers: list = []
        key = None
        while self._heap:
            entry = heapq.heappop(self._heap)
            job = entry[3]
            if job.state not in (JobState.QUEUED, JobState.RETRYING):
                self._depth(job, -1)
                continue       # cancelled while queued (already terminal)
            if job.not_before is not None and time.time() < job.not_before:
                leftovers.append(entry)    # backoff not elapsed
                continue
            if job.state is JobState.QUEUED and \
                    job.spec.deadline is not None and \
                    time.time() > job.spec.deadline:
                # start-deadline applies to the FIRST start only: a
                # RETRYING job already met it
                self._depth(job, -1)
                if job.expire():
                    self._finalize_metrics(job)
                continue
            if not group:
                group.append(job)
                self._depth(job, -1)
                key = batch_key(job.spec)
                if key is None:
                    break      # unbatchable head runs alone
                continue
            if batch_key(job.spec) == key and len(group) < self.max_batch:
                group.append(job)
                self._depth(job, -1)
                if len(group) >= self.max_batch:
                    break      # full batch: stop draining the heap
            else:
                leftovers.append(entry)
        for entry in leftovers:
            heapq.heappush(self._heap, entry)
        return group

    def _requeue(self, job: Job) -> None:
        """Put a RETRYING job back on the heap (its ``not_before``
        backoff gate keeps _pop_group from re-running it early). Under
        a closing scheduler the close() sweep fails it instead. The
        state is re-checked here so a cancel landing between the
        worker's RETRYING check and this call neither requeues a
        terminal job nor counts a phantom retry."""
        with self._cv:
            requeued = job.state is JobState.RETRYING
            if requeued:
                self._metrics.counter("serving.recovery.retries").inc()
                if job.trace is not None:
                    job.trace.event(
                        "retrying", parent=job.trace.root,
                        attempt=job.attempt,
                        backoff_s=round(max(0.0, (job.not_before or 0)
                                            - time.time()), 4),
                        **({"error": job.error} if job.error else {}))
                self._push_locked(job)
        if not requeued:
            # cancel raced the RETRYING check: finalize OUTSIDE the cv
            # — a terminal job that entered execution dumps its
            # postmortem here, and the bundle write (ring + state
            # serialized to disk) must never stall the scheduler API
            self._finalize_metrics(job)
            return
        # postmortem on the FIRST retry (the failure evidence is
        # freshest now; later attempts overwrite nothing — each dump
        # file is its own sequence-numbered bundle)
        if self.recorder is not None and job.attempt == 2:
            self._dump(job, reason="retrying")

    def _run(self) -> None:
        while True:
            # autotune tick (olap/serving/autotune): evaluated on the
            # worker thread between batches — the same thread that owns
            # max_batch, so K moves race nothing. Nothing the
            # controller does may take the worker down.
            if self.controller is not None:
                try:
                    self.controller.maybe_tick()
                except Exception:
                    pass
            with self._cv:
                # bounded single wait, NOT a drain-the-heap loop: an
                # idle scheduler must keep cycling through the
                # controller tick above (restores fire when traffic
                # STOPS — the empty-queue state is a control signal,
                # not a reason to sleep forever)
                if not self._stop and not self._heap:
                    self._cv.wait(0.1)
                if self._stop:
                    return
                group = self._pop_group()
                if group:
                    self._running_batch = len(group)
                else:
                    # heap holds only backoff-deferred entries: idle
                    # briefly instead of spinning on the pop
                    self._cv.wait(0.05)
            if not group:
                continue
            try:
                self._execute(group)
            except Exception as e:
                # belt and braces: NOTHING may kill the single worker
                # thread (a dead worker leaves every later job QUEUED
                # forever with no error surfaced) — fail the group and
                # keep serving
                for job in group:
                    job.fail(f"scheduler: {type(e).__name__}: {e}")
            finally:
                with self._cv:
                    self._running_batch = 0
            for job in group:
                if job.state is JobState.RETRYING:
                    if job.trace is not None \
                            and job.trace.attempt is not None:
                        job.trace.end(job.trace.attempt,
                                      state=JobState.RETRYING.value)
                        job.trace.attempt = None
                    self._requeue(job)
                else:
                    self._finalize_metrics(job)

    def _attribute(self, group: list[Job], wall: float,
                   nbytes: int) -> None:
        """Resource attribution for one executed batch: the shared
        level loop served all K jobs at once, so the batch wall time —
        and the leased graph image's ledger bytes × that wall — split
        EVENLY across the K members (the amortization-aware split; a
        job's fused cost IS wall/K, that being the whole point of
        fusion). Accumulates on both the per-job view (wire envelope)
        and the per-tenant ledger."""
        if not group or wall <= 0:
            return
        dev_share = wall / len(group)
        hbm_share = nbytes * wall / len(group)
        for job in group:
            job.device_seconds += dev_share
            job.hbm_byte_seconds += hbm_share
            self.tenants.device_seconds(job.tenant, dev_share)
            if hbm_share:
                self.tenants.hbm_byte_seconds(job.tenant, hbm_share)

    def _stitch_device_cost(self, group: list[Job], cost: dict) -> None:
        """Per-job device-cost attribution (obs/devprof, ISSUE 10):
        the executed batch's profiler window — kernel calls, compiles,
        compile/exec wall, H2D/D2H bytes — lands on each member's trace
        as a ``device_cost`` event, with the divisible costs split
        evenly over the K fused jobs exactly like the device-seconds
        accounting (the whole point of fusion is that a job's share IS
        total/K). Compile and call counts stay batch-wide: a compile is
        shared, not divisible."""
        if not cost["calls"]:
            return
        k = len(group)
        for job in group:
            h = job.trace
            if h is None:
                continue
            h.event("device_cost", k=k,
                    kernel_calls=cost["calls"],
                    compiles=cost["compiles"],
                    compile_ms_share=round(cost["compile_s"] * 1e3 / k,
                                           3),
                    exec_ms_share=round(cost["exec_s"] * 1e3 / k, 3),
                    h2d_bytes_share=cost["h2d_bytes"] // k,
                    d2h_bytes_share=cost["d2h_bytes"] // k)

    def _execute(self, group: list[Job]) -> None:
        head = group[0]
        # cancel raced between pop and start: honor it before any work
        group = [j for j in group
                 if not j.state.terminal
                 and not (j.cancel_requested and j.mark_cancelled())]
        if not group:
            return
        for job in group:
            first_start = job.started_at is None
            job.start()
            h = job.trace
            if h is not None:
                if first_start and h.queue is not None:
                    h.end(h.queue)
                h.attempt = h.start("attempt", parent=h.root,
                                    attempt=job.attempt)
            q = job.queue_seconds()
            # retry attempts keep the FIRST start time: sample the
            # submit->start latency once per job, not once per attempt
            if q is not None and first_start:
                self._metrics.histogram(
                    "serving.job.queue_ms",
                    labels=self._job_labels(job)).update(q * 1e3)
                self.tenants.queue_ms(job.tenant, q * 1e3)
        self._metrics.histogram("serving.batch.occupancy").update(
            float(len(group)))
        # decision spans (olap/serving/autotune): jobs executing under
        # freshly-APPLIED controller decisions carry them in their
        # traces — the "why did my batch shape change" evidence.
        # Enforce mode only: shadow decisions stay journal/
        # `controller`-trace-only (an unapplied decision affected no
        # job, and the default-shadow hot path must not re-scan the
        # journal per batch for nothing).
        if self.controller is not None \
                and self.controller.mode == "enforce":
            decs = [d for d in self.controller.decisions_since(
                self._ctl_stitch_seq) if d["applied"]]
            if decs:
                self._ctl_stitch_seq = decs[-1]["seq"]
                brief = [{k: d[k] for k in ("seq", "rule", "knob",
                                            "old", "new")}
                         for d in decs]
                for job in group:
                    if job.trace is not None:
                        job.trace.event("controller", decisions=brief)
        if head.spec.kind == "callable":
            t0 = time.time()
            for job in group:
                self.batcher.run_single(job, None)
            self._attribute(group, time.time() - t0, 0)
            if self.recorder is not None:
                self.recorder.metric_delta()
            return
        spec = head.spec
        edge_keys = tuple(spec.edge_keys or ())
        if spec.kind == "dense" and not edge_keys:
            # a DenseProgram that reads edge properties needs them
            # extracted into the snapshot — derive from the program
            program = spec.params.get("program")
            if program is not None and hasattr(program, "edge_keys"):
                edge_keys = tuple(program.edge_keys())
        try:
            # dense window sweeps (pagerank / DenseProgram) have no
            # overlay seam: the live pool folds the overlay into the
            # base BEFORE leasing for these kinds (the documented
            # compact-before-run fallback, models/frontier.py)
            lease = self.pool.acquire(labels=spec.labels,
                                      edge_keys=edge_keys,
                                      directed=spec.directed,
                                      compacted=spec.kind in
                                      ("pagerank", "dense"))
        except Exception as e:
            for job in group:
                job.fail(f"snapshot: {type(e).__name__}: {e}")
            return
        with lease as snap:
            overlay = lease.overlay
            epoch_info = lease.epoch_info \
                or {"epoch": getattr(snap, "epoch", 0)}
            for job in group:
                job.ran_epoch = epoch_info
            ledger_key = id(snap)
            # mesh-placed cohorts charge the PER-DEVICE share (the
            # edge image shards over the mesh — hbm.meshed_snapshot_
            # csr_bytes); only batched BFS runs meshed (single-run
            # kinds and overlay leases keep the single-device layout).
            # The predicate is the BATCHER's (Batcher.would_mesh) —
            # the accounting here and the placement there must answer
            # from one definition. A snapshot already resident under
            # the other accounting keeps its first byte count
            # (reserve() pins existing keys without re-pricing) —
            # conservative either way.
            meshed = self.batcher.would_mesh(spec.kind, overlay)
            if meshed:
                from titan_tpu.olap.serving.hbm import \
                    meshed_snapshot_csr_bytes
                nbytes = meshed_snapshot_csr_bytes(
                    snap, int(self.mesh.devices.size))
            else:
                nbytes = snapshot_csr_bytes(snap)
            try:
                self.ledger.reserve(ledger_key, nbytes)
            except AdmissionError as e:
                for job in group:
                    job.fail(str(e))
                return
            self._evictable.setdefault(ledger_key, snap)
            # the batch shares one graph image: its ledger bytes are
            # held against each member's tenant (per-K share) for the
            # duration of the run — the live view max_hbm_bytes quotas
            # check against — then released and converted into
            # byte-seconds attribution
            share = nbytes / len(group)
            for job in group:
                self.tenants.hold_hbm(job.tenant, share)
            t0 = time.time()
            w = self.profiler.window() if self.profiler is not None \
                else None
            try:
                if len(group) > 1 or batch_key(spec) is not None:
                    self.batcher.run_batch(group, snap,
                                           overlay=overlay)
                else:
                    self.batcher.run_single(group[0], snap,
                                            overlay=overlay)
            finally:
                wall = time.time() - t0
                for job in group:
                    self.tenants.drop_hbm(job.tenant, share)
                self._attribute(group, wall, nbytes)
                self.ledger.unpin(ledger_key)
                if w is not None:
                    self._stitch_device_cost(group, w.close())
                if self.recorder is not None:
                    self.recorder.metric_delta()
