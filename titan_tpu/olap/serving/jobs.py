"""Job lifecycle for the OLAP serving layer.

A ``Job`` is the handle the scheduler returns at submit time and the
server serializes over the wire: spec + state machine + result/error +
timing fields. States:

    QUEUED ──► RUNNING ──► DONE
       │        │ ▲   ├──► FAILED      (exception / admission rejection
       │        │ │   │                 with no retry budget left)
       │        │ │   ├──► TIMEOUT     (ran past spec.timeout_s)
       │        │ │   ├──► CANCELLED   (DELETE while running — the
       │        │ │   │                 batched kernel drops the job at
       │        │ │   │                 the next level boundary)
       │        ▼ │   │
       │      RETRYING─┴──► CANCELLED  (recovery plane: a retryable
       │       (requeued with backoff;  failure with attempts left —
       │        resumes from its        see olap/recovery; DELETE while
       │        newest checkpoint)      RETRYING cancels immediately)
       ├──► CANCELLED                  (DELETE while queued)
       └──► EXPIRED                    (spec.deadline passed before start)

RETRYING is NON-terminal: ``wait()`` keeps blocking, the scheduler
requeues the job after its backoff (``Job.not_before``) and the next
attempt resumes from the newest valid checkpoint. Terminal transitions
are idempotent-guarded under a lock (a cancel racing completion keeps
whichever landed first) and release ``wait()``; a job can therefore
never go DONE after FAILED (pinned by tests/test_serving_recovery.py).
"""

from __future__ import annotations

import enum
import itertools
import threading
import time
from typing import Any, Optional

from titan_tpu.olap.api import JobSpec


class JobState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    RETRYING = "retrying"
    DONE = "done"
    FAILED = "failed"
    TIMEOUT = "timeout"
    CANCELLED = "cancelled"
    EXPIRED = "expired"

    @property
    def terminal(self) -> bool:
        return self not in (JobState.QUEUED, JobState.RUNNING,
                            JobState.RETRYING)


_ids = itertools.count(1)


class Job:
    """Scheduler-owned job handle. ``result`` is a dict (kind-specific;
    large arrays stay host-side under keys the wire form omits);
    ``batch_k`` records the occupancy of the batch the job ran in (1 for
    single execution) — the amortization evidence per job."""

    def __init__(self, spec: JobSpec):
        from titan_tpu.olap.serving.tenants import effective_tenant
        self.id = f"job-{next(_ids)}"
        self.spec = spec
        self.state = JobState.QUEUED
        self.result: Optional[dict] = None
        self.error: Optional[str] = None
        self.batch_k: int = 0
        # tenancy (olap/serving/tenants): the attribution identity —
        # absent/empty spec.tenant falls back to "default", never a
        # KeyError downstream. device_seconds / hbm_byte_seconds
        # accumulate the job's batch-share of device wall time and
        # ledger bytes x seconds across attempts (the scheduler feeds
        # the per-tenant accounting as it goes; these are the per-job
        # view for the wire envelope)
        self.tenant: str = effective_tenant(getattr(spec, "tenant",
                                                    None))
        self.device_seconds: float = 0.0
        self.hbm_byte_seconds: float = 0.0
        self.submitted_at = time.time()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        # recovery plane (olap/recovery): attempt counter, backoff gate,
        # round progress + checkpoint bookkeeping; ``recovery`` is the
        # scheduler-attached JobRecovery (None when disabled)
        self.attempt: int = 1
        # the graph epoch the job's snapshot lease covered (set by the
        # scheduler at lease time; live plane leases carry the
        # compaction epoch + overlay delta seq) — freshness provenance
        # in the wire envelope
        self.ran_epoch: Optional[dict] = None
        self.not_before: Optional[float] = None
        self.retries_exhausted: bool = False
        self.last_round: int = 0
        self.rounds_replayed: int = 0
        self.checkpoint_round: Optional[int] = None
        self.recovery = None
        # observability plane (titan_tpu/obs): the scheduler-attached
        # TraceHandle when tracing is enabled; None otherwise —
        # execution hooks test this ONE attribute, so tracing-off costs
        # nothing per round
        self.trace = None
        # postmortem bundle path (obs/flightrec): set by the scheduler
        # when an abnormal end wrote a dump — GET /jobs/<id> references
        # it so a triager can jump from the job to its bundle
        self.dump_path: Optional[str] = None
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._cancel = threading.Event()
        self._metered = False

    def metered_once(self) -> bool:
        """True exactly once — the scheduler's guard so a job's terminal
        metrics (state counter + latency sample) are recorded a single
        time even when two paths race to finalize it (e.g. a client
        cancel landing between queue pop and batch start)."""
        with self._lock:
            if self._metered:
                return False
            self._metered = True
            return True

    # -- state machine ------------------------------------------------------

    def _finish(self, state: JobState, *, result: Optional[dict] = None,
                error: Optional[str] = None) -> bool:
        """Terminal transition; returns False if already terminal."""
        with self._lock:
            if self.state.terminal:
                return False
            self.state = state
            self.result = result
            self.error = error
            self.finished_at = time.time()
        self._done.set()
        return True

    def start(self) -> bool:
        """QUEUED/RETRYING → RUNNING (False if the job went terminal
        first). ``started_at`` keeps the FIRST start so queue latency
        is measured once."""
        with self._lock:
            if self.state not in (JobState.QUEUED, JobState.RETRYING):
                return False
            self.state = JobState.RUNNING
            if self.started_at is None:
                self.started_at = time.time()
        return True

    def complete(self, result: dict) -> bool:
        return self._finish(JobState.DONE, result=result)

    def fail(self, error: str, *, permanent: bool = False) -> bool:
        """Record a failure. A RUNNING job with retry budget left
        (``spec.max_retries``) transitions to RETRYING instead of
        FAILED — attempt bumps, ``not_before`` gates the requeue with
        exponential backoff, and ``wait()`` keeps blocking; the
        scheduler requeues it and the next attempt resumes from the
        newest checkpoint. ``permanent=True`` (param errors, scheduler
        shutdown) skips retry and goes straight to FAILED."""
        with self._lock:
            if self.state.terminal:
                return False
            if not permanent and self.state is JobState.RUNNING \
                    and self.spec.max_retries > 0:
                if self.attempt <= self.spec.max_retries:
                    self.state = JobState.RETRYING
                    self.error = error
                    self.not_before = time.time() + \
                        self.spec.retry_backoff_s \
                        * (2 ** (self.attempt - 1))
                    self.attempt += 1
                    return True
                # it is THIS branch declining the retry that means
                # "budget exhausted" — a later permanent failure (param
                # error, scheduler close) must not read as exhaustion
                self.retries_exhausted = True
        return self._finish(JobState.FAILED, error=error)

    def expire(self) -> bool:
        return self._finish(JobState.EXPIRED, error="deadline passed "
                            "before the job started")

    def time_out(self) -> bool:
        return self._finish(JobState.TIMEOUT,
                            error=f"exceeded timeout_s="
                                  f"{self.spec.timeout_s}")

    def cancel(self) -> bool:
        """Request cancellation. A queued job goes CANCELLED now; a
        running one is dropped from its batch at the next level boundary
        (the worker observes ``cancel_requested``). Returns False only
        when the job already finished in another state."""
        self._cancel.set()
        with self._lock:
            if self.state.terminal:
                return self.state is JobState.CANCELLED
            if self.state is JobState.RUNNING:
                return True   # the worker completes the transition
            self.state = JobState.CANCELLED
            self.finished_at = time.time()
        self._done.set()
        return True

    def mark_cancelled(self) -> bool:
        return self._finish(JobState.CANCELLED)

    @property
    def cancel_requested(self) -> bool:
        return self._cancel.is_set()

    # -- observation --------------------------------------------------------

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until terminal; True if it finished within timeout."""
        return self._done.wait(timeout)

    def queue_seconds(self) -> Optional[float]:
        if self.started_at is None:
            return None
        return self.started_at - self.submitted_at

    def exec_seconds(self) -> Optional[float]:
        if self.started_at is None or self.finished_at is None:
            return None
        return self.finished_at - self.started_at

    def to_wire(self) -> dict:
        """JSON-safe summary (large result arrays omitted)."""
        out: dict[str, Any] = {
            "job": self.id,
            "kind": self.spec.kind,
            "status": self.state.value,
            "priority": self.spec.priority,
            "tenant": self.tenant,
            "submitted_at": self.submitted_at,
            "started_at": self.started_at,
            "finished_at": self.finished_at,
            "batch_k": self.batch_k,
            "attempt": self.attempt,
        }
        if self.device_seconds:
            out["device_ms"] = round(self.device_seconds * 1e3, 3)
        if self.hbm_byte_seconds:
            out["hbm_byte_seconds"] = round(self.hbm_byte_seconds, 3)
        if self.ran_epoch is not None:
            out["epoch"] = self.ran_epoch
        if self.spec.max_retries:
            out["max_retries"] = self.spec.max_retries
        if self.checkpoint_round is not None:
            out["checkpoint_round"] = self.checkpoint_round
        if self.rounds_replayed:
            out["rounds_replayed"] = self.rounds_replayed
        if self.state is JobState.RETRYING and self.not_before is not None:
            out["retry_at"] = self.not_before
        q, e = self.queue_seconds(), self.exec_seconds()
        if q is not None:
            out["queue_ms"] = round(q * 1e3, 3)
        if e is not None:
            out["exec_ms"] = round(e * 1e3, 3)
        if self.error is not None:
            out["error"] = self.error
        if self.dump_path is not None:
            out["postmortem"] = self.dump_path
        if self.result is not None:
            out["result"] = {
                k: v for k, v in self.result.items()
                if isinstance(v, (int, float, str, bool, list, dict))
                or v is None}
        return out

    def __repr__(self) -> str:
        return f"<Job {self.id} {self.spec.kind} {self.state.value}>"
