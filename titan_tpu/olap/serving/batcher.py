"""Multi-source fusion: execute compatible jobs as one batched device run.

The batcher is the execution half of the serving layer: given a group of
admitted jobs leased onto ONE snapshot, it

* fuses BFS jobs into a single ``[K, n]`` multi-source run
  (models/bfs_hybrid.frontier_bfs_batched) — the per-level plan and
  every edge-chunk gather are shared across the K jobs, amortizing the
  per-round plan floor K-fold (PERF_NOTES "K-way plan-amortization
  model"). Cancellation and timeout act through the kernel's per-job
  early-exit mask at level boundaries;
* runs everything else singly (sssp / pagerank / wcc frontier kernels,
  'dense' DensePrograms through the TPU engine, 'callable' host
  delegations), honoring cancel-before-start.

Results are plain dicts; the full distance arrays stay host-side under
keys the wire form omits (Job.to_wire) — callers resolve per-target
distances via ``params['targets']``.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from titan_tpu.olap.serving.jobs import Job

#: jobs of these kinds fuse into one batched run when they share a
#: snapshot — BFS through the [K, n] batched kernel, SSSP/WCC through
#: the per-member cohort driver (models/frontier._frontier_cohort)
BATCHABLE_KINDS = ("bfs", "sssp", "wcc")

#: kinds the mesh placement path understands (parallel/partition
#: places the BATCHED BFS layout only) — the would_mesh predicate and
#: the scheduler's per-device ledger accounting key off this, NOT off
#: BATCHABLE_KINDS, so adding cohort kinds cannot silently change what
#: the admission guard charges per device
_MESH_KINDS = ("bfs",)


def batch_key(spec) -> Optional[tuple]:
    """Grouping key: jobs with equal keys may fuse into one batch. The
    kind is always in the key (a mixed stream fuses into PER-ALGORITHM
    cohorts, never across kinds), plus every knob the fused run shares:
    ``max_levels`` for BFS (one shared level loop), the scheduler-mode
    knobs ``max_rounds``/``delta``/``quantile_mass`` for SSSP (the
    cohort runs each member's trajectory under cohort-wide mode knobs,
    so differing knobs must not fuse)."""
    if spec.kind not in BATCHABLE_KINDS:
        return None
    base = (spec.kind,
            tuple(spec.labels) if spec.labels is not None else None,
            bool(spec.directed))
    try:
        if spec.kind == "bfs":
            return base + (int(spec.params.get("max_levels", 1000)),)
        if spec.kind == "sssp":
            delta = spec.params.get("delta")
            qm = spec.params.get("quantile_mass")
            return base + (
                int(spec.params.get("max_rounds", 10_000)),
                float(delta) if delta is not None else None,
                int(qm) if qm is not None else None)
        return base          # wcc: no per-job kernel knobs
    except (TypeError, ValueError):
        return None      # junk knob values: run (and fail) alone


def _dense_source(snap, params: dict) -> int:
    """Resolve a job's source to a dense index: ``source_dense`` wins,
    else ``source`` is an original vertex id mapped through the
    snapshot. Raises ValueError for ANY malformed value (None, lists,
    non-numeric strings) — callers catch it per job; it must never
    escape as a TypeError that could take the worker thread down."""
    try:
        if "source_dense" in params:
            return int(params["source_dense"])
        if "source" in params:
            return snap.dense_of(int(params["source"]))
    except KeyError as e:                 # dense_of: unknown vertex
        raise ValueError(str(e)) from e
    except (TypeError, ValueError) as e:
        raise ValueError(f"bad source value: {e}") from e
    raise ValueError("job params need 'source' (vertex id) or "
                     "'source_dense'")


def _epoch_token(snap, overlay):
    """Checkpoint-compatibility token: the snapshot epoch, widened with
    the overlay delta seq when a live overlay is active. Checkpoints
    resume only on an EXACT match (olap/recovery JobRecovery.latest) —
    overlay deltas between attempts would otherwise leak stale
    reachability into the resumed state (tombstones are not monotone),
    so a changed seq forces a clean restart instead."""
    e = getattr(snap, "epoch", None)
    if overlay is not None and not overlay.empty:
        return [e, overlay.seq]
    return e


def _bfs_result(snap, dist_row: np.ndarray, levels: int, inf: int,
                params: dict) -> dict:
    reached = int((dist_row < inf).sum())
    out = {"levels": int(levels), "reached": reached, "n": int(dist_row.shape[0]),
           "dist": dist_row}
    targets = params.get("targets")
    if targets:
        td = {}
        for t in targets:
            try:
                d = int(dist_row[snap.dense_of(int(t))])
            except Exception:     # unknown vertex / malformed value —
                d = None          # a bad target is None, never a crash
            td[str(t)] = d if d is not None and d < inf else None
        out["targets"] = td
    return out


class Batcher:
    """Stateless executor over leased snapshots (the scheduler owns the
    queue, admission and leases).

    Mesh-aware placement (ISSUE 13): with ``mesh`` set, batched BFS
    cohorts run over the multi-device mesh — the leased snapshot's
    chunked CSR is placed once per snapshot through
    ``parallel/partition.place_batched_csr`` (edge image's chunk
    columns sharded over ``"v"``, per-vertex arrays replicated, the
    ``[K, n]`` dist sharded ``P(None, "v")`` with K replicated) and the
    UNCHANGED batched kernels are GSPMD-partitioned from those
    committed placements, so K-way plan amortization and sharding
    compose. Live-overlay leases run unmeshed (the overlay's COO/
    tombstone buffers belong to the single-device layout) — recorded
    per group as ``meshed`` on the run span."""

    def __init__(self, max_batch: int = 16, mesh=None):
        self.max_batch = max_batch
        self.mesh = mesh

    def would_mesh(self, kind: str, overlay) -> bool:
        """THE meshed-execution predicate — the scheduler's per-device
        HBM admission accounting queries this exact method, so the
        bytes the ledger charges and the layout this batcher actually
        uploads can never disagree (a forked copy relaxing one side
        would over-commit real device HBM past the admission guard)."""
        return (self.mesh is not None
                and int(self.mesh.devices.size) > 1
                and kind in _MESH_KINDS
                and (overlay is None or overlay.empty))

    def run_batch(self, jobs: list[Job], snap, overlay=None) -> None:
        """Kind-generic batch entry (the scheduler's one dispatch
        point): BFS groups go through the [K, n] batched kernel,
        SSSP/WCC groups through the frontier cohort driver. The
        scheduler's grouping key always carries the kind, so a group
        is single-kind by construction."""
        kind = jobs[0].spec.kind
        if kind == "bfs":
            self.run_bfs_batch(jobs, snap, overlay=overlay)
        elif kind in ("sssp", "wcc"):
            self.run_frontier_batch(jobs, snap, overlay=overlay)
        else:
            for job in jobs:
                self.run_single(job, snap, overlay=overlay)

    # -- batched BFS --------------------------------------------------------

    def run_bfs_batch(self, jobs: list[Job], snap, overlay=None) -> None:
        """Execute K BFS jobs as one batched [K, n] device run; each
        job's row is bit-equal to a sequential single-source run. Jobs
        whose source does not resolve fail up front (they never join the
        batch); cancellation/timeout drop individual jobs at level
        boundaries via the kernel's keep mask.

        Recovery plane: a retry attempt with a valid checkpoint resumes
        SOLO (its level counter differs from any fresh batchmate, and
        the batched kernel runs ONE shared level loop); fresh jobs — and
        retries restarting clean — fuse as usual. Checkpoints capture
        each active job's dist row at its cadence; an injected fault
        raising out of a level boundary fails the WHOLE batch (that is
        what a real worker death does), and each member then retries
        under its own policy."""
        t_fuse0 = time.time()
        fresh: list[Job] = []
        fresh_src: list[int] = []
        resumed: list[tuple[Job, int, object]] = []
        for job in jobs:
            try:
                src = _dense_source(snap, job.spec.params)
                # junk max_levels is a param error too — it must fail
                # permanently HERE, not detonate retryably mid-group
                int(job.spec.params.get("max_levels", 1000))
            except (KeyError, ValueError, TypeError) as e:
                # param errors are permanent: retrying cannot fix them
                job.fail(f"{type(e).__name__}: {e}", permanent=True)
                continue
            ck = None
            rec = job.recovery
            # adoption: any retry attempt, OR a FIRST attempt carrying
            # an idempotency key (fleet failover redispatch — the
            # logical job already ran elsewhere and its checkpoints
            # share the key, so attempt 1 here must resume, not
            # restart; a keyed first run with no checkpoint is simply
            # fresh, never counted restarted)
            if rec is not None and (job.attempt > 1
                                    or job.spec.idempotency_key):
                ck = rec.latest(kind="bfs",
                                epoch=_epoch_token(snap, overlay))
                if ck is not None:
                    rec.resumed(ck.round)
                elif job.attempt > 1:
                    rec.restarted()
            if ck is not None:
                resumed.append((job, src, ck))
            else:
                fresh.append(job)
                fresh_src.append(src)
        # fuse decision record (obs): K, shared-plan reuse, and why a
        # member ran solo — the amortization evidence per trace
        t_fuse1 = time.time()
        for job in fresh:
            if job.trace is not None:
                job.trace.event("fuse", t0=t_fuse0, t1=t_fuse1,
                                k=len(fresh), shared_plan=len(fresh) > 1)
        for job, _src, ck in resumed:
            if job.trace is not None:
                job.trace.event("fuse", t0=t_fuse0, t1=t_fuse1, k=1,
                                shared_plan=False,
                                solo="resumed from checkpoint "
                                     f"round {ck.round}")
        if fresh:
            self._bfs_group(fresh, fresh_src, snap, None, 0,
                            overlay=overlay)
        for job, src, ck in resumed:
            self._bfs_group([job], [src], snap,
                            np.asarray(ck.arrays["dist"])[None, :],
                            ck.round, overlay=overlay)

    def _bfs_group(self, runnable: list[Job], sources: list[int], snap,
                   init_dist, start_level: int, overlay=None) -> None:
        from titan_tpu.models.bfs import INF
        from titan_tpu.models.bfs_hybrid import frontier_bfs_batched

        K = len(runnable)
        for job in runnable:
            job.batch_k = K
        started = time.time()
        dropped = [None] * K    # terminal state decided at a boundary
        n = snap.n if hasattr(snap, "n") else snap["n"]
        # mesh placement: overlay leases stay single-device (the
        # overlay's device buffers belong to the unsharded layout);
        # everything else runs over the mesh via the placed graph dict
        target = snap
        meshed = self.would_mesh("bfs", overlay)
        if meshed:
            from titan_tpu.parallel.partition import place_batched_csr
            target = place_batched_csr(snap, self.mesh)
        # device-run spans (obs): one "run" per job covering the shared
        # level loop; per-level "round" children carry the job's OWN
        # frontier count — all host timestamps from the level callback
        # the kernel already makes (no extra syncs)
        runs = [job.trace.start("run", k=K, start_level=start_level,
                                **({"overlay_edges": overlay.count,
                                    "overlay_tombs": overlay.tomb_count}
                                   if overlay is not None
                                   and not overlay.empty else {}),
                                **({"meshed": int(self.mesh.devices.size)}
                                   if meshed else {}))
                if job.trace is not None else None
                for job in runnable]
        # anchor AFTER the run spans open so the first round's window
        # nests inside them (children must not start before parents)
        prev_t = [time.time()]

        def on_level(level, nf):
            keep = np.ones(K, bool)
            now = time.time()
            for i, job in enumerate(runnable):
                if job.trace is not None and dropped[i] is None:
                    job.trace.event("round", parent=runs[i],
                                    t0=prev_t[0], t1=now, level=level,
                                    frontier=int(nf[i]))
                if dropped[i] is not None:
                    keep[i] = False
                    continue
                job.last_round = level
                rec = job.recovery
                if rec is not None and rec.faults is not None:
                    # deterministic fault injection (tests): raising
                    # here kills the batch, like a real worker death
                    rec.faults.check(level, job.attempt, snap)
                if job.cancel_requested:
                    dropped[i] = "cancel"
                    keep[i] = False
                elif job.spec.timeout_s is not None and \
                        now - started > job.spec.timeout_s:
                    dropped[i] = "timeout"
                    keep[i] = False
            prev_t[0] = now
            return keep if not keep.all() else None

        token = _epoch_token(snap, overlay)

        def checkpoint(level, dist, act):
            for i, job in enumerate(runnable):
                rec = job.recovery
                if rec is not None and act[i] and rec.due(level):
                    rec.save(level,
                             {"dist": np.asarray(dist[i, :n])},
                             kind="bfs",
                             meta={"epoch": token})

        wants_ckpt = any(j.recovery is not None
                         and j.recovery.store is not None
                         for j in runnable)
        try:
            dist, levels, completed = frontier_bfs_batched(
                target, sources, max_levels=int(
                    runnable[0].spec.params.get("max_levels", 1000)),
                on_level=on_level,
                init_dist=init_dist, start_level=start_level,
                checkpoint=checkpoint if wants_ckpt else None,
                overlay=overlay)
        except Exception as e:
            for i, job in enumerate(runnable):
                if job.trace is not None:
                    job.trace.end(runs[i], error=f"{type(e).__name__}")
                job.fail(f"{type(e).__name__}: {e}")
            return
        inf = int(INF)
        for i, job in enumerate(runnable):
            if job.trace is not None:
                job.trace.end(runs[i], levels=int(levels[i]))
        for i, job in enumerate(runnable):
            if completed[i]:
                job.complete(_bfs_result(snap, dist[i], levels[i], inf,
                                         job.spec.params))
            elif dropped[i] == "timeout":
                job.time_out()
            else:
                job.mark_cancelled()

    # -- batched SSSP / WCC cohorts -----------------------------------------

    def run_frontier_batch(self, jobs: list[Job], snap,
                           overlay=None) -> None:
        """Execute a same-kind group of SSSP or WCC jobs as one fused
        cohort (models/frontier.frontier_sssp_batched /
        frontier_wcc_batched): per-member device state under ONE shared
        round loop with a single stacked plan readback per round, each
        member bit-equal to its sequential run. Fresh first attempts
        fuse; retry attempts and idempotency-keyed redispatches run
        SOLO through ``run_single`` (their adoption bookkeeping and —
        when a checkpoint matches — a round counter no fresh batchmate
        shares; the same split the batched BFS makes for resumes)."""
        t_fuse0 = time.time()
        kind = jobs[0].spec.kind
        fresh: list[Job] = []
        fresh_src: list[int] = []
        solo: list[Job] = []
        for job in jobs:
            src = 0
            if kind == "sssp":
                try:
                    src = _dense_source(snap, job.spec.params)
                except (KeyError, ValueError, TypeError) as e:
                    job.fail(f"{type(e).__name__}: {e}", permanent=True)
                    continue
            rec = job.recovery
            if rec is not None and (job.attempt > 1
                                    or job.spec.idempotency_key):
                solo.append(job)
            else:
                fresh.append(job)
                fresh_src.append(src)
        t_fuse1 = time.time()
        for job in fresh:
            if job.trace is not None:
                job.trace.event("fuse", t0=t_fuse0, t1=t_fuse1,
                                k=len(fresh), kind=kind,
                                shared_plan=len(fresh) > 1)
        for job in solo:
            if job.trace is not None:
                job.trace.event("fuse", t0=t_fuse0, t1=t_fuse1, k=1,
                                kind=kind, shared_plan=False,
                                solo="retry/redispatch attempt: may "
                                     "resume from a checkpoint")
        if fresh:
            self._frontier_group(fresh, fresh_src, snap,
                                 overlay=overlay)
        for job in solo:
            self.run_single(job, snap, overlay=overlay)

    def _frontier_group(self, runnable: list[Job], sources: list[int],
                        snap, overlay=None) -> None:
        from titan_tpu.models.frontier import (FINF,
                                               frontier_sssp_batched,
                                               frontier_wcc_batched)

        kind = runnable[0].spec.kind
        K = len(runnable)
        for job in runnable:
            job.batch_k = K
        started = time.time()
        dropped = [None] * K    # terminal state decided at a boundary
        runs = [job.trace.start("run", kind=kind, k=K,
                                **({"overlay_edges": overlay.count,
                                    "overlay_tombs": overlay.tomb_count}
                                   if overlay is not None
                                   and not overlay.empty else {}))
                if job.trace is not None else None
                for job in runnable]
        # per-member round-window anchors, after the run spans open
        prev_t = [time.time()] * K

        def on_round(k, rounds):
            job = runnable[k]
            now = time.time()
            if job.trace is not None:
                job.trace.event("round", parent=runs[k],
                                t0=prev_t[k], t1=now, round=rounds)
                prev_t[k] = now
            job.last_round = rounds
            rec = job.recovery
            if rec is not None and rec.faults is not None:
                # raising here kills the WHOLE cohort — that is what a
                # real worker death does, same as the batched BFS; each
                # member then retries under its own policy
                rec.faults.check(rounds, job.attempt, snap)
            if job.cancel_requested:
                dropped[k] = "cancel"
                return False
            if job.spec.timeout_s is not None and \
                    now - started > job.spec.timeout_s:
                dropped[k] = "timeout"
                return False
            return True

        token = _epoch_token(snap, overlay)

        def ckpt(k, rounds, state):
            rec = runnable[k].recovery
            if rec is None or rec.store is None or not rec.due(rounds):
                return
            arrays = {"val": np.asarray(state["val"]),
                      "val_exp": np.asarray(state["val_exp"])}
            if kind == "sssp":
                rec.save(rounds, arrays, kind="sssp",
                         meta={"epoch": token,
                               "bucket_end": float(state["bucket_end"]),
                               "quantile_mass":
                                   int(state["quantile_mass"])})
            else:
                rec.save(rounds, arrays, kind="wcc",
                         meta={"epoch": token,
                               "levels": int(state["levels"])})

        wants_ckpt = any(j.recovery is not None
                         and j.recovery.store is not None
                         for j in runnable)
        params0 = runnable[0].spec.params
        try:
            if kind == "sssp":
                outs, rounds_l, stopped = frontier_sssp_batched(
                    snap, sources,
                    delta=params0.get("delta"),
                    quantile_mass=params0.get("quantile_mass"),
                    max_rounds=int(params0.get("max_rounds", 10_000)),
                    on_round=on_round,
                    checkpoint=ckpt if wants_ckpt else None,
                    overlay=overlay)
            else:
                outs, rounds_l, stopped = frontier_wcc_batched(
                    snap, K, on_round=on_round,
                    checkpoint=ckpt if wants_ckpt else None,
                    overlay=overlay)
        except Exception as e:
            for i, job in enumerate(runnable):
                if job.trace is not None:
                    job.trace.end(runs[i], error=f"{type(e).__name__}")
                job.fail(f"{type(e).__name__}: {e}")
            return
        from titan_tpu.obs import devprof
        for i, job in enumerate(runnable):
            if job.trace is not None:
                job.trace.end(runs[i], rounds=int(rounds_l[i]))
            if stopped[i] is not None:
                if dropped[i] == "timeout":
                    job.time_out()
                else:
                    job.mark_cancelled()
                continue
            arr = outs[i]
            devprof.count_d2h("frontier.result",
                              getattr(arr, "nbytes", 0))
            if kind == "sssp":
                job.complete({"rounds": int(rounds_l[i]),
                              "reached":
                                  int((arr < float(FINF)).sum()),
                              "dist": arr})
            else:
                job.complete({"rounds": int(rounds_l[i]),
                              "components": int(len(np.unique(arr))),
                              "labels": arr})

    # -- single execution ---------------------------------------------------

    def run_single(self, job: Job, snap, overlay=None) -> None:
        """One job alone (still async from the caller's view). The
        frontier kinds honor cancellation/timeout at ROUND boundaries
        through ``_frontier_run``'s on_round veto (models/frontier
        RoundInterrupted) — the single-execution analog of the batched
        kernel's level mask. The same boundaries drive the recovery
        plane (job.recovery): fault injection, checkpoint capture at
        the job's cadence, and — on a retry attempt — resume from the
        newest valid checkpoint (epoch-matched; otherwise clean
        restart). Param errors fail permanently (no retry)."""
        job.batch_k = 1
        kind = job.spec.kind
        params = dict(job.spec.params)
        params.pop("faults", None)       # injector is not a kernel param
        rec = job.recovery
        started = time.time()
        interrupted = {}

        if kind == "bfs":
            # bfs delegates wholesale — run_bfs_batch owns its own
            # resume bookkeeping (doing it here too would double-count
            # serving.recovery.resumes / rounds_replayed)
            self.run_bfs_batch([job], snap, overlay=overlay)
            return

        h = job.trace
        run_span = None
        if h is not None and kind != "callable":
            run_span = h.start(
                "run", kind=kind,
                **({"overlay_edges": overlay.count,
                    "overlay_tombs": overlay.tomb_count}
                   if overlay is not None and not overlay.empty
                   else {}))
        # round-window anchor: at/after the run span's start so round
        # children nest inside it
        prev_t = [time.time()]
        # per-round timeline (obs): pagerank/dense rounds are stamped
        # from the host callbacks below; sssp/wcc rounds come from
        # _frontier_run's existing mass-accounting trace instead — it
        # already carries frontier size / listed chunk mass / plan cost
        # per round at zero extra syncs (the stats readback happens
        # regardless), so the span timeline gets the band/plan story
        # for free
        trace_rounds = None
        _csr_trace_prev = None
        if h is not None and kind in ("sssp", "wcc"):
            from titan_tpu.models.bfs_hybrid import build_chunked_csr
            _csr = build_chunked_csr(snap)
            _csr_trace_prev = _csr.get("_trace_rounds")
            trace_rounds = []
            _csr["_trace_rounds"] = trace_rounds

        def on_round(rounds):
            job.last_round = rounds
            if h is not None and trace_rounds is None:
                now = time.time()
                h.event("round", parent=run_span, t0=prev_t[0], t1=now,
                        round=rounds)
                prev_t[0] = now
            if rec is not None and rec.faults is not None:
                rec.faults.check(rounds, job.attempt, snap)
            if job.cancel_requested:
                interrupted["why"] = "cancel"
                return False
            if job.spec.timeout_s is not None and \
                    time.time() - started > job.spec.timeout_s:
                interrupted["why"] = "timeout"
                return False
            return True
        epoch = _epoch_token(snap, overlay)
        ck = None
        # adoption: any retry attempt, OR a first attempt under an
        # idempotency key (fleet failover redispatch: the checkpoint
        # store is shared and keyed, so attempt 1 here resumes the
        # logical job's newest checkpoint instead of restarting; keyed
        # first runs with no checkpoint are fresh, never "restarted")
        if rec is not None and kind != "callable" \
                and (job.attempt > 1 or job.spec.idempotency_key):
            ck = rec.latest(kind=kind, epoch=epoch)
            if ck is not None:
                rec.resumed(ck.round)
            elif job.attempt > 1:
                rec.restarted()
        wants_ckpt = rec is not None and rec.store is not None

        try:
            if kind == "sssp":
                from titan_tpu.models.frontier import FINF, frontier_sssp
                try:
                    src = _dense_source(snap, params)
                except (KeyError, ValueError) as e:
                    job.fail(f"{type(e).__name__}: {e}", permanent=True)
                    return
                ckpt = None
                if wants_ckpt:
                    def ckpt(rounds, state):
                        if rec.due(rounds):
                            rec.save(rounds,
                                     {"val": np.asarray(state["val"]),
                                      "val_exp":
                                          np.asarray(state["val_exp"])},
                                     kind="sssp",
                                     meta={"epoch": epoch,
                                           "bucket_end":
                                               float(state["bucket_end"]),
                                           "quantile_mass":
                                               int(state["quantile_mass"])})
                resume = None
                if ck is not None:
                    resume = {"val": ck.arrays["val"],
                              "val_exp": ck.arrays["val_exp"],
                              "rounds": ck.round,
                              "bucket_end": ck.meta["bucket_end"],
                              "quantile_mass": ck.meta["quantile_mass"]}
                dist, rounds = frontier_sssp(
                    snap, src,
                    delta=params.get("delta"),
                    quantile_mass=params.get("quantile_mass"),
                    max_rounds=int(params.get("max_rounds", 10_000)),
                    on_round=on_round, checkpoint=ckpt, resume=resume,
                    overlay=overlay)
                from titan_tpu.obs import devprof
                devprof.count_d2h("frontier.result",
                                  getattr(dist, "nbytes", 0))
                dist = np.asarray(dist)
                job.complete({"rounds": int(rounds),
                              "reached": int((dist < float(FINF)).sum()),
                              "dist": dist})
            elif kind == "pagerank":
                from titan_tpu.models.frontier import pagerank_dense
                ckpt = None
                if wants_ckpt:
                    def ckpt(it, state):
                        if rec.due(it):
                            rec.save(it,
                                     {"rank": np.asarray(state["rank"])},
                                     kind="pagerank",
                                     meta={"epoch": epoch})
                resume = None
                if ck is not None:
                    resume = {"rank": ck.arrays["rank"], "it": ck.round}
                rank, iters = pagerank_dense(
                    snap, iterations=int(params.get("iterations", 20)),
                    damping=float(params.get("damping", 0.85)),
                    tol=params.get("tol"), on_round=on_round,
                    checkpoint=ckpt, resume=resume, overlay=overlay)
                job.complete({"iterations": int(iters),
                              "rank": np.asarray(rank)})
            elif kind == "wcc":
                from titan_tpu.models.frontier import frontier_wcc
                ckpt = None
                if wants_ckpt:
                    def ckpt(rounds, state):
                        if rec.due(rounds):
                            rec.save(rounds,
                                     {"val": np.asarray(state["val"]),
                                      "val_exp":
                                          np.asarray(state["val_exp"])},
                                     kind="wcc",
                                     meta={"epoch": epoch,
                                           "levels": int(state["levels"])})
                resume = None
                if ck is not None:
                    resume = {"val": ck.arrays["val"],
                              "val_exp": ck.arrays["val_exp"],
                              "rounds": ck.round,
                              "levels": ck.meta.get("levels", 0)}
                lab, rounds = frontier_wcc(snap, on_round=on_round,
                                           checkpoint=ckpt, resume=resume,
                                           overlay=overlay)
                from titan_tpu.obs import devprof
                devprof.count_d2h("frontier.result",
                                  getattr(lab, "nbytes", 0))
                lab = np.asarray(lab)
                job.complete({"rounds": int(rounds),
                              "components": int(len(np.unique(lab))),
                              "labels": lab})
            elif kind == "dense":
                from titan_tpu.olap.tpu.engine import run_single
                program = params.pop("program")
                ckpt = None
                every = 0
                if rec is not None and (wants_ckpt
                                        or rec.faults is not None):
                    # dense programs have no on_round veto; the chunk
                    # boundary is the only host hook, so faults fire
                    # here — and a fault plan WITHOUT a store still
                    # needs the chunked loop (every=1) to get hooks
                    every = rec.every if wants_ckpt else 1

                    def ckpt(it, state):
                        job.last_round = it
                        if h is not None:
                            now = time.time()
                            h.event("round", parent=run_span,
                                    t0=prev_t[0], t1=now, round=it)
                            prev_t[0] = now
                        if rec.faults is not None:
                            rec.faults.check(it, job.attempt, snap)
                        if wants_ckpt and rec.due(it):
                            rec.save(it,
                                     {k: np.asarray(v)
                                      for k, v in state.items()},
                                     kind="dense",
                                     meta={"epoch": epoch})
                resume = None
                if ck is not None:
                    resume = {"state": ck.arrays, "iteration": ck.round}
                res = run_single(
                    program, snap, params, resume=resume, checkpoint=ckpt,
                    checkpoint_every=every)
                job.complete({"iterations": res.iterations,
                              **{k: np.asarray(v) for k, v in res.items()}})
            elif kind == "callable":
                job.complete({"value": params["fn"]()})
            else:
                job.fail(f"unknown job kind {kind!r}", permanent=True)
        except Exception as e:
            from titan_tpu.models.frontier import RoundInterrupted
            if isinstance(e, RoundInterrupted):
                if interrupted.get("why") == "timeout":
                    job.time_out()
                else:
                    job.mark_cancelled()
            else:
                job.fail(f"{type(e).__name__}: {e}")
        finally:
            if h is not None:
                if trace_rounds is not None:
                    # bridge _frontier_run's per-round tuples
                    # (band, frontier, chunk_mass, t_plan_done, plan_s)
                    # into the span timeline, then detach the hook from
                    # the snapshot's cached CSR
                    t_prev = run_span.t_start if run_span is not None \
                        else started
                    for i, (band, nf, m8, t, plan_s) in \
                            enumerate(trace_rounds):
                        extra = {"band": float(band)} \
                            if 0.0 < float(band) < 1e30 else {}
                        h.event("round", parent=run_span, t0=t_prev,
                                t1=t, round=i, frontier=int(nf),
                                chunk_mass=int(m8),
                                plan_ms=round(plan_s * 1e3, 3), **extra)
                        t_prev = t
                    if _csr_trace_prev is None:
                        _csr.pop("_trace_rounds", None)
                    else:
                        _csr["_trace_rounds"] = _csr_trace_prev
                if run_span is not None:
                    h.end(run_span, rounds=int(job.last_round))
