"""Multi-source fusion: execute compatible jobs as one batched device run.

The batcher is the execution half of the serving layer: given a group of
admitted jobs leased onto ONE snapshot, it

* fuses BFS jobs into a single ``[K, n]`` multi-source run
  (models/bfs_hybrid.frontier_bfs_batched) — the per-level plan and
  every edge-chunk gather are shared across the K jobs, amortizing the
  per-round plan floor K-fold (PERF_NOTES "K-way plan-amortization
  model"). Cancellation and timeout act through the kernel's per-job
  early-exit mask at level boundaries;
* runs everything else singly (sssp / pagerank / wcc frontier kernels,
  'dense' DensePrograms through the TPU engine, 'callable' host
  delegations), honoring cancel-before-start.

Results are plain dicts; the full distance arrays stay host-side under
keys the wire form omits (Job.to_wire) — callers resolve per-target
distances via ``params['targets']``.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from titan_tpu.olap.serving.jobs import Job

#: jobs of these kinds fuse into one batched run when they share a
#: snapshot (the only batchable kind today; SSSP banding is next)
BATCHABLE_KINDS = ("bfs",)


def batch_key(spec) -> Optional[tuple]:
    """Grouping key: jobs with equal keys may fuse into one batch.
    ``max_levels`` is part of the key — the batched kernel runs ONE
    shared level loop, so a job with a tighter level cap must not drag
    batchmates down to it (nor ride past its own)."""
    if spec.kind not in BATCHABLE_KINDS:
        return None
    try:
        max_levels = int(spec.params.get("max_levels", 1000))
    except (TypeError, ValueError):
        return None      # junk max_levels: run (and fail) alone
    return (spec.kind,
            tuple(spec.labels) if spec.labels is not None else None,
            bool(spec.directed),
            max_levels)


def _dense_source(snap, params: dict) -> int:
    """Resolve a job's source to a dense index: ``source_dense`` wins,
    else ``source`` is an original vertex id mapped through the
    snapshot. Raises ValueError for ANY malformed value (None, lists,
    non-numeric strings) — callers catch it per job; it must never
    escape as a TypeError that could take the worker thread down."""
    try:
        if "source_dense" in params:
            return int(params["source_dense"])
        if "source" in params:
            return snap.dense_of(int(params["source"]))
    except KeyError as e:                 # dense_of: unknown vertex
        raise ValueError(str(e)) from e
    except (TypeError, ValueError) as e:
        raise ValueError(f"bad source value: {e}") from e
    raise ValueError("job params need 'source' (vertex id) or "
                     "'source_dense'")


def _bfs_result(snap, dist_row: np.ndarray, levels: int, inf: int,
                params: dict) -> dict:
    reached = int((dist_row < inf).sum())
    out = {"levels": int(levels), "reached": reached, "n": int(dist_row.shape[0]),
           "dist": dist_row}
    targets = params.get("targets")
    if targets:
        td = {}
        for t in targets:
            try:
                d = int(dist_row[snap.dense_of(int(t))])
            except Exception:     # unknown vertex / malformed value —
                d = None          # a bad target is None, never a crash
            td[str(t)] = d if d is not None and d < inf else None
        out["targets"] = td
    return out


class Batcher:
    """Stateless executor over leased snapshots (the scheduler owns the
    queue, admission and leases)."""

    def __init__(self, max_batch: int = 16):
        self.max_batch = max_batch

    # -- batched BFS --------------------------------------------------------

    def run_bfs_batch(self, jobs: list[Job], snap) -> None:
        """Execute K BFS jobs as one batched [K, n] device run; each
        job's row is bit-equal to a sequential single-source run. Jobs
        whose source does not resolve fail up front (they never join the
        batch); cancellation/timeout drop individual jobs at level
        boundaries via the kernel's keep mask."""
        from titan_tpu.models.bfs import INF
        from titan_tpu.models.bfs_hybrid import frontier_bfs_batched

        runnable: list[Job] = []
        sources: list[int] = []
        for job in jobs:
            try:
                sources.append(_dense_source(snap, job.spec.params))
                runnable.append(job)
            except (KeyError, ValueError) as e:
                job.fail(f"{type(e).__name__}: {e}")
        if not runnable:
            return
        K = len(runnable)
        for job in runnable:
            job.batch_k = K
        started = time.time()
        dropped = [None] * K    # terminal state decided at a boundary

        def on_level(level, nf):
            keep = np.ones(K, bool)
            now = time.time()
            for i, job in enumerate(runnable):
                if dropped[i] is not None:
                    keep[i] = False
                    continue
                if job.cancel_requested:
                    dropped[i] = "cancel"
                    keep[i] = False
                elif job.spec.timeout_s is not None and \
                        now - started > job.spec.timeout_s:
                    dropped[i] = "timeout"
                    keep[i] = False
            return keep if not keep.all() else None

        try:
            dist, levels, completed = frontier_bfs_batched(
                snap, sources, max_levels=int(
                    runnable[0].spec.params.get("max_levels", 1000)),
                on_level=on_level)
        except Exception as e:
            for job in runnable:
                job.fail(f"{type(e).__name__}: {e}")
            return
        inf = int(INF)
        for i, job in enumerate(runnable):
            if completed[i]:
                job.complete(_bfs_result(snap, dist[i], levels[i], inf,
                                         job.spec.params))
            elif dropped[i] == "timeout":
                job.time_out()
            else:
                job.mark_cancelled()

    # -- single execution ---------------------------------------------------

    def run_single(self, job: Job, snap) -> None:
        """One job alone (still async from the caller's view). The
        frontier kinds honor cancellation/timeout at ROUND boundaries
        through ``_frontier_run``'s on_round veto (models/frontier
        RoundInterrupted) — the single-execution analog of the batched
        kernel's level mask."""
        job.batch_k = 1
        kind = job.spec.kind
        params = dict(job.spec.params)
        started = time.time()
        interrupted = {}

        def on_round(rounds):
            if job.cancel_requested:
                interrupted["why"] = "cancel"
                return False
            if job.spec.timeout_s is not None and \
                    time.time() - started > job.spec.timeout_s:
                interrupted["why"] = "timeout"
                return False
            return True

        try:
            if kind == "bfs":
                self.run_bfs_batch([job], snap)
                return
            if kind == "sssp":
                from titan_tpu.models.frontier import FINF, frontier_sssp
                src = _dense_source(snap, params)
                dist, rounds = frontier_sssp(
                    snap, src,
                    delta=params.get("delta"),
                    quantile_mass=params.get("quantile_mass"),
                    max_rounds=int(params.get("max_rounds", 10_000)),
                    on_round=on_round)
                dist = np.asarray(dist)
                job.complete({"rounds": int(rounds),
                              "reached": int((dist < float(FINF)).sum()),
                              "dist": dist})
            elif kind == "pagerank":
                from titan_tpu.models.frontier import pagerank_dense
                rank, iters = pagerank_dense(
                    snap, iterations=int(params.get("iterations", 20)),
                    damping=float(params.get("damping", 0.85)),
                    tol=params.get("tol"), on_round=on_round)
                job.complete({"iterations": int(iters),
                              "rank": np.asarray(rank)})
            elif kind == "wcc":
                from titan_tpu.models.frontier import frontier_wcc
                lab, rounds = frontier_wcc(snap, on_round=on_round)
                lab = np.asarray(lab)
                job.complete({"rounds": int(rounds),
                              "components": int(len(np.unique(lab))),
                              "labels": lab})
            elif kind == "dense":
                from titan_tpu.olap.tpu.engine import run_single
                program = params.pop("program")
                res = run_single(program, snap, params)
                job.complete({"iterations": res.iterations,
                              **{k: np.asarray(v) for k, v in res.items()}})
            elif kind == "callable":
                job.complete({"value": params["fn"]()})
            else:
                job.fail(f"unknown job kind {kind!r}")
        except Exception as e:
            from titan_tpu.models.frontier import RoundInterrupted
            if isinstance(e, RoundInterrupted):
                if interrupted.get("why") == "timeout":
                    job.time_out()
                else:
                    job.mark_cancelled()
            else:
                job.fail(f"{type(e).__name__}: {e}")
