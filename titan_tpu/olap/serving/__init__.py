"""OLAP serving layer: concurrent job scheduling + multi-source batching.

This package rebuilds the reference's L7→L4b serving seam — gremlin-server
YAML endpoints feeding ``FulgoraGraphComputer``'s executor service
(reference: titan-dist conf/gremlin-server/gremlin-server.yaml +
graphdb/olap/computer/FulgoraGraphComputer.java:48-120) — as an
admission-controlled asynchronous job plane over the TPU engine:

* ``jobs``      — job/handle lifecycle (queued → running → terminal).
* ``pool``      — epoch-aware snapshot pool: concurrent jobs share one
                  ``GraphSnapshot`` per parameter set, refreshed through
                  the epoch/refresh() freshness contract before hand-out.
* ``hbm``       — device-memory accounting (the bench ``_DEV_GRAPHS``
                  budget/eviction logic as a library) backing admission.
* ``batcher``   — multi-source fusion: compatible same-snapshot BFS jobs
                  execute as ONE batched [K, n] device run
                  (models/bfs_hybrid.frontier_bfs_batched), amortizing
                  the per-level plan floor K-fold.
* ``scheduler`` — priority queue + admission + worker, with per-job
                  latency / queue-depth / batch-occupancy metrics
                  through utils/metrics.
* ``autotune``  — the closed-loop decision plane (ROADMAP #4): a
                  per-scheduler Controller ticks over the metric/SLO
                  registries and journals bounded, replayable knob
                  decisions (batch K, tenant quota scaling, compaction
                  triggers, checkpoint cadence); shadow by default,
                  ``autotune="enforce"`` applies them.
                  ``GET /controller`` serves the journal.
* ``tenants``   — per-tenant resource attribution (queue-ms /
                  device-seconds / HBM byte-seconds / replayed rounds)
                  and quota admission (``TenantQuota``, enforced at
                  submit behind ``JobScheduler(enforce_quotas=True)``;
                  shadow-counted otherwise). ``GET /tenants`` +
                  ``GET /slo`` expose the plane; docs/monitoring.md
                  documents the label/tenant model.

``server.py`` exposes this as ``POST /jobs`` / ``GET /jobs/<id>`` /
``DELETE /jobs/<id>``; docs/serving.md documents the contract. The
checkpoint & recovery plane (preemption-safe jobs: RETRYING + backoff
requeue + deterministic resume from superstep checkpoints) lives in
``olap/recovery`` and plugs in through ``JobScheduler(checkpoint_dir=)``
+ ``JobSpec.max_retries`` / ``checkpoint_every``; docs/recovery.md.
"""

from titan_tpu.olap.serving.jobs import Job, JobState            # noqa: F401
from titan_tpu.olap.serving.scheduler import JobScheduler        # noqa: F401
from titan_tpu.olap.serving.tenants import (DEFAULT_TENANT,      # noqa: F401
                                            QuotaExceeded,
                                            TenantQuota)
