"""Device-memory (HBM) accounting for graph images — admission's ledger.

This is the bench driver's ``_DEV_GRAPHS`` budget logic promoted to a
library (ISSUE r7: "as a library, not a script-local"): the serving
scheduler admits jobs against it before building a snapshot's chunked
CSR on device, and bench.py's stage-shared graph cache is the same
``DeviceGraphCache`` re-used. The byte model matches what the kernels
actually upload: the transposed 8-aligned ``dstT`` [8, q_total] int32
plus three [n+1] int32 side arrays (colstart/degc/deg) —
models/bfs_hybrid.build_chunked_csr's exact footprint. Eviction is
largest-first over unpinned entries (the bench policy); pinned entries
(graphs under a running batch) are never evicted.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

#: default budget, bench.py's historical 12 GB of a 16 GB v5e HBM
#: (leaving headroom for kernel state/temporaries)
DEFAULT_BUDGET_BYTES = 12.0e9


def chunked_csr_bytes(n: int, q_total: int) -> int:
    """Device bytes of a chunked CSR: dstT [8, q_total] int32 + 3 x
    [n+1] int32 (colstart/degc/deg)."""
    return q_total * 8 * 4 + 3 * 4 * (n + 1)


def graph_bytes(hg: dict) -> int:
    """Bytes for a host-graph dict (graph500.load_or_build result)."""
    return chunked_csr_bytes(hg["n"], hg["q_total"])


def snapshot_csr_bytes(snap) -> int:
    """Predicted device bytes for a GraphSnapshot's chunked CSR,
    computable BEFORE the build (admission must not pay the upload to
    learn it doesn't fit): q_total = sum(ceil(deg/8)) + 1 pad column."""
    deg = snap.out_degree
    q_total = int((-(-deg.astype("int64") // 8)).sum()) + 1
    return chunked_csr_bytes(snap.n, q_total)


def meshed_snapshot_csr_bytes(snap, num_devices: int) -> int:
    """PER-DEVICE bytes of a MESH-PLACED chunked CSR (ISSUE 13,
    ``parallel/partition.place_batched_csr``): the ``dstT`` edge image
    shards its chunk columns over the mesh — each device holds ~1/D of
    it — while the per-vertex side arrays replicate. The ledger models
    ONE device's HBM, so a mesh-placed cohort charges this, not the
    whole image; that reduction is the memory half of why batching and
    sharding compose."""
    total = snapshot_csr_bytes(snap)
    n = getattr(snap, "n", 0)
    vert = 3 * 4 * (n + 1)                    # colstart/degc/deg
    edges = max(total - vert, 0)
    return int(vert + -(-edges // max(int(num_devices), 1)))


class AdmissionError(RuntimeError):
    """The job's graph image cannot fit the HBM budget even after
    evicting every unpinned resident graph."""


class HBMLedger:
    """Budgeted accounting of device-resident graph images.

    ``reserve(key, nbytes)`` charges an entry, evicting largest-first
    among unpinned entries until it fits (``on_evict(key)`` lets the
    owner drop the device arrays — actual frees happen when the last
    jax reference dies). Raises AdmissionError when even a full sweep
    cannot make room. Entries are pinned while reserved; ``unpin``
    leaves them resident-but-evictable (the warm-cache state),
    ``release`` drops them entirely."""

    def __init__(self, budget_bytes: float = DEFAULT_BUDGET_BYTES,
                 on_evict: Optional[Callable[[object], None]] = None):
        self.budget_bytes = float(budget_bytes)
        self._on_evict = on_evict
        self._bytes: dict = {}
        self._pins: dict = {}
        self._lock = threading.Lock()

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(self._bytes.values())

    def pinned_bytes(self) -> int:
        """Bytes held by PINNED entries (graphs under a running batch)
        — the unevictable share of ``resident_bytes``; exported as the
        ``serving.hbm.pinned_bytes`` gauge."""
        with self._lock:
            return sum(b for k, b in self._bytes.items()
                       if self._pins.get(k, 0) > 0)

    def reserve(self, key, nbytes: int) -> None:
        evicted = []
        with self._lock:
            if key in self._bytes:
                self._pins[key] = self._pins.get(key, 0) + 1
                return
            pinned = sum(self._bytes[k] for k, c in self._pins.items()
                         if c > 0)
            if pinned + nbytes > self.budget_bytes:
                raise AdmissionError(
                    f"admission: graph image needs {nbytes/1e9:.2f}GB "
                    f"but only {max(self.budget_bytes - pinned, 0)/1e9:.2f}"
                    f"GB of the {self.budget_bytes/1e9:.2f}GB HBM budget "
                    "is free of pinned (in-use) graphs")
            # evict largest unpinned until the new entry fits
            while sum(self._bytes.values()) + nbytes > self.budget_bytes:
                victims = {k: b for k, b in self._bytes.items()
                           if self._pins.get(k, 0) == 0}
                if not victims:
                    raise AdmissionError(
                        "admission: HBM budget exhausted by pinned "
                        "graphs")
                victim = max(victims, key=victims.get)
                self._bytes.pop(victim)
                self._pins.pop(victim, None)
                evicted.append(victim)
            self._bytes[key] = int(nbytes)
            self._pins[key] = 1
        for k in evicted:
            if self._on_evict is not None:
                self._on_evict(k)

    def unpin(self, key) -> None:
        with self._lock:
            if key in self._pins and self._pins[key] > 0:
                self._pins[key] -= 1

    def release(self, key) -> None:
        with self._lock:
            self._bytes.pop(key, None)
            self._pins.pop(key, None)


class DeviceGraphCache:
    """Stage-shared device-graph cache (bench.py's ``_DEV_GRAPHS`` as a
    class): ``get_or_load(key, host_loader, uploader)`` returns
    ``(host_graph, device_graph, gen_s, upload_s)``, keeping every
    loaded graph resident and evicting largest-first only when a new
    graph would overflow the budget."""

    def __init__(self, budget_bytes: float = DEFAULT_BUDGET_BYTES):
        self._ledger = HBMLedger(budget_bytes, on_evict=self._drop)
        self._graphs: dict = {}
        self._lock = threading.Lock()

    def __contains__(self, key) -> bool:
        return key in self._graphs

    def _drop(self, key) -> None:
        self._graphs.pop(key, None)

    def get_or_load(self, key, host_loader, uploader):
        import time as _time
        with self._lock:
            got = self._graphs.get(key)
            if got is not None:
                return got + (0.0, 0.0)
            t0 = _time.time()
            hg = host_loader()
            gen_s = _time.time() - t0
            self._ledger.reserve(key, graph_bytes(hg))
            self._ledger.unpin(key)   # resident-but-evictable
            t0 = _time.time()
            g = uploader(hg)
            upload_s = _time.time() - t0
            self._graphs[key] = (hg, g)
            return hg, g, gen_s, upload_s
