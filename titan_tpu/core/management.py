"""Management system: schema DDL surface.

(reference: titan-core graphdb/database/management/ManagementSystem.java:1304
— schema creation/inspection; index lifecycle (SchemaAction) and instance
management land with the index subsystem.)
"""

from __future__ import annotations

from typing import Optional

from titan_tpu.core.defs import Cardinality, Multiplicity
from titan_tpu.core.schema import EdgeLabel, PropertyKey, VertexLabel
from titan_tpu.errors import TitanError


class ManagementSystem:
    def __init__(self, graph):
        self.graph = graph
        self.schema = graph.schema
        self._open = True
        # keys created through THIS management session: an index over only
        # fresh keys can be ENABLED immediately, one over pre-existing keys
        # starts INSTALLED and must go through REGISTER/REINDEX/ENABLE
        # (reference: ManagementSystem.buildIndex + SchemaStatus rules)
        self._created_keys: set[int] = set()

    # -- makers --------------------------------------------------------------

    def make_property_key(self, name: str, dtype: type = str,
                          cardinality: Cardinality = Cardinality.SINGLE
                          ) -> PropertyKey:
        pk = self.schema.make_property_key(name, dtype, cardinality)
        self._created_keys.add(pk.id)
        return pk

    def make_edge_label(self, name: str,
                        multiplicity: Multiplicity = Multiplicity.MULTI,
                        unidirected: bool = False,
                        sort_key: tuple = ()) -> EdgeLabel:
        return self.schema.make_edge_label(name, multiplicity, unidirected,
                                           sort_key)

    def make_vertex_label(self, name: str, partitioned: bool = False,
                          static: bool = False) -> VertexLabel:
        return self.schema.make_vertex_label(name, partitioned, static)

    # -- TTL (reference: TitanManagement.setTTL/getTTL — per-type cell TTL
    # honored by stores with features.cell_ttl) ------------------------------

    def set_ttl(self, schema_type, ttl_seconds: float):
        """TTL for relations of an edge label / property key, or for whole
        vertices of a STATIC vertex label (the reference's constraint:
        vertex TTL requires a static label, since later modifications would
        outlive the original cells)."""
        import dataclasses

        from titan_tpu.core.schema import (EdgeLabel, PropertyKey,
                                           SchemaType, VertexLabel)
        st = schema_type if isinstance(schema_type, SchemaType) \
            else self.schema.get_by_name(schema_type)
        if st is None or not isinstance(st, (EdgeLabel, PropertyKey,
                                             VertexLabel)):
            raise TitanError(f"cannot set TTL on {schema_type!r}")
        if isinstance(st, VertexLabel) and not st.static and ttl_seconds > 0:
            raise TitanError(
                f"vertex label {st.name!r} must be static to carry a TTL")
        if not self.graph.backend.features.cell_ttl and ttl_seconds > 0:
            raise TitanError(
                "storage backend does not support cell TTL")
        return self.schema.update_type(
            dataclasses.replace(st, ttl=float(ttl_seconds)))

    def get_ttl(self, schema_type) -> float:
        from titan_tpu.core.schema import SchemaType
        st = schema_type if isinstance(schema_type, SchemaType) \
            else self.schema.get_by_name(schema_type)
        return getattr(st, "ttl", 0.0) if st is not None else 0.0

    # -- inspection ----------------------------------------------------------

    def get_property_key(self, name: str) -> Optional[PropertyKey]:
        st = self.schema.get_by_name(name)
        return st if isinstance(st, PropertyKey) else None

    def get_edge_label(self, name: str) -> Optional[EdgeLabel]:
        st = self.schema.get_by_name(name)
        return st if isinstance(st, EdgeLabel) else None

    def get_vertex_label(self, name: str) -> Optional[VertexLabel]:
        st = self.schema.get_by_name(name)
        return st if isinstance(st, VertexLabel) else None

    def contains_relation_type(self, name: str) -> bool:
        st = self.schema.get_by_name(name)
        return isinstance(st, (PropertyKey, EdgeLabel))

    def contains_vertex_label(self, name: str) -> bool:
        return isinstance(self.schema.get_by_name(name), VertexLabel)

    # -- consistency (reference: TitanManagement.setConsistency) -------------

    def set_consistency(self, schema_type, modifier: str):
        """``modifier``: 'none' or 'lock' — LOCK types acquire consistent-key
        locks on their unique columns at commit."""
        if modifier not in ("none", "lock"):
            raise ValueError("consistency must be 'none' or 'lock'")
        import dataclasses
        updated = dataclasses.replace(schema_type, consistency=modifier)
        return self.schema.update_type(updated)

    # -- instances (reference: ManagementSystem instance surface) ------------

    def open_instances(self) -> list:
        return self.graph.backend.instance_registry.instances()

    # reference API name
    get_open_instances = open_instances

    def force_close_instance(self, instance_id: str) -> None:
        """Evict a dead instance's registration (reference:
        ManagementSystem.forceCloseInstance — for instances that crashed
        without deregistering)."""
        if instance_id == self.graph.instance_id:
            raise TitanError(
                "cannot force-close the current instance; close the graph")
        self.graph.backend.instance_registry.force_evict(instance_id)

    # -- graph indexes (reference: TitanManagement.buildIndex) ---------------

    def build_index(self, name: str, element: str = "vertex") -> "IndexBuilder":
        return IndexBuilder(self, name, element)

    def get_graph_index(self, name: str):
        from titan_tpu.core.schema import IndexDefinition
        st = self.schema.get_by_name(name)
        return st if isinstance(st, IndexDefinition) else None

    def get_graph_indexes(self, element: Optional[str] = None) -> list:
        return self.schema.indexes(element)

    def contains_graph_index(self, name: str) -> bool:
        return self.get_graph_index(name) is not None

    def update_index(self, index, action, num_threads: int = 2):
        """Apply a lifecycle transition (reference:
        ManagementSystem.updateIndex + SchemaAction semantics — REGISTER
        broadcasts and awaits acks; single-coordinator here, so transitions
        apply immediately; REINDEX/REMOVE run the scan jobs inline)."""
        from titan_tpu.core.defs import SchemaAction, SchemaStatus
        from titan_tpu.errors import TitanError
        if isinstance(action, str):
            action = SchemaAction(action)
        idx = self.get_graph_index(index if isinstance(index, str)
                                   else index.name)
        if idx is None:
            raise TitanError(f"unknown index: {index!r}")
        if not action.applicable_from(idx.status):
            raise TitanError(
                f"cannot {action.value} index {idx.name!r} from status "
                f"{idx.status.value}")

        from titan_tpu.indexing import jobs as index_jobs
        if action is SchemaAction.REGISTER_INDEX:
            return self._set_index_status(idx, SchemaStatus.REGISTERED)
        if action is SchemaAction.ENABLE_INDEX:
            return self._set_index_status(idx, SchemaStatus.ENABLED)
        if action is SchemaAction.DISABLE_INDEX:
            return self._set_index_status(idx, SchemaStatus.DISABLED)
        if action is SchemaAction.REINDEX:
            index_jobs.reindex(self.graph, idx, num_threads)
            return self._set_index_status(idx, SchemaStatus.ENABLED)
        if action is SchemaAction.REMOVE_INDEX:
            index_jobs.remove_index_data(self.graph, idx, num_threads)
            return idx

    def _set_index_status(self, idx, status):
        import dataclasses
        updated = dataclasses.replace(idx, status=status)
        return self.schema.update_type(updated)

    def await_graph_index_status(self, name: str, status=None,
                                 timeout_s: float = 60.0):
        """Block until the index reaches ``status`` (reference:
        GraphIndexStatusWatcher). Transitions are synchronous here, so this
        returns immediately — kept for API parity with the reference."""
        idx = self.get_graph_index(name)
        if idx is None:
            raise ValueError(f"unknown index {name!r}")
        return idx

    # -- cluster-global options ----------------------------------------------

    def set_global_option(self, option, value, *umbrella) -> None:
        from titan_tpu.config import ModifiableConfiguration, Restriction, defaults
        mod = ModifiableConfiguration(defaults.ROOT,
                                      self.graph.backend.global_config_store,
                                      Restriction.GLOBAL)
        mod.set(option, value, *umbrella)

    def get_global_option(self, option, *umbrella):
        from titan_tpu.config import Configuration, defaults
        cfg = Configuration(defaults.ROOT,
                            self.graph.backend.global_config_store)
        return cfg.get(option, *umbrella)

    def commit(self):
        self._open = False

    def rollback(self):
        self._open = False


class IndexBuilder:
    """Fluent index construction (reference: TitanManagement.IndexBuilder,
    ManagementSystem.buildIndex)."""

    def __init__(self, mgmt: ManagementSystem, name: str, element: str):
        if element not in ("vertex", "edge"):
            raise ValueError("element must be 'vertex' or 'edge'")
        self.mgmt = mgmt
        self.name = name
        self.element = element
        self._keys: list[tuple[int, str]] = []      # (key id, mapping param)
        self._unique = False
        self._index_only = 0

    def add_key(self, key, *params) -> "IndexBuilder":
        pk = key if not isinstance(key, str) else \
            self.mgmt.schema.get_by_name(key)
        if pk is None or not pk.is_property_key:
            raise ValueError(f"not a property key: {key!r}")
        self._keys.append((pk.id, params[0] if params else "DEFAULT"))
        return self

    def unique(self) -> "IndexBuilder":
        self._unique = True
        return self

    def index_only(self, label) -> "IndexBuilder":
        st = label if not isinstance(label, str) else \
            self.mgmt.schema.get_by_name(label)
        if st is None:
            raise ValueError(f"unknown schema type {label!r}")
        self._index_only = st.id
        return self

    def _initial_status(self):
        from titan_tpu.core.defs import SchemaStatus
        fresh = all(kid in self.mgmt._created_keys
                    for kid, _ in self._keys)
        return SchemaStatus.ENABLED if fresh else SchemaStatus.INSTALLED

    def build_composite_index(self):
        if not self._keys:
            raise ValueError("an index needs at least one key")
        return self.mgmt.schema.make_index(
            self.name, self.element, composite=True,
            key_ids=tuple(k for k, _ in self._keys),
            key_params=tuple(p for _, p in self._keys),
            unique=self._unique, index_only=self._index_only,
            status=self._initial_status())

    def build_mixed_index(self, backing: str):
        if not self._keys:
            raise ValueError("an index needs at least one key")
        if self._unique:
            raise ValueError("mixed indexes cannot be unique")
        idx = self.mgmt.schema.make_index(
            self.name, self.element, composite=False,
            key_ids=tuple(k for k, _ in self._keys),
            key_params=tuple(p for _, p in self._keys),
            backing=backing, index_only=self._index_only,
            status=self._initial_status())
        provider = self.mgmt.graph.index_provider(backing)
        if provider is not None:
            self.mgmt.graph.index_serializer.register_keys(provider, idx)
        return idx
