"""Management system: schema DDL surface.

(reference: titan-core graphdb/database/management/ManagementSystem.java:1304
— schema creation/inspection; index lifecycle (SchemaAction) and instance
management land with the index subsystem.)
"""

from __future__ import annotations

from typing import Optional

from titan_tpu.core.defs import Cardinality, Multiplicity
from titan_tpu.core.schema import EdgeLabel, PropertyKey, VertexLabel


class ManagementSystem:
    def __init__(self, graph):
        self.graph = graph
        self.schema = graph.schema
        self._open = True

    # -- makers --------------------------------------------------------------

    def make_property_key(self, name: str, dtype: type = str,
                          cardinality: Cardinality = Cardinality.SINGLE
                          ) -> PropertyKey:
        return self.schema.make_property_key(name, dtype, cardinality)

    def make_edge_label(self, name: str,
                        multiplicity: Multiplicity = Multiplicity.MULTI,
                        unidirected: bool = False,
                        sort_key: tuple = ()) -> EdgeLabel:
        return self.schema.make_edge_label(name, multiplicity, unidirected,
                                           sort_key)

    def make_vertex_label(self, name: str, partitioned: bool = False,
                          static: bool = False) -> VertexLabel:
        return self.schema.make_vertex_label(name, partitioned, static)

    # -- inspection ----------------------------------------------------------

    def get_property_key(self, name: str) -> Optional[PropertyKey]:
        st = self.schema.get_by_name(name)
        return st if isinstance(st, PropertyKey) else None

    def get_edge_label(self, name: str) -> Optional[EdgeLabel]:
        st = self.schema.get_by_name(name)
        return st if isinstance(st, EdgeLabel) else None

    def get_vertex_label(self, name: str) -> Optional[VertexLabel]:
        st = self.schema.get_by_name(name)
        return st if isinstance(st, VertexLabel) else None

    def contains_relation_type(self, name: str) -> bool:
        st = self.schema.get_by_name(name)
        return isinstance(st, (PropertyKey, EdgeLabel))

    def contains_vertex_label(self, name: str) -> bool:
        return isinstance(self.schema.get_by_name(name), VertexLabel)

    # -- consistency (reference: TitanManagement.setConsistency) -------------

    def set_consistency(self, schema_type, modifier: str):
        """``modifier``: 'none' or 'lock' — LOCK types acquire consistent-key
        locks on their unique columns at commit."""
        if modifier not in ("none", "lock"):
            raise ValueError("consistency must be 'none' or 'lock'")
        import dataclasses
        updated = dataclasses.replace(schema_type, consistency=modifier)
        return self.schema.update_type(updated)

    # -- instances (reference: ManagementSystem instance surface) ------------

    def open_instances(self) -> list:
        return self.graph.backend.instance_registry.instances()

    def force_close_instance(self, instance_id: str) -> None:
        self.graph.backend.instance_registry.force_evict(instance_id)

    # -- cluster-global options ----------------------------------------------

    def set_global_option(self, option, value, *umbrella) -> None:
        from titan_tpu.config import ModifiableConfiguration, Restriction, defaults
        mod = ModifiableConfiguration(defaults.ROOT,
                                      self.graph.backend.global_config_store,
                                      Restriction.GLOBAL)
        mod.set(option, value, *umbrella)

    def get_global_option(self, option, *umbrella):
        from titan_tpu.config import Configuration, defaults
        cfg = Configuration(defaults.ROOT,
                            self.graph.backend.global_config_store)
        return cfg.get(option, *umbrella)

    def commit(self):
        self._open = False

    def rollback(self):
        self._open = False
