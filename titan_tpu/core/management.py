"""Management system: schema DDL surface.

(reference: titan-core graphdb/database/management/ManagementSystem.java:1304
— schema creation/inspection; index lifecycle (SchemaAction) and instance
management land with the index subsystem.)
"""

from __future__ import annotations

from typing import Optional

from titan_tpu.core.defs import Cardinality, Multiplicity
from titan_tpu.core.schema import EdgeLabel, PropertyKey, VertexLabel


class ManagementSystem:
    def __init__(self, graph):
        self.graph = graph
        self.schema = graph.schema
        self._open = True

    # -- makers --------------------------------------------------------------

    def make_property_key(self, name: str, dtype: type = str,
                          cardinality: Cardinality = Cardinality.SINGLE
                          ) -> PropertyKey:
        return self.schema.make_property_key(name, dtype, cardinality)

    def make_edge_label(self, name: str,
                        multiplicity: Multiplicity = Multiplicity.MULTI,
                        unidirected: bool = False,
                        sort_key: tuple = ()) -> EdgeLabel:
        return self.schema.make_edge_label(name, multiplicity, unidirected,
                                           sort_key)

    def make_vertex_label(self, name: str, partitioned: bool = False,
                          static: bool = False) -> VertexLabel:
        return self.schema.make_vertex_label(name, partitioned, static)

    # -- inspection ----------------------------------------------------------

    def get_property_key(self, name: str) -> Optional[PropertyKey]:
        st = self.schema.get_by_name(name)
        return st if isinstance(st, PropertyKey) else None

    def get_edge_label(self, name: str) -> Optional[EdgeLabel]:
        st = self.schema.get_by_name(name)
        return st if isinstance(st, EdgeLabel) else None

    def get_vertex_label(self, name: str) -> Optional[VertexLabel]:
        st = self.schema.get_by_name(name)
        return st if isinstance(st, VertexLabel) else None

    def contains_relation_type(self, name: str) -> bool:
        st = self.schema.get_by_name(name)
        return isinstance(st, (PropertyKey, EdgeLabel))

    def contains_vertex_label(self, name: str) -> bool:
        return isinstance(self.schema.get_by_name(name), VertexLabel)

    def commit(self):
        self._open = False

    def rollback(self):
        self._open = False
