"""Public element objects: Vertex, Edge, VertexProperty.

(reference: titan-core core/TitanVertex.java, TitanEdge.java,
TitanVertexProperty.java + the internal implementations under
graphdb/vertices/ and graphdb/relations/. These are thin tx-bound handles:
all state lives in the transaction's caches and the store.)
"""

from __future__ import annotations

from typing import Any, Iterator, Optional

from titan_tpu.core.defs import Cardinality, Direction, RelationCategory
from titan_tpu.core.relations import InternalRelation
from titan_tpu.errors import InvalidElementError


_UNSET = object()


class Element:
    __slots__ = ("tx", "_id")

    def __init__(self, tx, eid: int):
        self.tx = tx
        self._id = eid

    @property
    def id(self) -> int:
        return self._id

    @property
    def graph(self):
        return self.tx.graph

    def __eq__(self, other):
        return isinstance(other, Element) and other._id == self._id

    def __hash__(self):
        return hash(self._id)


class Vertex(Element):
    __slots__ = ()

    # -- schema --------------------------------------------------------------

    def label(self) -> str:
        return self.tx.vertex_label_name(self._id)

    # -- properties ----------------------------------------------------------

    def property(self, key: str, value: Any = _UNSET) -> Any:
        """``v.property("k")`` reads; ``v.property("k", v)`` writes."""
        if value is _UNSET:
            props = list(self.tx.vertex_properties(self._id, [key]))
            return props[0] if props else None
        return self.tx.add_property(self, key, value)

    def value(self, key: str, default: Any = None) -> Any:
        props = list(self.tx.vertex_properties(self._id, [key]))
        if not props:
            return default
        return props[0].value

    def values(self, *keys: str) -> list:
        return [p.value for p in self.tx.vertex_properties(self._id,
                                                           list(keys) or None)]

    def properties(self, *keys: str) -> Iterator["VertexProperty"]:
        return self.tx.vertex_properties(self._id, list(keys) or None)

    # -- adjacency -----------------------------------------------------------

    def add_edge(self, label: str, in_vertex: "Vertex", **props) -> "Edge":
        return self.tx.add_edge(self, label, in_vertex, props)

    def edges(self, direction: Direction = Direction.BOTH,
              *labels: str) -> Iterator["Edge"]:
        return self.tx.vertex_edges(self._id, direction, list(labels) or None)

    def out_edges(self, *labels: str):
        return self.edges(Direction.OUT, *labels)

    def in_edges(self, *labels: str):
        return self.edges(Direction.IN, *labels)

    def vertices(self, direction: Direction = Direction.BOTH,
                 *labels: str) -> Iterator["Vertex"]:
        for e in self.edges(direction, *labels):
            yield e.other(self)

    def out(self, *labels: str):
        return self.vertices(Direction.OUT, *labels)

    def in_(self, *labels: str):
        return self.vertices(Direction.IN, *labels)

    def both(self, *labels: str):
        return self.vertices(Direction.BOTH, *labels)

    def query(self):
        from titan_tpu.query.vertexquery import VertexCentricQueryBuilder
        return VertexCentricQueryBuilder(self.tx, self._id)

    def degree(self, direction: Direction = Direction.BOTH, *labels) -> int:
        return sum(1 for _ in self.edges(direction, *labels))

    def remove(self) -> None:
        self.tx.remove_vertex(self)

    def __repr__(self):
        return f"v[{self._id}]"


class RelationElement(Element):
    """Base for edges and vertex properties (both are relations)."""
    __slots__ = ("rel",)

    def __init__(self, tx, rel: InternalRelation):
        super().__init__(tx, rel.relation_id)
        self.rel = rel

    def type_name(self) -> str:
        return self.tx.schema_name(self.rel.type_id)

    def property_map(self) -> dict:
        """Inline properties by key name: edge properties on an Edge,
        meta-properties on a VertexProperty."""
        return {self.tx.schema_name(kid): v
                for kid, v in self.rel.properties.items()}

    def remove(self) -> None:
        self.tx.remove_relation(self.rel)


class Edge(RelationElement):
    __slots__ = ()

    def label(self) -> str:
        return self.type_name()

    def out_vertex(self) -> Vertex:
        return self.tx.vertex_handle(self.rel.out_vertex_id)

    def in_vertex(self) -> Vertex:
        return self.tx.vertex_handle(self.rel.in_vertex_id)

    def other(self, v: Vertex) -> Vertex:
        return self.tx.vertex_handle(self.rel.other_vertex_id(v.id))

    def vertices(self):
        return (self.out_vertex(), self.in_vertex())

    def value(self, key: str, default: Any = None) -> Any:
        st = self.tx.schema.get_by_name(key)
        if st is None:
            return default
        return self.rel.properties.get(st.id, default)

    def values(self, *keys: str) -> list:
        return [self.value(k) for k in keys]

    def __repr__(self):
        return (f"e[{self._id}][{self.rel.out_vertex_id}-"
                f"{self.label()}->{self.rel.in_vertex_id}]")


class VertexProperty(RelationElement):
    __slots__ = ()

    def key(self) -> str:
        return self.type_name()

    @property
    def value(self) -> Any:
        return self.rel.value

    def meta(self, key: str, default: Any = None) -> Any:
        """Read a meta-property (reference: TitanVertexProperty.value(key));
        set via tx.add_meta_property."""
        st = self.tx.schema.get_by_name(key)
        if st is None:
            return default
        return self.rel.properties.get(st.id, default)

    def element(self) -> Vertex:
        return self.tx.vertex_handle(self.rel.out_vertex_id)

    def __repr__(self):
        return f"vp[{self.key()}->{self.value!r}]"
