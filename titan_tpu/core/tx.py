"""Graph transaction: element caches, read-your-writes queries, commit.

Re-creation of the reference's transaction engine (reference: titan-core
graphdb/transaction/StandardTitanTx.java:83-1414 — per-tx vertex cache,
added/deleted relation sets, the ``edgeProcessor`` merge of stored slices
with in-tx deltas :1049-1122, commit/rollback :1344-1390) and the graph
commit path (graphdb/database/StandardTitanGraph.java prepareCommit
:493-616, commit :634-789): added/deleted relations re-serialize through the
deterministic edge codec into per-vertex-row mutation batches, flushed as one
batched backend call.

Constraint enforcement (reference: StandardTitanTx connectionEdges /
MultiplicityConstraint checks): SINGLE-cardinality properties replace the
previous value; SET rejects duplicates; unique edge directions
(MANY2ONE/ONE2ONE/ONE2MANY) reject a second edge; SIMPLE rejects parallel
edges.
"""

from __future__ import annotations

import threading
from typing import Any, Iterator, Optional

from titan_tpu.core.defs import (Cardinality, Direction, ElementLifecycle,
                                 Multiplicity, RelationCategory)
from titan_tpu.core.elements import Edge, Vertex, VertexProperty
from titan_tpu.core.relations import InternalRelation
from titan_tpu.errors import (InvalidElementError, SchemaViolationError,
                              TransactionClosedError)
from titan_tpu.ids import IDType
from titan_tpu.storage.api import Entry, KeySliceQuery, SliceQuery


_EMPTY_PROPS: Optional[bytes] = None


def _empty_props_bytes() -> bytes:
    """The codec's encoding of an empty edge property section (the uvar
    for count 0 — one 0x80 byte in the MSB-terminated varint scheme)."""
    global _EMPTY_PROPS
    if _EMPTY_PROPS is None:
        from titan_tpu.codec.dataio import DataOutput
        out = DataOutput()
        out.put_uvar(0)
        _EMPTY_PROPS = out.getvalue()
    return _EMPTY_PROPS


def _values_equal(a: Any, b: Any) -> bool:
    """Property-value equality that tolerates ndarray values (whose ==
    broadcasts instead of answering)."""
    import numpy as np
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return bool(np.array_equal(a, b))
    return bool(a == b)


class GraphTransaction:
    def __init__(self, graph, read_only: bool = False,
                 log_identifier: Optional[str] = None):
        self.graph = graph
        self.schema = graph.schema
        self.codec = graph.codec
        self.idm = graph.idm
        self.read_only = read_only
        # trigger log: changes of this tx stream to ulog_<identifier>
        # (reference: docs/TitanBus.md:5-13, tx logIdentifier)
        self.log_identifier = log_identifier
        self._backend_tx = None
        self._open = True
        self._lock = threading.RLock()

        # caches & deltas
        self._vertices: dict[int, Vertex] = {}
        self._new_vertices: set[int] = set()
        self._removed_vertices: set[int] = set()
        self._vertex_labels: dict[int, int] = {}     # vid -> label schema id
        self._added: dict[int, InternalRelation] = {}        # rel id -> rel
        self._deleted: dict[int, InternalRelation] = {}      # rel id -> rel
        self._added_by_vertex: dict[int, list] = {}          # vid -> [rel]
        # per-vertex slice cache with query subsumption (reference:
        # CacheVertex — loaded EntryLists are reused within the tx; deltas
        # are merged on top by _iter_relations, so no invalidation needed)
        self._slice_cache: dict[bytes, list] = {}   # key -> [(SliceQuery, entries)]
        self._slice_cache_size = 0
        # parsed-adjacency cache: (vid, direction, type_ids) -> [Edge] for
        # the STORED part of the adjacency (deltas are merged per read).
        # The reference's tx vertex cache holds parsed relations, not raw
        # bytes (StandardTitanTx.java:83-1414 vertex cache + CacheVertex),
        # so repeated traversals over the same vertices skip the column
        # decode entirely; this is the analog for the batched DSL path.
        self._adj_cache: dict[tuple, list] = {}
        self._adj_cache_size = 0
        from titan_tpu.config import defaults as _d
        self._slice_cache_cap = graph.config.get(_d.TX_CACHE_SIZE)
        self._fast_property = graph.config.get(_d.FAST_PROPERTY)
        from titan_tpu.storage.locking import LockState
        self._lock_state = LockState()

    # ------------------------------------------------------------------ infra

    @property
    def backend_tx(self):
        if self._backend_tx is None:
            self._backend_tx = self.graph.backend.begin_transaction(
                index_txs=self.graph.open_index_txs())
        return self._backend_tx

    def _check_open(self):
        if not self._open:
            raise TransactionClosedError("transaction is closed")

    @property
    def is_open(self) -> bool:
        return self._open

    def edge_query(self, ksq) -> list:
        """Edgestore slice read through the per-tx vertex slice cache
        (reference: CacheVertex.loadRelations — an already-loaded slice that
        subsumes the request answers it without a backend call)."""
        cached = self._slice_cache.get(ksq.key)
        if cached is not None:
            for q, entries in cached:
                if q.subsumes(ksq.slice):
                    from titan_tpu.storage.api import apply_slice
                    return apply_slice(entries, ksq.slice)
        entries = self.backend_tx.edge_store_query(ksq)
        if self._slice_cache_size < self._slice_cache_cap:
            self._slice_cache.setdefault(ksq.key, []).append((ksq.slice, entries))
            self._slice_cache_size += len(entries) + 1
        return entries

    def _multi_edge_query(self, keys, q) -> dict:
        """Batched multi-row slice read through the tx slice cache: cached
        rows answer locally, the rest go in ONE edge_store_multi_query."""
        from titan_tpu.storage.api import apply_slice
        result: dict[bytes, list] = {}
        misses = []
        for kb in keys:
            hit = None
            for cq, entries in self._slice_cache.get(kb, ()):
                if cq.subsumes(q):
                    hit = apply_slice(entries, q)
                    break
            if hit is None:
                misses.append(kb)
            else:
                result[kb] = hit
        if misses:
            fetched = self.backend_tx.edge_store_multi_query(misses, q)
            result.update(fetched)
            for kb in misses:
                if self._slice_cache_size < self._slice_cache_cap:
                    entries = fetched.get(kb, [])
                    self._slice_cache.setdefault(kb, []).append((q, entries))
                    self._slice_cache_size += len(entries) + 1
        return result

    def vertex_handle(self, vid: int) -> Vertex:
        v = self._vertices.get(vid)
        if v is None:
            v = Vertex(self, vid)
            self._vertices[vid] = v
        return v

    def schema_name(self, type_id: int) -> str:
        name = self.schema.system.name_of(type_id)
        if name is not None:
            return name
        st = self.schema.get_type(type_id)
        if st is None:
            raise InvalidElementError(f"unknown schema id {type_id}")
        return st.name

    # ---------------------------------------------------------------- writes

    def add_vertex(self, label: Optional[str] = None, vertex_id: Optional[int] = None,
                   **props) -> Vertex:
        self._check_open()
        if self.read_only:
            raise SchemaViolationError("read-only transaction")
        label_type = None
        if label is not None:
            label_type = self.schema.get_or_create_vertex_label(label)
        if vertex_id is not None:
            if not self.graph.allow_custom_vid:
                raise SchemaViolationError(
                    "custom vertex ids disabled (graph.set-vertex-id)")
            vid = vertex_id
        else:
            idtype = IDType.NORMAL_VERTEX
            if label_type is not None and label_type.partitioned:
                idtype = IDType.PARTITIONED_VERTEX
            vid = self.graph.id_assigner.next_vertex_id(idtype=idtype)
            if idtype is IDType.PARTITIONED_VERTEX:
                # the user-visible id of a vertex-cut vertex is its CANONICAL
                # representative; system relations and properties live on the
                # canonical row, adjacency spreads over all representatives
                # (reference: IDManager.getCanonicalVertexId, vertex state at
                # the canonical copy)
                vid = self.idm.canonical_vertex_id(vid)
        v = self.vertex_handle(vid)
        self._new_vertices.add(vid)
        # existence marker (reference: BaseKey.VertexExists)
        self._add_relation(InternalRelation(
            self.graph.id_assigner.next_relation_id(),
            self.schema.system.vertex_exists, RelationCategory.PROPERTY,
            vid, value=True))
        if label_type is not None:
            self._vertex_labels[vid] = label_type.id
            self._add_relation(InternalRelation(
                self.graph.id_assigner.next_relation_id(),
                self.schema.system.vertex_label_edge, RelationCategory.EDGE,
                vid, label_type.id))
        for k, val in props.items():
            self.add_property(v, k, val)
        return v

    def _add_relation(self, rel: InternalRelation) -> InternalRelation:
        self._added[rel.relation_id] = rel
        for vid in rel.vertex_ids():
            if vid is not None and not self.idm.is_schema_id(vid):
                self._added_by_vertex.setdefault(vid, []).append(rel)
            elif vid is not None and self.idm.is_schema_id(vid):
                # vertex-label edges point at schema vertices; only the OUT
                # side materializes (labels don't list their members here)
                pass
        return rel

    def add_property(self, v: Vertex, key: str, value: Any) -> VertexProperty:
        self._check_open()
        if self.read_only:
            raise SchemaViolationError("read-only transaction")
        self._check_vertex_writable(v.id)
        pk = self.schema.get_or_create_key(key, value)
        value = self._validate_value(pk, key, value)
        if pk.cardinality is Cardinality.SINGLE:
            for p in self.vertex_properties(v.id, [key]):
                self.remove_relation(p.rel)
        elif pk.cardinality is Cardinality.SET:
            for p in self.vertex_properties(v.id, [key]):
                if _values_equal(p.rel.value, value):
                    return p  # set semantics: already present
        rel = self._add_relation(InternalRelation(
            self.graph.id_assigner.next_relation_id(), pk.id,
            RelationCategory.PROPERTY, v.id, value=value))
        return VertexProperty(self, rel)

    def add_meta_property(self, p: VertexProperty, key: str,
                          value: Any) -> VertexProperty:
        """Attach a meta-property to a vertex property (reference:
        TitanVertexProperty.property() — properties ON properties ride the
        owning relation's inline property map, like edge properties).

        Meta data is serialized inline with the owning relation, so a
        property LOADED from storage is rewritten: the old relation is
        deleted and re-added with the merged property map (same value,
        same key, new relation id) — matching the reference, where
        setting a property on a loaded TitanVertexProperty also rewrites
        the backing relation."""
        self._check_open()
        if self.read_only:
            raise SchemaViolationError("read-only transaction")
        pk = self.schema.get_or_create_key(key, value)
        value = self._validate_value(pk, key, value)
        if p.rel.relation_id in self._added:
            p.rel.properties[pk.id] = value
            return p
        old = p.rel
        self._check_vertex_writable(old.out_vertex_id)
        self.remove_relation(old)
        rel = InternalRelation(
            self.graph.id_assigner.next_relation_id(), old.type_id,
            RelationCategory.PROPERTY, old.out_vertex_id, value=old.value)
        rel.properties.update(old.properties)
        rel.properties[pk.id] = value
        self._add_relation(rel)
        # repoint the caller's handle at the rewritten relation so a second
        # add_meta_property on the same handle merges instead of rewriting
        # from the stale pre-rewrite relation (which would drop this meta)
        p.rel = rel
        return p

    def _validate_value(self, pk, key: str, value: Any) -> Any:
        """Enforce the key's declared dtype, coercing where lossless."""
        if pk.dtype is not None and not isinstance(value, pk.dtype):
            coerced = self._coerce(value, pk.dtype)
            if coerced is None:
                raise SchemaViolationError(
                    f"value {value!r} is not a {pk.dtype.__name__} "
                    f"(key {key!r})")
            value = coerced
        return value

    @staticmethod
    def _coerce(value, dtype):
        if dtype is float and isinstance(value, int):
            return float(value)
        if dtype is int and isinstance(value, bool):
            return None
        return None

    def add_edge(self, out_v: Vertex, label: str, in_v: Vertex,
                 props: Optional[dict] = None) -> Edge:
        self._check_open()
        if self.read_only:
            raise SchemaViolationError("read-only transaction")
        self._check_vertex_writable(out_v.id)
        self._check_vertex_writable(in_v.id)
        el = self.schema.get_or_create_label(label)
        self._check_multiplicity(el, out_v, in_v)
        rel = InternalRelation(
            self.graph.id_assigner.next_relation_id(), el.id,
            RelationCategory.EDGE, out_v.id, in_v.id)
        for k, val in (props or {}).items():
            pk = self.schema.get_or_create_key(k, val)
            rel.properties[pk.id] = self._validate_value(pk, k, val)
        self._add_relation(rel)
        return Edge(self, rel)

    def _check_multiplicity(self, el, out_v: Vertex, in_v: Vertex):
        mult = el.multiplicity
        if mult is Multiplicity.MULTI:
            return
        if mult.unique(Direction.OUT) or mult is Multiplicity.SIMPLE:
            for e in self.vertex_edges(out_v.id, Direction.OUT, [el.name]):
                if mult is not Multiplicity.SIMPLE or \
                        e.rel.other_vertex_id(out_v.id) == in_v.id:
                    raise SchemaViolationError(
                        f"multiplicity {mult.value} violated on {el.name!r} "
                        f"(existing out-edge)")
        if mult.unique(Direction.IN):
            for _ in self.vertex_edges(in_v.id, Direction.IN, [el.name]):
                raise SchemaViolationError(
                    f"multiplicity {mult.value} violated on {el.name!r} "
                    f"(existing in-edge)")

    def _check_vertex_writable(self, vid: int):
        if vid in self._removed_vertices:
            raise InvalidElementError(f"vertex {vid} was removed in this tx")
        # static vertex labels are immutable after the creating tx
        # (reference: VertexLabel.isStatic — required for vertex TTL, since
        # later writes would outlive the original cells)
        if vid not in self._new_vertices and self.idm.is_user_vertex_id(vid):
            self.vertex_label_name(vid)      # populate the label cache
            lid = self._vertex_labels.get(vid) or 0
            if lid:
                st = self.schema.get_type(lid)
                if st is not None and getattr(st, "static", False):
                    raise SchemaViolationError(
                        f"vertex {vid} has static label {st.name!r} and "
                        "cannot be modified after creation")

    def remove_relation(self, rel: InternalRelation) -> None:
        self._check_open()
        if self.read_only:
            raise SchemaViolationError("read-only transaction")
        # removing a relation modifies BOTH endpoint vertices — static
        # (immutable-after-creation) endpoints forbid it
        for vid in rel.vertex_ids():
            if vid is not None and not self.idm.is_schema_id(vid):
                self._check_vertex_writable(vid)
        if rel.relation_id in self._added:
            del self._added[rel.relation_id]
            for vid in rel.vertex_ids():
                if vid is not None and vid in self._added_by_vertex:
                    try:
                        self._added_by_vertex[vid].remove(rel)
                    except ValueError:
                        pass
        else:
            rel.lifecycle = ElementLifecycle.REMOVED
            self._deleted[rel.relation_id] = rel

    def remove_vertex(self, v: Vertex) -> None:
        self._check_open()
        if self.read_only:
            raise SchemaViolationError("read-only transaction")
        if v.id not in self._removed_vertices:
            self._check_vertex_writable(v.id)
        # delete every incident relation (incl. existence + label edge)
        for rel in list(self._iter_relations(v.id, Direction.BOTH, None,
                                             RelationCategory.RELATION,
                                             include_system=True)):
            self.remove_relation(rel)
        self._removed_vertices.add(v.id)
        self._new_vertices.discard(v.id)

    # ----------------------------------------------------------------- reads

    def vertex(self, vid: int) -> Optional[Vertex]:
        """Vertex by id, or None if it doesn't exist. A representative id of
        a vertex cut resolves to its canonical vertex."""
        self._check_open()
        if self.idm.is_partitioned_vertex(vid):
            vid = self.idm.canonical_vertex_id(vid)
        if vid in self._removed_vertices:
            return None
        if vid in self._new_vertices:
            return self.vertex_handle(vid)
        if not self.idm.is_user_vertex_id(vid):
            return None
        if self._vertex_exists(vid):
            return self.vertex_handle(vid)
        return None

    def _vertex_exists(self, vid: int) -> bool:
        [q] = self.codec.query_type(self.schema.system.vertex_exists,
                                    Direction.OUT, self.schema)
        entries = self.edge_query(KeySliceQuery(self.idm.key_bytes(vid), q))
        return bool(entries)

    def vertices(self) -> Iterator[Vertex]:
        """All vertices (full scan; reference: StandardTitanTx.java:1260-1282
        full-scan fallback)."""
        self._check_open()
        [q] = self.codec.query_type(self.schema.system.vertex_exists,
                                    Direction.OUT, self.schema)
        seen = set()
        for key, entries in self.backend_tx.edge_store_keys(q):
            if not entries:
                continue
            vid = self.idm.id_of_key_bytes(key)
            if not self.idm.is_user_vertex_id(vid):
                continue
            if vid in self._removed_vertices or vid in seen:
                continue
            seen.add(vid)
            yield self.vertex_handle(vid)
        for vid in sorted(self._new_vertices - seen):
            if vid not in self._removed_vertices:
                yield self.vertex_handle(vid)

    def vertex_label_name(self, vid: int) -> str:
        lid = self._vertex_labels.get(vid)
        if lid is None:
            for rel in self._iter_relations(vid, Direction.OUT, None,
                                            RelationCategory.EDGE,
                                            include_system=True):
                if rel.type_id == self.schema.system.vertex_label_edge:
                    lid = rel.in_vertex_id
                    break
            self._vertex_labels[vid] = lid if lid is not None else 0
        if not lid:
            return "vertex"
        st = self.schema.get_type(lid)
        return st.name if st else "vertex"

    def vertex_properties(self, vid: int, keys: Optional[list] = None
                          ) -> Iterator[VertexProperty]:
        self._check_open()
        type_ids = None
        if keys is not None:
            type_ids = []
            for k in keys:
                st = self.schema.get_by_name(k)
                if st is not None:
                    type_ids.append(st.id)
            if not type_ids:
                return
            if self._fast_property and vid not in self._new_vertices and \
                    self._slice_cache_size < self._slice_cache_cap:
                # property prefetch (reference: query.fast-property,
                # StandardTitanTx — load the whole property slice once so
                # subsequent single-key reads answer from the tx cache)
                self.edge_query(KeySliceQuery(
                    self.idm.key_bytes(vid),
                    self.codec.query_category(RelationCategory.PROPERTY,
                                              Direction.OUT,
                                              include_system=False)))
        for rel in self._iter_relations(vid, Direction.OUT, type_ids,
                                        RelationCategory.PROPERTY):
            yield VertexProperty(self, rel)

    def vertex_edges(self, vid: int, direction: Direction = Direction.BOTH,
                     labels: Optional[list] = None) -> Iterator[Edge]:
        self._check_open()
        type_ids = None
        if labels is not None:
            type_ids = []
            for name in labels:
                st = self.schema.get_by_name(name)
                if st is not None:
                    type_ids.append(st.id)
            if not type_ids:
                return
        for rel in self._iter_relations(vid, direction, type_ids,
                                        RelationCategory.EDGE):
            yield Edge(self, rel)

    # the edgeProcessor: merge stored slices with the tx delta
    def _iter_relations(self, vid: int, direction: Direction,
                        type_ids: Optional[list], category: RelationCategory,
                        include_system: bool = False) -> Iterator[InternalRelation]:
        emitted: set[tuple] = set()
        if vid not in self._new_vertices:
            for rel in self._stored_relations(vid, direction, type_ids,
                                              category, include_system):
                key = (rel.relation_id, rel.direction_of(vid) if rel.is_edge
                       else Direction.OUT)
                if rel.relation_id in self._deleted or key in emitted:
                    continue
                emitted.add(key)
                yield rel
        for rel in self._added_by_vertex.get(vid, ()):  # in-tx additions
            if not self._matches(rel, vid, direction, type_ids, category,
                                 include_system):
                continue
            key = (rel.relation_id,
                   rel.direction_of(vid) if rel.is_edge else Direction.OUT)
            if key in emitted:
                continue
            emitted.add(key)
            yield rel

    def _matches(self, rel: InternalRelation, vid: int, direction: Direction,
                 type_ids: Optional[list], category: RelationCategory,
                 include_system: bool) -> bool:
        if category is RelationCategory.EDGE and not rel.is_edge:
            return False
        if category is RelationCategory.PROPERTY and not rel.is_property:
            return False
        if type_ids is not None:
            if rel.type_id not in type_ids:
                return False
        elif not include_system and self.schema.system.is_system(rel.type_id):
            return False
        if rel.is_edge:
            d = rel.direction_of(vid)
            if direction is not Direction.BOTH and d is not direction:
                return False
        return True

    def _slices_for(self, direction, type_ids, category, include_system):
        if type_ids is not None:
            slices = []
            for tid in type_ids:
                slices.extend(self.codec.query_type(tid, direction, self.schema))
            return slices
        if category is RelationCategory.RELATION and include_system:
            return [self.codec.query_all()]
        return [self.codec.query_category(category, direction, include_system)]

    def _stored_relations(self, vid, direction, type_ids, category,
                          include_system) -> Iterator[InternalRelation]:
        # a vertex cut's adjacency is spread over ALL representative rows;
        # properties/system relations live on the canonical row only
        # (reference: OLTP reads fan out over getPartitionedVertexRepresentatives)
        if self.idm.is_partitioned_vertex(vid) and \
                category is not RelationCategory.PROPERTY:
            keys = [self.idm.key_bytes(r)
                    for r in self.idm.partitioned_vertex_representatives(vid)]
        else:
            keys = [self.idm.key_bytes(vid)]
        for q in self._slices_for(direction, type_ids, category, include_system):
            if len(keys) == 1:
                per_key = {keys[0]: self.edge_query(KeySliceQuery(keys[0], q))}
            else:
                # vertex cut: ONE batched multi-row read over all
                # representative rows instead of num_partitions point reads
                per_key = self._multi_edge_query(keys, q)
            for key in keys:
                for entry in per_key.get(key, ()):
                    rc = self.codec.parse(entry, self.schema)
                    rel = self._relation_from_cache(vid, rc)
                    if self._matches(rel, vid, direction, type_ids, category,
                                     include_system):
                        yield rel

    def _bulk_parse_out(self, items: list):
        """Vectorized decode of OUT-edge entries via the native codec
        (cites the same fast-shape rules as olap/tpu/snapshot._scan_native):
        returns a list aligned with ``items`` holding
        (relation_id, type_id, other_vertex_id) for entries of MULTI
        labels with no sort key and an empty property section (the value
        is exactly the codec's uvar encoding of property-count 0 — one
        0x80 byte in the MSB-terminated scheme, see
        _empty_props_bytes), and None where the per-entry parser must
        run. Returns None when the native codec is unavailable."""
        from titan_tpu import native
        if not native.available:
            return None
        import numpy as np

        cols = bytearray()
        offs = [0]
        for _vid, e in items:
            cols += e.column
            offs.append(len(cols))
        col_buf = np.frombuffer(bytes(cols), dtype=np.uint8)
        offs_a = np.asarray(offs, dtype=np.int64)
        try:
            kind, tcount, dpos = native.parse_heads(col_buf, offs_a, b"")
        except ValueError:
            return None             # unknown head shape: per-entry parse
        fast_counts = []
        for c in np.unique(tcount[kind == native.KIND_OUT_EDGE]).tolist():
            tid = self.idm.schema_id(IDType.USER_EDGE_LABEL, int(c))
            if (self.schema.multiplicity(tid) is Multiplicity.MULTI
                    and not self.schema.sort_key(tid)):
                fast_counts.append(c)
        ends = offs_a[1:]
        mask = (kind == native.KIND_OUT_EDGE) \
            & np.isin(tcount, fast_counts)
        if mask.any():
            # empty-props check: the value section is exactly the uvar
            # encoding of property-count 0
            empty = _empty_props_bytes()
            vempty = np.fromiter((e.value == empty for _v, e in items),
                                 dtype=bool, count=len(items))
            mask &= vempty
        idx = np.flatnonzero(mask)
        if not len(idx):
            return None
        others, p2 = native.bulk_read_uvar(col_buf, dpos[idx], ends[idx])
        relids, _ = native.bulk_read_uvar(col_buf, p2, ends[idx])
        out: list = [None] * len(items)
        sid = self.idm.schema_id
        for k, j in enumerate(idx.tolist()):
            out[j] = (int(relids[k]),
                      sid(IDType.USER_EDGE_LABEL, int(tcount[j])),
                      int(others[k]))
        return out

    def _relation_from_cache(self, vid: int, rc) -> InternalRelation:
        if rc.category is RelationCategory.PROPERTY:
            return InternalRelation(rc.relation_id, rc.type_id, rc.category,
                                    vid, value=rc.value,
                                    properties=dict(rc.properties),
                                    lifecycle=ElementLifecycle.LOADED)
        if rc.direction is Direction.OUT:
            out_id, in_id = vid, rc.other_vertex_id
        else:
            out_id, in_id = rc.other_vertex_id, vid
        return InternalRelation(rc.relation_id, rc.type_id, rc.category,
                                out_id, in_id, properties=dict(rc.properties),
                                lifecycle=ElementLifecycle.LOADED)

    # multi-vertex batched adjacency (reference: TitanMultiVertexQuery /
    # edgeMultiQuery StandardTitanGraph.java:416-427)
    def multi_vertex_properties(self, vids: list,
                                keys: Optional[list] = None) -> dict:
        """``{vid: {key: value}}`` across many vertices with ONE batched
        property-slice read per slice query, instead of a point read per
        vertex (reference: TitanMultiVertexQuery properties() /
        optimize/TitanVertexStep.java:69-96 batch fill). Last parsed
        value per key wins — SINGLE-cardinality semantics matching
        ``Vertex.value``; multi-valued keys should use
        ``vertex_properties`` per vertex."""
        self._check_open()
        type_ids = None
        if keys is not None:
            type_ids = [st.id for k in keys
                        if (st := self.schema.get_by_name(k)) is not None]
            if not type_ids:
                return {vid: {} for vid in vids}
        out: dict[int, dict] = {vid: {} for vid in vids}
        kb: dict[bytes, int] = {}
        for v in set(vids):
            if v not in self._new_vertices:
                # properties live on the canonical row only (vertex cuts
                # fan out for EDGES, not properties — _stored_relations)
                kb[self.idm.key_bytes(v)] = v
        for q in self._slices_for(Direction.OUT, type_ids,
                                  RelationCategory.PROPERTY, False):
            if not kb:
                break
            result = self._multi_edge_query(list(kb), q)
            for key_bytes, entries in result.items():
                vid = kb[key_bytes]
                for entry in entries:
                    rc = self.codec.parse(entry, self.schema)
                    rel = self._relation_from_cache(vid, rc)
                    if rel.relation_id in self._deleted:
                        continue
                    if self._matches(rel, vid, Direction.OUT, type_ids,
                                     RelationCategory.PROPERTY, False):
                        out[vid][self.schema_name(rel.type_id)] = \
                            rel.value
        for vid in vids:                       # in-tx additions overlay
            for rel in self._added_by_vertex.get(vid, ()):
                if self._matches(rel, vid, Direction.OUT, type_ids,
                                 RelationCategory.PROPERTY, False):
                    out[vid][self.schema_name(rel.type_id)] = rel.value
        return out

    def multi_vertex_edges(self, vids: list, direction: Direction = Direction.BOTH,
                           labels: Optional[list] = None) -> dict:
        self._check_open()
        type_ids = None
        if labels is not None:
            type_ids = [st.id for name in labels
                        if (st := self.schema.get_by_name(name)) is not None]
            if not type_ids:
                return {vid: [] for vid in vids}
        out: dict[int, list] = {vid: [] for vid in vids}
        ckey = (direction, tuple(sorted(type_ids)) if type_ids else None)
        stored_vids = []
        seen_vids = set()
        for v in vids:
            if v in self._new_vertices or v in seen_vids:
                continue
            seen_vids.add(v)
            hit = self._adj_cache.get((v, *ckey))
            if hit is not None:
                # deletions made after the fill are filtered per read
                out[v] = ([e for e in hit
                           if e.rel.relation_id not in self._deleted]
                          if self._deleted else list(hit))
            else:
                stored_vids.append(v)
        keys: dict[bytes, int] = {}
        for v in stored_vids:
            if self.idm.is_partitioned_vertex(v):
                # vertex cut: one batched read covers every representative row
                for r in self.idm.partitioned_vertex_representatives(v):
                    keys[self.idm.key_bytes(r)] = v
            else:
                keys[self.idm.key_bytes(v)] = v
        stored: dict[int, list] = {v: [] for v in stored_vids}
        for q in self._slices_for(direction, type_ids, RelationCategory.EDGE,
                                  False):
            if not keys:
                break
            # answer cached keys from the tx slice cache; batch only the rest
            result = self._multi_edge_query(list(keys), q)
            items = [(keys[kb], e) for kb, entries in result.items()
                     for e in entries]
            # cold-path bulk decode: codec.parse per entry dominates the
            # first-touch 4-hop (measured ~60% of a cold LDBC query);
            # the native codec decodes the common shape (OUT edge,
            # MULTI label, no sort key, no properties) in two vectorized
            # sweeps, everything else falls back per entry
            bulk = self._bulk_parse_out(items) \
                if direction is Direction.OUT and len(items) >= 256 \
                else None
            for j, (vid, entry) in enumerate(items):
                fastrel = bulk[j] if bulk is not None else None
                if fastrel is not None:
                    relation_id, type_id, other = fastrel
                    rel = InternalRelation(
                        relation_id, type_id, RelationCategory.EDGE,
                        vid, other, properties={},
                        lifecycle=ElementLifecycle.LOADED)
                else:
                    rc = self.codec.parse(entry, self.schema)
                    rel = self._relation_from_cache(vid, rc)
                if self._matches(rel, vid, direction, type_ids,
                                 RelationCategory.EDGE, False):
                    stored[vid].append(Edge(self, rel))
        for vid in stored_vids:
            edges = stored[vid]
            # cap counts VERTICES, matching the reference's tx-cache-size
            # semantics (a vertex-count bound on the tx vertex cache)
            if self._adj_cache_size < self._slice_cache_cap:
                self._adj_cache[(vid, *ckey)] = edges
                self._adj_cache_size += 1
            out[vid] = ([e for e in edges
                         if e.rel.relation_id not in self._deleted]
                        if self._deleted else list(edges))
        for vid in dict.fromkeys(vids):     # dedup: out[vid] is shared
            for rel in self._added_by_vertex.get(vid, ()):
                if self._matches(rel, vid, direction, type_ids,
                                 RelationCategory.EDGE, False):
                    out[vid].append(Edge(self, rel))
        return out

    # ------------------------------------------------------ graph-centric query

    def query(self):
        """``tx.query().has(...)`` (reference: TitanTransaction.query())."""
        from titan_tpu.query.graphquery import GraphQuery
        self._check_open()
        return GraphQuery(self)

    # ------------------------------------------------------------- lifecycle

    def commit(self) -> None:
        self._check_open()
        self.graph.count_tx("commit")
        try:
            if self._added or self._deleted:
                self.graph.commit_transaction(self)
            elif self._backend_tx is not None:
                self._backend_tx.commit()
        except BaseException:
            self.graph.count_tx("commit.exceptions")
            raise
        finally:
            self._open = False

    def rollback(self) -> None:
        if not self._open:
            return
        self.graph.count_tx("rollback")
        try:
            if self._backend_tx is not None:
                self._backend_tx.rollback()
        finally:
            self._open = False
            if self._lock_state.has_locks and \
                    self.graph.backend.locker is not None:
                self.graph.backend.locker.release_locks(self._lock_state)
        self._added.clear()
        self._deleted.clear()
        self._added_by_vertex.clear()

    def has_modifications(self) -> bool:
        return bool(self._added or self._deleted)
