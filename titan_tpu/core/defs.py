"""Basic graph-model enums shared by the codec, schema and query layers.

(reference: titan-core core/Cardinality.java, core/Multiplicity.java,
TinkerPop Direction; RelationCategory in graphdb/internal/)
"""

from __future__ import annotations

import enum


class Direction(enum.IntEnum):
    OUT = 0
    IN = 1
    BOTH = 2

    def reverse(self) -> "Direction":
        if self is Direction.OUT:
            return Direction.IN
        if self is Direction.IN:
            return Direction.OUT
        return Direction.BOTH


class Cardinality(enum.Enum):
    """Property cardinality per vertex (reference: core/Cardinality.java)."""
    SINGLE = "single"
    LIST = "list"
    SET = "set"


class Multiplicity(enum.Enum):
    """Edge multiplicity constraint (reference: core/Multiplicity.java)."""
    MULTI = "multi"
    SIMPLE = "simple"        # at most one edge between a vertex pair
    MANY2ONE = "many2one"    # each vertex: at most one OUT edge (e.g. "mother")
    ONE2MANY = "one2many"    # each vertex: at most one IN edge (e.g. "winnerOf")
    ONE2ONE = "one2one"

    def unique(self, direction: Direction) -> bool:
        """Is there at most one edge in ``direction`` per vertex?
        (reference: Multiplicity.isUnique)"""
        if self is Multiplicity.MANY2ONE:
            return direction is Direction.OUT
        if self is Multiplicity.ONE2MANY:
            return direction is Direction.IN
        if self is Multiplicity.ONE2ONE:
            return direction in (Direction.OUT, Direction.IN)
        return False

    @staticmethod
    def from_cardinality(c: Cardinality) -> "Multiplicity":
        # properties are modeled as relations; SINGLE → MANY2ONE etc.
        return {Cardinality.SINGLE: Multiplicity.MANY2ONE,
                Cardinality.LIST: Multiplicity.MULTI,
                Cardinality.SET: Multiplicity.SIMPLE}[c]


class RelationCategory(enum.Enum):
    EDGE = "edge"
    PROPERTY = "property"
    RELATION = "relation"   # either


class ElementLifecycle(enum.IntEnum):
    """(reference: graphdb/internal/ElementLifeCycle.java)"""
    NEW = 1
    LOADED = 2
    MODIFIED = 3
    REMOVED = 4


class SchemaStatus(enum.Enum):
    """Index/schema lifecycle states (reference: core/schema/SchemaStatus.java)."""
    INSTALLED = "installed"
    REGISTERED = "registered"
    ENABLED = "enabled"
    DISABLED = "disabled"


class SchemaAction(enum.Enum):
    """Index lifecycle transitions (reference: core/schema/SchemaAction.java:12-50
    — REGISTER_INDEX/REINDEX/ENABLE_INDEX/DISABLE_INDEX/REMOVE_INDEX with
    applicable source states)."""
    REGISTER_INDEX = "register"
    REINDEX = "reindex"
    ENABLE_INDEX = "enable"
    DISABLE_INDEX = "disable"
    REMOVE_INDEX = "remove"

    def applicable_from(self, status: "SchemaStatus") -> bool:
        return status in {
            SchemaAction.REGISTER_INDEX: (SchemaStatus.INSTALLED,),
            SchemaAction.REINDEX: (SchemaStatus.REGISTERED,
                                   SchemaStatus.ENABLED),
            SchemaAction.ENABLE_INDEX: (SchemaStatus.REGISTERED,),
            SchemaAction.DISABLE_INDEX: (SchemaStatus.REGISTERED,
                                         SchemaStatus.INSTALLED,
                                         SchemaStatus.ENABLED),
            SchemaAction.REMOVE_INDEX: (SchemaStatus.DISABLED,),
        }[self]
