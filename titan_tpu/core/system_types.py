"""Built-in system schema types with fixed ids.

(reference: titan-core graphdb/types/system/BaseKey.java, BaseLabel.java,
ImplicitKey.java — system property keys/labels with hardcoded ids that the
engine needs before any user schema exists: the vertex-existence marker, the
schema-name lookup key, the type-definition payload and the vertex-label
edge.)
"""

from __future__ import annotations

from titan_tpu.core.defs import Cardinality, Multiplicity
from titan_tpu.ids import IDManager, IDType

# fixed counts in the system id spaces — part of the stored format
VERTEX_EXISTS_COUNT = 1
SCHEMA_NAME_COUNT = 2
TYPE_DEFINITION_COUNT = 3
VERTEX_LABEL_EDGE_COUNT = 1

_SYS_KEYS = {
    VERTEX_EXISTS_COUNT: ("~exists", bool, Cardinality.SINGLE),
    SCHEMA_NAME_COUNT: ("~schemaname", str, Cardinality.SINGLE),
    TYPE_DEFINITION_COUNT: ("~typedefinition", dict, Cardinality.SINGLE),
}

_SYS_LABELS = {
    VERTEX_LABEL_EDGE_COUNT: ("~vertexlabel", Multiplicity.MANY2ONE),
}


class SystemTypes:
    """Resolves the fixed system ids for a given IDManager width."""

    def __init__(self, idm: IDManager):
        self.idm = idm
        self.vertex_exists = idm.schema_id(IDType.SYSTEM_PROPERTY_KEY,
                                           VERTEX_EXISTS_COUNT)
        self.schema_name = idm.schema_id(IDType.SYSTEM_PROPERTY_KEY,
                                         SCHEMA_NAME_COUNT)
        self.type_definition = idm.schema_id(IDType.SYSTEM_PROPERTY_KEY,
                                             TYPE_DEFINITION_COUNT)
        self.vertex_label_edge = idm.schema_id(IDType.SYSTEM_EDGE_LABEL,
                                               VERTEX_LABEL_EDGE_COUNT)
        self._keys = {idm.schema_id(IDType.SYSTEM_PROPERTY_KEY, c): v
                      for c, v in _SYS_KEYS.items()}
        self._labels = {idm.schema_id(IDType.SYSTEM_EDGE_LABEL, c): v
                        for c, v in _SYS_LABELS.items()}

    def is_system(self, type_id: int) -> bool:
        return type_id in self._keys or type_id in self._labels

    def key_info(self, key_id: int):
        return self._keys.get(key_id)

    def label_info(self, label_id: int):
        return self._labels.get(label_id)

    def name_of(self, type_id: int) -> str | None:
        if type_id in self._keys:
            return self._keys[type_id][0]
        if type_id in self._labels:
            return self._labels[type_id][0]
        return None
