"""Write-ahead transaction log + recovery.

(reference: titan-core graphdb/database/StandardTitanGraph.java:657-772 — the
commit path logs PRECOMMIT (serialized mutations), then PRIMARY_SUCCESS
atomically-adjacent to the storage commit, then SECONDARY_SUCCESS/FAILURE
after index/trigger writes; graphdb/log/StandardTransactionLogProcessor.java:57
replays the log and re-applies lost secondary (index) writes for transactions
whose primary succeeded but secondary persistence failed.)

Record format (payload via the self-describing serializer):
    [txid u64][status u8][dict payload]
payload = {store_name: {key: [[(col, val), ...], [col, ...]]}}
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from titan_tpu.codec.attributes import Serializer
from titan_tpu.storage.log import KCVSLog, LogMessage, ReadMarker

PRECOMMIT = 1
PRIMARY_SUCCESS = 2
SECONDARY_SUCCESS = 3
SECONDARY_FAILURE = 4

_STATUS_NAMES = {1: "PRECOMMIT", 2: "PRIMARY_SUCCESS",
                 3: "SECONDARY_SUCCESS", 4: "SECONDARY_FAILURE"}


class TransactionLog:
    """Writer side, used by the graph commit path."""

    def __init__(self, log: KCVSLog, serializer: Optional[Serializer] = None):
        self._log = log
        self._ser = serializer or Serializer()
        # random high bits make txids unique across instances sharing the
        # txlog (a time-seeded counter collides when two instances open in
        # the same millisecond, corrupting recovery bookkeeping)
        import os as _os
        self._txid_counter = (int.from_bytes(_os.urandom(8), "big") >> 1) \
            & ~0xFFFFF
        self._lock = threading.Lock()

    def next_txid(self) -> int:
        with self._lock:
            self._txid_counter += 1
            return self._txid_counter

    def _record(self, txid: int, status: int, payload: Optional[dict] = None
                ) -> bytes:
        body = txid.to_bytes(8, "big") + bytes([status])
        if payload is not None:
            body += self._ser.value_bytes(payload)
        return body

    def log_precommit(self, txid: int, mutations: dict) -> None:
        """mutations: {store: {key(bytes): (additions [(col,val)...],
        deletions [col...])}} — serialized so recovery can re-apply."""
        payload = {store: {key: [[list(e) for e in adds], list(dels)]
                           for key, (adds, dels) in by_key.items()}
                   for store, by_key in mutations.items()}
        self._log.add(self._record(txid, PRECOMMIT, payload))

    def log_primary_success(self, txid: int) -> None:
        self._log.add(self._record(txid, PRIMARY_SUCCESS))

    def log_secondary_success(self, txid: int) -> None:
        self._log.add(self._record(txid, SECONDARY_SUCCESS))

    def log_secondary_failure(self, txid: int) -> None:
        self._log.add(self._record(txid, SECONDARY_FAILURE))

    def parse(self, msg: LogMessage) -> tuple[int, int, Optional[dict]]:
        body = msg.content
        txid = int.from_bytes(body[:8], "big")
        status = body[8]
        payload = None
        if len(body) > 9:
            payload = self._ser.value_from_bytes(body[9:])
        return txid, status, payload


class TransactionRecovery:
    """Replays the tx log and re-applies lost SECONDARY (index-store) writes.
    (reference: StandardTransactionLogProcessor; started via
    TitanFactory.startTransactionRecovery)"""

    SECONDARY_STORES = ("graphindex",)

    def __init__(self, graph, txlog: KCVSLog, start_time: Optional[int] = None,
                 persistence_timeout_s: float = 2.0):
        self.graph = graph
        self._txlog = txlog
        self._wal = TransactionLog(txlog, graph.serializer)
        self._timeout = persistence_timeout_s
        self._pending: dict[int, dict] = {}  # txid -> {payload, primary, t}
        self._lock = threading.Lock()
        self.recovered = 0
        self._txlog.register_reader(
            ReadMarker(identifier="recovery", start_time=start_time),
            self._on_message)

    def _on_message(self, msg: LogMessage) -> None:
        txid, status, payload = self._wal.parse(msg)
        with self._lock:
            entry = self._pending.setdefault(
                txid, {"payload": None, "primary": False,
                       "t": time.monotonic()})
            if status == PRECOMMIT:
                entry["payload"] = payload
            elif status == PRIMARY_SUCCESS:
                entry["primary"] = True
            elif status == SECONDARY_SUCCESS:
                self._pending.pop(txid, None)
            elif status == SECONDARY_FAILURE:
                entry["primary"] = True  # definitely needs secondary replay
        self._sweep()

    def _sweep(self) -> None:
        now = time.monotonic()
        replay = []
        with self._lock:
            for txid, entry in list(self._pending.items()):
                if entry["primary"] and entry["payload"] is not None and \
                        now - entry["t"] >= self._timeout:
                    replay.append((txid, entry["payload"]))
                    del self._pending[txid]
                elif not entry["primary"] and \
                        now - entry["t"] >= 10 * self._timeout:
                    # primary never confirmed: tx failed before storage
                    # commit — nothing to repair
                    del self._pending[txid]
        for txid, payload in replay:
            self._replay_secondary(txid, payload)

    def force_sweep(self) -> None:
        """Test/shutdown helper: replay everything eligible right now."""
        with self._lock:
            for entry in self._pending.values():
                entry["t"] = -1e18
        self._sweep()

    def _replay_secondary(self, txid: int, payload: dict) -> None:
        from titan_tpu.storage.api import Entry
        backend = self.graph.backend
        txh = backend.manager.begin_transaction()
        try:
            for store_name, by_key in payload.items():
                if store_name not in self.SECONDARY_STORES:
                    continue
                store = backend.manager.open_database(store_name)
                for key, (adds, dels) in by_key.items():
                    # a third element is the cell TTL (TTLEntry rows) —
                    # preserve it so recovered cells still expire (the clock
                    # restarts at replay time: at-least-lifetime semantics)
                    from titan_tpu.storage.api import TTLEntry
                    store.mutate(
                        key,
                        [TTLEntry(a[0], a[1], a[2]) if len(a) > 2 and a[2]
                         else Entry(a[0], a[1]) for a in adds],
                        list(dels), txh)
            txh.commit()
            self.recovered += 1
        except BaseException:
            txh.rollback()
            raise
