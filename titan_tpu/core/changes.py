"""Trigger logs + change streaming: the application-facing TitanBus.

(reference: titan-core docs/TitanBus.md:5-13 — transactions tagged with a
log identifier write their change set to the user log ``ulog_<id>`` at
commit; graphdb/log/StandardLogProcessorFramework.java +
core/log/LogProcessorFramework.java deliver a ``ChangeState`` of
added/removed elements per committed transaction to registered processors.)

Payload layout (self-describing serializer):
  {"txid": int, "time": int,
   "added_vertices": [vid...], "removed_vertices": [vid...],
   "added": [rel...], "removed": [rel...]}
rel = {"rel_id", "type", "out", "in"(edges) | "value"(properties)}
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

from titan_tpu.storage.log import LogMessage, ReadMarker

log_ = logging.getLogger(__name__)

USER_LOG_PREFIX = "ulog_"

# in-process listener backlog bound: past this, the queue declares
# overflow and the subscriber must full-rebuild instead of delta-refresh
CHANGE_QUEUE_CAP = 10_000


class ChangeQueue(list):
    """Bounded change-payload backlog for in-process subscribers (list
    subclass so the graph's registry can hold it by WEAK reference —
    builtin lists aren't weak-referenceable). ``overflowed`` means
    payloads were dropped: delta refresh is no longer sound. The cap is
    configurable per graph (computer.tpu.change-backlog)."""

    __slots__ = ("__weakref__", "overflowed", "cap")

    def __init__(self, cap: int = CHANGE_QUEUE_CAP):
        super().__init__()
        self.overflowed = False
        self.cap = cap

    def push(self, payload: dict) -> None:
        if self.overflowed:
            return
        if len(self) >= self.cap:
            self.overflowed = True
            self.clear()
            return
        self.append(payload)

    def reanchor(self) -> None:
        """Resume accumulation after the subscriber re-anchored at a
        freshly rebuilt epoch (``GraphSnapshot.rebuild_in_place`` /
        the live plane's resync): the dropped backlog is covered by the
        rebuild's store scan, so the overflow verdict no longer applies.
        Must be called under the graph's commit lock, atomically with
        the rebuild's epoch verification — otherwise a commit racing
        the clear could land in storage but not in the queue (ISSUE r9
        satellite: the flag was never reset, so one >cap backlog forced
        every future refresh() into a full rebuild forever)."""
        self.clear()
        self.overflowed = False


class ChangeState:
    """One committed transaction's change set, as delivered to processors
    (reference: core/log/ChangeState.java). ``sender`` is the writing
    instance's rid bytes when the state arrived over the durable log
    (None for states built directly from payloads) — the live plane's
    ChangeFeed uses it to drop this instance's own messages, which it
    already saw through the in-process listener."""

    def __init__(self, payload: dict, sender: Optional[bytes] = None):
        self._p = payload
        self.sender = sender

    @property
    def txid(self) -> int:
        return self._p["txid"]

    @property
    def timestamp(self) -> int:
        return self._p.get("time", 0)

    def added_vertices(self) -> list[int]:
        return list(self._p.get("added_vertices", ()))

    def removed_vertices(self) -> list[int]:
        return list(self._p.get("removed_vertices", ()))

    def added_relations(self, type_name: Optional[str] = None) -> list[dict]:
        return [r for r in self._p.get("added", ())
                if type_name is None or r.get("type") == type_name]

    def removed_relations(self, type_name: Optional[str] = None) -> list[dict]:
        return [r for r in self._p.get("removed", ())
                if type_name is None or r.get("type") == type_name]

    def added_edges(self, type_name: Optional[str] = None) -> list[dict]:
        return [r for r in self.added_relations(type_name) if "in" in r]

    def added_properties(self, type_name: Optional[str] = None) -> list[dict]:
        return [r for r in self.added_relations(type_name) if "in" not in r]


def change_payload(graph, tx, txid: int) -> dict:
    """Serialize a committed tx's deltas (called from the commit path)."""

    def rel_dict(rel) -> dict:
        d = {"rel_id": rel.relation_id,
             "type": tx.schema_name(rel.type_id),
             "out": rel.out_vertex_id}
        if rel.is_edge:
            d["in"] = rel.in_vertex_id
        else:
            d["value"] = rel.value
        return d

    sys = graph.schema.system
    return {
        "txid": txid,
        "time": graph.backend.times.time(),
        "added_vertices": sorted(tx._new_vertices),
        "removed_vertices": sorted(tx._removed_vertices),
        "added": [rel_dict(r) for r in tx._added.values()
                  if not sys.is_system(r.type_id)],
        "removed": [rel_dict(r) for r in tx._deleted.values()
                    if not sys.is_system(r.type_id)],
    }


class LogProcessorBuilder:
    def __init__(self, framework: "LogProcessorFramework", identifier: str):
        self._framework = framework
        self._identifier = identifier
        self._processors: list[Callable] = []
        self._start_time: Optional[int] = None
        self._reader_id: Optional[str] = None
        self._read_interval_ms: Optional[int] = None

    def set_start_time_now(self) -> "LogProcessorBuilder":
        self._start_time = None
        return self

    def set_start_time(self, t: int) -> "LogProcessorBuilder":
        self._start_time = t
        return self

    def set_processor_identifier(self, ident: str) -> "LogProcessorBuilder":
        """Named readers persist their cursor and resume where they stopped
        (reference: durable read markers, KCVSLog.java:31-35)."""
        self._reader_id = ident
        return self

    def set_read_interval_ms(self, ms: int) -> "LogProcessorBuilder":
        self._read_interval_ms = ms
        return self

    def add_processor(self, fn: Callable) -> "LogProcessorBuilder":
        """fn(graph, txid, change_state)"""
        self._processors.append(fn)
        return self

    def build(self) -> None:
        self._framework._register(self._identifier, self._reader_id,
                                  self._start_time, list(self._processors),
                                  self._read_interval_ms)


class LogProcessorFramework:
    """(reference: StandardLogProcessorFramework — obtained via
    ``titan_tpu.open_log_processors(graph)``)"""

    def __init__(self, graph):
        self.graph = graph
        self._lock = threading.Lock()
        self._logs: list = []

    def add_log_processor(self, identifier: str) -> LogProcessorBuilder:
        return LogProcessorBuilder(self, identifier)

    def _register(self, identifier: str, reader_id: Optional[str],
                  start_time: Optional[int], processors: list,
                  read_interval_ms: Optional[int] = None) -> None:
        overrides = {}
        if read_interval_ms is not None:
            overrides["read_interval_ms"] = read_interval_ms
        log = self.graph.backend.log_manager.open_log(
            USER_LOG_PREFIX + identifier, **overrides)
        ser = self.graph.serializer

        def on_message(msg: LogMessage) -> None:
            # per-message/per-processor error isolation: a raising processor
            # must not wedge the bucket cursor and stall the whole stream
            # (reference: StandardLogProcessorFramework catches per-processor
            # Throwables)
            try:
                state = ChangeState(ser.value_from_bytes(msg.content),
                                    sender=msg.sender)
            except Exception:
                log_.warning("undecodable change message on %s; skipped",
                             identifier, exc_info=True)
                return
            for fn in processors:
                try:
                    fn(self.graph, state.txid, state)
                except Exception:
                    log_.warning("change processor %r failed for tx %s",
                                 fn, state.txid, exc_info=True)

        marker = ReadMarker(identifier=reader_id, start_time=start_time)
        log.register_reader(marker, on_message)
        with self._lock:
            self._logs.append(log)
