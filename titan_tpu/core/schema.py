"""Schema model, storage and cache.

Re-creation of the reference's schema-in-the-graph design (reference:
titan-core graphdb/types/ — TitanSchemaVertex, TypeDefinitionMap,
typemaker/*; cache in graphdb/database/cache/StandardSchemaCache.java):
schema elements ARE vertices. A schema vertex's row in the edgestore holds
its ~schemaname and ~typedefinition system properties; a system name index
row in the graphindex store maps name → id so lookups need one slice each
way. A process-wide SchemaCache fronts both directions.

Auto schema creation (the reference's DefaultSchemaMaker): unknown property
keys default to (type(value), SINGLE); unknown edge labels to MULTI.
"""

from __future__ import annotations

import threading
import time as _time
from dataclasses import dataclass, field as dc_field
from typing import Optional

from titan_tpu.codec.attributes import Serializer
from titan_tpu.codec.edges import EdgeCodec
from titan_tpu.core.defs import Cardinality, Multiplicity, SchemaStatus
from titan_tpu.core.system_types import SystemTypes
from titan_tpu.errors import (SchemaNameExistsError,
                              SchemaViolationError,
                              TemporaryLockingError)
from titan_tpu.storage.backend import INDEXSTORE_NAME
from titan_tpu.storage.locking import LockID, LockState
from titan_tpu.ids import IDManager, IDType
from titan_tpu.storage.api import Entry, KeySliceQuery, SliceQuery

_NAME_INDEX_PREFIX = b"\x00sn\x00"   # system rows in graphindex
_INDEX_REGISTRY_KEY = b"\x00sidx\x00"   # column per index name -> schema id

# dtype registry: stored code <-> python type (extend via register_dtype)
_DTYPES: dict[str, type] = {}
_DTYPE_NAMES: dict[type, str] = {}


def register_dtype(name: str, t: type) -> None:
    _DTYPES[name] = t
    _DTYPE_NAMES[t] = name


import datetime as _dt
import uuid as _uuid

from titan_tpu.core.attribute import Geoshape as _Geoshape

import decimal as _decimal

for _n, _t in [("bool", bool), ("int", int), ("float", float), ("str", str),
               ("bytes", bytes), ("uuid", _uuid.UUID), ("datetime", _dt.datetime),
               ("list", list), ("dict", dict), ("geoshape", _Geoshape),
               ("decimal", _decimal.Decimal), ("date", _dt.date),
               ("time", _dt.time), ("timedelta", _dt.timedelta),
               ("tuple", tuple), ("set", set), ("frozenset", frozenset)]:
    register_dtype(_n, _t)

import numpy as _np

register_dtype("ndarray", _np.ndarray)

import enum as _enum

# any Enum subclass maps to the base dtype (the serializer stores the
# concrete class path per value; reference: EnumSerializer)
register_dtype("enum", _enum.Enum)


@dataclass(frozen=True)
class SchemaType:
    id: int
    name: str

    @property
    def is_property_key(self) -> bool:
        return isinstance(self, PropertyKey)

    @property
    def is_edge_label(self) -> bool:
        return isinstance(self, EdgeLabel)

    @property
    def is_vertex_label(self) -> bool:
        return isinstance(self, VertexLabel)


@dataclass(frozen=True)
class PropertyKey(SchemaType):
    dtype: type = str
    cardinality: Cardinality = Cardinality.SINGLE
    status: SchemaStatus = SchemaStatus.ENABLED
    consistency: str = "none"   # none | lock (reference: ConsistencyModifier)
    ttl: float = 0.0            # seconds; 0 = never (reference: mgmt.setTTL)

    def definition(self) -> dict:
        return {"kind": "key", "dtype": _DTYPE_NAMES[self.dtype],
                "cardinality": self.cardinality.value,
                "status": self.status.value, "consistency": self.consistency,
                "ttl": self.ttl}


@dataclass(frozen=True)
class EdgeLabel(SchemaType):
    multiplicity: Multiplicity = Multiplicity.MULTI
    unidirected: bool = False
    sort_key: tuple = ()
    status: SchemaStatus = SchemaStatus.ENABLED
    consistency: str = "none"
    ttl: float = 0.0            # seconds; 0 = never (reference: mgmt.setTTL)

    def definition(self) -> dict:
        return {"kind": "label", "multiplicity": self.multiplicity.value,
                "unidirected": self.unidirected,
                "sort_key": list(self.sort_key), "status": self.status.value,
                "consistency": self.consistency, "ttl": self.ttl}


@dataclass(frozen=True)
class VertexLabel(SchemaType):
    partitioned: bool = False
    static: bool = False
    ttl: float = 0.0   # only meaningful for static labels (reference:
                       # vertex TTL requires a static vertex label)

    def definition(self) -> dict:
        return {"kind": "vertexlabel", "partitioned": self.partitioned,
                "static": self.static, "ttl": self.ttl}


@dataclass(frozen=True)
class IndexDefinition(SchemaType):
    """A graph index — composite (graphindex store) or mixed (external
    provider). (reference: graphdb/types/indextype/*, TitanGraphIndex in
    core/schema/ — indexes are schema vertices like everything else.)

    ``key_ids`` is ordered (composite row-key field order). ``key_params``
    aligns with it (mixed-index mapping hints, e.g. ``"TEXT"``/``"STRING"``).
    ``status`` drives the lifecycle: writes go to REGISTERED+ENABLED indexes,
    reads only use ENABLED ones (reference: SchemaStatus semantics).
    """
    element: str = "vertex"                     # vertex | edge
    composite: bool = True
    key_ids: tuple = ()
    key_params: tuple = ()
    unique: bool = False
    backing: str = ""                           # mixed: provider name
    index_only: int = 0                         # restrict to label/type id
    status: SchemaStatus = SchemaStatus.ENABLED

    def definition(self) -> dict:
        return {"kind": "index", "element": self.element,
                "composite": self.composite, "key_ids": list(self.key_ids),
                "key_params": list(self.key_params), "unique": self.unique,
                "backing": self.backing, "index_only": self.index_only,
                "status": self.status.value}

    @property
    def writable(self) -> bool:
        return self.status in (SchemaStatus.REGISTERED, SchemaStatus.ENABLED)

    @property
    def queryable(self) -> bool:
        return self.status is SchemaStatus.ENABLED


def _from_definition(schema_id: int, name: str, d: dict) -> SchemaType:
    kind = d["kind"]
    if kind == "key":
        return PropertyKey(schema_id, name, _DTYPES[d["dtype"]],
                           Cardinality(d["cardinality"]),
                           SchemaStatus(d.get("status", "enabled")),
                           d.get("consistency", "none"),
                           d.get("ttl", 0.0))
    if kind == "label":
        return EdgeLabel(schema_id, name, Multiplicity(d["multiplicity"]),
                         d.get("unidirected", False),
                         tuple(d.get("sort_key", ())),
                         SchemaStatus(d.get("status", "enabled")),
                         d.get("consistency", "none"),
                         d.get("ttl", 0.0))
    if kind == "vertexlabel":
        return VertexLabel(schema_id, name, d.get("partitioned", False),
                           d.get("static", False), d.get("ttl", 0.0))
    if kind == "index":
        return IndexDefinition(schema_id, name, d["element"], d["composite"],
                               tuple(d["key_ids"]), tuple(d["key_params"]),
                               d["unique"], d.get("backing", ""),
                               d.get("index_only", 0),
                               SchemaStatus(d.get("status", "enabled")))
    raise SchemaViolationError(f"unknown schema kind {kind!r}")


class SchemaManager:
    """Creates, stores, loads and caches schema types; implements the codec's
    TypeInspector protocol for BOTH system and user types."""

    def __init__(self, graph):
        self._graph = graph
        self.idm: IDManager = graph.idm
        self.serializer: Serializer = graph.serializer
        self.codec: EdgeCodec = graph.codec
        self.system = SystemTypes(self.idm)
        self._by_id: dict[int, SchemaType] = {}
        self._by_name: dict[str, int] = {}
        self._index_ids: Optional[list] = None   # cached registry row
        self._lock = threading.RLock()

    # -- TypeInspector protocol (codec callbacks) ----------------------------

    def is_edge_label(self, type_id: int) -> bool:
        t = self.idm.id_type(type_id)
        return t.is_edge_label

    def data_type(self, key_id: int) -> type:
        info = self.system.key_info(key_id)
        if info is not None:
            return info[1]
        st = self.get_type(key_id)
        assert isinstance(st, PropertyKey), key_id
        return st.dtype

    def cardinality(self, key_id: int) -> Cardinality:
        info = self.system.key_info(key_id)
        if info is not None:
            return info[2]
        st = self.get_type(key_id)
        assert isinstance(st, PropertyKey)
        return st.cardinality

    def multiplicity(self, label_id: int) -> Multiplicity:
        info = self.system.label_info(label_id)
        if info is not None:
            return info[1]
        st = self.get_type(label_id)
        assert isinstance(st, EdgeLabel)
        return st.multiplicity

    def sort_key(self, label_id: int) -> tuple:
        if self.system.label_info(label_id) is not None:
            return ()
        st = self.get_type(label_id)
        assert isinstance(st, EdgeLabel)
        return st.sort_key

    # -- lookup --------------------------------------------------------------

    def get_type(self, schema_id: int) -> Optional[SchemaType]:
        with self._lock:
            st = self._by_id.get(schema_id)
        if st is not None:
            return st
        st = self._load_by_id(schema_id)
        if st is not None:
            with self._lock:
                self._by_id[schema_id] = st
                self._by_name[st.name] = schema_id
        return st

    def get_by_name(self, name: str) -> Optional[SchemaType]:
        with self._lock:
            sid = self._by_name.get(name)
        if sid is not None:
            return self.get_type(sid)
        sid = self._load_name_index(name)
        if sid is None:
            return None
        return self.get_type(sid)

    def contains(self, name: str) -> bool:
        return self.get_by_name(name) is not None

    # -- creation ------------------------------------------------------------

    def make_property_key(self, name: str, dtype: type = str,
                          cardinality: Cardinality = Cardinality.SINGLE
                          ) -> PropertyKey:
        if dtype not in _DTYPE_NAMES:
            raise SchemaViolationError(f"unsupported dtype {dtype!r}")
        sid = self._graph.id_assigner.next_schema_id(IDType.USER_PROPERTY_KEY)
        return self._store_type(PropertyKey(sid, name, dtype, cardinality))

    def make_edge_label(self, name: str,
                        multiplicity: Multiplicity = Multiplicity.MULTI,
                        unidirected: bool = False,
                        sort_key: tuple = ()) -> EdgeLabel:
        for key_id in sort_key:
            if not isinstance(self.get_type(key_id), PropertyKey):
                raise SchemaViolationError("sort key must be property keys")
            if not self.serializer.orderable(self.data_type(key_id)):
                raise SchemaViolationError("sort key dtype must be orderable")
        sid = self._graph.id_assigner.next_schema_id(IDType.USER_EDGE_LABEL)
        return self._store_type(EdgeLabel(sid, name, multiplicity,
                                          unidirected, tuple(sort_key)))

    def make_vertex_label(self, name: str, partitioned: bool = False,
                          static: bool = False) -> VertexLabel:
        sid = self._graph.id_assigner.next_schema_id(IDType.VERTEX_LABEL)
        return self._store_type(VertexLabel(sid, name, partitioned, static))

    # auto schema maker (reference: DefaultSchemaMaker)
    def get_or_create_key(self, name: str, sample_value=None) -> PropertyKey:
        st = self.get_by_name(name)
        if st is not None:
            if not isinstance(st, PropertyKey):
                raise SchemaViolationError(f"{name!r} is not a property key")
            return st
        if self._graph.auto_schema is False:
            raise SchemaViolationError(f"unknown property key {name!r} "
                                       f"(auto schema disabled)")
        dtype = type(sample_value) if sample_value is not None else str
        if dtype not in _DTYPE_NAMES:
            # Enum FIRST (mirrors the serializer's handler_for): IntEnum/
            # StrEnum also pass isinstance(int/str) and the generic loop
            # would auto-create a primitive-typed key
            if isinstance(sample_value, _enum.Enum):
                dtype = _enum.Enum
            else:
                for base in _DTYPE_NAMES:
                    if isinstance(sample_value, base):
                        dtype = base
                        break
        return self._create_or_adopt(name, PropertyKey,
                                     lambda: self.make_property_key(name, dtype))

    def _create_or_adopt(self, name: str, kind: type, make):
        """Auto-schema creation that survives a racing creator (another
        thread or instance): if the create collides, adopt the winner.
        (reference: DefaultSchemaMaker under concurrent tx / the
        schema-broadcast path.)

        When the backend has a consistent-key locker, _store_type serializes
        creation on a name lock (reference closes the same window with
        consistent-key locks on the system name index), so a loser discovers
        the winner BEFORE any data is written under its id. Without a locker
        the claim-column protocol in _store_type still yields a deterministic
        winner; pre-creating schema (auto_schema=False) remains the guidance
        for locker-less eventually-consistent deployments."""
        st = None
        lock_exc: Optional[TemporaryLockingError] = None
        for attempt in range(5):
            try:
                st = make()
                break
            except SchemaNameExistsError:
                # only the collision case — other schema errors propagate
                self.expire(by_name=name)   # the peer's write made it stale
                st = self.get_by_name(name)
                break
            except TemporaryLockingError as e:
                # a racing creator holds the name lock and may not have
                # committed yet: poll for its write, else retry the creation
                lock_exc = e
                deadline = _time.monotonic() + 2.0
                while _time.monotonic() < deadline:
                    self.expire(by_name=name)
                    st = self.get_by_name(name)
                    if st is not None:
                        break
                    _time.sleep(0.02)
                if st is not None:
                    break
        if st is None:
            self.expire(by_name=name)
            st = self.get_by_name(name)
        if st is None and lock_exc is not None:
            # the lock never cleared (e.g. a crashed peer's claim outlives
            # it until lock expiry) and nothing was committed under the
            # name: surface the retriable condition, not a schema error
            raise lock_exc
        if st is None or not isinstance(st, kind):
            raise SchemaViolationError(
                f"{name!r} exists but is not a {kind.__name__}")
        return st

    def get_or_create_label(self, name: str) -> EdgeLabel:
        st = self.get_by_name(name)
        if st is not None:
            if not isinstance(st, EdgeLabel):
                raise SchemaViolationError(f"{name!r} is not an edge label")
            return st
        if self._graph.auto_schema is False:
            raise SchemaViolationError(f"unknown edge label {name!r}")
        return self._create_or_adopt(name, EdgeLabel,
                                     lambda: self.make_edge_label(name))

    def get_or_create_vertex_label(self, name: str) -> VertexLabel:
        st = self.get_by_name(name)
        if st is not None:
            if not isinstance(st, VertexLabel):
                raise SchemaViolationError(f"{name!r} is not a vertex label")
            return st
        if self._graph.auto_schema is False:
            raise SchemaViolationError(f"unknown vertex label {name!r}")
        return self._create_or_adopt(name, VertexLabel,
                                     lambda: self.make_vertex_label(name))

    def update_type(self, st: SchemaType) -> SchemaType:
        """Rewrite a type's definition (index lifecycle transitions etc.)."""
        return self._store_type(st, expect_new=False)

    def ttl_of(self, type_id: int) -> float:
        """Cell TTL (seconds) for relations of this type; 0 = never."""
        if self.system.is_system(type_id):
            return 0.0
        st = self.get_type(type_id)
        return getattr(st, "ttl", 0.0) if st is not None else 0.0

    # -- graph indexes -------------------------------------------------------

    def make_index(self, name: str, element: str, composite: bool,
                   key_ids: tuple, key_params: tuple = (),
                   unique: bool = False, backing: str = "",
                   index_only: int = 0,
                   status: SchemaStatus = SchemaStatus.ENABLED
                   ) -> IndexDefinition:
        for kid in key_ids:
            if not isinstance(self.get_type(kid), PropertyKey):
                raise SchemaViolationError("index keys must be property keys")
        if composite:
            for kid in key_ids:
                if not self.serializer.orderable(self.data_type(kid)):
                    raise SchemaViolationError(
                        "composite index keys need byte-ordered dtypes")
        if unique and (not composite or element != "vertex"):
            raise SchemaViolationError(
                "uniqueness requires a composite vertex index")
        if not key_params:
            key_params = ("DEFAULT",) * len(key_ids)
        sid = self._graph.id_assigner.next_schema_id(IDType.GENERIC_SCHEMA)
        idx = self._store_type(IndexDefinition(
            sid, name, element, composite, tuple(key_ids), tuple(key_params),
            unique, backing, index_only, status))
        self._register_index(idx)
        return idx

    def _register_index(self, idx: IndexDefinition) -> None:
        backend = self._graph.backend
        txh = backend.manager.begin_transaction()
        try:
            backend.index_store.store.mutate(
                _INDEX_REGISTRY_KEY,
                [Entry(idx.name.encode("utf-8"), idx.id.to_bytes(8, "big"))],
                [], txh)
            txh.commit()
        except BaseException:
            txh.rollback()
            raise
        with self._lock:
            self._index_ids = None
        backend.index_store.invalidate(_INDEX_REGISTRY_KEY)

    def indexes(self, element: Optional[str] = None) -> list:
        """All graph indexes (optionally only vertex/edge ones)."""
        with self._lock:
            ids = self._index_ids
        if ids is None:
            backend = self._graph.backend
            txh = backend.manager.begin_transaction()
            try:
                entries = backend.index_store.store.get_slice(
                    KeySliceQuery(_INDEX_REGISTRY_KEY, SliceQuery()), txh)
            finally:
                txh.commit()
            ids = [int.from_bytes(e.value, "big") for e in entries]
            with self._lock:
                self._index_ids = ids
        out = []
        for iid in ids:
            idx = self.get_type(iid)
            if isinstance(idx, IndexDefinition) and \
                    (element is None or idx.element == element):
                out.append(idx)
        return out

    def indexes_for_key(self, key_id: int, element: str) -> list:
        return [ix for ix in self.indexes(element) if key_id in ix.key_ids]

    # -- storage -------------------------------------------------------------

    def _name_index_key(self, name: str) -> bytes:
        return _NAME_INDEX_PREFIX + name.encode("utf-8")

    def all_types(self) -> list:
        """Every declared user schema type, loaded from the name index
        (reference: ManagementSystem.getRelationTypes/getVertexLabels)."""
        backend = self._graph.backend
        from titan_tpu.storage.api import KeyRangeQuery
        lo = _NAME_INDEX_PREFIX
        hi = _NAME_INDEX_PREFIX[:-1] + \
            bytes([_NAME_INDEX_PREFIX[-1] + 1])
        txh = backend.manager.begin_transaction()
        out = []
        try:
            for key, entries in backend.index_store.store.get_keys(
                    KeyRangeQuery(lo, hi, SliceQuery()), txh):
                if entries:
                    # first claim column = smallest id = the winner
                    # (legacy rows carry the id in the value instead)
                    first = entries[0]
                    sid = int.from_bytes(
                        first.value if len(first.column) == 1
                        else first.column, "big")
                    st = self.get_type(sid)
                    if st is not None:
                        out.append(st)
        finally:
            txh.commit()
        return sorted(out, key=lambda t: t.id)

    def _store_type(self, st: SchemaType, expect_new: bool = True) -> SchemaType:
        if expect_new and self.get_by_name(st.name) is not None:
            raise SchemaNameExistsError(
                f"schema name already exists: {st.name!r}")
        backend = self._graph.backend
        locker = getattr(backend, "locker", None)
        lock_state = None
        if expect_new and locker is not None:
            # Lock-backed creation (reference: consistent-key locking on the
            # system name index): serialize creators of the same name so the
            # loser learns of the winner BEFORE writing data under its id.
            lock_state = LockState()
            locker.write_lock(
                LockID(INDEXSTORE_NAME, self._name_index_key(st.name),
                       b"\x00sc"),
                lock_state)
            try:
                winner = self._load_name_index(st.name)
            except BaseException:
                locker.release_locks(lock_state)
                raise
            if winner is not None:
                # a racing creator committed before our lock claim landed
                locker.release_locks(lock_state)
                self.expire(by_name=st.name)
                raise SchemaNameExistsError(
                    f"schema name already exists: {st.name!r}")
        try:
            return self._store_type_locked(st, expect_new)
        finally:
            if lock_state is not None:
                locker.release_locks(lock_state)

    def _store_type_locked(self, st: SchemaType,
                           expect_new: bool) -> SchemaType:
        backend = self._graph.backend
        txh = backend.manager.begin_transaction()
        try:
            key = self.idm.key_bytes(st.id)
            name_entry = self.codec.write_property(
                self.system.schema_name, self._graph.id_assigner.next_relation_id(),
                st.name, self)
            def_entry = self.codec.write_property(
                self.system.type_definition,
                self._graph.id_assigner.next_relation_id(),
                st.definition(), self)
            backend.edge_store.store.mutate(key, [name_entry, def_entry], [], txh)
            # name-index entries are CLAIM COLUMNS keyed by the schema id;
            # concurrent creators of the same name each write their own
            # column and the smallest id deterministically wins (reference:
            # the ConsistentKeyIDAuthority claim protocol shape) — no
            # last-write-wins divergence between racing instances
            backend.index_store.store.mutate(
                self._name_index_key(st.name),
                [Entry(st.id.to_bytes(8, "big"), b"")], [], txh)
            txh.commit()
        except BaseException:
            txh.rollback()
            raise
        if expect_new:
            # re-read: did a racing creator's smaller id win the name?
            winner_id = self._load_name_index(st.name)
            if winner_id is not None and winner_id != st.id:
                winner = self.get_type(winner_id)
                if winner is not None:
                    with self._lock:
                        self._by_name[st.name] = winner_id
                    return winner
        with self._lock:
            self._by_id[st.id] = st
            self._by_name[st.name] = st.id
        backend.edge_store.invalidate(self.idm.key_bytes(st.id))
        return st

    def _load_name_index(self, name: str) -> Optional[int]:
        backend = self._graph.backend
        txh = backend.manager.begin_transaction()
        try:
            entries = backend.index_store.store.get_slice(
                KeySliceQuery(self._name_index_key(name), SliceQuery()), txh)
        finally:
            txh.commit()
        if not entries:
            return None
        first = entries[0]
        if len(first.column) == 1:
            # legacy layout (pre-claim-column): fixed 1-byte column, id in
            # the VALUE. It predates any claim, so it IS the winner; upgrade
            # the row in place so future readers take the claim path.
            legacy_id = int.from_bytes(first.value, "big")
            try:
                txh2 = backend.manager.begin_transaction()
                backend.index_store.store.mutate(
                    self._name_index_key(name),
                    [Entry(legacy_id.to_bytes(8, "big"), b"")],
                    [first.column], txh2)
                txh2.commit()
            except Exception:
                pass   # reads still work off the legacy row
            return legacy_id
        # columns are big-endian id claims; ascending column order makes the
        # first entry the smallest id — the deterministic winner
        return int.from_bytes(first.column, "big")

    def _load_by_id(self, schema_id: int) -> Optional[SchemaType]:
        if not self.idm.is_schema_id(schema_id):
            return None
        backend = self._graph.backend
        txh = backend.manager.begin_transaction()
        try:
            entries = backend.edge_store.store.get_slice(
                KeySliceQuery(self.idm.key_bytes(schema_id), SliceQuery()), txh)
        finally:
            txh.commit()
        name = None
        definition = None
        for e in entries:
            rc = self.codec.parse(e, self)
            if rc.type_id == self.system.schema_name:
                name = rc.value
            elif rc.type_id == self.system.type_definition:
                definition = rc.value
        if name is None or definition is None:
            return None
        return _from_definition(schema_id, name, definition)

    def expire(self, schema_id: Optional[int] = None,
               by_name: Optional[str] = None) -> None:
        with self._lock:
            self._index_ids = None
            if by_name is not None:
                sid = self._by_name.pop(by_name, None)
                if sid is not None:
                    self._by_id.pop(sid, None)
                return
            if schema_id is None:
                self._by_id.clear()
                self._by_name.clear()
            else:
                st = self._by_id.pop(schema_id, None)
                if st is not None:
                    self._by_name.pop(st.name, None)
