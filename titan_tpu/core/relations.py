"""Internal relation model shared by the transaction and commit path.

(reference: titan-core graphdb/relations/ — StandardEdge, StandardVertexProperty,
CacheEdge/CacheVertexProperty and graphdb/internal/InternalRelation: a
relation is an edge OR a vertex property; edges span (out, in) vertices,
properties attach to one vertex. The codec (codec/edges.py) is deterministic,
so deletions re-serialize the relation instead of caching raw entry bytes.)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from titan_tpu.core.defs import Direction, ElementLifecycle, RelationCategory


@dataclass
class InternalRelation:
    relation_id: int
    type_id: int
    category: RelationCategory
    out_vertex_id: int                  # property: owning vertex
    in_vertex_id: Optional[int] = None  # edges only
    value: Any = None                   # properties only
    properties: dict = field(default_factory=dict)  # meta-properties / edge props
    lifecycle: ElementLifecycle = ElementLifecycle.NEW

    @property
    def is_edge(self) -> bool:
        return self.category is RelationCategory.EDGE

    @property
    def is_property(self) -> bool:
        return self.category is RelationCategory.PROPERTY

    def vertex_ids(self) -> tuple:
        if self.is_edge:
            return (self.out_vertex_id, self.in_vertex_id)
        return (self.out_vertex_id,)

    def direction_of(self, vertex_id: int) -> Direction:
        if not self.is_edge:
            return Direction.OUT
        if vertex_id == self.out_vertex_id:
            return Direction.OUT
        if vertex_id == self.in_vertex_id:
            return Direction.IN
        raise ValueError(f"vertex {vertex_id} not incident to relation "
                         f"{self.relation_id}")

    def other_vertex_id(self, vertex_id: int) -> int:
        if vertex_id == self.out_vertex_id:
            return self.in_vertex_id
        return self.out_vertex_id
