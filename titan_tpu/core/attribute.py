"""Geoshape attribute type + geo predicates.

(reference: titan-core core/attribute/Geoshape.java:672 — point / circle /
box shapes with haversine distance, within/intersect/disjoint relations, a
compact custom serializer, and the ``Geo`` predicate enum used in ``has()``
conditions and mixed-index queries.)
"""

from __future__ import annotations

import math
from typing import Optional

EARTH_RADIUS_KM = 6371.0


class Geoshape:
    """Immutable geo shape: POINT, CIRCLE (center + radius km) or BOX."""

    POINT, CIRCLE, BOX = "point", "circle", "box"

    __slots__ = ("kind", "coords", "radius")

    def __init__(self, kind: str, coords: tuple, radius: float = 0.0):
        self.kind = kind
        self.coords = coords          # ((lat, lon), ...) 1 for point/circle, 2 for box
        self.radius = radius          # km, circles only
        for lat, lon in coords:
            if not (-90.0 <= lat <= 90.0 and -180.0 <= lon <= 180.0):
                raise ValueError(f"illegal (lat, lon): ({lat}, {lon})")

    # -- constructors --------------------------------------------------------

    @staticmethod
    def point(lat: float, lon: float) -> "Geoshape":
        return Geoshape(Geoshape.POINT, ((float(lat), float(lon)),))

    @staticmethod
    def circle(lat: float, lon: float, radius_km: float) -> "Geoshape":
        if radius_km <= 0:
            raise ValueError("radius must be positive")
        return Geoshape(Geoshape.CIRCLE, ((float(lat), float(lon)),),
                        float(radius_km))

    @staticmethod
    def box(sw_lat: float, sw_lon: float, ne_lat: float,
            ne_lon: float) -> "Geoshape":
        if sw_lat > ne_lat or sw_lon > ne_lon:
            raise ValueError("box corners must be (SW, NE)")
        return Geoshape(Geoshape.BOX, ((float(sw_lat), float(sw_lon)),
                                       (float(ne_lat), float(ne_lon))))

    # -- accessors -----------------------------------------------------------

    @property
    def lat(self) -> float:
        return self.coords[0][0]

    @property
    def lon(self) -> float:
        return self.coords[0][1]

    def center(self) -> tuple[float, float]:
        if self.kind == Geoshape.BOX:
            (a, b), (c, d) = self.coords
            return ((a + c) / 2.0, (b + d) / 2.0)
        return self.coords[0]

    # -- geometry ------------------------------------------------------------

    @staticmethod
    def distance_km(a: tuple[float, float], b: tuple[float, float]) -> float:
        """Haversine great-circle distance."""
        la1, lo1 = map(math.radians, a)
        la2, lo2 = map(math.radians, b)
        h = (math.sin((la2 - la1) / 2) ** 2 +
             math.cos(la1) * math.cos(la2) * math.sin((lo2 - lo1) / 2) ** 2)
        return 2 * EARTH_RADIUS_KM * math.asin(min(1.0, math.sqrt(h)))

    def _contains_point(self, p: tuple[float, float]) -> bool:
        if self.kind == Geoshape.POINT:
            return self.coords[0] == p
        if self.kind == Geoshape.CIRCLE:
            return self.distance_km(self.coords[0], p) <= self.radius
        (sw, ne) = self.coords
        return sw[0] <= p[0] <= ne[0] and sw[1] <= p[1] <= ne[1]

    def within(self, outer: "Geoshape") -> bool:
        """Is this shape entirely inside ``outer``? (points fully supported;
        area-in-area approximated by corner/center containment, matching the
        reference's point-in-shape primary use)"""
        if self.kind == Geoshape.POINT:
            return outer._contains_point(self.coords[0])
        if self.kind == Geoshape.BOX:
            (sw, ne) = self.coords
            return outer._contains_point(sw) and outer._contains_point(ne)
        # circle in shape: center inside with radius margin
        if outer.kind == Geoshape.CIRCLE:
            return (self.distance_km(self.coords[0], outer.coords[0]) +
                    self.radius) <= outer.radius
        return outer._contains_point(self.coords[0])

    def intersect(self, other: "Geoshape") -> bool:
        if self.kind == Geoshape.POINT:
            return other._contains_point(self.coords[0])
        if other.kind == Geoshape.POINT:
            return self._contains_point(other.coords[0])
        if self.kind == Geoshape.CIRCLE and other.kind == Geoshape.CIRCLE:
            return self.distance_km(self.coords[0], other.coords[0]) <= \
                self.radius + other.radius
        if self.kind == Geoshape.BOX and other.kind == Geoshape.BOX:
            (asw, ane), (bsw, bne) = self.coords, other.coords
            return not (ane[0] < bsw[0] or bne[0] < asw[0] or
                        ane[1] < bsw[1] or bne[1] < asw[1])
        # box vs circle: nearest point on box to circle center
        box, circ = (self, other) if self.kind == Geoshape.BOX else (other, self)
        (sw, ne) = box.coords
        c = circ.coords[0]
        nearest = (min(max(c[0], sw[0]), ne[0]), min(max(c[1], sw[1]), ne[1]))
        return self.distance_km(c, nearest) <= circ.radius

    def disjoint(self, other: "Geoshape") -> bool:
        return not self.intersect(other)

    # -- equality / repr -----------------------------------------------------

    def __eq__(self, other):
        return (isinstance(other, Geoshape) and self.kind == other.kind and
                self.coords == other.coords and self.radius == other.radius)

    def __hash__(self):
        return hash((self.kind, self.coords, self.radius))

    def __repr__(self):
        if self.kind == Geoshape.POINT:
            return f"point[{self.lat},{self.lon}]"
        if self.kind == Geoshape.CIRCLE:
            return f"circle[{self.lat},{self.lon}:{self.radius}]"
        (sw, ne) = self.coords
        return f"box[{sw[0]},{sw[1]},{ne[0]},{ne[1]}]"

    # -- codec hooks (registered with the attribute serializer) --------------

    def to_floats(self) -> list[float]:
        kind_code = {self.POINT: 0.0, self.CIRCLE: 1.0, self.BOX: 2.0}[self.kind]
        flat = [kind_code]
        for lat, lon in self.coords:
            flat += [lat, lon]
        if self.kind == self.CIRCLE:
            flat.append(self.radius)
        return flat

    @staticmethod
    def from_floats(flat: list[float]) -> "Geoshape":
        kind = [Geoshape.POINT, Geoshape.CIRCLE, Geoshape.BOX][int(flat[0])]
        if kind == Geoshape.POINT:
            return Geoshape.point(flat[1], flat[2])
        if kind == Geoshape.CIRCLE:
            return Geoshape.circle(flat[1], flat[2], flat[3])
        return Geoshape.box(flat[1], flat[2], flat[3], flat[4])
