"""StandardGraph: graph lifetime and the commit orchestration.

(reference: titan-core graphdb/database/StandardTitanGraph.java:78-808 —
opens the Backend, builds serializers/caches/id-assigner, registers the
instance, and hosts the commit path that turns a transaction's deltas into
batched per-row store mutations.)
"""

from __future__ import annotations

import os
import threading
import uuid as _uuid
from typing import Optional

from titan_tpu.codec.attributes import Serializer
from titan_tpu.codec.edges import EdgeCodec
from titan_tpu.config import (Configuration, MapConfiguration, defaults as d)
from titan_tpu.core.defs import Direction, RelationCategory
from titan_tpu.core.schema import SchemaManager
from titan_tpu.core.tx import GraphTransaction
from titan_tpu.errors import ConfigurationError, TitanError
from titan_tpu.ids import IDManager
from titan_tpu.ids.assigner import IDAssigner
from titan_tpu.storage.api import Entry
from titan_tpu.storage.backend import Backend


class StandardGraph:
    def __init__(self, config: Configuration):
        self.local_config = config
        self.instance_id = config.get(d.UNIQUE_INSTANCE_ID) or \
            f"{os.getpid()}-{_uuid.uuid4().hex[:8]}"
        self.backend = Backend(config, instance_id=self.instance_id)

        # merge cluster-global config stored IN the backend with the local
        # file: GLOBAL/FIXED options are authoritative from the store;
        # first opener initializes them from its local values (reference:
        # GraphDatabaseConfiguration ctor + KCVSConfiguration)
        from titan_tpu.config import (Configuration as _Cfg,
                                      MergedConfiguration,
                                      ModifiableConfiguration, Restriction)
        global_raw = self.backend.global_config_store
        if global_raw.get("cluster.frozen") is None:
            init = ModifiableConfiguration(d.ROOT, global_raw)
            init.set(d.MAX_PARTITIONS, config.get(d.MAX_PARTITIONS), force=True)
            init.set(d.TIMESTAMP_PROVIDER, config.get(d.TIMESTAMP_PROVIDER),
                     force=True)
            global_raw.set("cluster.frozen", True)
        self.config = MergedConfiguration(
            config, _Cfg(d.ROOT, global_raw))
        config = self.config

        # the backend was built from the LOCAL config; FIXED options from the
        # global store are authoritative — re-align the timestamp provider
        # (drives lock claims and log ordering across instances)
        self.backend.set_timestamp_provider(config.get(d.TIMESTAMP_PROVIDER))

        self.backend.instance_registry.register(self.instance_id)
        self.idm = IDManager(
            partition_bits=(config.get(d.MAX_PARTITIONS)).bit_length() - 1)
        self.serializer = Serializer()
        self.codec = EdgeCodec(self.serializer, self.idm)

        # snapshot freshness: monotone commit counter + in-process change
        # listeners (OLAP snapshots subscribe so refresh() can apply
        # deltas without re-scanning the store; the reference instead
        # re-scans live data every OLAP run — StandardScannerExecutor).
        # Held WEAKLY: a snapshot dropped without close() auto-unregisters
        # instead of accumulating payloads forever.
        import weakref
        self._mutation_epoch = 0
        self._change_listeners: "weakref.WeakValueDictionary" = \
            weakref.WeakValueDictionary()
        self._listener_seq = 0

        # WAL (reference: tx.log-tx → txlog writes in the commit path)
        self._wal = None
        if config.get(d.LOG_TX):
            from titan_tpu.core.wal import TransactionLog
            self._wal = TransactionLog(
                self.backend.log_manager.open_log(config.get(d.TX_LOG_NAME)),
                self.serializer)
        self.id_assigner = IDAssigner(
            self.idm, self.backend.id_authority,
            block_size=config.get(d.IDS_BLOCK_SIZE),
            renew_percentage=config.get(d.IDS_RENEW_PERCENTAGE))
        self.schema = SchemaManager(self)
        from titan_tpu.indexing.serializer import IndexSerializer
        self.index_serializer = IndexSerializer(self.serializer, self.idm,
                                                self.schema)
        self.auto_schema = True
        self.allow_custom_vid = config.get(d.ALLOW_SETTING_VERTEX_ID)
        self._open = True
        self._tlocal = threading.local()
        self._index_providers: dict = {}   # name -> IndexProvider
        try:
            for name in config.container_names(d.INDEX_NS):
                self._open_index_provider(name)
        except Exception:
            # ANY raising provider open (ConfigurationError, a bad
            # import path, ...) must not leak the already-opened storage
            # backend or leave a ghost entry in the instance registry
            try:
                self.backend.instance_registry.deregister(self.instance_id)
            except Exception:   # noqa: BLE001 — best-effort cleanup
                pass
            try:
                self.backend.close()
            except Exception:   # noqa: BLE001
                pass
            self._open = False
            raise
        self._commit_lock = threading.Lock()
        self._metrics = None
        self._metrics_prefix = config.get(d.METRICS_PREFIX) or "titan_tpu"
        self._reporters = []
        if config.get(d.BASIC_METRICS):
            from titan_tpu.utils.metrics import (MetricManager,
                                                 start_reporters)
            self._metrics = MetricManager.instance()
            # periodic background reporters (console/CSV/Graphite), each
            # gated on its interval option; stopped at close(). Only
            # started when collection is on — a reporter without
            # metrics.enabled would dump empty (or another graph's)
            # snapshots from the shared registry forever. Startup is
            # deduped per (manager, sink): two graphs with the same
            # reporter config share one refcounted reporter thread, so
            # neither emits a duplicate stream and closing one graph
            # doesn't silence the other
            self._reporters = start_reporters(config, self._metrics)

    # -- mixed index providers ----------------------------------------------

    def _open_index_provider(self, name: str):
        from titan_tpu.config import defaults as d
        backend = self.config.get(d.INDEX_BACKEND, name)
        directory = self.config.get(d.INDEX_DIRECTORY, name)
        if backend in ("lucene", "fts"):
            # embedded persistent full-text engine (the Lucene-role provider)
            from titan_tpu.indexing.ftsindex import FTSIndex
            provider = FTSIndex(name, directory or None)
        elif backend == "remote-index":
            # networked index node (the ES/Solr role)
            from titan_tpu.indexing.remote import RemoteIndexProvider
            hosts = self.config.get(d.INDEX_HOSTNAME, name) or []
            provider = RemoteIndexProvider(
                name, hostname=hosts[0] if hosts else "127.0.0.1",
                port=self.config.get(d.INDEX_PORT, name) or 8284)
        elif backend in ("elasticsearch", "solr"):
            # honesty over convenience: these names promise a CLUSTER
            # index (reference: StandardIndexProvider maps them to real
            # providers) — silently handing back the in-process
            # MemoryIndex would give a user a non-durable per-process
            # index while they believe they attached a cluster
            raise ConfigurationError(
                f"index.{name}.backend={backend!r} names a cluster index "
                "this build does not embed; use backend=remote-index "
                "pointing at a `python -m titan_tpu.indexing.remote` "
                "node (the ES/Solr-role networked provider), "
                "backend=lucene for the embedded full-text engine, or "
                "backend=memindex for an explicit in-process index")
        elif backend == "memindex":
            from titan_tpu.indexing.memindex import MemoryIndex
            provider = MemoryIndex(name, directory or None)
        else:
            import importlib
            mod, _, cls = backend.rpartition(".")
            provider = getattr(importlib.import_module(mod), cls)(
                name, directory or None)
        self._index_providers[name] = provider
        return provider

    def index_provider(self, name: str):
        """Provider by config name; opens on demand so an index built before
        the provider was configured still resolves."""
        p = self._index_providers.get(name)
        if p is None and name:
            try:
                p = self._open_index_provider(name)
            except ConfigurationError:
                raise          # misconfiguration must not degrade to None
            except Exception:
                return None
        return p

    # -- transactions --------------------------------------------------------

    def new_transaction(self, read_only: bool = False,
                        log_identifier: Optional[str] = None
                        ) -> GraphTransaction:
        self._check_open()
        self.count_tx("begin")
        return GraphTransaction(self, read_only=read_only,
                                log_identifier=log_identifier)

    def count_tx(self, event: str) -> None:
        """tx begin/commit/rollback counters (reference: docs/monitoring.txt:7-12
        measured domains; counters live in the shared MetricManager)."""
        if self._metrics is not None:
            self._metrics.counter(f"{self._metrics_prefix}.tx.{event}").inc()

    def tx(self) -> GraphTransaction:
        """Thread-bound current transaction (reference: thread-bound tx in
        TitanBlueprintsGraph)."""
        cur = getattr(self._tlocal, "tx", None)
        if cur is None or not cur.is_open:
            cur = self.new_transaction()
            self._tlocal.tx = cur
        return cur

    def traversal(self):
        from titan_tpu.traversal.dsl import GraphTraversalSource
        return GraphTraversalSource(self)

    def open_index_txs(self) -> dict:
        return {name: provider.begin_transaction()
                for name, provider in self._index_providers.items()}

    # -- convenience (delegate to the thread tx) ----------------------------

    def add_vertex(self, label: Optional[str] = None, **props):
        return self.tx().add_vertex(label, **props)

    def vertex(self, vid: int):
        return self.tx().vertex(vid)

    def vertices(self):
        return self.tx().vertices()

    def query(self):
        """Graph-centric query (reference: TitanGraph.query())."""
        return self.tx().query()

    def index_query(self, index_name: str, raw: str, limit=None, offset=0):
        """Direct native query against a mixed index (reference:
        TitanGraph.indexQuery → IndexQueryBuilder). Yields (element, score)."""
        from titan_tpu.core.schema import IndexDefinition
        from titan_tpu.indexing.provider import RawQuery
        st = self.schema.get_by_name(index_name)
        if not isinstance(st, IndexDefinition) or st.composite:
            raise TitanError(f"{index_name!r} is not a mixed index")
        provider = self.index_provider(st.backing)
        if provider is None:
            raise TitanError(f"provider {st.backing!r} not configured")
        tx = self.tx()
        out = []
        hits = provider.raw_query(index_name,
                                  RawQuery(raw, limit=limit, offset=offset))
        if st.element == "vertex":
            for docid, score in hits:
                el = tx.vertex(self.index_serializer.element_id_of(docid))
                if el is not None:
                    out.append((el, score))
            return out
        from titan_tpu.query.graphquery import GraphQuery
        eids = [self.index_serializer.element_id_of(d) for d, _ in hits]
        rel_map = GraphQuery(tx)._edges_by_rel_ids(set(eids))
        for (docid, score), eid in zip(hits, eids):
            el = rel_map.get(eid)
            if el is not None:
                out.append((el, score))
        return out

    def commit(self):
        cur = getattr(self._tlocal, "tx", None)
        if cur is not None and cur.is_open:
            cur.commit()
        self._tlocal.tx = None

    def rollback(self):
        cur = getattr(self._tlocal, "tx", None)
        if cur is not None and cur.is_open:
            cur.rollback()
        self._tlocal.tx = None

    # -- management ----------------------------------------------------------

    def management(self):
        from titan_tpu.core.management import ManagementSystem
        return ManagementSystem(self)

    def compute(self, backend: Optional[str] = None):
        from titan_tpu.olap import graph_computer
        return graph_computer(self, backend or self.config.get(d.COMPUTER_BACKEND))

    # -- commit orchestration (reference: StandardTitanGraph.commit) ---------

    def commit_transaction(self, tx: GraphTransaction) -> None:
        additions: dict[bytes, list] = {}
        deletions: dict[bytes, list] = {}
        # (vertex row, column) -> expected old value, for LOCK-consistency
        lock_targets: dict[tuple, Optional[bytes]] = {}

        # vertex-label TTLs: every cell of a TTL'd STATIC-label vertex
        # expires together (reference: prepareCommit TTL metadata,
        # StandardTitanGraph.java:558-592; vertex TTL requires static labels)
        label_ttl: dict[int, float] = {}
        for vid, lid in tx._vertex_labels.items():
            if lid:
                st = self.schema.get_type(lid)
                t = getattr(st, "ttl", 0.0) if st is not None else 0.0
                if t > 0:
                    label_ttl[vid] = t

        def entry_with_ttl(rel, entry: Entry) -> Entry:
            from titan_tpu.storage.api import TTLEntry
            ttls = [self.schema.ttl_of(rel.type_id)]
            ttls.append(label_ttl.get(rel.out_vertex_id, 0.0))
            if rel.is_edge:
                ttls.append(label_ttl.get(rel.in_vertex_id, 0.0))
            live = [t for t in ttls if t > 0]
            if not live:
                return entry
            return TTLEntry(entry.column, entry.value, min(live))

        def add(vid: int, entry: Entry):
            additions.setdefault(self.idm.key_bytes(vid), []).append(entry)

        def delete(vid: int, column: bytes):
            deletions.setdefault(self.idm.key_bytes(vid), []).append(column)

        # deleted relations first (an added SINGLE property both deletes the
        # old entry and writes the new one on the same column — consolidation
        # in the mutator keeps the addition; reference: prepareCommit order)
        for rel in tx._deleted.values():
            locked = self._needs_lock(rel)
            for vid, entry in self._serialize(rel):
                delete(vid, entry.column)
                if locked:
                    lock_targets[(self.idm.key_bytes(vid), entry.column)] = \
                        entry.value
        for rel in tx._added.values():
            locked = self._needs_lock(rel)
            for vid, entry in self._serialize(rel):
                add(vid, entry_with_ttl(rel, entry))
                if locked:
                    lock_targets.setdefault(
                        (self.idm.key_bytes(vid), entry.column), None)

        # index updates implied by this tx (reference: prepareCommit collects
        # IndexUpdates per mutation, IndexSerializer.getIndexUpdates)
        index_updates = self.index_serializer.collect_updates(tx)
        idx_additions: dict[bytes, list] = {}
        idx_deletions: dict[bytes, list] = {}
        unique_adds: list = []            # (row_key, column) to enforce
        mixed_updates: list = []
        for u in index_updates:
            if u.key is None:
                mixed_updates.append(u)
                continue
            if u.addition:
                idx_additions.setdefault(u.key, []).append(u.entry)
                if u.index.unique:
                    unique_adds.append((u.key, u.entry.column))
            else:
                idx_deletions.setdefault(u.key, []).append(u.entry.column)

        btx = tx.backend_tx
        for u in mixed_updates:   # buffered; flushed by commit_indexes
            itx = btx.index_txs.get(u.index.backing)
            if itx is None:
                # the backend tx may have snapshotted index_txs before this
                # provider was (lazily) opened — attach a fresh provider tx
                provider = self.index_provider(u.index.backing)
                if provider is None:
                    raise TitanError(
                        f"mixed index {u.index.name!r} needs provider "
                        f"{u.index.backing!r} — configure "
                        f"index.{u.index.backing}.backend")
                itx = btx.index_txs.setdefault(u.index.backing,
                                               provider.begin_transaction())
            if u.addition:
                itx.add(u.index.name, u.docid, u.field, u.value)
            else:
                itx.delete(u.index.name, u.docid, u.field)
        locker = self.backend.locker
        lock_state = tx._lock_state
        try:
            if lock_targets and locker is not None:
                from titan_tpu.storage.locking import LockID
                for (key, column), expected in lock_targets.items():
                    lid = LockID("edgestore", key, column)
                    lock_state.expected.setdefault(lid, expected)
                    locker.write_lock(lid, lock_state)
            if unique_adds and locker is not None:
                from titan_tpu.storage.locking import LockID
                for row_key, _col in unique_adds:
                    lid = LockID("graphindex", row_key, b"\x00u")
                    lock_state.expected.setdefault(lid, None)
                    locker.write_lock(lid, lock_state)

            wal, txid = self._wal, None
            if wal is not None:
                txid = wal.next_txid()
                payload = {
                    "edgestore": {key: ([tuple(e) for e in additions.get(key, [])],
                                        list(deletions.get(key, [])))
                                  for key in set(additions) | set(deletions)}}
                if idx_additions or idx_deletions:
                    payload["graphindex"] = {
                        key: ([tuple(e) for e in idx_additions.get(key, [])],
                              list(idx_deletions.get(key, [])))
                        for key in set(idx_additions) | set(idx_deletions)}
                wal.log_precommit(txid, payload)

            with self._commit_lock:
                if lock_state.has_locks and locker is not None:
                    locker.check_locks(lock_state, self._read_current_value)
                self._check_unique(unique_adds, idx_deletions)
                for key in set(additions) | set(deletions):
                    btx.mutate_edges(
                        key,
                        additions.get(key, ()),
                        deletions.get(key, ()))
                for key in set(idx_additions) | set(idx_deletions):
                    btx.mutate_index(
                        key,
                        idx_additions.get(key, ()),
                        idx_deletions.get(key, ()))
                try:
                    btx.commit_storage()
                except BaseException:
                    btx.rollback()
                    raise
                # WAL primary-success IMMEDIATELY after the storage
                # commit: a crash while building/pushing change payloads
                # below must not leave a durable commit classified by
                # TransactionRecovery as "failed before storage commit"
                if wal is not None:
                    wal.log_primary_success(txid)
                # storage is durable: feed subscribed snapshots their
                # delta, THEN bump the epoch — in the SAME lock block as
                # commit_storage, so storage visibility and epoch order
                # are atomic. (If the lock were dropped between the two,
                # a snapshot build() scanning in the gap would see the
                # edge in storage AND later receive its payload with an
                # epoch > epoch0, double-applying it through refresh()'s
                # continuity check.)
                epoch_next = self._mutation_epoch + 1
                listeners = list(self._change_listeners.values())
                if listeners:
                    from titan_tpu.core.changes import change_payload
                    payload = change_payload(self, tx,
                                             txid if txid is not None
                                             else epoch_next)
                    payload["epoch"] = epoch_next
                    for q in listeners:
                        q.push(payload)
                self._mutation_epoch = epoch_next
            try:
                btx.commit_indexes()
                # user trigger log between index commit and the SECONDARY
                # WAL record (reference: StandardTitanGraph.commit:725-772)
                if tx.log_identifier:
                    from titan_tpu.core.changes import (USER_LOG_PREFIX,
                                                        change_payload)
                    ulog = self.backend.log_manager.open_log(
                        USER_LOG_PREFIX + tx.log_identifier)
                    # without a WAL there is no txid; a commit timestamp is
                    # the next-best unique tag for the change stream
                    tag = txid if txid is not None \
                        else self.backend.times.time()
                    ulog.add(self.serializer.value_bytes(
                        change_payload(self, tx, tag)))
                if wal is not None:
                    wal.log_secondary_success(txid)
            except BaseException:
                if wal is not None:
                    wal.log_secondary_failure(txid)
                raise
        finally:
            # EVERY exit path releases locks — a leak would wedge this
            # column for every later tx until expiry
            if locker is not None and lock_state.has_locks:
                locker.release_locks(lock_state)

    # ------------------------------------------------- change subscription

    @property
    def mutation_epoch(self) -> int:
        """Monotone counter of committed transactions on THIS instance —
        the snapshot staleness epoch (snapshot.epoch < graph.mutation_epoch
        means the snapshot misses committed data)."""
        return self._mutation_epoch

    def subscribe_changes(self) -> tuple[int, "ChangeQueue"]:
        """Register an in-process change listener; every commit pushes its
        change payload (core/changes.change_payload shape + ``epoch``) to
        the returned queue. The registry holds it WEAKLY — keep a strong
        reference (snapshots do) or it auto-unregisters. Used by OLAP
        snapshots for delta refresh."""
        with self._commit_lock:
            return self._subscribe_locked()

    def _subscribe_locked(self) -> tuple[int, "ChangeQueue"]:
        """Register a listener; caller must hold ``_commit_lock`` (lets
        snapshot.build() atomically check the epoch and subscribe)."""
        from titan_tpu.core.changes import ChangeQueue
        self._listener_seq += 1
        token = self._listener_seq
        q = ChangeQueue(cap=self.config.get(d.TPU_CHANGE_BACKLOG))
        self._change_listeners[token] = q
        return token, q

    def unsubscribe_changes(self, token: int) -> None:
        self._change_listeners.pop(token, None)

    def _needs_lock(self, rel) -> bool:
        if self.backend.locker is None:
            return False
        if self.schema.system.is_system(rel.type_id):
            return False
        st = self.schema.get_type(rel.type_id)
        return st is not None and getattr(st, "consistency", "none") == "lock"

    def _read_current_value(self, lid) -> Optional[bytes]:
        from titan_tpu.storage.api import KeySliceQuery, SliceQuery
        from titan_tpu.codec.relation_ids import next_prefix
        store = (self.backend.index_store.store if lid.store == "graphindex"
                 else self.backend.edge_store.store)
        txh = self.backend.manager.begin_transaction()
        try:
            entries = store.get_slice(
                KeySliceQuery(lid.key, SliceQuery(lid.column,
                                                  next_prefix(lid.column))), txh)
        finally:
            txh.commit()
        for e in entries:
            if e.column == lid.column:
                return e.value
        return None

    def _check_unique(self, unique_adds: list, idx_deletions: dict) -> None:
        """Uniqueness constraint: the composite row of a unique index must be
        empty (or already hold only this element) before the write — entries
        this same transaction deletes don't count, so a unique value can move
        between elements in one commit. (reference: unique composite indexes
        lock the index row and fail on a conflicting entry)"""
        if not unique_adds:
            return
        from titan_tpu.storage.api import KeySliceQuery, SliceQuery
        from titan_tpu.errors import SchemaViolationError
        by_row: dict[bytes, set] = {}
        for row_key, column in unique_adds:   # intra-tx duplicates
            by_row.setdefault(row_key, set()).add(column)
            if len(by_row[row_key]) > 1:
                raise SchemaViolationError(
                    "unique index constraint violated: two elements in this "
                    "transaction share the same indexed value")
        txh = self.backend.manager.begin_transaction()
        try:
            for row_key, column in unique_adds:
                dropped = set(idx_deletions.get(row_key, ()))
                entries = self.backend.index_store.store.get_slice(
                    KeySliceQuery(row_key, SliceQuery()), txh)
                for e in entries:
                    if e.column != column and e.column not in dropped:
                        raise SchemaViolationError(
                            "unique index constraint violated: value already "
                            "bound to another element")
        finally:
            txh.commit()

    def _route_row(self, row_vid: int, other_vid: int) -> int:
        """Physical row for one endpoint of an edge. A vertex cut's edge
        entry lands on the representative copy in the OTHER endpoint's
        partition, so the two rows of an edge colocate (reference:
        docs/partitioning.txt:33-47 — writes go to the copy colocated with
        the other endpoint; system relations stay on the canonical copy)."""
        if self.idm.is_partitioned_vertex(row_vid) and \
                not self.idm.is_schema_id(other_vid):
            return self.idm.partitioned_vertex_id(
                self.idm.count(row_vid), self.idm.partition(other_vid))
        return row_vid

    def _serialize(self, rel):
        """Yield (row_vertex_id, Entry) per materialized endpoint row.
        Relation endpoints inside the entry are always CANONICAL ids; only
        the row key is representative-routed."""
        if rel.is_property:
            yield rel.out_vertex_id, self.codec.write_property(
                rel.type_id, rel.relation_id, rel.value, self.schema,
                rel.properties)
            return
        # edge: OUT row always; IN row unless unidirected or endpoint is a
        # schema vertex (vertex-label edges only materialize on the OUT side)
        yield self._route_row(rel.out_vertex_id, rel.in_vertex_id), \
            self.codec.write_edge(
                rel.type_id, rel.relation_id, Direction.OUT, rel.in_vertex_id,
                self.schema, rel.properties)
        unidirected = False
        st = self.schema.get_type(rel.type_id) \
            if not self.schema.system.is_system(rel.type_id) else None
        if st is not None and getattr(st, "unidirected", False):
            unidirected = True
        if self.idm.is_schema_id(rel.in_vertex_id):
            unidirected = True
        if not unidirected:
            yield self._route_row(rel.in_vertex_id, rel.out_vertex_id), \
                self.codec.write_edge(
                    rel.type_id, rel.relation_id, Direction.IN,
                    rel.out_vertex_id, self.schema, rel.properties)

    # -- lifecycle -----------------------------------------------------------

    def _check_open(self):
        if not self._open:
            raise TitanError("graph is closed")

    @property
    def is_open(self) -> bool:
        return self._open

    def close(self) -> None:
        if not self._open:
            return
        self._open = False
        for r in getattr(self, "_reporters", ()):
            r.stop()
        try:
            self.backend.instance_registry.deregister(self.instance_id)
        except Exception:
            pass
        self.id_assigner.close()
        for provider in self._index_providers.values():
            try:
                provider.close()
            except Exception:
                pass
        self.backend.close()

    def clear(self) -> None:
        """Drop all data (test helper; reference: TitanCleanup)."""
        self.backend.clear_storage()
        for provider in self._index_providers.values():
            provider.clear_storage()
        self.schema.expire()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
